# Shared helpers for the TPU measurement scripts (sourced by
# tpu_campaign5.sh and relay_watch.sh so the resumability condition
# cannot drift between the full campaign and the watcher's mini set).

# already_measured NAME — true if campaign/NAME.json holds a real
# (non-degraded would also say platform=tpu) TPU row worth keeping.
already_measured() {
  grep -q '"platform": "tpu"' "campaign/$1.json" 2>/dev/null
}

# relay_up — a bounded jax-init probe; the relay wedges at init when it
# is down, so a 90 s timeout is the detection, not a race.
relay_up() {
  timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1
}
