#!/bin/bash
# Wait for the axon relay, then run the highest-value pending TPU
# measurements. Deadline-aware: after DEADLINE_EPOCH the watcher exits
# without starting anything, and the mini set (~35 min) is used instead
# of the full campaign when less than ~90 min remain — the driver's
# end-of-round bench must not contend with a long campaign.
set -u
cd "$(dirname "$0")/.."
. scripts/campaign_lib.sh
DEADLINE_EPOCH=${DEADLINE_EPOCH:-$(date -d '15:05' +%s 2>/dev/null || echo 0)}
mkdir -p campaign
mini() {
  name=$1; shift
  if already_measured "$name"; then
    echo "=== $name: already measured on tpu, skipping ==="
    return 0
  fi
  echo "=== $name: $* ==="
  env BENCH_ATTEMPTS=1 BENCH_TIMEOUT=600 BENCH_TOTAL_BUDGET=600 "$@" \
    timeout 700 python bench.py >"campaign/$name.json" 2>"campaign/$name.log"
  echo "--- rc=$? json:"; cat "campaign/$name.json"
}
while true; do
  now=$(date +%s)
  if [ "$DEADLINE_EPOCH" -gt 0 ] && [ "$now" -ge "$DEADLINE_EPOCH" ]; then
    echo "deadline passed at $(date); exiting without measurements"
    exit 0
  fi
  if relay_up; then
    echo "relay up at $(date)"
    remaining=$(( DEADLINE_EPOCH - $(date +%s) ))
    if [ "$DEADLINE_EPOCH" -le 0 ] || [ "$remaining" -gt 5400 ]; then
      # Campaign 5 is resumable (per-config tpu-row skip + fail-fast
      # relay probe, exit 3 on mid-campaign wedge): on exit 3, go back
      # to probing instead of giving up the round's remaining windows.
      bash scripts/tpu_campaign5.sh
      rc=$?
      if [ "$rc" -eq 3 ]; then
        echo "campaign aborted on relay wedge at $(date); resuming watch"
        sleep 300
        continue
      fi
      PYTHONPATH=/root/.axon_site:/root/repo timeout 600 \
        python scripts/tpu_probe.py llama-1b 32 1024 2>&1 | grep "probe:"
      if bash -c '. scripts/campaign_lib.sh; for f in campaign/*.json; do
            n=$(basename "$f" .json); already_measured "$n" || exit 1
          done'; then
        echo "full ladder measured; watcher done at $(date)"
        exit 0
      fi
      # Ladder incomplete (some configs degraded/failed): keep watching —
      # a later window can fill them (every run() skips measured rows).
      sleep 300
      continue
    else
      echo "short window (${remaining}s): mini harvest — mega A/B first"
      mini r4-1b BENCH_MODEL=llama-1b BENCH_MEGA=0
      mini r4-1b-mega8 BENCH_MODEL=llama-1b BENCH_MEGA=8
      mini r4-8b-kv8-mega8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8 BENCH_MEGA=8
      mini r4-1b-int4 BENCH_MODEL=llama-1b BENCH_QUANT=int4
      mini r5-mistral-8k BENCH_MODEL=mistral-7b BENCH_MAX_LEN=8192 BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_QUANT=int8 BENCH_KV_QUANT=int8 BENCH_NEW_TOKENS=64 BENCH_PREFILL_DEPTH=8 BENCH_MEGA=8
      # A short window that completed the mini set may be followed by a
      # longer one — keep watching until the deadline.
      sleep 300
      continue
    fi
  fi
  echo "relay down at $(date)"
  sleep 300
done
