#!/bin/bash
# Wait for the axon relay to come back, then run the pending TPU work:
# campaign 4 (spec + s64 retest + headline re-runs) and the dispatch-cost
# probe. Probe cadence 5 min; each probe is timeout-guarded because a
# wedged relay HANGS jax.devices() rather than failing it.
set -u
cd "$(dirname "$0")/.."
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "relay up at $(date)"
    bash scripts/tpu_campaign4.sh
    PYTHONPATH=/root/.axon_site:/root/repo timeout 600 \
      python scripts/tpu_probe.py llama-1b 32 1024 2>&1 | grep "probe:"
    PYTHONPATH=/root/.axon_site:/root/repo timeout 900 \
      python scripts/tpu_configs234.py 2>&1 | grep "config"
    exit 0
  fi
  echo "relay down at $(date)"
  sleep 300
done
