#!/bin/bash
# Campaign 4: speculative decoding (n-gram, greedy-lossless) + retest of
# 64-slot scaling with the quarter-capacity admission drain.
set -u
cd "$(dirname "$0")/.."
mkdir -p campaign
run() {
  name=$1; shift
  echo "=== $name: $* ==="
  # NB: per-run env comes LAST so a run's GOFR_TPU_FLASH_DECODE etc.
  # wins; the auto heuristic already picks dense at max_len<=2048.
  env BENCH_ATTEMPTS=1 BENCH_TIMEOUT=900 BENCH_TOTAL_BUDGET=900 "$@" \
    timeout 1000 python bench.py >"campaign/$name.json" 2>"campaign/$name.log"
  echo "--- rc=$? json:"; cat "campaign/$name.json"
  tail -n 3 "campaign/$name.log"
}
# 1. Speculation on the headline config. NOTE: random-weight greedy output
#    loops, which flatters n-gram acceptance — report as a labeled row,
#    never as the headline number.
run r3d-1b-spec3 BENCH_MODEL=llama-1b BENCH_SPEC=3
run r3d-8b-spec3 BENCH_MODEL=llama-3-8b BENCH_SLOTS=16 BENCH_REQUESTS=32 BENCH_SPEC=3
# 2. 64-slot retest (quarter-capacity drain + prefill_batch 8).
run r3d-1b-s64 BENCH_MODEL=llama-1b BENCH_SLOTS=64 BENCH_REQUESTS=128
# 3. Headline re-run for the drain/prefill-batch deltas.
run r3d-1b BENCH_MODEL=llama-1b
run r3d-1b-w16 BENCH_MODEL=llama-1b BENCH_WINDOW=16
run r3d-8b-kv8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8
# 4. Paged KV cache: dense (gather) fallback vs the table-indexed kernel
#    — the auto heuristic always kernels paged caches, so the dense row
#    needs the explicit override.
run r3d-1b-paged BENCH_MODEL=llama-1b BENCH_KV_BLOCK=128 GOFR_TPU_FLASH_DECODE=0
run r3d-1b-paged-kern BENCH_MODEL=llama-1b BENCH_KV_BLOCK=256 GOFR_TPU_FLASH_DECODE=1
# 5. int4 weights, now nibble-packed uint8 (the s4 relay bug is dodged).
run r3d-1b-int4 BENCH_MODEL=llama-1b BENCH_QUANT=int4
run r3d-8b-int4-kv8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=32 BENCH_QUANT=int4 BENCH_KV_QUANT=int8
# 6a. Steady-state (staggered arrivals, varied budgets) vs the default
#     synchronized-burst workload.
run r3d-1b-steady BENCH_MODEL=llama-1b BENCH_ARRIVAL_MS=25 BENCH_TOKEN_SPREAD=0.5
# 6. Long context (max_len 4096): the auto heuristic picks the kernel
#    here (length-skipping pays); the dense run is the A/B.
run r3d-1b-4k BENCH_MODEL=llama-1b BENCH_MAX_LEN=4096 BENCH_SLOTS=16 BENCH_REQUESTS=32
run r3d-1b-4k-dense BENCH_MODEL=llama-1b BENCH_MAX_LEN=4096 BENCH_SLOTS=16 BENCH_REQUESTS=32 GOFR_TPU_FLASH_DECODE=0
