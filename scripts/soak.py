"""Serving soak: continuous mixed load across the full feature matrix.

One engine (mega windows + paged KV + penalties + top_logprobs +
multi-LoRA + sliding window) takes wave after wave of requests churning
seeds, penalties, logit_bias, top_logprobs, stop sequences, adapters,
and mid-flight cancellations, with adapters loaded/unloaded between
waves. After every wave the engine must return to VERIFIED IDLE: all
slots free, every paged KV block back in the pool, no pending queue,
futures all resolved. Exit code 1 on any invariant break.

Usage: [SOAK_SECONDS=300] python scripts/soak.py
(CPU by default — set nothing; on a live chip prefix with the usual
env. The r4 close-out ran 600 s ≈ 27k requests with zero leaks.)
"""

from __future__ import annotations

import json
import os
import random
import resource
import sys
import time
from concurrent.futures import CancelledError

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    import jax

    from gofr_tpu.models.registry import get_model
    from gofr_tpu.models.transformer import lora_dims
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    import dataclasses

    from gofr_tpu.models.registry import ModelSpec, register_model

    seconds = float(os.environ.get("SOAK_SECONDS", "300"))
    # llama-tiny with an ACTIVE sliding window (32 < max_len 256): the
    # claimed feature matrix includes window masking, and in particular
    # the paged+window decode combination (kv_block below) — llama-tiny
    # itself has sliding_window=0 and would never exercise it.
    tiny = get_model("llama-tiny")
    cfg = dataclasses.replace(tiny.config, sliding_window=32)
    register_model(dataclasses.replace(tiny, name="soak-swa-tiny", config=cfg))
    eng = InferenceEngine(
        "soak-swa-tiny", n_slots=8, max_len=256, window_k=4, mega_windows=4,
        enable_penalties=True, top_logprobs=2, kv_block=32,
        tokenizer=ByteTokenizer(), lora_slots=2, lora_rank=4,
    )
    eng.start_sync()
    rng = random.Random(0)

    def rand_adapter(seed: int) -> dict:
        leaves = {}
        for ti, t in enumerate(("wq", "wv")):
            d_in, d_out = lora_dims(cfg, t)
            k1, k2 = jax.random.split(
                jax.random.fold_in(jax.random.PRNGKey(seed), ti)
            )
            leaves[t] = (
                0.3 * jax.random.normal(k1, (cfg.n_layers, d_in, 4)),
                0.3 * jax.random.normal(k2, (cfg.n_layers, 4, d_out)),
            )
        return leaves

    eng.load_lora("a", rand_adapter(1))
    free_blocks_full = len(eng._free_blocks)
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

    waves = requests = cancels = errors = adapter_races = 0
    t_end = time.time() + seconds
    # Compile-cache growth tripwire (r4 VERDICT weak #9 → next #6): the
    # program-variant caches are BOUNDED by construction — the only
    # static compile switches are use_bias (2 variants per program) and
    # the engine-level feature flags; penalties/seeds/top_logprobs ride
    # as dynamic operands. Measured: 12 churn waves hold jit cache sizes
    # at {prefill: 2, mega: 2} with RSS flat at 454 MB. The r4 soak's
    # 0.27→0.52 GB was first-touch compile warmup, not monotonic growth.
    # This assertion makes any regression (a new static arg minting
    # per-request variants) fail the soak loudly: peak RSS after the
    # warmup third must not grow more than SOAK_RSS_CEILING_MB.
    warmup_until = time.time() + seconds / 3
    rss_warm = None
    rss_ceiling_mb = float(os.environ.get("SOAK_RSS_CEILING_MB", "192"))
    try:
        while time.time() < t_end:
            reqs = []
            for i in range(rng.randint(8, 16)):
                kw: dict = {
                    "max_new_tokens": rng.choice([4, 9, 17, 30]),
                    "temperature": rng.choice([0.0, 0.0, 0.9]),
                    "stop_on_eos": False,
                }
                adapters = [""] + eng.lora_names()
                kw["adapter"] = rng.choice(adapters)
                if rng.random() < 0.3:
                    kw["seed"] = rng.randint(0, 2**31 - 1)
                if rng.random() < 0.3:
                    kw["frequency_penalty"] = 1.0
                if rng.random() < 0.2:
                    kw["logit_bias"] = {rng.randint(0, 511): -100}
                if rng.random() < 0.3:
                    kw["top_logprobs"] = 2
                if rng.random() < 0.2:
                    kw["stop"] = [chr(97 + rng.randint(0, 25))]
                reqs.append(eng.submit_generate(f"wave {waves} req {i}", **kw))
                requests += 1
            # Adapter churn WHILE the wave's requests are live — this is
            # the load_lora/unload_lora "safe while serving" path the
            # soak exists to exercise (an idle-time swap would prove
            # nothing).
            if waves % 8 == 3:
                eng.load_lora("b", rand_adapter(100 + waves))
            elif waves % 8 == 7 and "b" in eng.lora_names():
                eng.unload_lora("b")
            # Cancel ~20% mid-flight (future.cancel() is the public
            # cancellation seam; False = already finished).
            for r in reqs:
                if rng.random() < 0.2 and r.future.cancel():
                    cancels += 1
            for r in reqs:
                try:
                    r.future.result(timeout=180)
                except CancelledError:
                    pass
                except RuntimeError as exc:
                    if "LoRA adapter" in str(exc):
                        # Designed outcome: churn invalidated a queued/
                        # in-flight adapter request (a completion must
                        # never mix weight sets) — count, don't fail.
                        adapter_races += 1
                    else:
                        errors += 1
                        print(f"wave {waves}: request failed: {exc!r}")
                except Exception as exc:  # noqa: BLE001
                    # A real request failure is exactly what the soak
                    # must surface, not swallow.
                    errors += 1
                    print(f"wave {waves}: request failed: {exc!r}")
            # Verified idle.
            deadline = time.time() + 30
            while time.time() < deadline:
                if (
                    all(s is None for s in eng._slots)
                    and not eng._prefilling
                    and eng._pending.empty()
                    and len(eng._free_blocks) == free_blocks_full
                ):
                    break
                time.sleep(0.05)
            else:
                print(json.dumps({
                    "soak": "FAIL", "wave": waves,
                    "slots_busy": sum(
                        1 for s in eng._slots if s is not None
                    ),
                    "blocks_leaked": free_blocks_full - len(eng._free_blocks),
                }))
                return 1
            waves += 1
            if rss_warm is None and time.time() >= warmup_until:
                rss_warm = resource.getrusage(
                    resource.RUSAGE_SELF
                ).ru_maxrss
    finally:
        eng.stop_sync()
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    rss_flat = True
    if rss_warm is not None:
        grew_mb = (rss1 - rss_warm) / 1024
        rss_flat = grew_mb <= rss_ceiling_mb
        if not rss_flat:
            print(f"RSS grew {grew_mb:.0f} MB past the post-warmup "
                  f"ceiling ({rss_ceiling_mb:.0f} MB) — a compile-cache "
                  f"or buffer leak regression")
    print(json.dumps({
        "soak": "OK" if errors == 0 and rss_flat else "FAIL",
        "seconds": seconds, "waves": waves,
        "requests": requests, "cancels": cancels, "errors": errors,
        "adapter_races": adapter_races,
        "rss_mb_start_to_peak": [round(rss0 / 1024), round(rss1 / 1024)],
        "rss_post_warmup_flat": rss_flat,
    }))
    return 0 if errors == 0 and rss_flat else 1


if __name__ == "__main__":
    sys.exit(main())
