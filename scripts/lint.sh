#!/usr/bin/env bash
# The repo lint/type gate, one command locally == the CI `lint` job:
#   ruff      — pycodestyle/pyflakes/bugbear subset (pyproject.toml),
#               plus import sorting scoped to the analysis package;
#   mypy      — scoped strictness (config/logging/service/scheduler strict,
#               rest permissive; see [tool.mypy] in pyproject.toml);
#   graftlint — TPU-correctness rules GL001–GL025 (per-file TPU rules
#               plus project-wide concurrency analysis) against the committed
#               baseline (gofr_tpu/analysis; docs/advanced-guide/
#               static-analysis.md).
#
# ruff/mypy are optional locally (skipped with a warning when not
# installed); graftlint ships with the repo and always runs.
set -uo pipefail
cd "$(dirname "$0")/.."

failed=0

if command -v ruff >/dev/null 2>&1; then
  echo "== ruff =="
  ruff check gofr_tpu/ tests/ examples/ bench.py __graft_entry__.py || failed=1
  ruff check --select I gofr_tpu/analysis tests/test_graftlint.py || failed=1
else
  echo "== ruff == SKIPPED (not installed; pip install ruff)"
fi

if command -v mypy >/dev/null 2>&1; then
  echo "== mypy (scoped) =="
  mypy gofr_tpu/analysis gofr_tpu/config gofr_tpu/logging \
    gofr_tpu/metrics gofr_tpu/tracing gofr_tpu/faults \
    gofr_tpu/ops/kv_cache.py \
    gofr_tpu/service \
    gofr_tpu/serving/types.py gofr_tpu/serving/lifecycle.py \
    gofr_tpu/serving/engine.py gofr_tpu/serving/backend.py \
    gofr_tpu/serving/batcher.py gofr_tpu/serving/brownout.py \
    gofr_tpu/serving/control_plane.py \
    gofr_tpu/serving/supervisor.py \
    gofr_tpu/serving/watchdog.py gofr_tpu/serving/scheduler.py \
    gofr_tpu/serving/observability.py gofr_tpu/serving/radix_cache.py \
    gofr_tpu/serving/prefix_cache.py gofr_tpu/serving/programs.py \
    gofr_tpu/serving/device_telemetry.py \
    gofr_tpu/serving/loop_profiler.py \
    gofr_tpu/serving/profiler_capture.py \
    gofr_tpu/serving/tenant_ledger.py gofr_tpu/serving/slo.py \
    gofr_tpu/serving/openai_compat.py \
    gofr_tpu/pubsub gofr_tpu/serving/async_serving.py || failed=1
else
  echo "== mypy == SKIPPED (not installed; pip install mypy)"
fi

echo "== graftlint =="
python -m gofr_tpu.analysis gofr_tpu/ --check-baseline || failed=1

exit "$failed"
