#!/bin/bash
# Follow-up TPU campaign: re-measure configs whose first runs were killed
# by the bench watchdog shadowing bug, plus scheduler-fix validation.
set -u
cd "$(dirname "$0")/.."
mkdir -p campaign
run() {
  name=$1; shift
  echo "=== $name: $* ==="
  env "$@" BENCH_ATTEMPTS=1 BENCH_TIMEOUT=900 BENCH_TOTAL_BUDGET=900 \
    timeout 1000 python bench.py >"campaign/$name.json" 2>"campaign/$name.log"
  echo "--- rc=$? json:"; cat "campaign/$name.json"
  tail -n 3 "campaign/$name.log"
}
# 1. Scheduler-fix validation: same config as r3-1b-int8 (1688 tok/s,
#    unloaded TTFT 361 ms before the early-emit + wave-drain fixes).
run r3b-1b-int8 BENCH_MODEL=llama-1b
# 2. Flagship 8B rows (first runs died at the unloaded-ttft stage).
run r3b-8b-int8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=16 BENCH_REQUESTS=32
run r3b-8b-int8-kv8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=32 BENCH_KV_QUANT=int8
# 3. Window 16 retest (prior 1192 tok/s was starved by 1:1 admission).
run r3b-1b-w16d3 BENCH_MODEL=llama-1b BENCH_WINDOW=16 BENCH_DEPTH=3
# 4. Slot scaling: does 64 slots amortize the fixed step cost?
run r3b-1b-int8-s64 BENCH_MODEL=llama-1b BENCH_SLOTS=64 BENCH_REQUESTS=128
run r3b-1b-int8-kv8-s64 BENCH_MODEL=llama-1b BENCH_SLOTS=64 BENCH_REQUESTS=128 BENCH_KV_QUANT=int8
# 5. Decode attention dense vs kernel at the split-cache step (probe says
#    dense 2.4 ms vs kernel 5.1 ms per full stack at half-full 1024).
run r3b-1b-int8-dense BENCH_MODEL=llama-1b GOFR_TPU_FLASH_DECODE=0
run r3b-8b-int8-kv8-dense BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=32 BENCH_KV_QUANT=int8 GOFR_TPU_FLASH_DECODE=0
