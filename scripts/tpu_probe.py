"""Decode-step component probe (run on the real chip, after bench).

Answers "where do the 12.6 ms/step go?" (round-3 profile: llama-1b int8,
32 slots → step 12.64 ms vs a ~2.5 ms roofline estimate: 1.5 ms int8
weight stream + ~0.9 ms bf16 cache reads + ~0.4 ms MXU). Times jitted
variants of the decode step at the exact serving shapes, each wrapped in a
lax.scan of K steps per dispatch so relay RTT amortizes out:

  * full        — the engine's decode step (matmuls + attention + argmax)
  * noattn      — attention monkeypatched to zeros (isolates matmul +
                  cache-write cost)
  * matmul-only — the 22-layer int8 einsum stack alone, no cache at all
                  (isolates the weight stream: if this alone is ~8 ms the
                  int8→bf16 convert is materializing weight copies in HBM)
  * attn-only   — decode attention alone over the full cache, dense vs
                  pallas kernel
  * dtypes      — bf16 vs int8 vs int4 full step

Usage:  python scripts/tpu_probe.py [model] [n_slots] [max_len]
Prints one line per probe: name, ms/step, implied tok/s at n_slots.
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

MODEL = sys.argv[1] if len(sys.argv) > 1 else "llama-1b"
SLOTS = int(sys.argv[2]) if len(sys.argv) > 2 else 32
MAX_LEN = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
K = 8  # steps per dispatch
REPS = 4  # dispatches per timing


def probe(name, fn, *args):
    try:
        jax.block_until_ready(fn(*args))  # compile
        t0 = time.perf_counter()
        out = None
        for _ in range(REPS):
            out = fn(*args)
        jax.block_until_ready(out)
        per_step = (time.perf_counter() - t0) / REPS / K * 1e3
        print(
            f"probe: {name:<28} {per_step:8.3f} ms/step  "
            f"→ {SLOTS / per_step * 1e3:7.0f} tok/s @ {SLOTS} slots",
            flush=True,
        )
        return per_step
    except Exception as exc:  # noqa: BLE001 — probes are advisory
        print(f"probe: {name:<28} FAILED: {exc!r}", flush=True)
        return None


def main() -> None:
    import gofr_tpu.models.transformer as tr
    from gofr_tpu.models.registry import get_model
    from gofr_tpu.ops.kv_cache import KVCache
    from gofr_tpu.ops.quant import quantize_params

    spec = get_model(MODEL)
    cfg = spec.config
    max_len = min(MAX_LEN, cfg.max_len)
    print(
        f"probe: model={MODEL} slots={SLOTS} max_len={max_len} "
        f"K={K} platform={jax.devices()[0].platform}",
        flush=True,
    )

    t0 = time.time()
    params8 = _init_quant(spec, cfg, "int8")
    print(f"probe: int8 params in {time.time() - t0:.1f}s", flush=True)

    cache = KVCache.create(
        cfg.n_layers, SLOTS, max_len, cfg.n_kv_heads, cfg.head_dim, cfg.dtype
    )
    # Warm cache: pretend every slot holds a half-full sequence.
    cache = cache._replace(
        lengths=jnp.full((SLOTS,), max_len // 2, jnp.int32)
    )
    tokens = jnp.ones((SLOTS,), jnp.int32)
    active = jnp.ones((SLOTS,), bool)

    def window(params, tokens, cache):
        def body(carry, _):
            tokens, cache = carry
            logits, cache = tr.transformer_decode_step(
                params, tokens, cache, active, cfg
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, cache), None

        (tokens, cache), _ = jax.lax.scan(body, (tokens, cache), length=K)
        return tokens, cache.lengths

    full = jax.jit(window)
    base = probe("full int8 (argmax)", full, params8, tokens, cache)

    # --- mega window: M k-step windows in a while_loop per dispatch (the
    # r4 serving throughput mode). vs `full`: quantifies (a) whether the
    # while_loop costs device time over the plain scan, (b) the dispatch
    # amortization — one host call per M*K steps.
    for M in (4, 16):
        def mega(params, tokens, cache, M=M):
            def body(carry, _):
                tokens, cache = carry
                logits, cache = tr.transformer_decode_step(
                    params, tokens, cache, active, cfg
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), None

            def win(state):
                i, tokens, cache = state
                (tokens, cache), _ = jax.lax.scan(
                    body, (tokens, cache), length=K
                )
                return i + 1, tokens, cache

            _, tokens, cache = jax.lax.while_loop(
                lambda s: s[0] < M, win,
                (jnp.asarray(0, jnp.int32), tokens, cache),
            )
            return tokens, cache.lengths

        try:
            fn = jax.jit(mega)
            jax.block_until_ready(fn(params8, tokens, cache))
            t0 = time.perf_counter()
            out = fn(params8, tokens, cache)
            jax.block_until_ready(out)
            per_step = (time.perf_counter() - t0) / (M * K) * 1e3
            print(
                f"probe: mega M={M:<3} (one dispatch)  {per_step:8.3f} "
                f"ms/step  → {SLOTS / per_step * 1e3:7.0f} tok/s "
                f"@ {SLOTS} slots",
                flush=True,
            )
        except Exception as exc:  # noqa: BLE001 — probe is advisory
            print(f"probe: mega M={M} FAILED: {exc!r}", flush=True)

    # --- attention monkeypatched out (still writes K/V into the cache).
    real_attn = tr.decode_attention
    tr.decode_attention = (
        lambda q, ck, cv, lens, **kw: jnp.zeros_like(q)
    )
    try:
        probe("int8 attention-zeroed", jax.jit(window), params8, tokens, cache)
    finally:
        tr.decode_attention = real_attn

    # --- matmul stack only: exact decode einsums, no cache, no attention.
    def matmul_window(params, x0):
        lhd = cfg.n_heads * cfg.head_dim
        kvd = cfg.n_kv_heads * cfg.head_dim

        def step(x, _):
            def body(x, lp):
                h = tr.rms_norm(x[:, None, :], lp["attn_norm"], cfg.norm_eps)[:, 0]
                q = tr._wein("bd,dh->bh", h, lp["wq"])
                k = tr._wein("bd,dh->bh", h, lp["wk"])
                v = tr._wein("bd,dh->bh", h, lp["wv"])
                attn = (
                    q + jnp.tile(k, (1, lhd // kvd)) + jnp.tile(v, (1, lhd // kvd))
                )
                x = x + tr._wein("bh,hd->bd", attn, lp["wo"])
                h = tr.rms_norm(x[:, None, :], lp["mlp_norm"], cfg.norm_eps)
                ffn = tr._ffn_dense(h, lp, cfg)
                return x + ffn[:, 0], None

            x, _ = jax.lax.scan(body, x, params["layers"])
            x = tr.rms_norm(x[:, None, :], params["final_norm"], cfg.norm_eps)[:, 0]
            logits = tr._wein("bd,dv->bv", x, params["lm_head"])
            return x * 0.999 + logits[:, :1] * 1e-6, None

        x, _ = jax.lax.scan(step, x0, length=K)
        return x

    x0 = jax.random.normal(jax.random.PRNGKey(2), (SLOTS, cfg.d_model), cfg.dtype)
    probe("matmul-stack int8", jax.jit(matmul_window), params8, x0)

    # --- attention alone at serving shapes, chained per dispatch.
    from gofr_tpu.ops.attention import decode_attention

    q0 = jax.random.normal(
        jax.random.PRNGKey(1), (SLOTS, cfg.n_heads, cfg.head_dim), cfg.dtype
    )
    kc, vc = cache.k[0], cache.v[0]

    def attn_window(q, kern):
        def body(q, _):
            o = decode_attention(q, kc, vc, cache.lengths, kernel=kern)
            return o * 0.999, None

        q, _ = jax.lax.scan(body, q, length=K * cfg.n_layers)
        return q

    for kern, nm in ((False, "dense"), (True, "kernel")):
        t = probe(
            f"decode-attn[{nm}] full stack",
            jax.jit(partial(attn_window, kern=kern)), q0,
        )

    # --- weight-dtype variants of the full window.
    del params8
    t0 = time.time()
    params_bf16 = jax.jit(lambda k: spec.init(k, cfg))(jax.random.PRNGKey(0))
    print(f"probe: bf16 params in {time.time() - t0:.1f}s", flush=True)
    probe("full bf16", full, params_bf16, tokens, cache)
    # Dispatch-cost probe (BEFORE the int4 quantize donates params_bf16):
    # how long does ONE jit call hold the host thread (async dispatch
    # return — NOT device completion)? The serving scheduler issues one
    # window call per cycle; if the relay charges a full RTT per
    # dispatch, the cycle floor is that RTT regardless of pipeline depth,
    # and overlapping dispatch with processing in separate threads is
    # the fix.
    for burst in (1, 4):
        t0 = time.perf_counter()
        outs = [full(params_bf16, tokens, cache) for _ in range(burst)]
        t_disp = (time.perf_counter() - t0) / burst * 1e3
        jax.block_until_ready(outs[-1])
        t_total = (time.perf_counter() - t0) * 1e3
        print(
            f"probe: dispatch burst={burst}: {t_disp:.1f} ms/call host-"
            f"blocked, {t_total:.1f} ms to completion",
            flush=True,
        )
    params4 = jax.jit(
        partial(quantize_params, mode="int4"), donate_argnums=(0,)
    )(params_bf16)
    probe("full int4", full, params4, tokens, cache)
    if base is not None:
        print(
            f"probe: roofline check — int8 step {base:.2f} ms; int8 weight "
            f"bytes alone need ~1.5 ms at 819 GB/s",
            flush=True,
        )


def _init_quant(spec, cfg, mode):
    from gofr_tpu.serving.engine import InferenceEngine

    eng = InferenceEngine.__new__(InferenceEngine)
    eng._jax, eng._jnp = jax, jnp
    eng.spec, eng.cfg, eng.quant = spec, cfg, mode
    return InferenceEngine._init_llm_quantized(eng, 0)


if __name__ == "__main__":
    main()
