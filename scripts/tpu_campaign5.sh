#!/bin/bash
# Campaign 5 (round 4): mega-window dispatch-amortization A/B — the r3
# VERDICT's top item. Measured r3: best 1b step 4.08 ms (device-bound
# ~7.8k tok/s at 32 slots) but ~72 ms of relay RTT per 8-step window
# dispatch capped throughput at 2421. Mega windows pay the RTT once per
# m*k steps. Then the queued campaign-4 features (int4, spec, paged,
# 4k kernel A/B) at their best-known configs.
set -u
cd "$(dirname "$0")/.."
. scripts/campaign_lib.sh
mkdir -p campaign
run() {
  name=$1; shift
  # Resumable: a config that already produced a real TPU row is skipped,
  # so the watcher can re-fire this script after a mid-campaign relay
  # wedge without repeating completed measurements.
  if already_measured "$name"; then
    echo "=== $name: already measured on tpu, skipping ==="
    return 0
  fi
  # Fail fast when the relay is wedged: a 90 s jax-init probe costs
  # little; without it every config burns its full timeout degrading
  # to CPU and the ladder wastes hours.
  if ! relay_up; then
    echo "=== $name: relay down at probe, aborting campaign ==="
    exit 3
  fi
  echo "=== $name: $* ==="
  env BENCH_ATTEMPTS=1 BENCH_TIMEOUT=900 BENCH_TOTAL_BUDGET=900 "$@" \
    timeout 1000 python bench.py >"campaign/$name.json" 2>"campaign/$name.log"
  echo "--- rc=$? json:"; cat "campaign/$name.json"
  tail -n 3 "campaign/$name.log"
}
# 1. Mega-window ladder on the 1b headline config (budget 128 = 16
#    windows of 8; m=16 covers a whole retirement wave in one dispatch).
run r4-1b BENCH_MODEL=llama-1b BENCH_MEGA=0
run r4-1b-mega4 BENCH_MODEL=llama-1b BENCH_MEGA=4
run r4-1b-mega8 BENCH_MODEL=llama-1b BENCH_MEGA=8
run r4-1b-mega16 BENCH_MODEL=llama-1b BENCH_MEGA=16
# 2. 8B at the r3-best config (32 slots, int8 kv, dense) + mega.
run r4-8b-kv8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8 BENCH_MEGA=0
run r4-8b-kv8-mega8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8 BENCH_MEGA=8
run r4-8b-kv8-mega16 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8 BENCH_MEGA=16
# 3. Steady-state workload with mega (arrival-staggered, spread budgets)
#    — the workload the VERDICT wants as the headline.
run r4-1b-steady BENCH_MODEL=llama-1b BENCH_ARRIVAL_MS=25 BENCH_TOKEN_SPREAD=0.5 BENCH_MEGA=0
run r4-1b-steady-mega8 BENCH_MODEL=llama-1b BENCH_ARRIVAL_MS=25 BENCH_TOKEN_SPREAD=0.5 BENCH_MEGA=8
# 4. int4 weights (nibble-packed), alone and with mega.
run r4-1b-int4 BENCH_MODEL=llama-1b BENCH_QUANT=int4 BENCH_MEGA=0
run r4-8b-int4-kv8-mega8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=32 BENCH_QUANT=int4 BENCH_KV_QUANT=int8 BENCH_MEGA=8
# 5. Speculation (labeled mechanism rows — random-weight greedy loops
#    flatter n-gram acceptance).
run r4-1b-spec3 BENCH_MODEL=llama-1b BENCH_SPEC=3 BENCH_MEGA=0
run r4-1b-spec3-mega8 BENCH_MODEL=llama-1b BENCH_SPEC=3 BENCH_MEGA=8
# 6. Paged KV, dense vs kernel.
run r4-1b-paged BENCH_MODEL=llama-1b BENCH_KV_BLOCK=128 GOFR_TPU_FLASH_DECODE=0 BENCH_MEGA=0
run r4-1b-paged-kern BENCH_MODEL=llama-1b BENCH_KV_BLOCK=256 GOFR_TPU_FLASH_DECODE=1 BENCH_MEGA=0
# 7. Long context 4k: kernel-vs-dense A/B (the flash_decode verdict), and
#    8k with paged KV + int8 kv — the long-context serving row.
run r4-1b-4k BENCH_MODEL=llama-1b BENCH_MAX_LEN=4096 BENCH_SLOTS=16 BENCH_REQUESTS=32 BENCH_MEGA=0
run r4-1b-4k-dense BENCH_MODEL=llama-1b BENCH_MAX_LEN=4096 BENCH_SLOTS=16 BENCH_REQUESTS=32 GOFR_TPU_FLASH_DECODE=0 BENCH_MEGA=0
run r4-8b-8k-paged BENCH_MODEL=llama-3-8b BENCH_MAX_LEN=8192 BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_KV_QUANT=int8 BENCH_KV_BLOCK=512 BENCH_NEW_TOKENS=64 BENCH_PREFILL_DEPTH=8 BENCH_MEGA=0
run r4-8b-8k-paged-mega8 BENCH_MODEL=llama-3-8b BENCH_MAX_LEN=8192 BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_KV_QUANT=int8 BENCH_KV_BLOCK=512 BENCH_NEW_TOKENS=64 BENCH_PREFILL_DEPTH=8 BENCH_MEGA=8
# 8. Long-prompt TTFT A/B: multi-chunk prefill on vs off (4k prompts).
run r4-1b-4k-pd8 BENCH_MODEL=llama-1b BENCH_MAX_LEN=4096 BENCH_SLOTS=16 BENCH_REQUESTS=32 BENCH_PREFILL_DEPTH=8 BENCH_MEGA=0
# 9. Multi-LoRA serving overhead: 4 rank-16 adapters round-robin vs base.
run r4-1b-lora4 BENCH_MODEL=llama-1b BENCH_LORA=4 BENCH_MEGA=0
run r4-1b-lora4-mega8 BENCH_MODEL=llama-1b BENCH_LORA=4 BENCH_MEGA=8
# 10. (r5) Sliding-window serving at mistral geometry: the windowed
#     flash-decode path (in-kernel window mask + block skip, O(window)
#     HBM reads) vs the dense full-cache read. int8 weights + int8 KV
#     keep 7B + 8×8k cache inside one v5e.
run r5-mistral-8k BENCH_MODEL=mistral-7b BENCH_MAX_LEN=8192 BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_QUANT=int8 BENCH_KV_QUANT=int8 BENCH_NEW_TOKENS=64 BENCH_PREFILL_DEPTH=8 BENCH_MEGA=8
run r5-mistral-8k-dense BENCH_MODEL=mistral-7b BENCH_MAX_LEN=8192 BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_QUANT=int8 BENCH_KV_QUANT=int8 BENCH_NEW_TOKENS=64 BENCH_PREFILL_DEPTH=8 BENCH_MEGA=8 GOFR_TPU_FLASH_DECODE=0
