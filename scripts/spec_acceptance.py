"""Speculative-decoding acceptance on LEARNED weights (VERDICT r3 #4).

Random-init greedy decode collapses into repetition loops that flatter
n-gram speculation; this script removes that caveat without network
access (zero-egress: no pretrained checkpoints) by TRAINING llama-tiny
on a real text corpus with the framework's own training step, then
serving the trained weights with speculation and measuring acceptance on
held-out prompts from the same distribution. It doubles as the
train→serve end-to-end proof: the params that come out of
``value_and_grad``+optax go straight into ``InferenceEngine(params=…)``.

Usage: [SPEC_STEPS=400] [SPEC_G=3] python scripts/spec_acceptance.py
Prints one JSON line:
  {"acceptance_tokens_per_step": …, "spec_tps": …, "plain_tps": …, …}

Acceptance reads the engine's own ``app_tpu_spec_tokens_per_step``
histogram (1.0 = no draft accepted per live step; G+1 = all accepted),
so the number reported is exactly what production metrics would show.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def corpus_text() -> str:
    """Real prose from the repo's own docs tree (stable, on-disk)."""
    import glob

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parts = []
    for path in sorted(glob.glob(os.path.join(root, "docs", "**", "*.md",),
                                 recursive=True)) + [
        os.path.join(root, "README.md"), os.path.join(root, "SURVEY.md")
    ]:
        try:
            with open(path, encoding="utf-8") as f:
                parts.append(f.read())
        except OSError:
            pass
    text = "\n\n".join(parts)
    assert len(text) > 50_000, f"corpus too small: {len(text)}"
    return text


def main() -> None:
    steps = int(os.environ.get("SPEC_STEPS", "400"))
    G = int(os.environ.get("SPEC_G", "3"))
    seq = 128
    batch = 16

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from gofr_tpu.models.registry import get_model
    from gofr_tpu.models.transformer import transformer_forward
    from gofr_tpu.parallel.sharding import cross_entropy_loss

    spec = get_model("llama-tiny")
    cfg = spec.config
    text = corpus_text()

    from gofr_tpu.serving.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    ids = np.frombuffer(text.encode("utf-8"), dtype=np.uint8).astype(np.int32)
    split = int(len(ids) * 0.9)
    train_ids, held = ids[:split], ids[split:]

    params = spec.init(jax.random.PRNGKey(0), cfg)
    optimizer = optax.adamw(3e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            return cross_entropy_loss(
                transformer_forward(p, tokens, cfg)[:, :-1], tokens[:, 1:]
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    rng = np.random.default_rng(0)
    t0 = time.time()
    loss = None
    for step in range(steps):
        starts = rng.integers(0, len(train_ids) - seq - 1, size=batch)
        tokens = jnp.asarray(
            np.stack([train_ids[s : s + seq] for s in starts])
        )
        loss, params, opt_state = train_step(params, opt_state, tokens)
        if step % 100 == 0 or step == steps - 1:
            print(
                f"train step {step}: loss {float(loss):.3f} "
                f"({time.time() - t0:.0f}s)",
                file=sys.stderr, flush=True,
            )
    final_loss = float(loss)

    # Serve the trained weights, speculation on vs off, same prompts.
    from gofr_tpu.metrics import new_metrics_manager
    from gofr_tpu.serving.engine import InferenceEngine

    prompts = []
    for i in range(8):
        s = int(rng.integers(0, len(held) - 96))
        prompts.append(
            bytes(held[s : s + 64].astype(np.uint8)).decode("utf-8", "replace")
        )

    def serve(spec_tokens: int):
        metrics = new_metrics_manager()
        metrics.new_histogram(
            "app_tpu_spec_tokens_per_step", "accepted+1 per live step"
        )
        eng = InferenceEngine(
            "llama-tiny", n_slots=8, max_len=256, window_k=8,
            tokenizer=tok, params=params, spec_tokens=spec_tokens,
            metrics=metrics,
        )
        eng.start_sync()
        t = time.time()
        reqs = [
            eng.submit_generate(
                p, max_new_tokens=64, temperature=0.0, stop_on_eos=False
            )
            for p in prompts
        ]
        results = [r.future.result(timeout=600) for r in reqs]
        wall = time.time() - t
        eng.stop_sync()
        total = sum(len(r.token_ids) for r in results)
        acc = None
        # Read the histogram through its public collect() shape.
        for inst in metrics._instruments.values():
            if inst.name == "app_tpu_spec_tokens_per_step":
                agg_sum = agg_n = 0.0
                for _, (_, (s_, n_)) in inst.collect().items():
                    agg_sum += s_
                    agg_n += n_
                if agg_n:
                    acc = agg_sum / agg_n
        return total / wall, acc

    spec_tps, acceptance = serve(G)
    plain_tps, _ = serve(0)

    out = {
        "metric": "spec_acceptance_tokens_per_step",
        "acceptance_tokens_per_step": round(acceptance, 3) if acceptance else None,
        "spec_g": G,
        "spec_tps": round(spec_tps, 1),
        "plain_tps": round(plain_tps, 1),
        "speedup": round(spec_tps / plain_tps, 3) if plain_tps else None,
        "train_steps": steps,
        "final_loss": round(final_loss, 3),
        "platform": jax.devices()[0].platform,
        "weights": "trained-on-docs-corpus (not random)",
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
