"""BASELINE configs 2-3 on the real chip: ResNet-50 classify and
BERT-base embed latency/throughput through the serving engines (the
CPU rows live in BASELINE.md; this fills the TPU column when the relay
is up). Prints one line per measurement.
"""

from __future__ import annotations

import statistics
import time

import numpy as np


def bench_engine(name, submit, n_serial=20, n_burst=32):
    # Warm (compile) then serial p50/p99 and a concurrent burst.
    submit().result(timeout=300)
    lat = []
    for _ in range(n_serial):
        t0 = time.perf_counter()
        submit().result(timeout=60)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    p50 = statistics.median(lat)
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    t0 = time.perf_counter()
    futs = [submit() for _ in range(n_burst)]
    for f in futs:
        f.result(timeout=120)
    wall = time.perf_counter() - t0
    print(
        f"{name}: serial p50={p50:.2f}ms p99={p99:.2f}ms; "
        f"{n_burst} concurrent in {wall * 1e3:.1f}ms "
        f"({n_burst / wall:.1f} req/s, dynamic batching)",
        flush=True,
    )


def main() -> None:
    import jax

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    print(f"platform={jax.devices()[0].platform}", flush=True)

    eng = InferenceEngine("resnet-50", max_batch=8, tokenizer=None)
    eng.start_sync()
    img = np.random.rand(224, 224, 3).astype(np.float32)
    bench_engine(
        "config2 resnet-50 classify",
        lambda: eng._batcher.submit(img),
    )
    eng.stop_sync()

    eng = InferenceEngine(
        "bert-base", max_batch=8, max_len=128, tokenizer=ByteTokenizer()
    )
    eng.start_sync()
    text = "the quick brown fox jumps over the lazy dog " * 2
    bench_engine(
        "config3 bert-base embed",
        lambda: eng._batcher.submit(text),
    )
    eng.stop_sync()


if __name__ == "__main__":
    main()
