"""Line coverage without pytest-cov: sys.monitoring (PEP 669) first-hit
LINE instrumentation over ``gofr_tpu/`` while the test suite runs.

The sandbox has no coverage/pytest-cov and installs are off-limits; CI
runs the real pytest-cov (``.github/workflows/test.yml`` unit-tests job)
— this script exists to measure a local number so the CI floor
(``--cov-fail-under``) can be set from data, and to spot-check coverage
rot between CI runs. First-hit callbacks return ``DISABLE`` so the
overhead after warmup is near zero; "possible" lines are enumerated from
compiled code objects (the same universe coverage.py uses for statement
coverage, minus arc analysis).

Usage: python scripts/coverage_lite.py [pytest args...]
Prints per-package and total percentages, one JSON line last.
"""

from __future__ import annotations

import dis
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # `python -m pytest` parity: repo root importable
PKG = os.path.join(REPO, "gofr_tpu")
OMIT = ("inference_pb2.py", "inference_pb2_grpc.py")

hit: set[tuple[str, int]] = set()
TOOL = sys.monitoring.COVERAGE_ID


def _on_line(code, line):
    f = code.co_filename
    if f.startswith(PKG) and not f.endswith(OMIT):
        hit.add((f, line))
    return sys.monitoring.DISABLE


def possible_lines(path: str) -> set[int]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        top = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [top]
    while stack:
        co = stack.pop()
        lines.update(
            ln for _, ln in dis.findlinestarts(co) if ln is not None
        )
        stack.extend(
            c for c in co.co_consts if isinstance(c, types.CodeType)
        )
    return lines


def main() -> int:
    sys.monitoring.use_tool_id(TOOL, "coverage-lite")
    sys.monitoring.register_callback(
        TOOL, sys.monitoring.events.LINE, _on_line
    )
    sys.monitoring.set_events(TOOL, sys.monitoring.events.LINE)

    import pytest

    args = sys.argv[1:] or ["tests/", "-x", "-q"]
    rc = pytest.main(args)

    sys.monitoring.set_events(TOOL, 0)
    per_file: dict[str, tuple[int, int]] = {}
    for root, _, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py") or name.endswith(OMIT):
                continue
            path = os.path.join(root, name)
            want = possible_lines(path)
            got = {ln for f, ln in hit if f == path} & want
            per_file[os.path.relpath(path, REPO)] = (len(got), len(want))

    by_pkg: dict[str, list[int]] = {}
    for path, (g, w) in sorted(per_file.items()):
        pkg = "/".join(path.split("/")[:2])
        by_pkg.setdefault(pkg, [0, 0])
        by_pkg[pkg][0] += g
        by_pkg[pkg][1] += w
    for pkg, (g, w) in sorted(by_pkg.items()):
        print(f"{pkg:42s} {g:5d}/{w:5d}  {100 * g / max(w, 1):5.1f}%",
              file=sys.stderr)
    dump = os.environ.get("COVERAGE_LITE_DUMP", "")
    if dump:
        missing = {}
        for root, _, files in os.walk(PKG):
            if "__pycache__" in root:
                continue
            for name in files:
                if not name.endswith(".py") or name.endswith(OMIT):
                    continue
                path = os.path.join(root, name)
                want = possible_lines(path)
                got = {ln for f, ln in hit if f == path}
                rel = os.path.relpath(path, REPO)
                missing[rel] = sorted(want - got)
        with open(dump, "w", encoding="utf-8") as f:
            json.dump(missing, f)
    total_g = sum(g for g, _ in per_file.values())
    total_w = sum(w for _, w in per_file.values())
    print(json.dumps({
        "coverage_lines_pct": round(100 * total_g / max(total_w, 1), 2),
        "lines_hit": total_g,
        "lines_total": total_w,
        "pytest_rc": int(rc),
    }))
    return int(rc)


if __name__ == "__main__":
    sys.exit(main())
