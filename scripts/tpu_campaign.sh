#!/bin/bash
# TPU measurement campaign for round 3 (run when the axon relay is up).
# Each run's stderr (profile lines, TTFT, A/B) + JSON goes to campaign/.
# Order: most valuable first, in case the relay window is short.
set -u
cd "$(dirname "$0")/.."
mkdir -p campaign
run() {
  name=$1; shift
  echo "=== $name: $* ==="
  env "$@" BENCH_ATTEMPTS=1 BENCH_TIMEOUT=900 BENCH_TOTAL_BUDGET=900 \
    timeout 1000 python bench.py >"campaign/$name.json" 2>"campaign/$name.log"
  echo "--- rc=$? json:"; cat "campaign/$name.json"
  tail -5 "campaign/$name.log"
}
# 1. Headline: llama-1b int8 32-slot (round-1 comparable).
run r3-1b-int8 BENCH_MODEL=llama-1b
# 2. + int8 KV cache (new lever).
run r3-1b-int8-kv8 BENCH_MODEL=llama-1b BENCH_KV_QUANT=int8
# 3. Flagship: llama-3-8b int8 (first ever 8B run).
run r3-8b-int8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=16 BENCH_REQUESTS=32
# 4. 8B + int8 KV (cache halved → more slots viable).
run r3-8b-int8-kv8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=32 BENCH_KV_QUANT=int8
# 5. Decode-path A/B forced dense (compare with default kernel runs above).
run r3-1b-dense-decode BENCH_MODEL=llama-1b GOFR_TPU_FLASH_DECODE=0
# 6. Window/depth sweep around the default.
run r3-1b-w16d3 BENCH_MODEL=llama-1b BENCH_WINDOW=16 BENCH_DEPTH=3
# 7. int4 weights (group-wise W4A16): weight stream quartered.
run r3-1b-int4 BENCH_MODEL=llama-1b BENCH_QUANT=int4
run r3-8b-int4-kv8 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=32 BENCH_QUANT=int4 BENCH_KV_QUANT=int8
