#!/bin/bash
# Campaign 3: dense-decode sweep. Campaign 2 found GOFR_TPU_FLASH_DECODE=0
# (one fused XLA op) beats the grid kernel at serving shapes: step
# 6.44 -> 4.08 ms, 1931 -> 2421 tok/s. Remaining gap is the ~70 ms
# dispatch cost per window cycle; sweep window/depth/slots to amortize it.
set -u
cd "$(dirname "$0")/.."
mkdir -p campaign
run() {
  name=$1; shift
  echo "=== $name: $* ==="
  env "$@" GOFR_TPU_FLASH_DECODE=0 BENCH_ATTEMPTS=1 BENCH_TIMEOUT=900 \
    BENCH_TOTAL_BUDGET=900 \
    timeout 1000 python bench.py >"campaign/$name.json" 2>"campaign/$name.log"
  echo "--- rc=$? json:"; cat "campaign/$name.json"
  tail -n 3 "campaign/$name.log"
}
run r3c-1b-kv8 BENCH_MODEL=llama-1b BENCH_KV_QUANT=int8
run r3c-1b-w16 BENCH_MODEL=llama-1b BENCH_WINDOW=16
run r3c-1b-w16-kv8 BENCH_MODEL=llama-1b BENCH_WINDOW=16 BENCH_KV_QUANT=int8
run r3c-1b-w24d3-kv8 BENCH_MODEL=llama-1b BENCH_WINDOW=24 BENCH_DEPTH=3 BENCH_KV_QUANT=int8
run r3c-1b-s64-kv8-w16 BENCH_MODEL=llama-1b BENCH_SLOTS=64 BENCH_REQUESTS=128 BENCH_KV_QUANT=int8 BENCH_WINDOW=16
run r3c-8b-kv8-s32 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8
run r3c-8b-kv8-s32-w16 BENCH_MODEL=llama-3-8b BENCH_SLOTS=32 BENCH_REQUESTS=64 BENCH_KV_QUANT=int8 BENCH_WINDOW=16
