# Serving image (deployment-artifact parity with the reference's
# /root/reference/Dockerfile:1, which ships a single static server binary).
# This image serves a model over HTTP :8000 / gRPC :9000 / metrics :2121.
#
# Build:  docker build -t gofr-tpu .
# Run  :  docker run -p 8000:8000 -p 9000:9000 -p 2121:2121 \
#             -e TPU_MODEL=llama-1b -e TPU_QUANT=int8 gofr-tpu
#
# On a TPU VM, base this on a libtpu-enabled image instead and install
# jax[tpu]; the framework auto-detects the backend via PJRT.

FROM python:3.12-slim

WORKDIR /app

RUN pip install --no-cache-dir \
    jax flax optax orbax-checkpoint chex einops numpy grpcio safetensors

COPY gofr_tpu/ gofr_tpu/
COPY examples/tpu-http/ examples/tpu-http/

ENV PYTHONPATH=/app \
    JAX_PLATFORMS=cpu \
    TPU_ENABLED=1 \
    TPU_MODEL=llama-tiny

EXPOSE 8000 9000 2121

# The tpu-http example is the canonical serving app: App + container TPU
# member + /generate route + health/metrics endpoints.
CMD ["python", "examples/tpu-http/main.py"]
