"""Example apps as integration tests (SURVEY §4: the reference boots each
example's real server in-process and asserts over localhost HTTP)."""

from __future__ import annotations

import asyncio
import http.client
import importlib.util
import json
import os
import threading
import uuid

import pytest

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def load_example(name: str):
    path = os.path.join(EXAMPLES, name, "main.py")
    spec = importlib.util.spec_from_file_location(
        f"example_{name.replace('-', '_')}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def clean_env(free_port):
    """Examples load their configs/.env into os.environ — isolate each test
    and pin ephemeral ports."""
    snapshot = dict(os.environ)
    os.environ["HTTP_PORT"] = str(free_port())
    os.environ["METRICS_PORT"] = str(free_port())
    yield
    os.environ.clear()
    os.environ.update(snapshot)


class Harness:
    """Runs an App's asyncio lifecycle on a background thread."""

    def __init__(self, app) -> None:
        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)

    def __enter__(self):
        self._thread.start()
        asyncio.run_coroutine_threadsafe(self.app.start(), self._loop).result(10)
        return self

    def __exit__(self, *exc):
        asyncio.run_coroutine_threadsafe(self.app.stop(), self._loop).result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    def request(self, method, path, body=None, headers=None, port=None):
        conn = http.client.HTTPConnection(
            "127.0.0.1", port or self.app.http_port, timeout=5
        )
        try:
            payload = body
            if body is not None and not isinstance(body, bytes):
                payload = json.dumps(body).encode()
            conn.request(method, path, body=payload, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()


def test_http_server_example():
    app = load_example("http-server").main()
    with Harness(app) as h:
        status, body = h.request("GET", "/hello?name=TPU")
        assert status == 200 and json.loads(body)["data"] == "Hello TPU!"
        status, _ = h.request("GET", "/error")
        assert status == 404


def test_http_server_using_redis_example():
    from gofr_tpu.datasource.redis.miniredis import MiniRedis

    server = MiniRedis()
    server.start()
    os.environ["REDIS_HOST"] = "127.0.0.1"
    os.environ["REDIS_PORT"] = str(server.port)
    try:
        app = load_example("http-server-using-redis").main()
        with Harness(app) as h:
            status, _ = h.request(
                "POST", "/redis", body={"key": "greeting", "value": "hi"}
            )
            assert status == 201
            status, body = h.request("GET", "/redis/greeting")
            assert status == 200
            assert json.loads(body)["data"]["value"] == "hi"
            status, _ = h.request("GET", "/redis/missing")
            assert status == 404
    finally:
        server.stop()


def test_using_custom_metrics_example():
    app = load_example("using-custom-metrics").main()
    with Harness(app) as h:
        for value in (3, 42):
            status, _ = h.request(
                "POST", "/order", body={"product": "tpu", "value": value}
            )
            assert status == 201
        h.request("DELETE", "/order/1")
        status, body = h.request(
            "GET", "/metrics", port=app.metrics_port
        )
        text = body.decode()
        assert status == 200
        assert 'orders_created{product="tpu"} 2.0' in text
        assert "orders_open 1.0" in text
        assert "order_value_dollars_bucket" in text


def test_using_file_bind_example():
    app = load_example("using-file-bind").main()
    boundary = uuid.uuid4().hex
    payload = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="name"\r\n\r\n'
        "report\r\n"
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="file"; filename="a.txt"\r\n'
        "Content-Type: text/plain\r\n\r\n"
        "hello world\r\n"
        f"--{boundary}--\r\n"
    ).encode()
    with Harness(app) as h:
        status, body = h.request(
            "POST", "/upload", body=payload,
            headers={"Content-Type": f"multipart/form-data; boundary={boundary}"},
        )
        assert status == 201
        data = json.loads(body)["data"]
        assert data == {"name": "report", "filename": "a.txt", "size": 11}


def test_using_http_service_example():
    app = load_example("using-http-service").main()
    with Harness(app) as h:
        status, body = h.request("GET", "/item")
        assert status == 200
        data = json.loads(body)["data"]
        assert data["downstream_status"] == 200
        assert data["body"]["data"]["sku"] == "tpu-pod"
        # Dependency shows up in aggregate health.
        status, body = h.request("GET", "/.well-known/health")
        assert "service:catalog" in json.loads(body)["data"]["details"]


def test_using_migrations_example():
    mod = load_example("using-migrations")
    app = mod.main()
    with Harness(app) as h:
        status, body = h.request("GET", "/employees")
        assert status == 200
        rows = json.loads(body)["data"]
        assert [r["name"] for r in rows] == ["ada", "bo"]
        # Re-running migrations is a no-op (versions in gofr_migrations).
        app.container.sql.exec("DELETE FROM employee WHERE name = ?", "bo")
        from gofr_tpu.migration import run

        run(mod.ALL, app.container)
        rows = app.container.sql.query("SELECT name FROM employee")
        assert [r["name"] for r in rows] == ["ada"]


def test_using_publisher_example():
    app = load_example("using-publisher").main()
    with Harness(app) as h:
        status, _ = h.request("POST", "/publish-order", body={"id": 7})
        assert status == 201
        status, body = h.request("GET", "/peek")
        assert json.loads(body)["data"]["message"] == {"id": 7}
        status, body = h.request("GET", "/peek")
        assert json.loads(body)["data"] == {"empty": True}


def test_using_cmd_example(capsys):
    mod = load_example("using-cmd")
    app = mod.main()
    rc = app.run(["hello", "-name=TPU"])
    assert rc == 0
    assert "Hello TPU!" in capsys.readouterr().out


def test_openai_server_example():
    mod = load_example("openai-server")
    with Harness(mod.main()) as h:
        status, body = h.request("GET", "/v1/models")
        assert status == 200
        assert json.loads(body)["object"] == "list"
        # First completion pays jit compile — needs more than the
        # harness's 5s default under full-suite CPU load.
        conn = http.client.HTTPConnection(
            "127.0.0.1", h.app.http_port, timeout=120
        )
        try:
            conn.request("POST", "/v1/completions", body=json.dumps({
                "prompt": "hi", "max_tokens": 4, "temperature": 0,
            }).encode())
            resp = conn.getresponse()
            assert resp.status == 200
            out = json.loads(resp.read())
        finally:
            conn.close()
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] >= 1


def test_using_train_example(capsys, tmp_path):
    """Train → orbax checkpoint → serve: the full TPU-native loop through
    the same CLI + HTTP app surfaces every other example uses."""
    mod = load_example("using-train")
    mod.CKPT = str(tmp_path / "ckpt")
    rc = mod.build_cmd().run(["train", "-steps=2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "final_loss" in out

    os.environ["TPU_CHECKPOINT"] = mod.CKPT
    with Harness(mod.build_app()) as h:
        conn = http.client.HTTPConnection(
            "127.0.0.1", h.app.http_port, timeout=180
        )
        try:
            conn.request("POST", "/generate", body=json.dumps({
                "prompt": "hi", "max_new_tokens": 4, "temperature": 0,
            }), headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 201, body  # POST default: Created
            data = json.loads(body)["data"]
            assert data["tokens"] == 4
        finally:
            conn.close()


def test_using_lora_example(capsys, tmp_path):
    """Train a LoRA adapter → HF-PEFT export → serve it as an OpenAI
    model id next to the base, one engine."""
    mod = load_example("using-lora")
    mod.ADAPTER = str(tmp_path / "adapter")
    rc = mod.build_cmd().run(["train", "-steps=30"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "final_loss" in out
    assert os.path.exists(
        os.path.join(mod.ADAPTER, "adapter_model.safetensors")
    )

    os.environ["TPU_LORA_ADAPTERS"] = f"tuned={mod.ADAPTER}"
    try:
        with Harness(mod.build_app()) as h:
            conn = http.client.HTTPConnection(
                "127.0.0.1", h.app.http_port, timeout=180
            )
            try:
                conn.request("GET", "/v1/models")
                models = json.loads(conn.getresponse().read())
                ids = {m["id"] for m in models["data"]}
                assert "tuned" in ids
                body = {
                    "model": "tuned", "prompt": "gofr serves tp",
                    "max_tokens": 8, "temperature": 0,
                }
                conn.request(
                    "POST", "/v1/completions", body=json.dumps(body),
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                tuned = json.loads(resp.read())
                assert resp.status == 200
                conn.request(
                    "POST", "/v1/completions",
                    body=json.dumps({**body, "model": "llama-tiny"}),
                    headers={"Content-Type": "application/json"},
                )
                base = json.loads(conn.getresponse().read())
                assert (
                    tuned["choices"][0]["text"] != base["choices"][0]["text"]
                )
            finally:
                conn.close()
    finally:
        os.environ.pop("TPU_LORA_ADAPTERS", None)
