"""Network-chaos suite for the multi-host replica data plane
(ISSUE 7 acceptance gate).

Everything network-shaped is driven deterministically through the
``gofr_tpu/faults`` HTTP transport points (``http.request``,
``http.stream.open``, ``http.stream.event``) — no real sockets except
where the test IS about socket behavior (the slow-loris stall and the
real-upstream integration test, both bounded by sub-second read
timeouts).

Covered:

* transport fault points: canned 5xx bursts and connect-refused on the
  unary path, fault-served SSE streams, truncation, mid-body reset;
* connect-vs-read budget separation (satellite: a loaded-but-alive
  remote is classified BUSY by the probe, never demoted; a dead one
  fails fast at the handshake);
* streaming through ``HTTPReplica``: SSE consumption with the
  ``include_tokens`` wire, upstream error events propagating
  un-rerouted, caller cancellation ending consumption without failover;
* THE acceptance paths: a remote replica killed mid-SSE (truncated
  stream), resetting mid-body, or stalling past the idle timeout
  (slow-loris, real socket) hands its live request to an in-proc
  sibling — the client stream is byte-identical to a fault-free run,
  zero 5xx, ONE trace id spans both replicas, and the pool's flight
  view shows the failover annotation; a LoRA-adapter request passes the
  same check with the adapter lazily reconciled onto the sibling;
* connect-reset during a hedged unary retry: the sibling answers, the
  client never sees the loss;
* streaming through a REAL remote gofr_tpu app (full OpenAI SSE +
  ``stream_options.include_tokens`` over a live socket) matches the
  remote engine's own generation;
* ``PoolScaler``: sustained pressure spawns through the injectable
  factory, idle drains retire with zero dropped in-flight requests,
  bounds ``TPU_POOL_{MIN,MAX}_REPLICAS`` are never violated, and a
  drain that cannot empty its replica aborts and re-admits it.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.config import MockConfig
from gofr_tpu.container import Container
from gofr_tpu.errors import ErrorServiceUnavailable
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.client import HTTPService, classify_transport_error
from gofr_tpu.service.pool_scaler import PoolScaler
from gofr_tpu.service.replica_pool import (
    EngineReplica,
    HTTPReplica,
    Replica,
    ReplicaPool,
)
from gofr_tpu.tracing import Tracer, get_tracer, set_tracer

TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


# ----------------------------------------------------------------------
# shared fixtures / helpers
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def metrics():
    # Container registration is the real instrument set — including the
    # pool gauges and scale/remote-failover counters this PR adds.
    return Container.create(MockConfig({"APP_NAME": "chaos-test"})).metrics


@pytest.fixture(scope="module")
def sibling(metrics):
    """The in-proc sibling every remote fails over TO. LoRA slots armed
    for the adapter-reconciliation acceptance test."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        metrics=metrics, lora_slots=2, lora_rank=4,
    )
    eng.start_sync()
    yield eng
    eng.stop_sync()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


class _CaptureExporter:
    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()

    def export(self, span, service_name):
        with self._lock:
            self.spans.append(span)

    def by_name(self, name):
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def clear(self):
        with self._lock:
            self.spans.clear()


@pytest.fixture()
def capture():
    old = get_tracer()
    cap = _CaptureExporter()
    set_tracer(Tracer(service_name="chaos-test", exporter=cap))
    yield cap
    set_tracer(old)


def counter_total(metrics, name: str) -> float:
    inst = {i.name: i for i in metrics.instruments()}[name]
    return sum(inst.collect().values())


def _drain(req, timeout=180.0) -> list[int]:
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _tagged(kind: str, msg: str = "injected transport loss") -> Exception:
    """The typed 503 the transport layer raises, pre-classified — what
    ``client._unavailable`` would build from the matching httpx error."""
    exc = ErrorServiceUnavailable(msg)
    exc.kind = kind
    return exc


def _sse(tokens, text="", finish=None, prompt_tokens=None) -> str:
    choice = {"index": 0, "token_ids": list(tokens), "text": text}
    if finish is not None:
        choice["finish_reason"] = finish
    if prompt_tokens is not None:
        choice["prompt_tokens"] = prompt_tokens
    return "data: " + json.dumps({"choices": [choice]})


def _sse_lines(token_ids, *, chunk=3, finish="stop", done=True,
               prompt_tokens=0) -> list[str]:
    """A well-formed (or deliberately truncated: ``finish=None`` /
    ``done=False``) SSE stream carrying the given token ids."""
    lines = []
    for i in range(0, len(token_ids), chunk):
        lines.append(_sse(token_ids[i:i + chunk]))
    if finish is not None:
        lines.append(_sse([], finish=finish, prompt_tokens=prompt_tokens))
    if done:
        lines.append("data: [DONE]")
    return lines


def _pool(replicas, metrics=None, **kw):
    kw.setdefault("probe_interval_s", 0)
    kw.setdefault("probe_timeout_s", 60.0)
    kw.setdefault("rng", random.Random(7))
    # Raw least-loaded routing: deterministic first pick (list order on
    # ties) regardless of what throughput the shared engine measured in
    # earlier tests.
    kw.setdefault("weighted", False)
    return ReplicaPool(replicas, metrics=metrics, **kw)


def _release(pool):
    pool.stop_prober()
    for replica in pool.replicas:
        replica.set_handoff(None)


# ----------------------------------------------------------------------
# transport fault points (no engine, no socket)
# ----------------------------------------------------------------------


def test_http_request_fault_point_cans_5xx_and_raises_transport_loss():
    from gofr_tpu.service.client import Response

    svc = HTTPService("http://127.0.0.1:9")  # never dialed: fault serves
    faults.arm(
        "http.request",
        action=lambda **ctx: Response(b'{"err":"burst"}', 503, {}),
    )
    resp = svc.post("v1/completions", json={"prompt": "x"})
    assert resp.status_code == 503  # canned 5xx, no socket involved
    faults.arm("http.request", raises=_tagged("connect", "refused"))
    with pytest.raises(ErrorServiceUnavailable) as exc_info:
        svc.get("v1/models")
    assert exc_info.value.kind == "connect"


def test_stream_fault_points_serve_truncate_and_reset():
    svc = HTTPService("http://127.0.0.1:9")
    lines = _sse_lines([1, 2, 3, 4], chunk=2)
    faults.arm("http.stream.open", action=lambda **ctx: list(lines))
    with svc.stream_lines("POST", "v1/completions", json={}) as got:
        assert list(got) == lines
    # Per-event verdict "truncate" = upstream vanished without EOF
    # framing: the stream ends early, no error at the transport level
    # (the CONSUMER detects the missing terminal framing).
    faults.arm("http.stream.open", action=lambda **ctx: list(lines))
    faults.arm("http.stream.event", action=lambda **ctx: "truncate", after=1)
    with svc.stream_lines("POST", "v1/completions", json={}) as got:
        assert list(got) == lines[:1]
    # Per-event raise = mid-body connection reset.
    faults.arm("http.stream.open", action=lambda **ctx: list(lines))
    faults.arm(
        "http.stream.event", raises=_tagged("read", "reset mid-body"),
        after=2,
    )
    with svc.stream_lines("POST", "v1/completions", json={}) as got:
        received = []
        with pytest.raises(ErrorServiceUnavailable):
            for line in got:
                received.append(line)
        assert received == lines[:2]


def test_classify_transport_error_separates_connect_from_read():
    import httpx

    assert classify_transport_error(httpx.ConnectError("refused")) == "connect"
    assert classify_transport_error(httpx.ConnectTimeout("syn")) == "connect"
    assert classify_transport_error(httpx.ReadTimeout("stall")) == "read"
    assert classify_transport_error(httpx.ReadError("reset")) == "read"
    assert classify_transport_error(RuntimeError("other")) == "transport"


def test_connect_budget_is_separate_from_and_shorter_than_read_budget():
    svc = HTTPService("http://127.0.0.1:9", timeout=30.0)
    # Default: the handshake budget never inherits a long read budget —
    # a dead upstream must fail in ~RTT time, not after 30s.
    assert svc.connect_timeout_s == 5.0
    assert svc.timeout == 30.0
    svc2 = HTTPService("http://127.0.0.1:9", timeout=2.0)
    assert svc2.connect_timeout_s == 2.0  # never above the total budget
    svc3 = HTTPService(
        "http://127.0.0.1:9", timeout=30.0, connect_timeout_s=1.5
    )
    assert svc3.connect_timeout_s == 1.5
    for s in (svc, svc2, svc3):
        s.close()


# ----------------------------------------------------------------------
# dead-vs-busy probe classification (satellite 1)
# ----------------------------------------------------------------------


class _ErrService:
    """Health endpoint that raises a pre-classified transport error."""

    def __init__(self, exc):
        self.exc = exc

    def get(self, path, **kw):
        raise self.exc

    def health_check(self):
        raise self.exc


def test_probe_classifies_read_timeout_behind_load_as_busy():
    replica = HTTPReplica(
        "loaded", _ErrService(_tagged("read", "slow behind queue")),
    )
    with replica._lock:
        replica._inflight = 3  # live upstream, busy serving queued work
    verdict, detail = replica.probe(timeout_s=5.0)
    assert verdict == "busy"
    assert "3 in-flight" in detail
    # Busy is never a demotion: the replica keeps routing (restarting a
    # merely-loaded replica would cascade its queue onto the siblings).
    assert replica.state() == "SERVING"


def test_probe_classifies_connect_failure_as_dead_even_under_load():
    replica = HTTPReplica(
        "dead", _ErrService(_tagged("connect", "nothing listening")),
    )
    with replica._lock:
        replica._inflight = 3
    verdict, _ = replica.probe(timeout_s=5.0)
    assert verdict == "fail"  # the HANDSHAKE failed: nobody is home
    assert replica.state() == "DOWN"


def test_probe_classifies_idle_read_timeout_as_dead():
    replica = HTTPReplica(
        "quiet", _ErrService(_tagged("read", "no answer")),
    )
    verdict, _ = replica.probe(timeout_s=5.0)  # zero in-flight: not busy
    assert verdict == "fail"
    assert replica.state() == "DOWN"


def test_probe_refreshes_advertised_adapter_set_from_health_payload():
    class _HealthService:
        def get(self, path, **kw):
            class _Resp:
                status_code = 200

                @staticmethod
                def json():
                    return {
                        "data": {
                            "status": "UP",
                            "details": {
                                "tpu": {
                                    "status": "UP",
                                    "details": {
                                        "lora_adapters": ["tuned", "fr"],
                                    },
                                },
                            },
                        },
                    }

            return _Resp()

    replica = HTTPReplica("remote", _HealthService())
    assert replica.adapters() == frozenset()
    verdict, _ = replica.probe(timeout_s=5.0)
    assert verdict == "pass"
    assert replica.adapters() == frozenset({"tuned", "fr"})


# ----------------------------------------------------------------------
# streaming HTTPReplica (fault-served SSE, no engine)
# ----------------------------------------------------------------------


def _stream_replica(name="remote", **kw):
    kw.setdefault("tokenizer", ByteTokenizer())
    return HTTPReplica(name, HTTPService("http://127.0.0.1:9"), **kw)


def test_http_replica_consumes_sse_stream_into_local_handle():
    ids = [72, 105, 33, 10, 65]
    faults.arm(
        "http.stream.open",
        action=lambda **ctx: _sse_lines(ids, prompt_tokens=4),
    )
    replica = _stream_replica()
    assert replica.supports_stream
    req = replica.submit("Hi!", max_new_tokens=8, temperature=0.0)
    toks = _drain(req)
    result = req.future.result(timeout=30)
    assert toks == ids
    assert result.token_ids == ids
    assert result.finish_reason == "stop"
    assert result.prompt_tokens == 4  # carried on the finish chunk
    assert result.text == ByteTokenizer().decode(ids)
    assert replica.load() == 0  # in-flight accounting drained


def test_truncated_stream_without_handoff_fails_with_tagged_503():
    ids = [1, 2, 3, 4, 5, 6]
    faults.arm(
        "http.stream.open",
        action=lambda **ctx: _sse_lines(ids, finish=None, done=False)[:1],
    )
    replica = _stream_replica()
    req = replica.submit("x", max_new_tokens=8, temperature=0.0)
    with pytest.raises(ErrorServiceUnavailable) as exc_info:
        req.future.result(timeout=30)
    assert exc_info.value.kind == "read"
    assert "truncated" in str(exc_info.value)
    assert _drain(req) == ids[:3]  # delivered prefix, then the sentinel


def test_upstream_4xx_error_event_propagates_without_failover():
    offered = []
    faults.arm(
        "http.stream.open",
        action=lambda **ctx: [
            "data: " + json.dumps({
                "error": {"message": "prompt too long", "code": 413},
            }),
        ],
    )
    replica = _stream_replica()
    replica.set_handoff(lambda req: offered.append(req) or True)
    req = replica.submit("x" * 64, max_new_tokens=8, temperature=0.0)
    with pytest.raises(Exception) as exc_info:
        req.future.result(timeout=30)
    assert getattr(exc_info.value, "status_code", 0) == 413
    # Request-shaped errors fail identically on every replica: a
    # failover would just re-fail elsewhere (and double-bill the work).
    assert offered == []


def test_cancelled_caller_stops_stream_consumption_without_failover():
    from gofr_tpu.errors import ErrorRequestCancelled

    replica = _stream_replica()
    offered = []
    replica.set_handoff(lambda req: offered.append(req) or True)
    holder = {}

    def lines(**ctx):
        # Trip the CANCEL TOKEN (not the future) mid-delivery — the
        # transport-agnostic cancellation path: the consumer must
        # notice at the next event, walk away quietly, and resolve the
        # future with the same typed error the in-proc reap uses.
        yield _sse([9, 8])
        holder["req"].cancel.cancel()
        yield _sse([7, 6])
        yield from _sse_lines([5], done=True)

    faults.arm("http.stream.open", action=lines)
    req = replica.submit("x", max_new_tokens=8, temperature=0.0)
    holder["req"] = req
    assert _drain(req) == [9, 8]
    with pytest.raises(ErrorRequestCancelled):
        req.future.result(timeout=10)
    assert offered == []  # nobody wants this stream: no failover
    assert replica.load() == 0


def test_sampling_body_forwards_explicit_seed_zero():
    # seed=0 is a valid explicit seed; dropping it from the wire while
    # remote_seeded marks the request resumable would let a sibling
    # re-walk a sampled prefix on a different sample path.
    body = HTTPReplica._sampling_body(
        "p", {"seed": 0, "temperature": 0.8}, stream=True
    )
    assert body["seed"] == 0
    assert "seed" not in HTTPReplica._sampling_body("p", {}, stream=True)


# ----------------------------------------------------------------------
# acceptance: remote dies mid-SSE → in-proc sibling, byte-identical,
# one trace
# ----------------------------------------------------------------------

PARAMS = dict(max_new_tokens=24, temperature=0.0, stop_on_eos=False)


def _flight_entries_with_failover(pool, trace_id):
    return [
        e
        for snap in pool.flight_records()["replicas"].values()
        for e in snap.get("records", []) + snap.get("pinned", [])
        if e["trace_id"] == trace_id
        and any(a["name"] == "tpu.failover" for a in e["annotations"])
    ]


def test_remote_truncated_sse_fails_over_byte_identical_one_trace(
    capture, metrics, sibling
):
    """THE acceptance path: a remote replica killed mid-SSE (truncated
    stream, no terminal framing) hands its live request to the in-proc
    sibling, which resumes from the delivered-token prefix — the client
    stream is byte-identical to a fault-free run, zero 5xx, one trace
    id spans both replicas, and /debug/flight shows the failover."""
    prompt = "multi-host failover stream"
    ref = sibling.generate_sync(prompt, **PARAMS)
    capture.clear()
    # The remote delivers the first 8 tokens of the (shared-weights)
    # greedy path, then vanishes without [DONE].
    faults.arm(
        "http.stream.open",
        action=lambda **ctx: _sse_lines(
            ref.token_ids[:8], chunk=3, finish=None, done=False
        ),
    )
    remote = _stream_replica("remote-a")
    pool = _pool([remote, EngineReplica("b", sibling)], metrics=metrics)
    before = counter_total(metrics, "app_tpu_remote_stream_failovers_total")
    try:
        req = pool.submit_generate(prompt, traceparent=TRACEPARENT, **PARAMS)
        toks = _drain(req)
        result = req.future.result(timeout=180)  # zero 5xx: resolves ok
        assert faults.fired("http.stream.open") == 1  # remote served first
        assert toks == ref.token_ids
        assert result.token_ids == ref.token_ids
        assert result.finish_reason == ref.finish_reason
        after = counter_total(
            metrics, "app_tpu_remote_stream_failovers_total"
        )
        assert after == before + 1

        # ONE trace: the timeline minted on the adopting replica joined
        # the caller's traceparent, so every span — including the
        # failover annotation — shares the request's trace id.
        root = capture.by_name("tpu.request")[0]
        assert root.trace_id == "ab" * 16
        span_names = {s.name for s in capture.spans}
        assert "tpu.failover" in span_names
        assert all(
            s.trace_id == root.trace_id
            for s in capture.spans if s.name.startswith("tpu.")
        )
        failover_span = capture.by_name("tpu.failover")[0]
        assert failover_span.attributes["source"] == "remote-a"
        assert failover_span.attributes["target"] == "b"

        # /debug/flight: the SAME timeline once, in the adopting
        # replica's recorder, with the failover annotation and the
        # replica-descriptor detail this PR adds.
        entries = _flight_entries_with_failover(pool, root.trace_id)
        assert len(entries) == 1
        assert entries[0]["outcome"] == "ok"
        flights = pool.flight_records()["replicas"]
        assert flights["remote-a"]["remote"] is True
        assert flights["remote-a"]["state"] == "SERVING"
        assert "adapters" in flights["b"]
    finally:
        faults.reset()
        _release(pool)


def test_remote_mid_body_reset_fails_over_byte_identical(metrics, sibling):
    """Same acceptance contract, different wound: the connection resets
    MID-BODY (tagged read loss between SSE events) instead of ending
    quietly."""
    prompt = "reset mid body"
    ref = sibling.generate_sync(prompt, **PARAMS)
    faults.arm(
        "http.stream.open",
        action=lambda **ctx: _sse_lines(ref.token_ids[:9], chunk=3),
    )
    # Three events (9 tokens) delivered, then the wire dies.
    faults.arm(
        "http.stream.event", raises=_tagged("read", "connection reset"),
        after=3,
    )
    remote = _stream_replica("remote-a")
    pool = _pool([remote, EngineReplica("b", sibling)], metrics=metrics)
    try:
        req = pool.submit_generate(prompt, **PARAMS)
        toks = _drain(req)
        result = req.future.result(timeout=180)
        assert toks == ref.token_ids
        assert result.token_ids == ref.token_ids
    finally:
        faults.reset()
        _release(pool)


class _StallServer(threading.Thread):
    """A real socket that answers one streaming request with valid SSE
    headers + the given events, then holds the connection open without
    ever sending another byte — the slow-loris upstream."""

    def __init__(self, payload: bytes):
        super().__init__(daemon=True)
        self.payload = payload
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(1)
        self.port = self._sock.getsockname()[1]
        self._conns = []

    def run(self):
        try:
            conn, _ = self._sock.accept()
        except OSError:
            return
        self._conns.append(conn)
        try:
            conn.recv(65536)  # the POST; no need to parse it
            conn.sendall(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/event-stream\r\n"
                b"Connection: close\r\n\r\n" + self.payload
            )
        except OSError:
            pass
        # ... and then silence: never more bytes, never EOF.

    def close(self):
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


def test_remote_slow_loris_stall_fails_over_past_idle_timeout(
    metrics, sibling
):
    """A remote that keeps the connection open but stops sending bytes
    (slow-loris) trips the per-read idle budget — classified as a read
    stall, the live request resumes on the sibling byte-identically."""
    prompt = "slow loris stall"
    ref = sibling.generate_sync(prompt, **PARAMS)
    payload = "".join(
        line + "\n" for line in _sse_lines(
            ref.token_ids[:4], chunk=2, finish=None, done=False
        )
    ).encode()
    server = _StallServer(payload)
    server.start()
    svc = HTTPService(f"http://127.0.0.1:{server.port}", timeout=10.0)
    remote = HTTPReplica(
        "stalled", svc, tokenizer=ByteTokenizer(), idle_timeout_s=0.3,
    )
    pool = _pool([remote, EngineReplica("b", sibling)], metrics=metrics)
    try:
        req = pool.submit_generate(prompt, **PARAMS)
        toks = _drain(req)
        result = req.future.result(timeout=180)
        assert toks == ref.token_ids
        assert result.token_ids == ref.token_ids
    finally:
        _release(pool)
        server.close()
        svc.close()


def test_lora_request_fails_over_with_lazy_adapter_reconciliation(
    metrics, sibling
):
    """Acceptance: a LoRA-adapter request has the same failover rights
    as a base-model one. The remote advertised (and was serving) the
    adapter; at failover NO routable sibling has it loaded, so the pool
    lazily reconciles — loading the registered source onto the sibling
    — and the stream completes byte-identically under the adapter's
    weights."""
    import jax

    from gofr_tpu.models.transformer import lora_dims

    rank, cfg = 4, sibling.cfg
    key = jax.random.PRNGKey(23)
    leaves = {}
    for target in ("wq", "wk", "wv", "wo"):
        d_in, d_out = lora_dims(cfg, target)
        key, k1, k2 = jax.random.split(key, 3)
        leaves[target] = (
            0.5 * jax.random.normal(k1, (cfg.n_layers, d_in, rank)),
            0.5 * jax.random.normal(k2, (cfg.n_layers, rank, d_out)),
        )
    prompt = "adapter failover"
    params = dict(PARAMS, adapter="tuned")
    # The oracle: generate WITH the adapter, then unload it — the
    # reconciliation below must reproduce this exactly from the
    # registered source.
    sibling.load_lora("tuned", leaves)
    try:
        ref = sibling.generate_sync(prompt, **params)
        base = sibling.generate_sync(prompt, **PARAMS)
        assert ref.token_ids != base.token_ids  # the adapter matters
    finally:
        sibling.unload_lora("tuned")

    faults.arm(
        "http.stream.open",
        action=lambda **ctx: _sse_lines(
            ref.token_ids[:6], chunk=3, finish=None, done=False
        ),
    )
    remote = _stream_replica("remote-lora")
    remote._adapters = frozenset({"tuned"})  # advertised via last probe
    pool = _pool([remote, EngineReplica("b", sibling)], metrics=metrics)
    pool.register_adapter_source("tuned", leaves)
    try:
        assert "tuned" not in pool.replicas[1].adapters()
        req = pool.submit_generate(prompt, **params)
        toks = _drain(req)
        result = req.future.result(timeout=180)
        assert faults.fired("http.stream.open") == 1  # routed to the
        # advertising remote, not the adapterless sibling
        assert toks == ref.token_ids
        assert result.token_ids == ref.token_ids
        # The sibling now advertises the adapter it lazily loaded.
        assert "tuned" in pool.replicas[1].adapters()
        assert "tuned" in pool.lora_names()
    finally:
        faults.reset()
        _release(pool)
        try:
            sibling.unload_lora("tuned")
        except KeyError:
            pass


def test_connect_reset_during_hedge_retries_on_sibling(metrics, sibling):
    """Unary path: the routed remote connect-resets; the budgeted
    fast-fail retry lands on the sibling and the caller never sees the
    loss. The remote is NOT demoted — that is the prober's decision."""
    prompt = "hedged connect reset"
    ref = sibling.generate_sync(prompt, **PARAMS)
    faults.arm("http.request", raises=_tagged("connect", "reset by peer"))
    remote = HTTPReplica(
        "flaky", HTTPService("http://127.0.0.1:9"), stream=False,
    )
    pool = _pool([remote, EngineReplica("b", sibling)], metrics=metrics)
    before = counter_total(metrics, "app_tpu_hedged_requests_total")
    try:
        result = pool.generate_sync(prompt, timeout=120, **PARAMS)
        assert faults.fired("http.request") == 1  # remote was tried first
        assert result.token_ids == ref.token_ids
        assert counter_total(
            metrics, "app_tpu_hedged_requests_total"
        ) == before + 1
        assert not remote.probe_failed
    finally:
        faults.reset()
        _release(pool)


# ----------------------------------------------------------------------
# streaming through a REAL remote gofr_tpu app (live socket)
# ----------------------------------------------------------------------


class _Harness:
    """Boot a gofr_tpu App on an ephemeral port (httptest.Server role)."""

    def __init__(self, app):
        import asyncio

        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )

    def __enter__(self):
        import asyncio

        self._thread.start()
        asyncio.run_coroutine_threadsafe(
            self.app.start(), self._loop
        ).result(120)
        return self

    def __exit__(self, *exc):
        import asyncio

        asyncio.run_coroutine_threadsafe(
            self.app.stop(), self._loop
        ).result(30)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    @property
    def address(self):
        return f"http://127.0.0.1:{self.app.http_port}"


def test_streaming_through_real_remote_app_matches_remote_engine():
    """Integration proof for the whole wire: a pool fronting a REAL
    remote gofr_tpu app consumes its OpenAI SSE with
    ``stream_options.include_tokens`` over a live socket; the streamed
    token ids match the remote engine's own generation, and the remote
    pod's flight recorder shows the request under the CALLER's trace id
    (one trace across hosts)."""
    from gofr_tpu import App
    from gofr_tpu.serving.openai_compat import add_openai_routes
    from gofr_tpu.service import new_http_service

    app = App(config=MockConfig({
        "APP_NAME": "remote-pod", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "128",
    }))
    add_openai_routes(app)
    prompt_ids = [72, 101, 108, 108, 111]  # id-array prompt: no
    # tokenizer coupling between the pool and the remote pod
    with _Harness(app) as harness:
        direct = app.container.tpu.generate_sync(
            prompt_ids, max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
        svc = new_http_service(harness.address)
        replica = HTTPReplica("pod-0", svc)
        pool = _pool([replica])
        try:
            assert replica.supports_stream  # streaming remotes default on
            req = pool.submit_generate(
                prompt_ids, max_new_tokens=8, temperature=0.0,
                stop_on_eos=False, traceparent=TRACEPARENT,
            )
            toks = _drain(req)
            result = req.future.result(timeout=120)
            assert toks == direct.token_ids
            assert result.token_ids == direct.token_ids
            assert result.prompt_tokens == len(prompt_ids)
            assert replica.load() == 0
            # The remote pod adopted the caller's traceparent from the
            # forwarded header: its OWN flight recorder shows the
            # request under the SAME trace id — cross-host stitching,
            # observed end to end on the receiving side.
            flights = app.container.tpu.flight_records()
            assert any(
                e["trace_id"] == "ab" * 16
                for e in flights.get("records", [])
                + flights.get("pinned", [])
            )
            # Probe over the live wire refreshes health + capabilities.
            assert pool.probe_once() == {"pod-0": "pass"}
        finally:
            _release(pool)


# ----------------------------------------------------------------------
# PoolScaler: load-adaptive spawn/drain (stub replicas, injected clocks)
# ----------------------------------------------------------------------


class _ScalerStub(Replica):
    supports_stream = True

    def __init__(self, name, load=0):
        super().__init__(name)
        self.load_value = load
        self.closed = False
        self.handoff = None

    def state(self):
        return "SERVING"

    def load(self):
        return self.load_value

    def set_handoff(self, handoff):
        self.handoff = handoff

    def close(self):
        self.closed = True


def _scaler(pool, spawn, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_load_per_replica", 4.0)
    kw.setdefault("down_load_per_replica", 0.5)
    kw.setdefault("scale_up_wait_s", 10.0)
    kw.setdefault("scale_down_wait_s", 60.0)
    kw.setdefault("interval_s", 0)  # no thread: tests drive evaluate()
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("metrics", pool._metrics)
    return PoolScaler(pool, spawn, **kw)


def test_scaler_spawns_under_sustained_pressure_never_past_max(metrics):
    spawned = []

    def spawn():
        replica = _ScalerStub(f"scaled-{len(spawned)}", load=9)
        spawned.append(replica)
        return replica

    a = _ScalerStub("a", load=9)
    pool = _pool([a], metrics=metrics)
    scaler = _scaler(pool, spawn)
    before = counter_total(metrics, "app_tpu_scale_events_total")
    # Pressure must SUSTAIN for scale_up_wait_s: a single bursty sweep
    # never spawns (cold engines take seconds to become useful).
    assert scaler.evaluate(now=0.0) == "steady"
    assert scaler.evaluate(now=9.9) == "steady"
    assert spawned == []
    assert scaler.evaluate(now=10.0) == "up"
    assert len(pool.replicas) == 2
    assert spawned[0].handoff is not None  # failover wiring on join
    # Still saturated: the window re-anchors, then the ceiling holds.
    assert scaler.evaluate(now=20.0) == "steady"
    assert scaler.evaluate(now=30.0) == "up"
    assert len(pool.replicas) == 3
    for t in (40.0, 50.0, 60.0, 70.0):
        assert scaler.evaluate(now=t) == "steady"  # at TPU_POOL_MAX
    assert len(pool.replicas) == 3
    assert len(spawned) == 2
    assert counter_total(
        metrics, "app_tpu_scale_events_total"
    ) == before + 2


def test_scaler_drains_idle_spawned_replica_and_respects_min(metrics):
    spawned = []

    def spawn():
        replica = _ScalerStub(f"scaled-{len(spawned)}", load=0)
        spawned.append(replica)
        return replica

    a = _ScalerStub("a", load=9)
    pool = _pool([a], metrics=metrics)
    # down threshold 0.6: a pool with ONE lingering in-flight request
    # across two replicas (0.5/replica) still counts as idle enough.
    scaler = _scaler(pool, spawn, down_load_per_replica=0.6)
    assert scaler.evaluate(now=0.0) == "steady"
    assert scaler.evaluate(now=10.0) == "up"
    victim = spawned[0]
    victim.load_value = 1  # one request still in flight
    a.load_value = 0  # the burst passed

    picked_during_drain = []

    def drain_sleep(_s):
        # While draining, routing already skips the victim — and the
        # in-flight request finishes before retirement (zero dropped).
        picked_during_drain.append(pool.pick().name)
        victim.load_value = 0

    scaler._sleep = drain_sleep
    # Idleness must sustain for scale_down_wait_s.
    assert scaler.evaluate(now=20.0) == "steady"
    assert scaler.evaluate(now=79.9) == "steady"
    assert scaler.evaluate(now=80.0) == "down"
    assert picked_during_drain == ["a"]  # never the draining victim
    assert victim.closed
    assert victim.handoff is None  # detached before retirement
    assert [r.name for r in pool.replicas] == ["a"]
    # At the floor now: idleness forever never drains below min.
    for t in (150.0, 220.0, 290.0):
        assert scaler.evaluate(now=t) == "steady"
    assert len(pool.replicas) == 1


def test_drain_aborts_and_readmits_when_inflight_never_completes(metrics):
    clock = [0.0]
    a = _ScalerStub("a")
    b = _ScalerStub("b", load=2)  # stuck in-flight work
    pool = _pool([a, b], metrics=metrics, clock=lambda: clock[0])

    def stuck_sleep(_s):
        clock[0] += 1.0  # time passes; the work never completes

    assert pool.drain_replica(b, timeout_s=5.0, sleep=stuck_sleep) is False
    # Nothing dropped, nothing closed: the replica re-entered routing.
    assert not b.closed
    assert not b.draining
    assert b in pool.replicas
    assert b.handoff is not None


def test_scaler_repairs_floor_immediately_when_capacity_dies(metrics):
    spawned = []

    def spawn():
        replica = _ScalerStub(f"scaled-{len(spawned)}")
        spawned.append(replica)
        return replica

    a, b = _ScalerStub("a"), _ScalerStub("b")
    pool = _pool([a, b], metrics=metrics)
    scaler = _scaler(pool, spawn, min_replicas=2, max_replicas=3)
    assert scaler.evaluate(now=0.0) == "steady"
    b.probe_failed = True  # demoted: no longer counts as capacity
    # Below min is a violation NOW — no sustain window.
    assert scaler.evaluate(now=0.1) == "up"
    assert len(pool.replicas) == 3
    # Another death: capacity is 2 == min again... then a third dies.
    spawned[0].probe_failed = True
    # MEMBERSHIP is at max_replicas: never exceeded, even to repair the
    # floor — recovering the demoted replicas is the prober's job.
    assert scaler.evaluate(now=0.2) == "steady"
    assert len(pool.replicas) == 3
    assert len(spawned) == 1


def test_pool_gauges_report_composition_by_state(metrics):
    a = _ScalerStub("a")
    b = _ScalerStub("b")
    c = _ScalerStub("c")
    pool = _pool([a, b, c], metrics=metrics)
    b.draining = True
    c.probe_failed = True
    pool.publish_pool_gauges()
    inst = {i.name: i for i in metrics.instruments()}["app_tpu_pool_replicas"]
    values = {
        dict(labels)["state"]: v for labels, v in inst.collect().items()
    }
    assert values["serving"] == 1.0
    assert values["draining"] == 1.0
    assert values["down"] == 1.0
    assert values["restarting"] == 0.0
