"""Request-lifecycle resilience suite (ISSUE 2 acceptance gate).

Every test is deterministic: no TPU (CPU backend), no sleeps as
synchronization — stalls are test-controlled ``threading.Event``s armed
through the fault-injection harness (``gofr_tpu.faults``), deadlines
ride injectable fake clocks (``serving/lifecycle.Deadline``), and the
watchdog is tripped by *stating* a timestamp (``Watchdog.check(now=)``).

Covered, each observable via the new metrics counters:

* a cancelled/disconnected stream's KV blocks free within one decode
  window (``app_tpu_requests_cancelled_total``);
* an over-budget submit is shed with 429 + ``Retry-After`` before
  admission (``app_tpu_requests_shed_total``);
* a deadline-exceeded stream ends with a terminal error event
  (``app_tpu_deadline_exceeded_total``);
* a stalled device step trips the watchdog and flips ``/health``
  (``app_tpu_watchdog_trips_total``).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.errors import (
    ErrorDeadlineExceeded,
    ErrorRequestCancelled,
    ErrorServiceUnavailable,
    ErrorTooManyRequests,
)
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.lifecycle import (
    AggregateThroughput,
    CancelToken,
    Deadline,
    coalesce_deadline,
)
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.serving.watchdog import Watchdog

RESILIENCE_COUNTERS = (
    "app_tpu_requests_shed_total",
    "app_tpu_requests_cancelled_total",
    "app_tpu_deadline_exceeded_total",
    "app_tpu_watchdog_trips_total",
)


def _metrics_manager():
    m = new_metrics_manager()
    for name in RESILIENCE_COUNTERS + ("app_tpu_tokens_generated",
                                       "app_tpu_prefix_hits"):
        m.new_counter(name)
    for name in ("app_tpu_queue_depth", "app_tpu_kv_slots_in_use",
                 "app_tpu_hbm_used_bytes", "app_tpu_kv_blocks_free"):
        m.new_gauge(name)
    m.new_histogram("app_tpu_infer_latency")
    m.new_histogram("app_tpu_batch_size")
    m.new_histogram("app_tpu_spec_tokens_per_step")
    return m


def counter_total(metrics, name: str) -> float:
    inst = {i.name: i for i in metrics.instruments()}[name]
    return sum(inst.collect().values())


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(scope="module")
def engine(metrics):
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, kv_block=16,
        tokenizer=ByteTokenizer(), watchdog_s=300.0, metrics=metrics,
    )
    eng.start_sync()
    # Warm the compile caches so later stall windows are scheduling, not
    # compilation.
    eng.generate_sync("warm", max_new_tokens=2, temperature=0.0,
                      stop_on_eos=False)
    yield eng
    eng.stop_sync()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _drain_stream(req, timeout=120.0) -> list[int]:
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _wait_until(cond, timeout=30.0) -> bool:
    """Poll a host-side condition the scheduler thread publishes. The
    terminal stream sentinel is the ordering edge; this only absorbs the
    scheduler's final bookkeeping writes."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


# ----------------------------------------------------------------------
# lifecycle primitives
# ----------------------------------------------------------------------


def test_deadline_fake_clock_and_coalesce():
    now = [0.0]
    d = Deadline(10.0, clock=lambda: now[0])
    assert not d.expired() and d.remaining() == 10.0
    now[0] = 10.0
    assert d.expired() and d.remaining() == 0.0
    assert coalesce_deadline(d, 99.0) is d  # explicit Deadline wins
    assert coalesce_deadline(None, None) is None
    rel = coalesce_deadline(None, 60.0)
    assert rel is not None and 0 < rel.remaining() <= 60.0


def test_cancel_token_latches():
    tok = CancelToken()
    assert not tok.cancelled
    tok.cancel()
    tok.cancel()  # idempotent
    assert tok.cancelled


def test_fault_injector_times_after_and_reset():
    inj = faults.FaultInjector()
    inj.arm("p", raises=ValueError("x"), times=1, after=1)
    inj.fire("p")  # skipped (after=1)
    with pytest.raises(ValueError):
        inj.fire("p")
    inj.fire("p")  # exhausted (times=1)
    assert inj.fired("p") == 1
    inj.reset()
    inj.fire("p")  # disarmed
    with pytest.raises(ValueError):
        inj.arm("q")  # neither raises nor action
    calls = []
    with inj.armed("r", action=lambda **kw: calls.append(kw)):
        inj.fire("r", a=1)
    assert calls == [{"a": 1}]
    inj.fire("r")  # context manager disarmed it
    assert inj.fired("r") == 0


def test_watchdog_unit_pet_check_reset():
    clock = [0.0]
    trips = []
    wd = Watchdog(5.0, clock=lambda: clock[0], on_trip=trips.append)
    assert not wd.check()
    clock[0] = 4.0
    assert not wd.check()
    wd.pet()  # heartbeat at t=4
    clock[0] = 8.0  # 4s since pet — under bound
    assert not wd.check()
    assert not wd.check(now=9.0)  # exactly 5s since pet: not over
    assert wd.check(now=9.1)
    assert wd.tripped and len(trips) == 1 and "no progress" in wd.reason
    assert wd.check(now=0.0)  # latched
    wd.reset()
    assert not wd.tripped and not wd.check()


# ----------------------------------------------------------------------
# cancellation frees KV blocks within one decode window
# ----------------------------------------------------------------------


def test_cancellation_frees_kv_blocks(engine, metrics):
    before = counter_total(metrics, "app_tpu_requests_cancelled_total")
    free0 = len(engine._free_blocks)
    req = engine.submit_generate(
        "cancel me", max_new_tokens=90, temperature=0.0, stop_on_eos=False
    )
    first = req.stream.get(timeout=120)  # admitted and decoding
    assert first is not None
    req.cancel.cancel()
    toks = _drain_stream(req)  # sentinel arrives ≤ one window later
    with pytest.raises(ErrorRequestCancelled):
        req.future.result(timeout=30)
    # Far fewer than the budget decoded, and the paged pool is whole again.
    assert len(toks) + 1 < 90
    assert _wait_until(lambda: len(engine._free_blocks) == free0)
    assert _wait_until(lambda: all(s is None for s in engine._slots))
    assert counter_total(
        metrics, "app_tpu_requests_cancelled_total"
    ) == before + 1


def test_disconnect_via_shared_cancel_token(engine, metrics):
    """The transport's token (HTTP server mints one per request) is the
    same object the engine reaps on."""
    token = CancelToken()
    free0 = len(engine._free_blocks)
    req = engine.submit_generate(
        "client gone", max_new_tokens=90, temperature=0.0,
        stop_on_eos=False, cancel=token,
    )
    assert req.cancel is token
    assert req.stream.get(timeout=120) is not None
    token.cancel()  # what the HTTP server does on a dead connection
    _drain_stream(req)
    with pytest.raises(ErrorRequestCancelled):
        req.future.result(timeout=30)
    assert _wait_until(lambda: len(engine._free_blocks) == free0)


def test_queued_cancelled_request_never_admitted(engine, metrics):
    """A request cancelled while still queued is failed at admission —
    no slot, no prefill, no tokens."""
    gate_in, gate_out = threading.Event(), threading.Event()

    def stall(**kw):
        gate_in.set()
        gate_out.wait(timeout=60)

    with faults.armed("scheduler.window", action=stall, times=1):
        assert gate_in.wait(30)  # scheduler parked at the top of its loop
        req = engine.submit_generate(
            "never runs", max_new_tokens=50, temperature=0.0,
            stop_on_eos=False,
        )
        req.cancel.cancel()
        gate_out.set()
    assert _drain_stream(req) == []
    with pytest.raises(ErrorRequestCancelled):
        req.future.result(timeout=30)
    assert req.token_ids == []


# ----------------------------------------------------------------------
# deadlines: early rejection and mid-stream retirement
# ----------------------------------------------------------------------


def test_deadline_exceeded_mid_stream(engine, metrics):
    before = counter_total(metrics, "app_tpu_deadline_exceeded_total")
    now = [0.0]
    d = Deadline(3600.0, clock=lambda: now[0])
    free0 = len(engine._free_blocks)
    req = engine.submit_generate(
        "deadline", max_new_tokens=90, temperature=0.0, stop_on_eos=False,
        deadline=d,
    )
    assert req.stream.get(timeout=120) is not None
    now[0] = 7200.0  # the clock statement that "expires" the deadline
    _drain_stream(req)
    with pytest.raises(ErrorDeadlineExceeded):
        req.future.result(timeout=30)
    assert _wait_until(lambda: len(engine._free_blocks) == free0)
    assert counter_total(
        metrics, "app_tpu_deadline_exceeded_total"
    ) == before + 1


def test_deadline_aware_early_rejection(engine, metrics):
    """Projected queue wait > deadline → shed at submit, before any
    admission work."""
    before = counter_total(metrics, "app_tpu_requests_shed_total")
    old_tps = engine._expected_tps
    engine._expected_tps = 1.0  # 1 tok/s → this request "takes" ~60s
    try:
        with pytest.raises(ErrorDeadlineExceeded) as exc:
            engine.submit_generate(
                "too slow for this deadline", max_new_tokens=40,
                temperature=0.0, deadline_s=1.0,
            )
        assert "projected queue wait" in str(exc.value)
    finally:
        engine._expected_tps = old_tps
    assert counter_total(
        metrics, "app_tpu_requests_shed_total"
    ) == before + 1


def test_already_expired_deadline_rejected_at_submit(engine):
    now = [100.0]
    dead = Deadline(50.0, clock=lambda: now[0])  # expired before submit
    with pytest.raises(ErrorDeadlineExceeded):
        engine.submit_generate(
            "late", max_new_tokens=4, temperature=0.0, deadline=dead
        )


# ----------------------------------------------------------------------
# aggregate-throughput estimator (projected-wait shedding denominator)
# ----------------------------------------------------------------------


def test_aggregate_throughput_sliding_window():
    now = [0.0]
    tput = AggregateThroughput(window_s=10.0, clock=lambda: now[0])
    assert tput.rate() == 0.0  # no signal → caller falls back to prior
    # 4 concurrent streams × 50 tok/s each = 200 tok/s aggregate.
    for step in range(1, 101):
        now[0] = step * 0.02  # a window's worth of emissions every 20ms
        tput.note(4)
    assert 180.0 <= tput.rate() <= 220.0
    # Old samples slide out of the window…
    now[0] += 11.0
    assert tput.rate() == 0.0
    # …and reset() forgets history (engine restart).
    tput.note(4)
    assert tput.rate() > 0
    tput.reset()
    assert tput.rate() == 0.0


def test_aggregate_throughput_governs_shed_decisions(engine):
    """Shed decisions under concurrent load: the old per-request EWMA
    measured ONE stream (~aggregate/batch) and over-shed by the batch
    size; the aggregate estimator admits what the engine can actually
    chew through. Simulated: 4 streams × 50 tok/s each."""
    now = [0.0]
    agg = AggregateThroughput(window_s=10.0, clock=lambda: now[0])
    per_stream_ewma = 50.0  # what the retired-request EWMA converged to
    for step in range(1, 101):
        now[0] = step * 0.02
        agg.note(4)  # all four slots emit each window
    old_tput, engine._tput = engine._tput, agg
    old_exp = engine._expected_tps
    engine._expected_tps = 0.0
    try:
        assert engine._throughput_tps() == pytest.approx(agg.rate())
        # A request needing ~1000 tokens of queue ahead of a 10s
        # deadline: at the TRUE 200 tok/s it waits ~5s → admit; the
        # per-request estimate (50 tok/s → 20s) would have shed it.
        cost = 1000
        wait_aggregate = engine._projected_wait_s(cost)
        wait_per_request = cost / per_stream_ewma
        assert wait_aggregate < 10.0 < wait_per_request
        req = engine.submit_generate(
            "admitted under aggregate throughput",
            max_new_tokens=cost - len(b"admitted under aggregate throughput"),
            temperature=0.0, stop_on_eos=False, deadline_s=10.0,
        )
        # Admitted (no ErrorDeadlineExceeded shed) — cancel it; the
        # admission decision is the test, not the decode.
        req.cancel_request()
        _drain_stream(req)
    finally:
        engine._tput = old_tput
        engine._expected_tps = old_exp


# ----------------------------------------------------------------------
# per-tenant admission quotas (TPU_TENANT_QUEUE_MAX)
# ----------------------------------------------------------------------


def test_tenant_quota_sheds_per_tenant_before_global(engine, metrics):
    """One tenant's flood sheds on ITS budget (429, reason
    tenant_quota) while other tenants and untenanted requests keep
    being admitted under the same global queue."""
    inst = {
        i.name: i for i in metrics.instruments()
    }["app_tpu_requests_shed_total"]

    def tenant_shed_total() -> float:
        return sum(
            v for k, v in inst.collect().items()
            if ("reason", "tenant_quota") in k
        )

    before = tenant_shed_total()
    gate_in, gate_out = threading.Event(), threading.Event()

    def stall(**kw):
        gate_in.set()
        gate_out.wait(timeout=60)

    old_max = engine.tenant_queue_max
    engine.tenant_queue_max = 2
    reqs = []
    try:
        with faults.armed("scheduler.window", action=stall, times=1):
            assert gate_in.wait(30)  # queue cannot drain while parked
            for _ in range(2):
                reqs.append(engine.submit_generate(
                    "tenant a", max_new_tokens=4, temperature=0.0,
                    stop_on_eos=False, tenant="acme",
                ))
            # Third same-tenant submit: shed on the TENANT budget…
            with pytest.raises(ErrorTooManyRequests) as exc:
                engine.submit_generate(
                    "tenant a again", max_new_tokens=4, temperature=0.0,
                    tenant="acme",
                )
            assert "acme" in str(exc.value)
            assert exc.value.status_code == 429
            assert int(exc.value.headers["Retry-After"]) >= 1
            # …while another tenant and an untenanted caller still fit.
            reqs.append(engine.submit_generate(
                "tenant b", max_new_tokens=4, temperature=0.0,
                stop_on_eos=False, tenant="globex",
            ))
            reqs.append(engine.submit_generate(
                "no tenant", max_new_tokens=4, temperature=0.0,
                stop_on_eos=False,
            ))
            gate_out.set()
        for req in reqs:
            req.future.result(timeout=120)
        assert tenant_shed_total() == before + 1
        # Quota seats return on dequeue: the tenant can submit again.
        done = engine.submit_generate(
            "tenant a after drain", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, tenant="acme",
        )
        done.future.result(timeout=120)
        assert engine._tenant_queued == {}
    finally:
        engine.tenant_queue_max = old_max


def test_tenant_rides_http_header_and_grpc_metadata(engine):
    """The engine-facing tenant key comes from X-Tenant-Id (HTTP) and
    x-tenant-id invocation metadata (gRPC) — both transports feed the
    same submit kwarg."""
    from gofr_tpu.grpc.server import tenant_from_context

    class _Ctx:
        def invocation_metadata(self):
            return (("user-agent", "t"), ("x-tenant-id", "acme"))

    assert tenant_from_context(_Ctx()) == "acme"

    class _NoMeta:
        pass

    assert tenant_from_context(_NoMeta()) == ""

    from gofr_tpu.context import Context
    from gofr_tpu.http.proto import RawRequest
    from gofr_tpu.http.request import Request

    raw = RawRequest(
        method="POST", target="/v1/completions", version="HTTP/1.1",
        headers={"x-tenant-id": "globex"}, body=b"{}",
    )
    ctx = Context(Request(raw), container=None)
    assert ctx.header("x-tenant-id") == "globex"


# ----------------------------------------------------------------------
# load shedding: 429 + Retry-After before admission
# ----------------------------------------------------------------------


def test_over_budget_submit_shed_with_429(engine, metrics):
    before = counter_total(metrics, "app_tpu_requests_shed_total")
    gate_in, gate_out = threading.Event(), threading.Event()

    def stall(**kw):
        gate_in.set()
        gate_out.wait(timeout=60)

    old_budget = engine.queue_max_tokens
    engine.queue_max_tokens = 60
    try:
        with faults.armed("scheduler.window", action=stall, times=1):
            assert gate_in.wait(30)  # queue cannot drain while parked
            first = engine.submit_generate(
                "fits in budget", max_new_tokens=30, temperature=0.0,
                stop_on_eos=False,
            )
            with pytest.raises(ErrorTooManyRequests) as exc:
                engine.submit_generate(
                    "over budget now", max_new_tokens=30, temperature=0.0,
                )
            gate_out.set()
        err = exc.value
        assert err.status_code == 429
        assert int(err.headers["Retry-After"]) >= 1
        assert "token budget" in str(err)
        first.future.result(timeout=120)  # the admitted one still finishes
    finally:
        engine.queue_max_tokens = old_budget
    assert counter_total(
        metrics, "app_tpu_requests_shed_total"
    ) == before + 1


def test_shed_maps_to_http_429_with_retry_after_header():
    from gofr_tpu.http.responder import Responder

    resp = Responder(method="POST").respond(
        None, ErrorTooManyRequests("queue full", retry_after_s=7.2)
    )
    assert resp.status == 429
    assert resp.headers["Retry-After"] == "8"
    assert b"request shed" in resp.body


def test_batcher_queue_full_sheds_429():
    from gofr_tpu.serving.batcher import DynamicBatcher

    b = DynamicBatcher(lambda xs: xs, max_batch=2, max_queue=1)
    # Worker not started: the queue cannot drain, deterministically.
    b.submit(1)
    with pytest.raises(ErrorTooManyRequests):
        b.submit(2)


def test_grpc_status_mapping():
    grpc = pytest.importorskip("grpc")
    from gofr_tpu.grpc.server import grpc_status_code

    assert grpc_status_code(
        ErrorTooManyRequests("q", 1)
    ) == grpc.StatusCode.RESOURCE_EXHAUSTED
    assert grpc_status_code(
        ErrorDeadlineExceeded()
    ) == grpc.StatusCode.DEADLINE_EXCEEDED
    assert grpc_status_code(
        ErrorRequestCancelled()
    ) == grpc.StatusCode.CANCELLED
    assert grpc_status_code(
        ErrorServiceUnavailable("drain")
    ) == grpc.StatusCode.UNAVAILABLE


# ----------------------------------------------------------------------
# watchdog: stalled device step → unhealthy + drain
# ----------------------------------------------------------------------


def test_watchdog_trip_flips_health_and_drains(engine, metrics):
    before = counter_total(metrics, "app_tpu_watchdog_trips_total")
    gate_in, gate_out = threading.Event(), threading.Event()

    def stall(**kw):
        gate_in.set()
        gate_out.wait(timeout=120)

    try:
        with faults.armed("scheduler.device_step", action=stall, times=1):
            req = engine.submit_generate(
                "stall me", max_new_tokens=4, temperature=0.0,
                stop_on_eos=False,
            )
            assert gate_in.wait(60)  # the "device step" is now hung
            # Deterministic trip: state a time past the bound instead of
            # sleeping through it.
            assert engine._watchdog.check(
                now=time.monotonic() + engine._watchdog.bound_s + 1
            )
            health = engine.health_check()
            assert health["status"] == "DOWN"
            assert health["details"]["watchdog"]["tripped"]
            assert "no progress" in health["details"]["watchdog"]["reason"]
            # Tripped engine drains: new submissions are rejected 503.
            with pytest.raises(ErrorServiceUnavailable):
                engine.submit_generate("rejected", max_new_tokens=4)
            gate_out.set()
        req.future.result(timeout=120)  # the stalled request completes
        assert counter_total(
            metrics, "app_tpu_watchdog_trips_total"
        ) == before + 1
    finally:
        gate_out.set()
        # Recovery is an explicit restart (the trip is latched).
        engine.stop_sync()
        engine.start_sync()
    assert engine.health_check()["status"] == "UP"
    r = engine.generate_sync("recovered", max_new_tokens=3, temperature=0.0,
                             stop_on_eos=False)
    assert len(r.token_ids) == 3


def test_watchdog_trip_degrades_container_health(engine, metrics):
    """/.well-known/health aggregates engine health: a tripped watchdog
    flips the app to DEGRADED (the /health unhealthy signal)."""
    from gofr_tpu.config import MockConfig
    from gofr_tpu.container import Container

    container = Container.create(MockConfig({"APP_NAME": "resilience"}))
    container.tpu = engine
    assert container.health()["status"] == "UP"
    gate_in, gate_out = threading.Event(), threading.Event()

    def stall(**kw):
        gate_in.set()
        gate_out.wait(timeout=120)

    try:
        with faults.armed("scheduler.device_step", action=stall, times=1):
            req = engine.submit_generate(
                "stall again", max_new_tokens=4, temperature=0.0,
                stop_on_eos=False,
            )
            assert gate_in.wait(60)
            assert engine._watchdog.check(
                now=time.monotonic() + engine._watchdog.bound_s + 1
            )
            health = container.health()
            assert health["status"] == "DEGRADED"
            assert health["details"]["tpu"]["status"] == "DOWN"
            gate_out.set()
        req.future.result(timeout=120)
    finally:
        gate_out.set()
        engine.stop_sync()
        engine.start_sync()


# ----------------------------------------------------------------------
# fault injection at the remaining seams
# ----------------------------------------------------------------------


def test_device_step_raise_fails_callers_and_engine_restarts(engine):
    with faults.armed(
        "scheduler.device_step", raises=RuntimeError("injected device loss")
    ):
        req = engine.submit_generate(
            "boom", max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        with pytest.raises(RuntimeError, match="injected device loss"):
            req.future.result(timeout=120)
        assert _drain_stream(req) == []  # sentinel delivered, no hang
        # The death is published: further submits fail fast, not hang.
        with pytest.raises(RuntimeError):
            engine.submit_generate("after death", max_new_tokens=4)
    engine.start_sync()
    r = engine.generate_sync("alive again", max_new_tokens=3,
                             temperature=0.0, stop_on_eos=False)
    assert len(r.token_ids) == 3


def test_tokenizer_fault_rejects_request_engine_survives(engine):
    with faults.armed(
        "engine.tokenize", raises=ValueError("corrupt merges")
    ):
        with pytest.raises(ValueError, match="corrupt merges"):
            engine.submit_generate("x", max_new_tokens=4)
    assert engine.health_check()["status"] == "UP"
    r = engine.generate_sync("fine", max_new_tokens=3, temperature=0.0,
                             stop_on_eos=False)
    assert len(r.token_ids) == 3


def test_submit_path_fault_rejects_request_engine_survives(engine):
    with faults.armed(
        "engine.submit", raises=RuntimeError("submit bookkeeping failure")
    ):
        with pytest.raises(RuntimeError, match="submit bookkeeping"):
            engine.submit_generate("x", max_new_tokens=4)
    r = engine.generate_sync("fine", max_new_tokens=3, temperature=0.0,
                             stop_on_eos=False)
    assert len(r.token_ids) == 3


# ----------------------------------------------------------------------
# deadline-exceeded stream ends with a terminal error EVENT (SSE)
# ----------------------------------------------------------------------


class _RouteRecorder:
    """Just enough App surface for add_openai_routes."""

    def __init__(self):
        self.routes = {}

    def _verb(self, method, path):
        def deco(fn):
            self.routes[(method, path)] = fn
            return fn

        return deco

    def post(self, path):
        return self._verb("POST", path)

    def get(self, path):
        return self._verb("GET", path)


class _FakeCtx:
    def __init__(self, engine, body, deadline=None, cancel=None):
        import types

        self.container = types.SimpleNamespace(tpu=engine, tpu_embed=None)
        self.request = types.SimpleNamespace(
            raw=types.SimpleNamespace(body=json.dumps(body).encode())
        )
        self.deadline = deadline
        self.cancel_token = cancel


def test_sse_stream_ends_with_terminal_error_event(engine):
    from gofr_tpu.serving.openai_compat import add_openai_routes

    app = _RouteRecorder()
    add_openai_routes(app)
    handler = app.routes[("POST", "/v1/completions")]
    now = [0.0]
    d = Deadline(3600.0, clock=lambda: now[0])
    ctx = _FakeCtx(
        engine,
        {"prompt": "stream until the deadline", "max_tokens": 90,
         "temperature": 0, "stream": True},
        deadline=d,
    )

    async def run():
        stream = await handler(ctx)
        events = []
        async for chunk in stream.chunks:
            events.append(chunk)
            # After the first delta is on the wire, the deadline expires.
            now[0] = 7200.0
        return events

    events = asyncio.run(run())
    assert events[-1] == "data: [DONE]\n\n"
    payloads = [
        json.loads(e[len("data: "):])
        for e in events
        if e.startswith("data: {")
    ]
    errors = [p for p in payloads if "error" in p]
    assert len(errors) == 1, "stream must end with ONE terminal error event"
    assert errors[0]["error"]["code"] == 504
    assert errors[0]["error"]["type"] == "ErrorDeadlineExceeded"
    assert "deadline" in errors[0]["error"]["message"]


def test_grpc_stream_shaping_surfaces_deadline_error(engine):
    """The shared gRPC stream shaper raises the terminal error out of the
    generator so the servicers abort with DEADLINE_EXCEEDED."""
    from gofr_tpu.serving.stream_text import stream_generation

    now = [0.0]
    d = Deadline(3600.0, clock=lambda: now[0])

    async def run():
        pieces = 0
        gen = stream_generation(
            engine, "grpc deadline", {
                "max_new_tokens": 90, "temperature": 0.0,
                "stop_on_eos": False, "deadline": d,
            }, engine.tokenizer,
        )
        with pytest.raises(ErrorDeadlineExceeded):
            async for ev in gen:
                if ev["type"] == "piece":
                    pieces += 1
                    now[0] = 7200.0  # expire after the first piece
        return pieces

    assert asyncio.run(run()) >= 1


# ----------------------------------------------------------------------
# deadline propagation from the HTTP edge
# ----------------------------------------------------------------------


def test_http_request_timeout_header_becomes_deadline():
    from gofr_tpu.http.proto import RawRequest

    raw = RawRequest(
        method="POST", target="/v1/completions", version="HTTP/1.1",
        headers={"x-request-timeout": "30"}, body=b"{}",
    )
    # The server-side parse is a couple of lines; mirror it here against
    # the shared primitives (the wire-level path is exercised by
    # tests/test_http_server.py's connection tests).
    d = Deadline.after(float(raw.headers["x-request-timeout"]))
    assert 0 < d.remaining() <= 30.0

    from gofr_tpu.context import Context
    from gofr_tpu.http.request import Request

    raw.ctx_data["deadline"] = d
    tok = CancelToken()
    raw.ctx_data["cancel"] = tok
    ctx = Context(Request(raw), container=None)
    assert ctx.deadline is d
    assert ctx.cancel_token is tok
