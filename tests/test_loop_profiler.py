"""Scheduler-loop profiler suite (ISSUE 15 acceptance gate).

Deterministic throughout: the profiler takes every timestamp as an
argument (the scheduler's one-clock-read-per-boundary contract), so
phase math, stall hysteresis, and ring bounds are driven with stated
clocks; the capture singleton's cooldown runs with injected clock /
start / stop / spawn. Engine-level tests use one small module-scoped
engine on the CPU backend.

Covered:

* per-phase durations of a pass sum to its wall time EXACTLY under
  stated clocks (residual in ``other``), and the exported
  ``app_tpu_loop_phase_seconds{phase}`` gauges sum to it too;
* utilization (busy fraction) and host-overhead-ratio (busy share
  outside the device-window seam) arithmetic;
* stall detection: absolute bound, k×p95 relative bound (floored,
  armed only past the minimum sample count), hysteresis in BOTH
  directions — a storm of stalled passes pins exactly one record,
  re-arming only after a clean pass;
* compile-pass exemption: a pass during which the compile counter grew
  is the compile tracker's to attribute, never a loop stall;
* the anomaly ring is bounded and absolute-stall records are PINNED —
  they survive a burst of relative anomalies;
* trace-capture cooldown: a stall storm triggers at most one capture
  per cooldown (suppressions counted), the capture slot is exclusive,
  and :func:`get_capture` is a race-free singleton (the /debug/
  tpu-trace lazy-init fix);
* layer-off (``TPU_LOOP_PROFILE=0``): no profiler object, no hooks, a
  byte-identical greedy stream;
* advertisement: health details / capacity_report / flight_records
  headline / pool ``loop_report`` all carry the loop stats.
"""

from __future__ import annotations

import threading

import pytest

from gofr_tpu.metrics import Manager
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.loop_profiler import (
    PHASES,
    REL_STALL_FLOOR_S,
    REL_STALL_MIN_SAMPLES,
    LoopProfiler,
)
from gofr_tpu.serving.profiler_capture import ProfilerCapture
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.replica_pool import EngineReplica, ReplicaPool


def loop_metrics() -> Manager:
    m = Manager()
    for name in (
        "app_tpu_loop_phase_seconds",
        "app_tpu_loop_utilization",
        "app_tpu_loop_host_overhead_ratio",
    ):
        m.new_gauge(name)
    m.new_counter("app_tpu_loop_stalls_total")
    return m


def gauge_values(m: Manager, name: str) -> dict:
    inst = [i for i in m.instruments() if i.name == name]
    return dict(inst[0].collect()) if inst else {}


def counter_value(m: Manager, name: str, **labels: str) -> float:
    inst = [i for i in m.instruments() if i.name == name]
    if not inst:
        return 0.0
    want = set(labels.items())
    return sum(
        v for k, v in inst[0].collect().items() if want <= set(k)
    )


def make_prof(**kw) -> LoopProfiler:
    defaults = dict(stall_s=1.0, stall_factor=0.0, anomaly_records=8)
    defaults.update(kw)
    return LoopProfiler("m", **defaults)


def drive_pass(
    prof: LoopProfiler, t0: float, laps: list[tuple[str, float]],
    t_end: float,
) -> None:
    """One full pass under stated clocks: lap each (phase, at) stamp
    and close the pass by beginning the next at ``t_end`` — exactly
    the scheduler's shape, where one ``begin_pass`` both closes pass N
    and opens pass N+1 (calling begin twice would interleave a
    zero-length pass and re-arm the stall latch)."""
    if prof._pass_start is None:
        prof.begin_pass(t0)
    else:
        assert prof._pass_start == pytest.approx(t0), (
            "non-contiguous stated clocks"
        )
    for phase, at in laps:
        prof.lap(phase, at)
    prof.begin_pass(t_end)


# ----------------------------------------------------------------------
# phase math
# ----------------------------------------------------------------------


def test_phase_durations_sum_to_pass_wall_exactly():
    m = loop_metrics()
    prof = make_prof(metrics=m)
    # Pass wall = 1.0s: reap 0.1, ledger 0.2, prefill 0.3,
    # device_window 0.25, residual 0.15 → "other".
    drive_pass(
        prof, 10.0,
        [("reap", 10.1), ("ledger", 10.3), ("prefill", 10.6),
         ("device_window", 10.85)],
        11.0,
    )
    snap = prof.snapshot()
    assert snap["passes"] == 1
    phases = snap["phases"]
    assert phases["reap"]["total_s"] == pytest.approx(0.1)
    assert phases["ledger"]["total_s"] == pytest.approx(0.2)
    assert phases["prefill"]["total_s"] == pytest.approx(0.3)
    assert phases["device_window"]["total_s"] == pytest.approx(0.25)
    assert phases["other"]["total_s"] == pytest.approx(0.15)
    assert sum(p["total_s"] for p in phases.values()) == pytest.approx(
        1.0
    )
    # The exported gauges carry the SAME per-pass attribution: the
    # phase gauges (absent phases publish 0.0) sum to pass wall time.
    vals = gauge_values(m, "app_tpu_loop_phase_seconds")
    assert len(vals) == len(PHASES)
    assert sum(vals.values()) == pytest.approx(1.0)


def test_multiple_laps_accumulate_within_a_pass():
    prof = make_prof()
    # tier_import laps twice in one pass (the wave-admission loop).
    drive_pass(
        prof, 0.0,
        [("tier_import", 0.1), ("prefill", 0.2), ("tier_import", 0.4)],
        0.5,
    )
    phases = prof.snapshot()["phases"]
    assert phases["tier_import"]["total_s"] == pytest.approx(0.3)
    assert phases["tier_import"]["count"] == 1  # one PASS touched it
    assert sum(p["total_s"] for p in phases.values()) == pytest.approx(
        0.5
    )


def test_lap_before_begin_is_a_noop():
    prof = make_prof()
    prof.lap("reap", 5.0)
    assert prof.snapshot()["passes"] == 0


# ----------------------------------------------------------------------
# utilization / host-overhead arithmetic
# ----------------------------------------------------------------------


def test_utilization_and_host_overhead_ratio_arithmetic():
    m = loop_metrics()
    prof = make_prof(metrics=m, stall_s=0.0)
    # Pass 1: 1.0s total, 0.4 idle → busy 0.6, of which 0.45 device.
    drive_pass(
        prof, 0.0,
        [("prefill", 0.15), ("device_window", 0.6), ("idle", 1.0)],
        1.0,
    )
    # Pass 2: 1.0s total, fully idle.
    drive_pass(prof, 1.0, [("idle", 2.0)], 2.0)
    # Window: total 2.0, idle 1.4 → utilization 0.3;
    # busy 0.6, device 0.45 → host overhead (0.6-0.45)/0.6 = 0.25.
    assert prof.utilization() == pytest.approx(0.3)
    assert prof.host_overhead_ratio() == pytest.approx(0.25)
    util = gauge_values(m, "app_tpu_loop_utilization")
    host = gauge_values(m, "app_tpu_loop_host_overhead_ratio")
    assert list(util.values())[0] == pytest.approx(0.3)
    assert list(host.values())[0] == pytest.approx(0.25)


def test_all_idle_window_reads_zero_utilization_and_host():
    prof = make_prof(stall_s=0.0)
    drive_pass(prof, 0.0, [("idle", 1.0)], 1.0)
    assert prof.utilization() == 0.0
    assert prof.host_overhead_ratio() == 0.0  # no busy time to blame


# ----------------------------------------------------------------------
# stall detection + hysteresis
# ----------------------------------------------------------------------


def test_absolute_stall_pins_exactly_one_record_per_incident():
    m = loop_metrics()
    prof = make_prof(stall_s=1.0, metrics=m)
    ctx_reads = []
    prof.context = lambda: (ctx_reads.append(1) or {"queue_depth": 7})
    # A fast pass, then THE deliberately-stalled pass.
    drive_pass(prof, 0.0, [("prefill", 0.01)], 0.01)
    drive_pass(prof, 0.01, [("prefill", 2.0)], 2.01)
    snap = prof.snapshot()
    assert snap["stalls"] == 1
    assert len(snap["pinned_anomalies"]) == 1
    rec = snap["pinned_anomalies"][0]
    assert rec["kind"] == "absolute"
    assert rec["total_s"] == pytest.approx(2.0)
    assert rec["phases"]["prefill"] == pytest.approx(1.99)
    assert rec["context"] == {"queue_depth": 7}
    assert ctx_reads == [1]
    assert counter_value(
        m, "app_tpu_loop_stalls_total", kind="absolute"
    ) == 1
    # Hysteresis: a STORM of stalled passes is one incident — the
    # detector stays latched until a clean pass re-arms it.
    drive_pass(prof, 2.01, [("prefill", 4.5)], 4.51)
    drive_pass(prof, 4.51, [("prefill", 7.0)], 7.01)
    assert prof.snapshot()["stalls"] == 1
    # Clean pass → re-armed → the next stall is a NEW incident.
    drive_pass(prof, 7.01, [("prefill", 7.02)], 7.02)
    drive_pass(prof, 7.02, [("prefill", 9.5)], 9.52)
    snap = prof.snapshot()
    assert snap["stalls"] == 2
    assert len(snap["pinned_anomalies"]) == 2


def test_relative_p95_stall_needs_samples_and_floor():
    prof = make_prof(stall_s=0.0, stall_factor=10.0)
    # Build a rolling baseline of 10ms passes (≥ the minimum samples).
    t = 0.0
    for _ in range(REL_STALL_MIN_SAMPLES):
        drive_pass(prof, t, [("prefill", t + 0.01)], t + 0.01)
        t += 0.01
    # 10× p95 = 0.1s but the floor is higher → 0.04s is NOT a stall...
    drive_pass(prof, t, [("prefill", t + 0.04)], t + 0.04)
    t += 0.04
    assert prof.snapshot()["stalls"] == 0
    assert REL_STALL_FLOOR_S > 0.01 * 10.0 / 10.0
    # ...while a pass over both k×p95 and the floor is.
    drive_pass(prof, t, [("prefill", t + 0.5)], t + 0.5)
    snap = prof.snapshot()
    assert snap["stalls"] == 1
    assert snap["anomalies"][0]["kind"] == "p95"
    assert snap["pinned_anomalies"] == []  # relative → rolling ring


def test_compile_pass_is_never_a_stall():
    prof = make_prof(stall_s=1.0)
    compiles = [0]
    prof.compiles = lambda: compiles[0]
    compiles[0] = 3  # XLA compiled during this (slow) pass
    drive_pass(prof, 0.0, [("prefill", 5.0)], 5.0)
    assert prof.snapshot()["stalls"] == 0
    # Counter stable + still slow → a genuine stall again.
    drive_pass(prof, 5.0, [("prefill", 10.0)], 10.0)
    assert prof.snapshot()["stalls"] == 1


def test_anomaly_ring_bounded_and_pins_survive_a_burst():
    # Rolling window just over the minimum sample count (the baseline
    # excludes the pass under judgment), so a full lap of clean passes
    # flushes each stall back out of the p95 baseline (a stall
    # inflating its own detection threshold is by design — the storm
    # path is the latch's job, not the ring's).
    prof = make_prof(
        stall_s=0.0, stall_factor=10.0, anomaly_records=4,
        window=REL_STALL_MIN_SAMPLES + 1,
    )
    t = 0.0

    def clean_laps(n: int) -> None:
        nonlocal t
        for _ in range(n):
            drive_pass(prof, t, [("prefill", t + 0.01)], t + 0.01)
            t += 0.01

    clean_laps(REL_STALL_MIN_SAMPLES)
    # One ABSOLUTE stall pins first.
    prof.stall_s = 1.0
    drive_pass(prof, t, [("prefill", t + 2.0)], t + 2.0)
    t += 2.0
    prof.stall_s = 0.0
    # A burst of relative anomalies (a clean window between incidents
    # re-arms the latch AND flushes the p95 baseline) overflows the
    # bounded rolling ring...
    for _ in range(6):
        clean_laps(REL_STALL_MIN_SAMPLES)
        drive_pass(prof, t, [("prefill", t + 0.5)], t + 0.5)
        t += 0.5
    snap = prof.snapshot()
    assert len(snap["anomalies"]) == 4  # bounded (maxlen) — 6 fired
    assert all(a["kind"] == "p95" for a in snap["anomalies"])
    # ...but the pinned absolute record SURVIVED the burst.
    assert len(snap["pinned_anomalies"]) == 1
    assert snap["pinned_anomalies"][0]["kind"] == "absolute"
    assert snap["stalls"] == 7


# ----------------------------------------------------------------------
# trace capture: cooldown + singleton
# ----------------------------------------------------------------------


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def make_capture(clock: FakeClock, cooldown_s: float = 60.0):
    events: list[str] = []
    cap = ProfilerCapture(
        cooldown_s=cooldown_s,
        clock=clock,
        sleep=lambda s: events.append(f"sleep:{s}"),
        starter=lambda d: events.append("start"),
        stopper=lambda: events.append("stop"),
        spawn=lambda fn: fn(),  # synchronous for determinism
    )
    return cap, events


def test_trace_capture_cooldown_bounds_a_stall_storm():
    clock = FakeClock(100.0)
    cap, events = make_capture(clock, cooldown_s=60.0)
    prof = make_prof(stall_s=1.0, trace_ms=50, capture=cap)
    # Stall → one capture; storm inside the cooldown → suppressed.
    drive_pass(prof, 0.0, [("prefill", 2.0)], 2.0)
    drive_pass(prof, 2.0, [("prefill", 2.01)], 2.01)  # re-arm
    clock.t = 130.0  # +30s: inside the cooldown
    drive_pass(prof, 2.01, [("prefill", 4.5)], 4.5)
    assert events == ["start", "sleep:0.05", "stop"]
    assert cap.captures == 1 and cap.suppressed == 1
    snap = prof.snapshot()
    assert snap["pinned_anomalies"][0]["trace_captured"] is True
    assert snap["pinned_anomalies"][1]["trace_captured"] is False
    assert snap["trace"]["suppressed"] == 1
    # Past the cooldown the next incident captures again.
    drive_pass(prof, 4.5, [("prefill", 4.51)], 4.51)  # re-arm
    clock.t = 200.0
    drive_pass(prof, 4.51, [("prefill", 7.0)], 7.0)
    assert cap.captures == 2


def test_capture_slot_is_exclusive_and_released_on_failure():
    clock = FakeClock(0.0)
    cap, _ = make_capture(clock, cooldown_s=0.0)
    assert cap.try_acquire()
    # Busy slot: a trigger is suppressed, never queued.
    assert cap.trigger(10) is False
    assert cap.suppressed == 1
    cap.release()
    # A failing capture still releases the slot.
    cap._starter = lambda d: (_ for _ in ()).throw(RuntimeError("boom"))
    assert cap.trigger(10) is True
    assert cap.busy is False
    assert "boom" in cap.snapshot()["last_error"]


def test_get_capture_is_a_race_free_singleton():
    """The /debug/tpu-trace lazy-init fix: concurrent first callers
    can no longer mint two dirs/locks and trace concurrently."""
    import gofr_tpu.serving.profiler_capture as pc

    old = pc._capture
    pc._capture = None
    try:
        got: list = []
        barrier = threading.Barrier(8)

        def grab():
            barrier.wait()
            got.append(pc.get_capture())

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len({id(c) for c in got}) == 1
        assert len({c.trace_dir for c in got}) == 1
        # The engine's cooldown knob updates the shared instance.
        assert pc.get_capture(cooldown_s=7.5).cooldown_s == 7.5
    finally:
        pc._capture = old


# ----------------------------------------------------------------------
# engine integration: hooks, layer-off, advertisement
# ----------------------------------------------------------------------

ENG_KW = dict(
    n_slots=2, max_len=128, window_k=4, pipeline_depth=1,
    prefill_chunk=32, kv_block=32, auto_prefix=True,
    # A generous absolute stall bound: a loaded CI runner's scheduling
    # hiccup must not pin a flaky anomaly into the shared fixture.
    loop_stall_s=30.0,
)


@pytest.fixture(scope="module")
def eng():
    m = loop_metrics()
    e = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(), metrics=m, **ENG_KW
    )
    e.start_sync()
    e.generate_sync(
        "warm the loop", max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    yield e, m
    e.stop_sync()


def _settled_report(e) -> dict:
    """The loop report once the post-generate passes have CLOSED: a
    pass's phases land when the next pass begins, and a result future
    resolves inside the device-window phase — an immediate read races
    it. Bounded poll, no fixed sleep."""
    import time as _time

    deadline = _time.monotonic() + 10.0
    while _time.monotonic() < deadline:
        rep = e.loop_report()
        if "device_window" in rep.get("phases", {}):
            return rep
        _time.sleep(0.005)
    return e.loop_report()


def test_engine_profiles_every_loop_phase(eng):
    e, m = eng
    rep = _settled_report(e)
    assert rep["enabled"] is True
    assert rep["passes"] >= 1 and rep["stalls"] == 0
    for phase in ("reap", "ledger", "sweep", "prefill", "emit_flush",
                  "dispatch", "device_window", "other"):
        assert phase in rep["phases"], sorted(rep["phases"])
    assert 0.0 <= rep["utilization"] <= 1.0
    assert 0.0 <= rep["host_overhead_ratio"] <= 1.0
    # The profiler measures itself.
    assert rep["self_overhead_s"] > 0.0
    # The exported phase gauges publish the full bounded label set
    # (GL016 discipline) — one value per phase, absent phases at 0.0.
    # (The sums-to-pass-wall contract is pinned exactly in the
    # stated-clock test above; the live gauges refresh per pass, so a
    # cross-read here would race the still-running loop.)
    vals = gauge_values(m, "app_tpu_loop_phase_seconds")
    assert len(vals) == len(PHASES)
    assert all(v >= 0.0 for v in vals.values())
    assert sum(vals.values()) > 0.0


def test_engine_advertises_loop_stats(eng):
    e, _ = eng
    compact = {"passes", "stalls", "utilization", "host_overhead_ratio"}
    assert set(e.health_check()["details"]["loop"]) == compact
    assert set(e.capacity_report()["loop"]) == compact
    assert set(e.flight_records()["loop"]) == compact


def test_pool_aggregates_loop_reports(eng):
    e, _ = eng
    pool = ReplicaPool([EngineReplica("r0", e)], probe_interval_s=0)
    try:
        rep = pool.loop_report()
        entry = rep["replicas"]["r0"]
        assert entry["enabled"] is True and entry["passes"] >= 1
        assert "state" in entry
    finally:
        # Detach without pool.close(): closing an EngineReplica stops
        # its engine, and this one is the shared module fixture.
        pool.stop_prober()
        for replica in pool._replicas:
            replica.set_handoff(None)
            replica.set_tier_exporter(None)


def test_layer_off_mints_nothing_and_streams_identically(eng):
    e, _ = eng
    off = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(), loop_profile=False,
        **ENG_KW,
    )
    off.start_sync()
    try:
        assert off._loop_prof is None
        assert off.loop_report() == {"enabled": False}
        assert "loop" not in off.health_check()["details"]
        assert "loop" not in off.capacity_report()
        assert "loop" not in off.flight_records()
        r_off = off.generate_sync(
            "loop ab prompt", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False, timeout=120,
        )
        r_on = e.generate_sync(
            "loop ab prompt", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False, timeout=120,
        )
        # TPU_LOOP_PROFILE=0 is byte-identical: same greedy stream.
        assert r_off.token_ids == r_on.token_ids
    finally:
        off.stop_sync()


def test_tier_import_phase_attributes_on_apply(eng):
    """The tier-import apply stamps its own phase (it would otherwise
    hide inside prefill): the paged engine laps it every pass."""
    e, _ = eng
    rep = e.loop_report()
    assert "tier_import" in rep["phases"]
    assert rep["phases"]["tier_import"]["count"] >= 1
