"""Mega-window decode: one dispatch runs many k-step windows on device
with budget/EOS early-exit (engine.py `mega_window`). Through a
network-attached relay every dispatch costs a host↔device RTT, so the
mega loop is the throughput-mode dispatch amortizer; these tests pin its
correctness contract on CPU: token-for-token parity with the pipelined
per-window path, exact budget delivery, EOS retirement, and composition
with paged KV and sampling."""

from __future__ import annotations

import pytest

from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer

PROMPT = "the quick brown fox"


def _greedy(engine, prompt=PROMPT, n=24, **kw):
    return engine.generate_sync(
        prompt, max_new_tokens=n, temperature=0.0, stop_on_eos=False, **kw
    )


@pytest.fixture(scope="module")
def base_tokens():
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(),
    )
    eng.start_sync()
    try:
        yield _greedy(eng).token_ids
    finally:
        eng.stop_sync()


def _mega_engine(**kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("window_k", 4)
    kw.setdefault("mega_windows", 4)
    kw.setdefault("tokenizer", ByteTokenizer())
    return InferenceEngine("llama-tiny", **kw)


def test_mega_matches_windowed_greedy(base_tokens):
    eng = _mega_engine()
    eng.start_sync()
    try:
        assert _greedy(eng).token_ids == base_tokens
    finally:
        eng.stop_sync()


def test_mega_budget_exact_and_multiple_dispatches(base_tokens):
    # 24 tokens at window 4 × mega 2 = 8-step coverage → 3+ mega
    # dispatches; the budget must come out exact, not window-rounded.
    eng = _mega_engine(mega_windows=2)
    eng.start_sync()
    try:
        r = _greedy(eng)
        assert len(r.token_ids) == 24
        assert r.token_ids == base_tokens
        assert r.finish_reason == "length"
    finally:
        eng.stop_sync()


def test_mega_uneven_budgets_concurrent():
    # Slots with different budgets: device early-exit covers the longest;
    # each request still gets exactly its own budget.
    eng = _mega_engine()
    eng.start_sync()
    try:
        reqs = [
            eng.submit_generate(
                PROMPT, max_new_tokens=n, temperature=0.0, stop_on_eos=False
            )
            for n in (3, 9, 17, 24)
        ]
        got = [len(r.future.result(timeout=120).token_ids) for r in reqs]
        assert got == [3, 9, 17, 24]
    finally:
        eng.stop_sync()


def test_mega_eos_stops_early():
    # ByteTokenizer eos_id=0; random-init llama-tiny rarely emits byte 0
    # greedily, so drive EOS via stop_on_eos=False vs True on the same
    # stream only if it appears — instead pin the *mechanism*: a stop
    # text retires at host mid-mega and the engine must not stall.
    eng = _mega_engine()
    eng.start_sync()
    try:
        base = _greedy(eng, n=24).text
        stop = base[2:6]
        r = eng.generate_sync(
            PROMPT, max_new_tokens=24, temperature=0.0, stop_on_eos=False,
            stop=[stop], timeout=120,
        )
        assert stop not in r.text
        assert r.finish_reason == "stop"
        # Engine still serves after the mid-mega retirement.
        assert _greedy(eng, n=8).token_ids == _greedy(eng, n=8).token_ids
    finally:
        eng.stop_sync()


def test_mega_with_paged_kv(base_tokens):
    eng = _mega_engine(kv_block=32, kv_pool_blocks=24)
    eng.start_sync()
    try:
        assert _greedy(eng).token_ids == base_tokens
    finally:
        eng.stop_sync()


def test_mega_sampled_path_runs():
    # Sampled slots (temperature>0) exercise the PRNG threading through
    # the while_loop carry; determinism across engines isn't asserted
    # (different dispatch partitioning consumes the key differently),
    # only that generation completes with the full budget.
    eng = _mega_engine()
    eng.start_sync()
    try:
        r = eng.generate_sync(
            PROMPT, max_new_tokens=12, temperature=0.8, stop_on_eos=False,
            timeout=120,
        )
        assert len(r.token_ids) == 12
    finally:
        eng.stop_sync()


@pytest.fixture(scope="module")
def spec_base_tokens():
    # The spec oracle is the NON-mega spec engine: bf16 argmax tie-breaks
    # differ between the verify [S, G+1] and decode [S] execution shapes
    # (see models/registry.py llama-tiny-f32 note), so plain decode is
    # not a valid oracle for speculative streams on the bf16 model.
    eng = _mega_engine(mega_windows=0, spec_tokens=2)
    eng.start_sync()
    try:
        yield _greedy(eng).token_ids
    finally:
        eng.stop_sync()


def test_mega_spec_matches_windowed_spec(spec_base_tokens):
    eng = _mega_engine(spec_tokens=2)
    eng.start_sync()
    try:
        assert _greedy(eng).token_ids == spec_base_tokens
    finally:
        eng.stop_sync()


def test_mega_spec_budgets_and_paged(spec_base_tokens):
    # Spec emits a VARIABLE number of tokens per step; budgets must still
    # come out exact across uneven concurrent requests, composed with the
    # paged KV allocator's worst-case-write accounting.
    eng = _mega_engine(spec_tokens=2, kv_block=32, kv_pool_blocks=40)
    eng.start_sync()
    try:
        reqs = [
            eng.submit_generate(
                PROMPT, max_new_tokens=n, temperature=0.0, stop_on_eos=False
            )
            for n in (3, 9, 24)
        ]
        results = [r.future.result(timeout=120) for r in reqs]
        assert [len(r.token_ids) for r in results] == [3, 9, 24]
        assert results[2].token_ids == spec_base_tokens
    finally:
        eng.stop_sync()


def test_mega_device_eos_early_exit(base_tokens):
    """Pin the DEVICE-side EOS exit: a tokenizer whose eos_id is a token
    the greedy stream actually emits must (a) stop that request at the
    EOS with finish_reason 'stop', and (b) leave a concurrent
    stop_on_eos=False request's full budget intact — the while_loop's
    `hit & eos_stop` must zero only the opted-in slot's remaining."""
    eos_tok = int(base_tokens[5])

    class EosTokenizer(ByteTokenizer):
        pass

    EosTokenizer.eos_id = eos_tok
    eng = _mega_engine(tokenizer=EosTokenizer())
    eng.start_sync()
    try:
        stopping = eng.submit_generate(
            PROMPT, max_new_tokens=24, temperature=0.0, stop_on_eos=True
        )
        free = eng.submit_generate(
            PROMPT, max_new_tokens=24, temperature=0.0, stop_on_eos=False
        )
        r_stop = stopping.future.result(timeout=120)
        r_free = free.future.result(timeout=120)
        first_eos = base_tokens.index(eos_tok)
        assert r_stop.token_ids == base_tokens[: first_eos + 1]
        assert r_stop.finish_reason == "stop"
        assert r_free.token_ids == base_tokens
    finally:
        eng.stop_sync()


class TestMultiChunkPrefill:
    """Device-side multi-chunk prefill (prefill_depth>1): the long-prompt
    dispatch amortizer must be invisible in the tokens."""

    PROMPT_LONG = "a quick brown fox jumps over the lazy dog " * 3  # ~129B

    def _tokens(self, **kw):
        eng = InferenceEngine(
            "llama-tiny", n_slots=4, max_len=256, window_k=4,
            prefill_chunk=16, tokenizer=ByteTokenizer(), **kw,
        )
        eng.start_sync()
        try:
            return eng.generate_sync(
                self.PROMPT_LONG, max_new_tokens=12, temperature=0.0,
                stop_on_eos=False, timeout=120,
            ).token_ids
        finally:
            eng.stop_sync()

    def test_matches_single_chunk_path(self):
        assert self._tokens(prefill_depth=4) == self._tokens()

    def test_with_spec_history(self):
        # Speculation drafts from the token history the multi-chunk loop
        # must have recorded — stream parity pins the history writes.
        base = self._tokens(spec_tokens=2)
        assert self._tokens(prefill_depth=4, spec_tokens=2) == base

    def test_with_paged_kv(self):
        base = self._tokens()
        assert self._tokens(
            prefill_depth=4, kv_block=32, kv_pool_blocks=40
        ) == base

    def test_with_mega_windows(self):
        base = self._tokens()
        assert self._tokens(prefill_depth=4, mega_windows=4) == base

    def test_mixed_lengths_concurrent(self):
        # A short prompt admitted alongside a long one must not disable
        # the amortizer for the long row, and both streams stay correct.
        eng = InferenceEngine(
            "llama-tiny", n_slots=4, max_len=256, window_k=4,
            prefill_chunk=16, prefill_depth=4, tokenizer=ByteTokenizer(),
        )
        ref = InferenceEngine(
            "llama-tiny", n_slots=4, max_len=256, window_k=4,
            prefill_chunk=16, tokenizer=ByteTokenizer(),
        )
        for e in (eng, ref):
            e.start_sync()
        try:
            short = "hi there"
            outs = {}
            for name, e in (("mega", eng), ("ref", ref)):
                reqs = [
                    e.submit_generate(
                        p, max_new_tokens=8, temperature=0.0,
                        stop_on_eos=False,
                    )
                    for p in (self.PROMPT_LONG, short)
                ]
                outs[name] = [
                    r.future.result(timeout=120).token_ids for r in reqs
                ]
            assert outs["mega"] == outs["ref"]
        finally:
            eng.stop_sync()
            ref.stop_sync()
