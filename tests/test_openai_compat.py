"""OpenAI-compatible surface: /v1/completions, /v1/chat/completions
(non-stream + SSE streaming over chunked transfer), /v1/models — wire
shapes an off-the-shelf OpenAI SDK expects."""

from __future__ import annotations

import asyncio
import http.client
import json
import threading

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig
from gofr_tpu.serving.openai_compat import (
    add_openai_routes,
    default_chat_template,
)


@pytest.fixture(scope="module")
def oai_app():
    app = App(config=MockConfig({
        "APP_NAME": "oai-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "128",
    }))
    add_openai_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=60)
    yield app
    asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
    loop.call_soon_threadsafe(loop.stop)


def _conn(app) -> http.client.HTTPConnection:
    return http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=120)


def test_completions_non_stream(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "model": "llama-tiny", "prompt": "hello", "max_tokens": 8,
        "temperature": 0,
    }))
    r = c.getresponse()
    assert r.status == 200  # OpenAI wire-compat: POST answers 200, not 201
    body = json.loads(r.read())
    assert body["object"] == "text_completion"
    assert body["id"].startswith("cmpl-")
    assert body["choices"][0]["finish_reason"] == "stop"
    assert isinstance(body["choices"][0]["text"], str)
    usage = body["usage"]
    assert usage["total_tokens"] == (
        usage["prompt_tokens"] + usage["completion_tokens"]
    )
    assert 1 <= usage["completion_tokens"] <= 8


def test_chat_completions_non_stream(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"},
        ],
        "max_tokens": 6, "temperature": 0,
    }))
    body = json.loads(c.getresponse().read())
    assert body["object"] == "chat.completion"
    msg = body["choices"][0]["message"]
    assert msg["role"] == "assistant"
    assert isinstance(msg["content"], str)


def test_completions_streaming_sse(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "stream me", "max_tokens": 6, "temperature": 0,
        "stream": True,
    }))
    r = c.getresponse()
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    raw = r.read().decode()  # http.client de-chunks transparently
    events = [
        line[len("data: "):]
        for line in raw.split("\n") if line.startswith("data: ")
    ]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(ch["object"] == "text_completion" for ch in chunks)
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    text = "".join(ch["choices"][0]["text"] for ch in chunks)
    assert len(text) > 0


def test_chat_streaming_deltas(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "go"}],
        "max_tokens": 4, "temperature": 0, "stream": True,
    }))
    raw = c.getresponse().read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.split("\n")
        if line.startswith("data: ") and not line.endswith("[DONE]")
    ]
    assert events[0]["choices"][0]["delta"]["role"] == "assistant"
    assert events[-1]["choices"][0]["finish_reason"] == "stop"
    assert all(e["object"] == "chat.completion.chunk" for e in events)


def test_models_endpoint(oai_app):
    c = _conn(oai_app)
    c.request("GET", "/v1/models")
    body = json.loads(c.getresponse().read())
    assert body["object"] == "list"
    ids = {m["id"] for m in body["data"]}
    assert {"llama-tiny", "llama-3-8b", "llama-3-70b"} <= ids
    loaded = [m for m in body["data"] if m["loaded"]]
    assert [m["id"] for m in loaded] == ["llama-tiny"]


def test_bad_requests_are_400(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=b"{not json")
    r = c.getresponse()
    assert r.status == 400
    r.read()  # drain before reusing the keep-alive connection
    c.request("POST", "/v1/chat/completions", body=json.dumps({"messages": []}))
    r = c.getresponse()
    assert r.status == 400
    r.read()


def test_stream_text_matches_non_stream(oai_app):
    """Cumulative UTF-8-safe decode: the streamed deltas concatenate to
    exactly the non-streamed text (ByteTokenizer splits multi-byte
    chars across tokens, so per-token decode would corrupt this)."""
    payload = {"prompt": "match", "max_tokens": 10, "temperature": 0}
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps(payload))
    want = json.loads(c.getresponse().read())["choices"][0]["text"]
    c.request("POST", "/v1/completions",
              body=json.dumps({**payload, "stream": True}))
    raw = c.getresponse().read().decode()
    got = "".join(
        json.loads(line[len("data: "):])["choices"][0]["text"]
        for line in raw.split("\n")
        if line.startswith("data: ") and not line.endswith("[DONE]")
    )
    assert got == want


def test_null_params_and_token_id_prompt(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": [1, 5, 9],  # token-id array form
        "max_tokens": 4, "temperature": None,
    }))
    r = c.getresponse()
    assert r.status == 200
    body = json.loads(r.read())
    assert body["usage"]["prompt_tokens"] == 3


def test_batch_prompts_yield_indexed_choices(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": ["one", "two"], "max_tokens": 3, "temperature": 0,
    }))
    body = json.loads(c.getresponse().read())
    assert [ch["index"] for ch in body["choices"]] == [0, 1]
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": ["one", "two"], "max_tokens": 3, "stream": True,
    }))
    r = c.getresponse()
    assert r.status == 400  # streaming is single-prompt
    r.read()


def test_stream_overlong_prompt_fails_before_headers(oai_app):
    """Prompt validation happens BEFORE the SSE response starts — the
    client gets a real 413, not a dead 200 stream."""
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "x" * 500, "max_tokens": 4, "stream": True,
    }))
    r = c.getresponse()
    assert r.status == 413
    r.read()


def test_default_chat_template():
    out = default_chat_template([
        {"role": "system", "content": "S"},
        {"role": "user", "content": "U"},
    ])
    assert out == "system: S\nuser: U\nassistant:"
