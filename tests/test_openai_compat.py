"""OpenAI-compatible surface: /v1/completions, /v1/chat/completions
(non-stream + SSE streaming over chunked transfer), /v1/models — wire
shapes an off-the-shelf OpenAI SDK expects."""

from __future__ import annotations

import asyncio
import http.client
import json
import threading

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig
from gofr_tpu.serving.openai_compat import (
    add_openai_routes,
    default_chat_template,
)


@pytest.fixture(scope="module")
def oai_app():
    app = App(config=MockConfig({
        "APP_NAME": "oai-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "128",
    }))
    add_openai_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=60)
    yield app
    asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
    loop.call_soon_threadsafe(loop.stop)


def _conn(app) -> http.client.HTTPConnection:
    return http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=120)


def test_completions_non_stream(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "model": "llama-tiny", "prompt": "hello", "max_tokens": 8,
        "temperature": 0,
    }))
    r = c.getresponse()
    assert r.status == 200  # OpenAI wire-compat: POST answers 200, not 201
    body = json.loads(r.read())
    assert body["object"] == "text_completion"
    assert body["id"].startswith("cmpl-")
    # Budget exhausted without eos → "length" (this model never emits eos
    # for this greedy prompt).
    assert body["choices"][0]["finish_reason"] == "length"
    assert isinstance(body["choices"][0]["text"], str)
    usage = body["usage"]
    assert usage["total_tokens"] == (
        usage["prompt_tokens"] + usage["completion_tokens"]
    )
    assert 1 <= usage["completion_tokens"] <= 8


def test_chat_completions_non_stream(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"},
        ],
        "max_tokens": 6, "temperature": 0,
    }))
    body = json.loads(c.getresponse().read())
    assert body["object"] == "chat.completion"
    msg = body["choices"][0]["message"]
    assert msg["role"] == "assistant"
    assert isinstance(msg["content"], str)


def test_completions_streaming_sse(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "stream me", "max_tokens": 6, "temperature": 0,
        "stream": True,
    }))
    r = c.getresponse()
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    raw = r.read().decode()  # http.client de-chunks transparently
    events = [
        line[len("data: "):]
        for line in raw.split("\n") if line.startswith("data: ")
    ]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(ch["object"] == "text_completion" for ch in chunks)
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    text = "".join(ch["choices"][0]["text"] for ch in chunks)
    assert len(text) > 0


def test_chat_streaming_deltas(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "go"}],
        "max_tokens": 4, "temperature": 0, "stream": True,
    }))
    raw = c.getresponse().read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.split("\n")
        if line.startswith("data: ") and not line.endswith("[DONE]")
    ]
    assert events[0]["choices"][0]["delta"]["role"] == "assistant"
    assert events[-1]["choices"][0]["finish_reason"] == "length"
    assert all(e["object"] == "chat.completion.chunk" for e in events)


def test_models_endpoint(oai_app):
    c = _conn(oai_app)
    c.request("GET", "/v1/models")
    body = json.loads(c.getresponse().read())
    assert body["object"] == "list"
    ids = {m["id"] for m in body["data"]}
    assert {"llama-tiny", "llama-3-8b", "llama-3-70b"} <= ids
    loaded = [m for m in body["data"] if m["loaded"]]
    assert [m["id"] for m in loaded] == ["llama-tiny"]


def test_bad_requests_are_400(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=b"{not json")
    r = c.getresponse()
    assert r.status == 400
    r.read()  # drain before reusing the keep-alive connection
    c.request("POST", "/v1/chat/completions", body=json.dumps({"messages": []}))
    r = c.getresponse()
    assert r.status == 400
    r.read()


def test_stream_text_matches_non_stream(oai_app):
    """Cumulative UTF-8-safe decode: the streamed deltas concatenate to
    exactly the non-streamed text (ByteTokenizer splits multi-byte
    chars across tokens, so per-token decode would corrupt this)."""
    payload = {"prompt": "match", "max_tokens": 10, "temperature": 0}
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps(payload))
    want = json.loads(c.getresponse().read())["choices"][0]["text"]
    c.request("POST", "/v1/completions",
              body=json.dumps({**payload, "stream": True}))
    raw = c.getresponse().read().decode()
    got = "".join(
        json.loads(line[len("data: "):])["choices"][0]["text"]
        for line in raw.split("\n")
        if line.startswith("data: ") and not line.endswith("[DONE]")
    )
    assert got == want


def test_null_params_and_token_id_prompt(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": [1, 5, 9],  # token-id array form
        "max_tokens": 4, "temperature": None,
    }))
    r = c.getresponse()
    assert r.status == 200
    body = json.loads(r.read())
    assert body["usage"]["prompt_tokens"] == 3


def test_batch_prompts_yield_indexed_choices(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": ["one", "two"], "max_tokens": 3, "temperature": 0,
    }))
    body = json.loads(c.getresponse().read())
    assert [ch["index"] for ch in body["choices"]] == [0, 1]
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": ["one", "two"], "max_tokens": 3, "stream": True,
    }))
    r = c.getresponse()
    assert r.status == 400  # streaming is single-prompt
    r.read()


def test_stream_overlong_prompt_fails_before_headers(oai_app):
    """Prompt validation happens BEFORE the SSE response starts — the
    client gets a real 413, not a dead 200 stream."""
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "x" * 500, "max_tokens": 4, "stream": True,
    }))
    r = c.getresponse()
    assert r.status == 413
    r.read()


def test_stop_sequences_and_finish_reason(oai_app):
    base = {"prompt": "det", "max_tokens": 10, "temperature": 0}
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps(base))
    first = json.loads(c.getresponse().read())["choices"][0]
    assert first["finish_reason"] == "length"  # budget exhausted, no eos
    full = first["text"]
    assert len(full) >= 2
    marker = full[1:3]  # greedy determinism → same text next time
    c.request("POST", "/v1/completions",
              body=json.dumps({**base, "stop": marker}))
    cut = json.loads(c.getresponse().read())["choices"][0]
    assert cut["finish_reason"] == "stop"
    assert cut["text"] == full[: full.find(marker)]
    assert marker not in cut["text"]
    # Streaming with the same stop cuts identically.
    c.request("POST", "/v1/completions",
              body=json.dumps({**base, "stop": marker, "stream": True}))
    raw = c.getresponse().read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.split("\n")
        if line.startswith("data: ") and not line.endswith("[DONE]")
    ]
    text = "".join(e["choices"][0]["text"] for e in events)
    assert text == cut["text"]
    assert events[-1]["choices"][0]["finish_reason"] == "stop"


def test_n_choices_and_logprobs(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "lp", "max_tokens": 4, "temperature": 0,
        "n": 2, "logprobs": 1,
    }))
    body = json.loads(c.getresponse().read())
    assert [ch["index"] for ch in body["choices"]] == [0, 1]
    lp = body["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == 4
    assert all(isinstance(v, float) and v <= 0.0 for v in lp["token_logprobs"])
    assert len(lp["tokens"]) == 4
    assert body["usage"]["completion_tokens"] == 8  # 2 choices x 4

    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 3, "temperature": 0, "logprobs": True,
    }))
    chat = json.loads(c.getresponse().read())
    content_lp = chat["choices"][0]["logprobs"]["content"]
    assert len(content_lp) == 3
    assert all(e["logprob"] <= 0.0 for e in content_lp)


def test_engine_result_carries_logprobs(oai_app):
    eng = oai_app.container.tpu
    r = eng.generate_sync(
        "lp check", max_new_tokens=5, temperature=0.0, stop_on_eos=False,
        timeout=120,
    )
    assert len(r.token_logprobs) == len(r.token_ids) == 5
    assert all(lp <= 0.0 for lp in r.token_logprobs)


def test_param_validation_limits(oai_app):
    c = _conn(oai_app)

    def post(payload):
        c.request("POST", "/v1/completions", body=json.dumps(payload))
        r = c.getresponse()
        r.read()
        return r.status

    base = {"prompt": "x", "max_tokens": 2}
    assert post({**base, "n": 0}) == 400
    assert post({**base, "n": 1000}) == 400  # unbounded n is a DoS vector
    assert post({**base, "n": 2, "stream": True}) == 400
    assert post({**base, "stop": ""}) == 400  # empty stop matches everything
    assert post({**base, "stop": ["a", "b", "c", "d", "e"]}) == 400


def test_stop_trims_logprobs_aligned(oai_app):
    """Engine-level stop: token/logprob lists are trimmed WITH the text."""
    eng = oai_app.container.tpu
    full = eng.generate_sync(
        "align", max_new_tokens=10, temperature=0.0, stop_on_eos=False,
        timeout=120,
    )
    marker = full.text[2:4]
    cut = eng.generate_sync(
        "align", max_new_tokens=10, temperature=0.0, stop_on_eos=False,
        stop=[marker], timeout=120,
    )
    assert cut.finish_reason == "stop"
    assert cut.text == full.text[: full.text.find(marker)]
    assert len(cut.token_logprobs) == len(cut.token_ids)
    assert len(cut.token_ids) < len(full.token_ids)
    # Trimmed ids decode to a prefix of the kept text.
    assert eng.tokenizer.decode(cut.token_ids) == cut.text[
        : len(eng.tokenizer.decode(cut.token_ids))
    ]
    assert full.finish_reason == "length"


def test_default_chat_template():
    out = default_chat_template([
        {"role": "system", "content": "S"},
        {"role": "user", "content": "U"},
    ])
    assert out == "system: S\nuser: U\nassistant:"


def test_chat_uses_tokenizer_template_when_available():
    """An HF-style tokenizer's own chat template wins over the generic
    flattening; an explicit chat_template arg overrides both."""
    import asyncio as aio

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    class TemplatedTokenizer(ByteTokenizer):
        def apply_chat_template(self, messages):
            return "<tmpl>" + messages[-1]["content"]

    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, tokenizer=TemplatedTokenizer()
    )
    eng.start_sync()
    seen = {}
    orig = eng.submit_generate

    def spy(prompt, **kw):
        seen["prompt"] = prompt
        return orig(prompt, **kw)

    eng.submit_generate = spy
    app = App(config=MockConfig({
        "APP_NAME": "tmpl", "HTTP_PORT": "0", "METRICS_PORT": "0",
    }))
    app.container.tpu = eng
    add_openai_routes(app)
    loop = aio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    aio.run_coroutine_threadsafe(app.start(), loop).result(timeout=30)
    try:
        c = http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=120)
        c.request("POST", "/v1/chat/completions", body=json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2, "temperature": 0,
        }))
        assert c.getresponse().status == 200
        assert seen["prompt"] == "<tmpl>hi"
    finally:
        aio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        eng.stop_sync()


def test_embeddings_endpoint_with_secondary_encoder():
    """TPU_EMBED_MODEL wires a second (encoder) engine into the container;
    /v1/embeddings serves from it while the primary llm serves chat, and
    /v1/models marks both loaded."""
    app = App(config=MockConfig({
        "APP_NAME": "embed-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "128",
        "TPU_EMBED_MODEL": "bert-tiny",
    }))
    add_openai_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=60)
    try:
        assert app.container.tpu_embed is not None
        c = _conn(app)
        c.request("POST", "/v1/embeddings", body=json.dumps({
            "input": ["the cat sat", "on the mat"],
        }))
        r = c.getresponse()
        assert r.status == 200
        body = json.loads(r.read())
        assert body["object"] == "list"
        assert [d["index"] for d in body["data"]] == [0, 1]
        dims = {len(d["embedding"]) for d in body["data"]}
        assert len(dims) == 1 and dims.pop() > 0
        assert body["usage"]["prompt_tokens"] > 0

        c = _conn(app)
        c.request("GET", "/v1/models")
        models = json.loads(c.getresponse().read())["data"]
        loaded = {m["id"] for m in models if m["loaded"]}
        assert loaded == {"llama-tiny", "bert-tiny"}

        # Bad input shape → OpenAI-style 400.
        c = _conn(app)
        c.request("POST", "/v1/embeddings", body=json.dumps({"input": []}))
        assert c.getresponse().status == 400
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)


def test_unknown_model_gets_404(oai_app):
    """Naming a model that isn't the loaded one must 404 (OpenAI wire
    code), never silently serve the loaded model's output."""
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "model": "llama-3-8b", "prompt": "hello", "max_tokens": 4,
    }))
    r = c.getresponse()
    assert r.status == 404
    assert "not loaded" in json.loads(r.read())["error"]["message"]

    # The loaded name (and omitting model entirely) still works.
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "model": "llama-tiny", "max_tokens": 2,
        "messages": [{"role": "user", "content": "hi"}],
    }))
    assert c.getresponse().status == 200


def test_top_p_zero_maps_to_greedy(oai_app):
    """OpenAI accepts top_p=0 (smallest nucleus = argmax) — it must work
    even on an engine compiled without the nucleus sampler, as greedy."""
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "greedy via top_p", "max_tokens": 4, "top_p": 0,
    }))
    r = c.getresponse()
    assert r.status == 200
    assert json.loads(r.read())["usage"]["completion_tokens"] >= 1


def test_completions_penalties(oai_app):
    # The engine behind oai_app is compiled WITHOUT TPU_PENALTIES: the
    # OpenAI-shaped error must say so (400), mirroring the top_p gate.
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "model": "llama-tiny", "prompt": "hello", "max_tokens": 4,
        "temperature": 0, "frequency_penalty": 0.8,
    }))
    r = c.getresponse()
    body = json.loads(r.read())
    assert r.status == 400
    assert "TPU_PENALTIES" in json.dumps(body)
    c.close()

    app = App(config=MockConfig({
        "APP_NAME": "oai-pen", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "128", "TPU_PENALTIES": "true",
    }))
    add_openai_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=60)
    try:
        c = _conn(app)
        c.request("POST", "/v1/completions", body=json.dumps({
            "model": "llama-tiny", "prompt": "hello", "max_tokens": 8,
            "temperature": 0, "frequency_penalty": 1.5,
        }))
        r = c.getresponse()
        assert r.status == 200
        out = json.loads(r.read())
        assert out["choices"][0]["text"]
        c.close()
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)


def test_completions_top_logprobs():
    app = App(config=MockConfig({
        "APP_NAME": "oai-lp", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "128", "TPU_TOP_LOGPROBS": "4",
    }))
    add_openai_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=60)
    try:
        c = _conn(app)
        # completions: logprobs=N → N alternatives per token.
        c.request("POST", "/v1/completions", body=json.dumps({
            "prompt": "hello", "max_tokens": 4, "temperature": 0,
            "logprobs": 3,
        }))
        r = c.getresponse()
        assert r.status == 200
        lp = json.loads(r.read())["choices"][0]["logprobs"]
        assert len(lp["top_logprobs"]) == 4
        # Keyed by decoded token STRING (the OpenAI completions schema):
        # distinct ids may decode identically and collapse, so <= 3.
        assert all(1 <= len(d) <= 3 for d in lp["top_logprobs"])
        # chat: logprobs=true + top_logprobs=N.
        c.request("POST", "/v1/chat/completions", body=json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 3, "temperature": 0,
            "logprobs": True, "top_logprobs": 2,
        }))
        r = c.getresponse()
        assert r.status == 200
        content = json.loads(r.read())["choices"][0]["logprobs"]["content"]
        assert len(content) == 3
        assert all(len(e["top_logprobs"]) == 2 for e in content)
        c.close()
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)


def test_completions_logprobs_backcompat_without_flag(oai_app):
    # logprobs=N on an engine WITHOUT TPU_TOP_LOGPROBS must stay a 200
    # with null alternatives (pre-flag behavior), never a 400.
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "hello", "max_tokens": 3, "temperature": 0,
        "logprobs": 2,
    }))
    r = c.getresponse()
    assert r.status == 200
    lp = json.loads(r.read())["choices"][0]["logprobs"]
    assert lp["top_logprobs"] is None
    assert len(lp["token_logprobs"]) == 3
    c.close()


def test_stream_options_include_usage(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "hi", "max_tokens": 4, "temperature": 0, "stream": True,
        "stream_options": {"include_usage": True},
    }))
    r = c.getresponse()
    assert r.status == 200
    raw = r.read().decode()
    chunks = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    assert raw.rstrip().endswith("data: [DONE]")
    usage_chunks = [ch for ch in chunks if "usage" in ch]
    assert len(usage_chunks) == 1
    u = usage_chunks[0]
    assert u["choices"] == []
    assert u["usage"]["completion_tokens"] == 4
    assert u["usage"]["total_tokens"] == (
        u["usage"]["prompt_tokens"] + 4
    )
    c.close()


def test_chat_top_logprobs_backcompat_without_flag(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 3, "temperature": 0,
        "logprobs": True, "top_logprobs": 2,
    }))
    r = c.getresponse()
    assert r.status == 200
    content = json.loads(r.read())["choices"][0]["logprobs"]["content"]
    assert all(e["top_logprobs"] == [] for e in content)
    c.close()


def test_completions_echo(oai_app):
    c = _conn(oai_app)
    c.request("POST", "/v1/completions", body=json.dumps({
        "prompt": "hello there", "max_tokens": 3, "temperature": 0,
        "echo": True,
    }))
    r = c.getresponse()
    assert r.status == 200
    text = json.loads(r.read())["choices"][0]["text"]
    assert text.startswith("hello there")
    assert len(text) > len("hello there")
    c.close()
