"""Prefix-KV reuse (serving/prefix_cache.py): shared system prompts
prefill once; later requests admission-copy the pooled rows and must
generate EXACTLY what full prefill would have (same cache values, same
global positions — chunk boundaries don't change the math)."""

from __future__ import annotations

import jax.numpy as jnp
import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer

SYSTEM = "You are a terse assistant. Answer in one word. "


def _engine(**kw):
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        **kw,
    )
    eng.start_sync()
    return eng


def test_prefix_reuse_matches_full_prefill():
    ref = _engine()
    try:
        want = ref.generate_sync(
            SYSTEM + "hi", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        ref.stop_sync()

    eng = _engine(prefix_slots=2)
    try:
        idx = eng.register_prefix_sync(SYSTEM)
        assert idx == 0
        assert len(eng._prefix_pool) == 1
        got = eng.generate_sync(
            SYSTEM + "hi", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
        # Second request re-hits the pool (fresh slot, same rows).
        again = eng.generate_sync(
            SYSTEM + "hi", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        eng.stop_sync()
    assert got.token_ids == want.token_ids
    assert again.token_ids == want.token_ids


def test_prefix_reuse_with_int8_kv_cache():
    ref = _engine(kv_quant="int8")
    try:
        want = ref.generate_sync(
            SYSTEM + "go", max_new_tokens=6, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        ref.stop_sync()
    eng = _engine(prefix_slots=1, kv_quant="int8")
    try:
        eng.register_prefix_sync(SYSTEM)
        got = eng.generate_sync(
            SYSTEM + "go", max_new_tokens=6, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        eng.stop_sync()
    assert got.token_ids == want.token_ids


def test_prefix_miss_and_exact_prompt():
    eng = _engine(prefix_slots=1)
    try:
        eng.register_prefix_sync(SYSTEM)
        # Prompt IS the prefix exactly — still generates (final token
        # chunk re-runs to sample).
        r = eng.generate_sync(
            SYSTEM, max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        assert len(r.token_ids) == 4
        # Unrelated prompt: plain miss, still correct.
        miss = eng.generate_sync(
            "completely different", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False,
        )
        assert len(miss.token_ids) == 4
    finally:
        eng.stop_sync()


def test_prefix_lru_eviction():
    eng = _engine(prefix_slots=1)
    try:
        eng.register_prefix_sync("prefix one ")
        idx2 = eng.register_prefix_sync("prefix two ")
        assert idx2 == 0  # evicted row reused
        assert len(eng._prefix_pool) == 1
        assert eng._prefix_pool.lookup(
            eng.tokenizer.encode("prefix one and more")
        ) == (-1, 0)
    finally:
        eng.stop_sync()


def test_prefix_pool_disabled_raises():
    eng = _engine()
    try:
        with pytest.raises(RuntimeError, match="prefix pool disabled"):
            eng.register_prefix("nope")
    finally:
        eng.stop_sync()


def test_prefix_via_config_and_longest_match():
    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "128", "TPU_PREFIX_SLOTS": "2",
    }))
    eng.tokenizer = ByteTokenizer()
    eng.start_sync()
    try:
        short = eng.register_prefix_sync("abcd")
        long = eng.register_prefix_sync("abcdefgh")
        ids = eng.tokenizer.encode("abcdefghij")
        idx, plen = eng._prefix_pool.lookup(ids)
        # longest match wins
        assert idx == long and plen == len(eng.tokenizer.encode("abcdefgh"))
        idx, plen = eng._prefix_pool.lookup(eng.tokenizer.encode("abcdx"))
        assert idx == short and plen == len(eng.tokenizer.encode("abcd"))
    finally:
        eng.stop_sync()


def test_prefix_pool_rows_are_real_kv():
    """The pool row holds the slot's actual K rows (not zeros)."""
    eng = _engine(prefix_slots=1)
    try:
        eng.register_prefix_sync(SYSTEM)
        pk = eng._prefix_pool._pool[0]
        plen = len(eng.tokenizer.encode(SYSTEM))
        assert float(jnp.abs(pk[0, 0, :, :plen]).max()) > 0.0
    finally:
        eng.stop_sync()


def test_prefix_pool_on_cp_mesh():
    """Prefix reuse on a cp-only mesh (no 'tp' axis): the pool must build
    with the same pruned, cp-aware shardings as the cache (regression —
    unpruned specs raised on the missing tp axis) and still serve."""
    cfg = MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "64", "TPU_MESH_CP": "2", "TPU_PREFIX_SLOTS": "2",
    })
    eng = InferenceEngine.from_config(cfg)
    assert "cp" in str(eng._prefix_pool._pool[0].sharding.spec)
    eng.start_sync()
    try:
        idx = eng.register_prefix_sync("System: be nice. ")
        assert idx >= 0
        r = eng.generate_sync(
            "System: be nice. hi", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False,
        )
        assert len(r.token_ids) == 4
    finally:
        eng.stop_sync()
