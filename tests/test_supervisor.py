"""Chaos suite for the engine supervisor (ISSUE 3 acceptance gate).

Self-healing serving: every recovery path is driven deterministically
through the existing fault-injection points (``gofr_tpu/faults``) — no
TPU, no sleeps-as-synchronization. Stalls are test-controlled
``threading.Event``s, the watchdog trips by *stating* a time
(``check(now=)``), backoff waits go through an injectable sleep that
records instead of sleeping, and the crash-loop clock is a fake.

Covered:

* a device crash mid-generation → supervisor warm-restarts within the
  backoff policy → the still-streaming request REPLAYS and completes
  with the full, correct token sequence (no duplicates, no gaps),
  while ``app_tpu_engine_restarts_total`` /
  ``app_tpu_requests_replayed_total`` and the
  SERVING→RESTARTING→SERVING transitions are asserted;
* a WEDGED scheduler (hung device step) → watchdog trip → the thread
  is abandoned behind the epoch fence and the engine restarts around
  it — including the zombie's eventual wake-up being inert;
* a crash-looping engine (fault armed forever) lands in DOWN after
  ``TPU_RESTART_MAX`` attempts instead of restarting forever;
* non-retryable requests (expired deadline) get the existing terminal
  error while retryable neighbors are carried across the restart;
* SSE streams resume from the last emitted token across a restart —
  same bytes as a fault-free run, no error event;
* the reused Watchdog instance re-arms cleanly after trip + restart.
"""

from __future__ import annotations

import asyncio
import json
import random
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.errors import ErrorServiceUnavailable
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.lifecycle import Deadline
from gofr_tpu.serving.supervisor import EngineSupervisor
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.serving.types import _GenRequest
from gofr_tpu.serving.watchdog import Watchdog

SUPERVISOR_INSTRUMENTS = (
    "app_tpu_engine_restarts_total",
    "app_tpu_requests_replayed_total",
    "app_tpu_watchdog_trips_total",
    "app_tpu_requests_shed_total",
    "app_tpu_requests_cancelled_total",
    "app_tpu_deadline_exceeded_total",
    "app_tpu_tokens_generated",
    "app_tpu_prefix_hits",
)


def _metrics_manager():
    m = new_metrics_manager()
    for name in SUPERVISOR_INSTRUMENTS:
        m.new_counter(name)
    for name in ("app_tpu_engine_state", "app_tpu_queue_depth",
                 "app_tpu_kv_slots_in_use", "app_tpu_hbm_used_bytes",
                 "app_tpu_kv_blocks_free"):
        m.new_gauge(name)
    for name in ("app_tpu_infer_latency", "app_tpu_batch_size",
                 "app_tpu_spec_tokens_per_step"):
        m.new_histogram(name)
    return m


def counter_total(metrics, name: str) -> float:
    inst = {i.name: i for i in metrics.instruments()}[name]
    return sum(inst.collect().values())


def gauge_value(metrics, name: str) -> float:
    inst = {i.name: i for i in metrics.instruments()}[name]
    values = list(inst.collect().values())
    return values[-1] if values else -1.0


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _drain_stream(req, timeout=120.0) -> list[int]:
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _wait_until(cond, timeout=30.0) -> bool:
    """Poll a host-side condition a background thread publishes. The
    ordering edges in these tests are stream sentinels and futures; this
    only absorbs the supervisor's final bookkeeping writes."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


def _make_supervised(metrics, *, max_restarts=3, watchdog_s=0.0,
                     join_timeout_s=5.0, clock=time.monotonic, **eng_kw):
    """One engine + supervisor with every timing seam injected: the
    sleep hook records (engine state, delay) instead of sleeping, so
    backoff never adds wall clock and RESTARTING is observable."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        watchdog_s=watchdog_s, metrics=metrics, **eng_kw,
    )
    sleeps: list[tuple[str, float]] = []
    sup = EngineSupervisor(
        eng,
        max_restarts=max_restarts,
        backoff_s=0.25,
        backoff_reset_s=60.0,
        join_timeout_s=join_timeout_s,
        clock=clock,
        rng=random.Random(1234),
        sleep=lambda s: sleeps.append((eng.state, s)),
        metrics=metrics,
    ).start()
    eng.start_sync()
    return eng, sup, sleeps


# ----------------------------------------------------------------------
# policy units: backoff + retryability
# ----------------------------------------------------------------------


def test_backoff_policy_exponential_jittered_capped():
    class _Eng:  # policy math needs no real engine
        def attach_supervisor(self, sup):
            pass

    sup = EngineSupervisor(
        _Eng(), max_restarts=5, backoff_s=1.0, backoff_cap_s=8.0,
        rng=random.Random(7),
    )
    delays = [sup.backoff_delay(a) for a in range(6)]
    for attempt, d in enumerate(delays):
        base = min(8.0, 1.0 * 2 ** attempt)
        # Jitter scales into [50%, 100%] of the exponential base.
        assert base * 0.5 <= d <= base, (attempt, d)
    # The cap holds: attempts 3+ (base 8.0) never exceed 8s.
    assert max(delays[3:]) <= 8.0
    # Jitter actually varies (not a constant factor).
    ratios = {round(d / min(8.0, 2 ** a), 6) for a, d in enumerate(delays)}
    assert len(ratios) > 1


def test_replay_state_retryability_rules():
    req = _GenRequest(
        prompt_ids=[1, 2, 3], max_new_tokens=10, temperature=0.5,
        stop_on_eos=True, top_p=0.9, seed=42, stop_texts=["END"],
    )
    req.token_ids.extend([7, 8])
    snap = req.replay_state()
    assert snap is not None
    assert snap.prompt_ids == [1, 2, 3]
    assert snap.emitted_ids == [7, 8]
    assert snap.remaining_tokens == 8
    assert (snap.temperature, snap.top_p, snap.seed) == (0.5, 0.9, 42)
    assert snap.stop_texts == ["END"]
    # prefill_ids covers the delivered continuation.
    assert req.prefill_ids() == [1, 2, 3, 7, 8]

    # Cancelled → not retryable.
    req.cancel.cancel()
    assert req.replay_state() is None

    # Expired deadline → not retryable (fake clock states the expiry).
    now = [0.0]
    req2 = _GenRequest(
        prompt_ids=[1], max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, deadline=Deadline(10.0, clock=lambda: now[0]),
    )
    assert req2.replay_state() is not None
    now[0] = 11.0
    assert req2.replay_state() is None

    # Prefix registrations → never replayed (pool rows died with the
    # engine; the caller re-registers).
    req3 = _GenRequest(
        prompt_ids=[1], max_new_tokens=1, temperature=0.0,
        stop_on_eos=False, prefix_store=True,
    )
    assert req3.replay_state() is None

    # Resolved future → nothing to carry.
    req4 = _GenRequest(
        prompt_ids=[1], max_new_tokens=4, temperature=0.0,
        stop_on_eos=False,
    )
    req4.future.set_result(object())
    assert req4.replay_state() is None


# ----------------------------------------------------------------------
# the acceptance path: device crash mid-generation → seamless recovery
# ----------------------------------------------------------------------


def test_device_crash_mid_generation_recovers_seamlessly(metrics):
    eng, sup, sleeps = _make_supervised(metrics)
    try:
        restarts0 = counter_total(metrics, "app_tpu_engine_restarts_total")
        replays0 = counter_total(metrics, "app_tpu_requests_replayed_total")
        # Warm the compile caches, and produce the fault-free REFERENCE
        # sequence (greedy: deterministic given the same warm params).
        ref = eng.generate_sync(
            "the quick brown fox", max_new_tokens=40, temperature=0.0,
            stop_on_eos=False,
        )
        assert len(ref.token_ids) == 40
        assert eng.state == "SERVING"

        # The device dies at the 5th dispatch — deterministically MID-
        # generation (hit 1 is the prefill chunk, hits 2-4 the first
        # three pipelined windows; window 1's 8 tokens are processed and
        # on the stream before hit 5 fires), exactly once.
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("injected device loss"),
            after=4, times=1,
        )
        req = eng.submit_generate(
            "the quick brown fox", max_new_tokens=40, temperature=0.0,
            stop_on_eos=False,
        )
        # The client consumes tokens BEFORE the crash lands, so the
        # recovery is provably a continuation, not a fresh retry.
        pre = [req.stream.get(timeout=120) for _ in range(3)]
        assert all(t is not None for t in pre)
        rest = _drain_stream(req)
        result = req.future.result(timeout=120)

        # Full, correct token sequence: what the client streamed is
        # exactly the fault-free reference — nothing duplicated by the
        # re-prefill, nothing dropped by the crash.
        assert pre + rest == ref.token_ids
        assert result.token_ids == ref.token_ids
        assert result.finish_reason == ref.finish_reason
        assert req.replays == 1

        # State machine walked SERVING → RESTARTING → SERVING: the
        # backoff hook observed RESTARTING, and recovery re-entered
        # SERVING (where new submissions work again).
        assert [s for s, _ in sleeps] == ["RESTARTING"]
        assert _wait_until(lambda: eng.state == "SERVING")
        # Backoff policy respected: first attempt waits within
        # [0.5, 1.0] × backoff_s.
        assert 0.125 <= sleeps[0][1] <= 0.25
        assert sup.restarts == 1
        assert counter_total(
            metrics, "app_tpu_engine_restarts_total"
        ) == restarts0 + 1
        assert counter_total(
            metrics, "app_tpu_requests_replayed_total"
        ) == replays0 + 1

        # Params were warm-reused, not re-initialized: the restarted
        # engine still greedy-decodes the identical sequence.
        again = eng.generate_sync(
            "the quick brown fox", max_new_tokens=40, temperature=0.0,
            stop_on_eos=False,
        )
        assert again.token_ids == ref.token_ids
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


def test_watchdog_trip_wedged_scheduler_abandoned_and_replayed(metrics):
    """A HUNG device step (not a raise): the watchdog trips, the
    supervisor cannot join the wedged thread, abandons it behind the
    epoch fence, restarts, and replays — and the zombie's eventual
    wake-up is inert (SchedulerSuperseded, no drain, no flag damage)."""
    eng, sup, sleeps = _make_supervised(
        metrics, watchdog_s=300.0, join_timeout_s=0.05,
    )
    try:
        trips0 = counter_total(metrics, "app_tpu_watchdog_trips_total")
        ref = eng.generate_sync(
            "wedge me", max_new_tokens=24, temperature=0.0,
            stop_on_eos=False,
        )
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall(**kw):
            gate_in.set()
            gate_out.wait(timeout=120)
            # Returning (not raising) models a wedged call that finally
            # completes: the epoch check right after the seam must turn
            # it into a silent SchedulerSuperseded exit.

        # Hang the 4th device dispatch (mid-generation), exactly once.
        faults.arm("scheduler.device_step", action=stall, after=3, times=1)
        req = eng.submit_generate(
            "wedge me", max_new_tokens=24, temperature=0.0,
            stop_on_eos=False,
        )
        assert gate_in.wait(60)  # the "device step" is now hung
        old_sched = eng._sched
        # Deterministic trip: state a time past the bound.
        assert eng._watchdog.check(
            now=time.monotonic() + eng._watchdog.bound_s + 1
        )
        # Recovery completes WHILE the old thread is still wedged.
        rest = _drain_stream(req)
        result = req.future.result(timeout=120)
        assert rest == ref.token_ids
        assert result.token_ids == ref.token_ids
        assert counter_total(
            metrics, "app_tpu_watchdog_trips_total"
        ) == trips0 + 1
        assert _wait_until(lambda: eng.state == "SERVING")
        assert eng._sched is not old_sched

        # Release the zombie: it must exit via the epoch fence without
        # draining or flipping the restarted engine's flags.
        gate_out.set()
        assert _wait_until(lambda: not old_sched.is_alive())
        assert eng._running and eng._fatal is None
        assert eng.state == "SERVING"
        after = eng.generate_sync(
            "wedge me", max_new_tokens=24, temperature=0.0,
            stop_on_eos=False,
        )
        assert after.token_ids == ref.token_ids
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


def test_watchdog_rearms_on_restarted_engine(metrics):
    """Satellite: a tripped-then-reset Watchdog (the supervisor reuses
    ONE instance across restarts) must re-arm cleanly — monitor thread
    alive, latch clear, and able to trip again."""
    eng, sup, _ = _make_supervised(
        metrics, watchdog_s=300.0, join_timeout_s=0.05,
    )
    try:
        wd = eng._watchdog
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall(**kw):
            gate_in.set()
            gate_out.wait(timeout=120)

        faults.arm("scheduler.device_step", action=stall, after=1, times=1)
        req = eng.submit_generate(
            "arm, trip, re-arm", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
        assert gate_in.wait(60)
        assert wd.check(now=time.monotonic() + wd.bound_s + 1)
        assert wd.tripped
        _drain_stream(req)
        req.future.result(timeout=120)
        gate_out.set()
        assert _wait_until(lambda: eng.state == "SERVING")
        # Same instance, fresh latch, live monitor — re-armed on the
        # restarted engine (the unit test below proves the reset →
        # start → re-trip cycle on the class itself).
        assert eng._watchdog is wd
        assert not wd.tripped and wd.reason == ""
        assert wd._thread is not None and wd._thread.is_alive()
        # Fresh pet baseline: no stale-pet instant re-trip.
        assert not wd.check()
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


def test_watchdog_unit_reset_restarts_monitor():
    """Satellite (unit half): trip → monitor thread exits (latched);
    reset + start must give a live monitor and a clean latch, petting
    from zero — the exact sequence start_sync runs on the reused
    instance."""
    clock = [0.0]
    trips = []
    wd = Watchdog(
        5.0, clock=lambda: clock[0], on_trip=trips.append,
        check_interval_s=0.01,
    )
    wd.start()
    try:
        clock[0] = 100.0  # way past the bound: monitor trips and exits
        assert _wait_until(lambda: wd.tripped, timeout=10)
        assert _wait_until(
            lambda: wd._thread is None or not wd._thread.is_alive(),
            timeout=10,
        )
        assert len(trips) == 1
        # Engine-restart sequence: reset() then start().
        wd.reset()
        assert not wd.tripped and wd.reason == ""
        wd.start()
        assert wd._thread is not None and wd._thread.is_alive()
        assert not wd.check(now=clock[0] + 4.9)  # fresh pet baseline
        assert wd.check(now=clock[0] + 5.1)  # and it can trip AGAIN
        assert len(trips) == 2
    finally:
        wd.stop()


# ----------------------------------------------------------------------
# crash loop → DOWN after TPU_RESTART_MAX
# ----------------------------------------------------------------------


def test_crash_loop_lands_down_after_restart_max(metrics):
    eng, sup, sleeps = _make_supervised(metrics, max_restarts=3)
    try:
        restarts0 = counter_total(metrics, "app_tpu_engine_restarts_total")
        eng.generate_sync(
            "warm", max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        # Park the scheduler at the top of its loop so the submit lands
        # BEFORE the crash deterministically, then swap the stall for a
        # persistent raise: every scheduler — including each restarted
        # one — dies on its next loop iteration (times=None → forever).
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall(**kw):
            gate_in.set()
            gate_out.wait(timeout=120)

        faults.arm("scheduler.window", action=stall, times=1)
        assert gate_in.wait(30)
        req = eng.submit_generate(
            "doomed", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
        faults.arm(
            "scheduler.window", raises=RuntimeError("persistent fault")
        )
        gate_out.set()
        assert _wait_until(lambda: eng.state == "DOWN", timeout=60)
        # Exactly max_restarts attempts — then it STOPPED retrying.
        assert sup.restarts == 3
        assert sup.consecutive_failures == 3
        assert counter_total(
            metrics, "app_tpu_engine_restarts_total"
        ) == restarts0 + 3
        assert len(sleeps) == 3
        # Exponential growth across attempts (jitter can't mask 2×:
        # max jittered delay of attempt n is the min of attempt n+2).
        assert sleeps[2][1] > sleeps[0][1]
        # The carried request fails with the crash-loop terminal error,
        # stream closed (sentinel delivered) — no hanging client.
        with pytest.raises(ErrorServiceUnavailable, match="DOWN after 3"):
            req.future.result(timeout=30)
        _drain_stream(req)  # terminates: the sentinel was delivered
        # Health surfaces it: status DOWN, state machine DOWN, gauge 3.
        health = eng.health_check()
        assert health["status"] == "DOWN"
        assert health["state"] == "DOWN"
        assert health["details"]["state"] == "DOWN"
        assert health["details"]["supervisor"]["consecutive_failures"] == 3
        assert gauge_value(metrics, "app_tpu_engine_state") == 3
        # New submissions are rejected, not queued into the void.
        with pytest.raises(Exception):
            eng.submit_generate("rejected", max_new_tokens=2)
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


def test_give_up_on_wedged_scheduler_fails_all_live_requests(metrics):
    """Budget exhausted by a watchdog trip whose scheduler is WEDGED:
    the thread never drains, so _give_up itself must tear down, salvage
    the queue/slot structures, and fail every live caller with the
    crash-loop error — DOWN may never strand a request."""
    eng, sup, _ = _make_supervised(
        metrics, max_restarts=1, watchdog_s=300.0, join_timeout_s=0.05,
    )
    try:
        eng.generate_sync(
            "warm", max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        # Failure 1 (fatal crash): consumes the whole budget of 1.
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall(**kw):
            gate_in.set()
            gate_out.wait(timeout=120)

        faults.arm("scheduler.window", action=stall, times=1)
        assert gate_in.wait(30)
        rider = eng.submit_generate(
            "first crash rider", max_new_tokens=6, temperature=0.0,
            stop_on_eos=False,
        )
        faults.arm(
            "scheduler.window", raises=RuntimeError("first crash"), times=1
        )
        gate_out.set()
        assert rider.future.result(timeout=120) is not None
        assert _wait_until(lambda: sup.restarts == 1)

        # Failure 2 (wedge + trip, inside the stability window): budget
        # is gone, and the wedged thread will never run its drain.
        gate_in2, gate_out2 = threading.Event(), threading.Event()

        def stall2(**kw):
            gate_in2.set()
            gate_out2.wait(timeout=120)

        faults.arm("scheduler.device_step", action=stall2, times=1)
        stranded = eng.submit_generate(
            "stranded unless give_up salvages", max_new_tokens=6,
            temperature=0.0, stop_on_eos=False,
        )
        assert gate_in2.wait(60)
        assert eng._watchdog.check(
            now=time.monotonic() + eng._watchdog.bound_s + 1
        )
        with pytest.raises(ErrorServiceUnavailable, match="DOWN after 1"):
            stranded.future.result(timeout=120)
        _drain_stream(stranded)  # sentinel delivered — no hanging client
        assert _wait_until(lambda: eng.state == "DOWN")
        gate_out2.set()  # release the zombie; the epoch fence absorbs it
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


def test_stop_mid_recovery_fails_parked_requests(metrics):
    """Shutdown while a recovery is parked in its backoff wait: the
    salvaged request must fail with the explicit shutdown error —
    nothing will ever requeue it, and a stopped supervisor must not
    leave a client hanging on an open stream/future."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        metrics=metrics,
    )
    sleep_entered, sleep_release = threading.Event(), threading.Event()

    def blocking_sleep(seconds):
        sleep_entered.set()
        sleep_release.wait(timeout=60)

    sup = EngineSupervisor(
        eng, max_restarts=3, backoff_s=0.25, rng=random.Random(1),
        sleep=blocking_sleep, metrics=metrics,
    ).start()
    eng.start_sync()
    try:
        eng.generate_sync(
            "warm", max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall(**kw):
            gate_in.set()
            gate_out.wait(timeout=120)

        faults.arm("scheduler.window", action=stall, times=1)
        assert gate_in.wait(30)
        rider = eng.submit_generate(
            "parked by shutdown", max_new_tokens=6, temperature=0.0,
            stop_on_eos=False,
        )
        faults.arm(
            "scheduler.window", raises=RuntimeError("crash then stop"),
            times=1,
        )
        gate_out.set()
        # Recovery salvaged the rider and is parked in its backoff wait.
        assert sleep_entered.wait(30)
        stopper = threading.Thread(target=sup.stop)
        stopper.start()
        assert _wait_until(lambda: sup._stopping)
        sleep_release.set()
        stopper.join(timeout=30)
        assert not stopper.is_alive()
        with pytest.raises(ErrorServiceUnavailable, match="shutting down"):
            rider.future.result(timeout=30)
        _drain_stream(rider)  # sentinel delivered — no hanging client
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


def test_start_after_stop_resets_stopping_latch(metrics):
    """A supervisor restarted after stop() must supervise again: start()
    resets the ``_stopping`` latch (under ``_lock``, like every other
    write to it — a lock-free reset could interleave into a concurrent
    stop() between its flag write and its event set, resurrecting a
    supervisor the operator is tearing down; this is the write GL020
    caught). The observable contract: after start(), ``stopping`` is
    False, so the scheduler's death drain offers salvage again."""
    eng, sup, _ = _make_supervised(metrics)
    try:
        sup.stop()
        assert sup.stopping
        sup.start()
        assert not sup.stopping
        assert sup._thread is not None and sup._thread.is_alive()
    finally:
        sup.stop()
        eng.stop_sync()


def test_stable_period_resets_crash_loop_counter(metrics):
    """Two crashes separated by a stable period must each count from a
    fresh window (injectable clock states the stability, no sleeping)."""
    now = [1000.0]
    eng, sup, sleeps = _make_supervised(
        metrics, max_restarts=2, clock=lambda: now[0]
    )
    try:
        eng.generate_sync(
            "warm", max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )

        def crash_with_rider(prompt, exc):
            """Park the loop, submit a rider, swap the stall for a
            one-shot raise: the crash deterministically lands with the
            rider in flight, and the replay completes it."""
            gate_in, gate_out = threading.Event(), threading.Event()

            def stall(**kw):
                gate_in.set()
                gate_out.wait(timeout=120)

            faults.arm("scheduler.window", action=stall, times=1)
            assert gate_in.wait(30)
            req = eng.submit_generate(
                prompt, max_new_tokens=6, temperature=0.0, stop_on_eos=False
            )
            faults.arm("scheduler.window", raises=exc, times=1)
            gate_out.set()
            return req

        req = crash_with_rider("ride one", RuntimeError("crash one"))
        assert req.future.result(timeout=120) is not None
        assert _wait_until(lambda: sup.restarts == 1)
        assert sup.consecutive_failures == 1

        now[0] += 120.0  # > backoff_reset_s: the engine proved stable
        req2 = crash_with_rider("ride two", RuntimeError("crash two"))
        assert req2.future.result(timeout=120) is not None
        assert _wait_until(lambda: sup.restarts == 2)
        # Crash two was attempt 1 of a NEW window, not attempt 2: the
        # engine is nowhere near DOWN (max_restarts=2 would have been
        # exhausted without the reset).
        assert sup.consecutive_failures == 1
        assert eng.state == "SERVING"
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


# ----------------------------------------------------------------------
# non-retryable requests keep the existing terminal error path
# ----------------------------------------------------------------------


def test_non_retryable_requests_fail_while_retryable_replay(metrics):
    eng, sup, _ = _make_supervised(metrics)
    try:
        ref = eng.generate_sync(
            "retryable one", max_new_tokens=16, temperature=0.0,
            stop_on_eos=False,
        )
        # Park the scheduler at the top of its loop so both requests sit
        # in the queue when the crash hits.
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall(**kw):
            gate_in.set()
            gate_out.wait(timeout=120)

        clock = [0.0]
        with faults.armed("scheduler.window", action=stall, times=1):
            assert gate_in.wait(30)
            live = eng.submit_generate(
                "retryable one", max_new_tokens=16, temperature=0.0,
                stop_on_eos=False,
            )
            dead = eng.submit_generate(
                "expired one", max_new_tokens=16, temperature=0.0,
                stop_on_eos=False,
                deadline=Deadline(3600.0, clock=lambda: clock[0]),
            )
            clock[0] = 7200.0  # 'dead' expires while queued
            # The next iteration crashes: the drain must salvage `live`
            # and fail `dead` through the existing terminal path.
            faults.arm(
                "scheduler.device_step",
                raises=RuntimeError("crash with mixed queue"), times=1,
            )
            gate_out.set()
        result = live.future.result(timeout=120)
        assert result.token_ids == ref.token_ids
        # The unconsumed stream carries the complete sequence too.
        assert _drain_stream(live) == ref.token_ids
        with pytest.raises(Exception) as excinfo:
            dead.future.result(timeout=120)
        # Existing terminal semantics: the expired request is NOT
        # replayed; it fails (deadline reap or the crash error,
        # whichever path got it first) and its stream closes.
        assert not isinstance(excinfo.value, ErrorServiceUnavailable)
        assert _drain_stream(dead) == []
        assert live.replays >= 1
        assert dead.replays == 0
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


# ----------------------------------------------------------------------
# SSE continuity across a restart
# ----------------------------------------------------------------------


class _RouteRecorder:
    """Just enough App surface for add_openai_routes."""

    def __init__(self):
        self.routes = {}

    def _verb(self, method, path):
        def deco(fn):
            self.routes[(method, path)] = fn
            return fn

        return deco

    def post(self, path):
        return self._verb("POST", path)

    def get(self, path):
        return self._verb("GET", path)


class _FakeCtx:
    def __init__(self, engine, body, deadline=None, cancel=None):
        import types

        self.container = types.SimpleNamespace(tpu=engine, tpu_embed=None)
        self.request = types.SimpleNamespace(
            raw=types.SimpleNamespace(body=json.dumps(body).encode())
        )
        self.deadline = deadline
        self.cancel_token = cancel


def test_sse_stream_resumes_across_restart(metrics):
    """The client-visible contract: one SSE stream, opened before the
    crash, carries the complete completion — the restart is invisible
    (no error event, text identical to a fault-free run)."""
    from gofr_tpu.serving.openai_compat import add_openai_routes

    eng, sup, _ = _make_supervised(metrics)
    try:
        ref = eng.generate_sync(
            "stream across the crash", max_new_tokens=32, temperature=0.0,
            stop_on_eos=False,
        )
        app = _RouteRecorder()
        add_openai_routes(app)
        handler = app.routes[("POST", "/v1/completions")]
        ctx = _FakeCtx(eng, {
            "prompt": "stream across the crash", "max_tokens": 32,
            "temperature": 0, "stream": True,
        })
        # The device dies mid-generation (4th dispatch), exactly once —
        # armed BEFORE the submit so the hit count, not wall clock,
        # decides where the crash lands.
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("mid-SSE device loss"),
            after=3, times=1,
        )

        async def run():
            stream = await handler(ctx)
            events = []
            async for chunk in stream.chunks:
                events.append(chunk)
            return events

        events = asyncio.run(run())
        assert events[-1] == "data: [DONE]\n\n"
        payloads = [
            json.loads(e[len("data: "):])
            for e in events if e.startswith("data: {")
        ]
        assert not [p for p in payloads if "error" in p], (
            "a replayed stream must NOT surface an error event"
        )
        text = "".join(
            c.get("text", "")
            for p in payloads for c in p.get("choices", [])
        )
        finish = [
            c["finish_reason"]
            for p in payloads for c in p.get("choices", [])
            if c.get("finish_reason")
        ]
        assert text == ref.text
        assert finish == [ref.finish_reason]
        assert _wait_until(lambda: eng.state == "SERVING")
        assert sup.restarts == 1
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()


# ----------------------------------------------------------------------
# paged-KV engines recover too (allocator rebuilt from scratch)
# ----------------------------------------------------------------------


def test_paged_kv_engine_restart_rebuilds_pool(metrics):
    eng, sup, _ = _make_supervised(metrics, kv_block=16)
    try:
        ref = eng.generate_sync(
            "paged recovery", max_new_tokens=20, temperature=0.0,
            stop_on_eos=False,
        )
        total_blocks = eng.cache.n_blocks - 1
        assert len(eng._free_blocks) == total_blocks
        # Crash at the 3rd dispatch (2nd decode window) — blocks are
        # allocated and mid-use when the device dies.
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("paged device loss"), after=2, times=1,
        )
        req = eng.submit_generate(
            "paged recovery", max_new_tokens=20, temperature=0.0,
            stop_on_eos=False,
        )
        result = req.future.result(timeout=120)
        assert result.token_ids == ref.token_ids
        _drain_stream(req)
        # The rebuilt pool is whole: nothing leaked across the crash.
        assert _wait_until(lambda: eng.state == "SERVING")
        assert _wait_until(
            lambda: len(eng._free_blocks) == eng.cache.n_blocks - 1
        )
    finally:
        faults.reset()
        sup.stop()
        eng.stop_sync()
