"""Closed-loop overload control: the brownout ladder
(serving/brownout.py; docs/advanced-guide/resilience.md "Brownout &
overload control").

Deterministic throughout: controller/SLO clocks are injectable (tests
state time instead of sleeping — real time only bounds the polls that
wait for the scheduler thread to observe stated time), greedy streams
are byte-compared for the off-switch contract, and the storm acceptance
path drives the ladder L0→L2 and back with zero 5xx."""

from __future__ import annotations

import time

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.errors import ErrorTooManyRequests
from gofr_tpu.metrics.manager import Manager
from gofr_tpu.serving.brownout import (
    BrownoutController,
    normalize_slo_class,
    parse_tenant_class_map,
)
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.slo import SLOEngine, tenant_objectives_from_config
from gofr_tpu.serving.tokenizer import ByteTokenizer


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def brownout_metrics() -> Manager:
    m = Manager()
    for name in (
        "app_tpu_brownout_transitions_total",
        "app_tpu_brownout_actions_total",
        "app_tpu_requests_shed_total",
    ):
        m.new_counter(name)
    for name in (
        "app_tpu_brownout_level",
        "app_tpu_slo_burn_rate",
        "app_tpu_slo_tenant_burn_rate",
        "app_tpu_slo_compliant",
    ):
        m.new_gauge(name)
    return m


def counter_value(m: Manager, name: str, **labels: str) -> float:
    inst = [i for i in m.instruments() if i.name == name]
    if not inst:
        return 0.0
    want = set(labels.items())
    return sum(
        v for k, v in inst[0].collect().items() if want <= set(k)
    )


def make_controller(**kw) -> tuple[BrownoutController, FakeClock]:
    clock = FakeClock(1000.0)
    defaults = dict(
        enter_burn=2.0, exit_burn=1.0, sustain_s=10.0,
        exit_sustain_s=20.0, max_new_tokens=8, aimd_cut=0.5,
        recover_per_s=0.05, clock=clock,
    )
    defaults.update(kw)
    return BrownoutController("m", **defaults), clock


def make_engine(**kw):
    defaults = dict(
        n_slots=2, max_len=128, kv_block=16,
        tokenizer=ByteTokenizer(), seed=0,
        slo_availability=0.999,
        # Force tests hold a level against the scheduler's continuous
        # re-evaluation: with burn 0 the ladder would descend after the
        # exit sustain, so park it out of reach unless a test says
        # otherwise.
        brownout_exit_sustain_s=100_000.0,
    )
    defaults.update(kw)
    eng = InferenceEngine("llama-tiny", **defaults)
    eng.start_sync()
    return eng


def wait_for(predicate, timeout_s: float = 30.0) -> None:
    """Bound a poll on the scheduler thread observing stated time —
    the OUTCOME is deterministic, only the thread interleaving isn't."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    assert predicate(), "condition never became true"


# ----------------------------------------------------------------------
# controller units: ladder math, hysteresis, AIMD
# ----------------------------------------------------------------------


def test_one_bad_tick_never_flips_a_level():
    bc, clock = make_controller()
    assert bc.evaluate(50.0) == 0          # over, but not sustained
    clock.advance(9.9)
    assert bc.evaluate(50.0) == 0          # still inside the sustain
    clock.advance(0.2)
    assert bc.evaluate(50.0) == 1          # sustained past 10s → L1
    # A single clean tick does NOT descend either (exit sustain).
    clock.advance(1.0)
    assert bc.evaluate(0.0) == 1


def test_ladder_climbs_one_rung_per_sustain_period_and_caps_at_l3():
    bc, clock = make_controller(sustain_s=5.0)
    bc.evaluate(10.0)
    for expected in (1, 2, 3, 3):           # re-armed per rung; caps
        clock.advance(5.1)
        assert bc.evaluate(10.0) == expected
    assert bc.describe()["routable"] is False
    assert not bc.routable()


def test_hysteresis_band_holds_and_exit_requires_sustained_recovery():
    bc, clock = make_controller(sustain_s=5.0, exit_sustain_s=20.0)
    bc.evaluate(10.0)
    clock.advance(5.1)
    assert bc.evaluate(10.0) == 1
    # Between exit (1.0) and enter (2.0): the band holds the level and
    # resets BOTH anchors — band time counts toward neither sustain.
    for _ in range(5):
        clock.advance(30.0)
        assert bc.evaluate(1.5) == 1
    # Clean signal: one rung only after a full exit-sustain period...
    assert bc.evaluate(0.2) == 1
    clock.advance(19.9)
    assert bc.evaluate(0.2) == 1
    clock.advance(0.2)
    assert bc.evaluate(0.2) == 0
    # ...and a recovery interrupted by the band restarts the clock.
    clock.advance(5.1)
    bc.evaluate(10.0)
    clock.advance(5.1)
    assert bc.evaluate(10.0) == 1
    bc.evaluate(0.5)
    clock.advance(10.0)
    bc.evaluate(1.5)                        # band tick resets the anchor
    clock.advance(15.0)
    assert bc.evaluate(0.5) == 1            # 15s < full 20s since reset


def test_aimd_cut_recovery_curve_and_l0_snap():
    m = brownout_metrics()
    bc, clock = make_controller(
        sustain_s=5.0, exit_sustain_s=40.0, aimd_cut=0.5,
        recover_per_s=0.01, metrics=m,
    )
    bc.evaluate(10.0)
    clock.advance(5.1)
    bc.evaluate(10.0)                       # L1: no budget action yet
    assert bc.budget_factor == 1.0
    assert bc.admission_fraction("interactive") == 1.0
    clock.advance(5.1)
    bc.evaluate(10.0)                       # L2: multiplicative cut
    assert bc.budget_factor == pytest.approx(0.5)
    # Priority-aware fractions: batch fills least, interactive most.
    assert bc.admission_fraction("batch") == pytest.approx(0.25)
    assert bc.admission_fraction("standard") == pytest.approx(0.4)
    assert bc.admission_fraction("interactive") == pytest.approx(0.5)
    # Additive recovery while the signal is below enter: 10s at
    # 0.01/s → +0.1.
    clock.advance(10.0)
    bc.evaluate(0.0)
    assert bc.budget_factor == pytest.approx(0.6)
    # Climbing again cuts multiplicatively from the recovered value.
    bc.evaluate(10.0)
    clock.advance(5.1)
    bc.evaluate(10.0)                       # L3 (still cuts at 2+)
    assert bc.budget_factor == pytest.approx(0.3)
    # Descend all the way: at L0 the factor SNAPS to exactly 1.0 — the
    # byte-identity contract.
    bc.force_level(0)
    assert bc.budget_factor == 1.0
    assert bc.admission_fraction("batch") == 1.0
    assert counter_value(
        m, "app_tpu_brownout_transitions_total", direction="up"
    ) == 3.0
    assert counter_value(
        m, "app_tpu_brownout_transitions_total", direction="down"
    ) == 3.0


def test_recovery_continues_at_l1_and_force_level_clamps():
    """The AIMD factor keeps recovering below L2 (a factor frozen at
    L1 would inflate every Retry-After's recovery floor and compound
    the next climb's cut), and force_level clamps out-of-range targets
    instead of spinning forever against _step's own clamp."""
    bc, clock = make_controller(aimd_cut=0.5, recover_per_s=0.01)
    bc.force_level(2)
    assert bc.budget_factor == pytest.approx(0.5)
    bc.force_level(1)           # descend: no cut, factor carried
    clock.advance(0.0)
    bc.evaluate(0.0)            # anchor the eval clock
    clock.advance(10.0)
    bc.evaluate(0.0)
    assert bc.budget_factor == pytest.approx(0.6)
    # Out-of-range targets clamp (and return promptly — an unclamped
    # loop target could never be reached).
    bc.force_level(99)
    assert bc.level == 3
    bc.force_level(-5)
    assert bc.level == 0
    assert bc.budget_factor == 1.0


def test_headroom_pressure_counts_like_burn():
    bc, clock = make_controller(min_headroom=0.1, sustain_s=5.0)
    bc.evaluate(0.0, headroom=0.05)         # burn clean, headroom low
    clock.advance(5.1)
    assert bc.evaluate(0.0, headroom=0.05) == 1
    # With the floor unset (default), low headroom is NOT pressure.
    bc2, clock2 = make_controller(sustain_s=5.0)
    bc2.evaluate(0.0, headroom=0.01)
    clock2.advance(5.1)
    assert bc2.evaluate(0.0, headroom=0.01) == 0


def test_projected_recovery_is_positive_and_scales_with_depth():
    bc, clock = make_controller(sustain_s=5.0, exit_sustain_s=20.0)
    assert bc.projected_recovery_s() >= 1.0
    bc.force_level(2)
    at_l2 = bc.projected_recovery_s()
    bc.force_level(3)
    at_l3 = bc.projected_recovery_s()
    assert at_l3 > at_l2 >= 1.0


def test_slo_class_parsing():
    assert normalize_slo_class(" Batch ") == "batch"
    assert normalize_slo_class("interactive") == "interactive"
    assert normalize_slo_class("gold") == ""
    assert normalize_slo_class("") == ""
    # Tenant keys lower-case: the lookup matches X-Tenant-Id
    # case-insensitively, same as the TPU_SLO_TENANT_<NAME>_* keys.
    assert parse_tenant_class_map(
        "ACME=batch, ops=interactive; bad=gold,=batch, x"
    ) == {"acme": "batch", "ops": "interactive"}


# ----------------------------------------------------------------------
# per-tenant SLO objectives (satellite)
# ----------------------------------------------------------------------


def test_tenant_objectives_from_config_parses_override_keys():
    cfg = MockConfig({
        "TPU_SLO_TENANT_ACME_TTFT_MS": "250",
        "TPU_SLO_TENANT_ACME_AVAILABILITY": "0.9995",
        "TPU_SLO_TENANT_BULK_JOBS_E2E_MS": "90000",
        "TPU_SLO_TENANT_BAD_TTFT_MS": "nope",  # unparseable: dropped
        "TPU_SLO_TTFT_MS": "500",               # global key: not a tenant
    })
    out = tenant_objectives_from_config(cfg)
    assert out["acme"] == {"ttft_ms": 250.0, "availability": 0.9995}
    # Tenant names may contain underscores: the suffix anchors parsing.
    assert out["bulk_jobs"] == {"e2e_ms": 90000.0}
    assert "bad" not in out


def test_slo_engine_evaluates_and_exports_per_tenant_burn():
    clock = FakeClock(10_000.0)
    m = brownout_metrics()
    slo = SLOEngine(
        "m", ttft_ms=60_000.0,
        tenant_objectives={"acme": {"ttft_ms": 50.0}},
        metrics=m, clock=clock,
    )
    # 120ms TTFT: good globally (60s threshold), bad for acme (50ms) —
    # and the tenant match is case-insensitive.
    slo.observe("ok", {"ttft_s": 0.12}, tenant="ACME")
    slo.observe("ok", {"ttft_s": 0.12}, tenant="other")
    assert slo.burn_rate("ttft", "5m") == 0.0
    assert slo.burn_rate("ttft", "5m", tenant="acme") == pytest.approx(
        1.0 / 0.01
    )
    gauge = [
        i for i in m.instruments()
        if i.name == "app_tpu_slo_tenant_burn_rate"
    ][0]
    labels = {dict(k).get("tenant") for k in gauge.collect()}
    assert labels == {"acme"}
    snap = slo.snapshot()
    assert snap["tenants"]["acme"]["ttft"]["threshold_ms"] == 50.0
    assert (
        snap["tenants"]["acme"]["ttft"]["windows"]["5m"]["total"] == 1
    )
    desc = slo.describe()
    assert desc["tenants"]["acme"]["compliant"] is False
    assert desc["compliant"] is True  # global objectives unaffected


def test_engine_serves_per_tenant_slo_section():
    eng = make_engine(
        slo_ttft_ms=60_000.0,
        slo_tenant_objectives={"acme": {"ttft_ms": 0.001}},
    )
    try:
        eng.generate_sync(
            "tenant slo", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, tenant="acme", timeout=300,
        )
        rep = eng.slo_report()
        acme = rep["tenants"]["acme"]["ttft"]
        assert acme["windows"]["5m"]["total"] >= 1
        # No real TTFT beats a 1µs threshold: the override burns.
        assert acme["windows"]["5m"]["burn_rate"] > 1.0
        assert rep["compliant"] is True
        assert eng.health_check()["details"]["slo"]["tenants"][
            "acme"
        ]["compliant"] is False
    finally:
        eng.close()


# ----------------------------------------------------------------------
# engine integration: off-switch byte-identity, L1 clamp, L2 ordering
# ----------------------------------------------------------------------


def _greedy(eng, prompt: str = "byte identical"):
    return eng.generate_sync(
        prompt, max_new_tokens=8, temperature=0.0, stop_on_eos=False,
        timeout=300,
    ).token_ids


def test_off_switch_and_l0_are_byte_identical():
    """TPU_BROWNOUT=0 builds no controller; an ARMED controller at L0
    changes nothing either — both streams match a no-SLO baseline."""
    base = make_engine(slo_availability=0.0, brownout=False)
    try:
        assert base._brownout is None and base._slo is None
        reference = _greedy(base)
    finally:
        base.close()
    off = make_engine(brownout=False)
    try:
        assert off._brownout is None and off._slo is not None
        # Layer off = signal ABSENT (None), not "armed at 0": the pool
        # must never suppress hedges/probes on an absent signal.
        assert off.brownout_level() is None
        assert _greedy(off) == reference
    finally:
        off.close()
    armed = make_engine()
    try:
        assert armed._brownout is not None
        assert armed.brownout_level() == 0
        assert _greedy(armed) == reference
        # L0 admission math is exactly nominal.
        assert armed._brownout.admission_fraction("batch") == 1.0
    finally:
        armed.close()


def test_l1_clamps_max_new_and_advertises_brownout():
    eng = make_engine(brownout_max_new=4)
    try:
        bc = eng._brownout
        bc.force_level(1)
        result = eng.generate_sync(
            "clamp me", max_new_tokens=32, temperature=0.0,
            stop_on_eos=False, timeout=300,
        )
        assert len(result.token_ids) == 4
        assert result.finish_reason == "length"
        assert result.brownout is True        # deliberate, advertised
        assert bc.snapshot()["actions"]["clamp_tokens"] >= 1
        # Back at L0 the clamp is gone and the field stays absent.
        bc.force_level(0)
        result = eng.generate_sync(
            "clamp me", max_new_tokens=32, temperature=0.0,
            stop_on_eos=False, timeout=300,
        )
        assert len(result.token_ids) == 32
        assert result.brownout is False
    finally:
        eng.close()


def test_l2_sheds_batch_first_interactive_last():
    m = brownout_metrics()
    eng = make_engine(metrics=m, queue_max_tokens=400)
    try:
        eng._brownout.force_level(2)   # budget_factor 0.5
        # Cost ~ prompt + max_new ≈ 150: over batch's 0.25×400=100,
        # within standard's 0.8×0.5×400=160 and interactive's 200.
        kw = dict(
            max_new_tokens=140, temperature=0.0, stop_on_eos=False,
        )
        with pytest.raises(ErrorTooManyRequests) as exc:
            eng.submit_generate("B" * 10, slo_class="batch", **kw)
        assert "brownout" in str(exc.value)
        assert exc.value.retry_after_s >= 1
        h = eng.submit_generate("I" * 10, slo_class="interactive", **kw)
        h.future.result(timeout=300)
        h = eng.submit_generate("S" * 10, slo_class="standard", **kw)
        h.future.result(timeout=300)
        assert eng._brownout.shed_count("batch") == 1
        assert eng._brownout.shed_count("interactive") == 0
        assert counter_value(
            m, "app_tpu_requests_shed_total", reason="brownout"
        ) == 1.0
    finally:
        eng.close()


def test_tenant_default_class_and_header_priority():
    eng = make_engine(tenant_slo_class="BULK=batch")
    try:
        # Case-insensitive tenant match (the SLO-override convention).
        h = eng.submit_generate(
            "via tenant", max_new_tokens=2, temperature=0.0,
            stop_on_eos=False, tenant="bulk",
        )
        assert h.slo_class == "batch"
        h.future.result(timeout=300)
        h = eng.submit_generate(
            "explicit wins", max_new_tokens=2, temperature=0.0,
            stop_on_eos=False, tenant="bulk", slo_class="interactive",
        )
        assert h.slo_class == "interactive"
        h.future.result(timeout=300)
        h = eng.submit_generate(
            "unknown falls back", max_new_tokens=2, temperature=0.0,
            stop_on_eos=False, slo_class="gold",
        )
        assert h.slo_class == "standard"
        h.future.result(timeout=300)
    finally:
        eng.close()


# ----------------------------------------------------------------------
# Retry-After: positive and load-sensitive on EVERY 429 path (satellite)
# ----------------------------------------------------------------------


def test_every_429_carries_positive_load_sensitive_retry_after():
    eng = make_engine(
        queue_max_tokens=64, tenant_fair_share=0.3, expected_tps=10.0,
    )
    try:
        sheds = []
        # queue_tokens: a request bigger than the whole budget.
        with pytest.raises(ErrorTooManyRequests) as exc:
            eng.submit_generate(
                "Q" * 40, max_new_tokens=60, temperature=0.0,
                stop_on_eos=False,
            )
        sheds.append(exc.value)
        # tenant_fair_share: the hog over its 0.3 × 64 token share.
        with pytest.raises(ErrorTooManyRequests) as exc:
            eng.submit_generate(
                "H" * 20, max_new_tokens=10, temperature=0.0,
                stop_on_eos=False, tenant="hog",
            )
        sheds.append(exc.value)
        # hbm_headroom: an impossible floor sheds every admit.
        eng.admit_min_headroom = 2.0
        with pytest.raises(ErrorTooManyRequests) as exc:
            eng.submit_generate(
                "M" * 4, max_new_tokens=4, temperature=0.0,
                stop_on_eos=False,
            )
        sheds.append(exc.value)
        for shed in sheds:
            assert shed.retry_after_s >= 1
            assert int(shed.headers["Retry-After"]) >= 1
        # Load sensitivity: the same shed under a deeper backlog quotes
        # a LONGER wait (the regression this satellite pins — several
        # paths used to answer a near-constant projected wait).
        idle_wait = eng.shed_retry_after_s("hbm_headroom", 10)
        eng._queued_tokens += 500
        assert eng.shed_retry_after_s("hbm_headroom", 10) > idle_wait
        ledger = eng._tenant_ledger

        class Req:
            prompt_ids = [1] * 100
            max_new_tokens = 100
            tenant = "hog"
            ledger_t0 = 0.0
            ledger_admitted = 0.0
            ledger_done = False

        idle_wait = eng.shed_retry_after_s("tenant_fair_share", 10, "hog")
        for _ in range(5):
            ledger.note_enqueued(Req())
        assert (
            eng.shed_retry_after_s("tenant_fair_share", 10, "hog")
            > idle_wait
        )
    finally:
        eng.close()


def test_batcher_queue_full_retry_after_scales_with_backlog():
    from gofr_tpu.serving.batcher import DynamicBatcher

    b = DynamicBatcher(lambda xs: xs, max_batch=2, max_queue=4)
    # Never started: the queue only fills. 4 seats, then sheds.
    for i in range(4):
        b.submit(i)
    with pytest.raises(ErrorTooManyRequests) as exc:
        b.submit(99)
    assert exc.value.retry_after_s >= 1
    # A measured 2s flush time quotes the 2-flush backlog honestly
    # (the regression: a constant 1s regardless of backlog).
    b._flush_ewma_s = 2.0
    with pytest.raises(ErrorTooManyRequests) as exc:
        b.submit(99)
    assert exc.value.retry_after_s >= 4


# ----------------------------------------------------------------------
# pool: routing, hedges, probes, scaler (tentpole wiring)
# ----------------------------------------------------------------------


class FakeReplica:
    """Minimal routable replica for pool policy tests."""

    def __init__(self, name, load=0, compliant=None, level=None):
        self.name = name
        self.role = "fused"
        self.probe_failed = False
        self.draining = False
        self.supports_stream = True
        self.remote = False
        self._load = load
        self._compliant = compliant
        self._level = level
        self.probes = 0

    def state(self):
        return "SERVING"

    def load(self):
        return self._load

    def throughput(self):
        return 0.0

    def adapters(self):
        return frozenset()

    def mesh_topology(self):
        return None

    def headroom(self):
        return None

    def slo_compliant(self):
        return self._compliant

    def brownout_level(self):
        return self._level

    def control_pressure(self):
        return None

    def set_handoff(self, handoff):
        pass

    def set_tier_exporter(self, exporter):
        pass

    def probe(self, timeout_s):
        self.probes += 1
        return "pass", ""

    def note_probe_success(self):
        pass

    def notify_probe_failure(self, reason):
        pass

    def revive(self, probe_timeout_s=5.0):
        return False

    def describe(self):
        return {"state": "SERVING"}

    def close(self):
        pass


def test_pick_deprioritizes_non_compliant_replicas():
    from gofr_tpu.service.replica_pool import ReplicaPool

    burned = FakeReplica("burned", load=0, compliant=False, level=3)
    healthy = FakeReplica("healthy", load=50, compliant=True)
    pool = ReplicaPool([burned, healthy], probe_interval_s=0)
    try:
        # The compliant replica wins despite 50× the load — compliance
        # outranks least-loaded, exactly like the tier preference.
        for _ in range(4):
            assert pool.pick().name == "healthy"
        # Preference, never a partition: an all-non-compliant pool
        # still serves.
        healthy._compliant = False
        assert pool.pick().name in ("burned", "healthy")
        # None (no SLOs configured) counts as compliant.
        unknown = FakeReplica("unknown", load=9)
        pool2 = ReplicaPool([burned, unknown], probe_interval_s=0)
        try:
            assert pool2.pick().name == "unknown"
        finally:
            pool2.close()
    finally:
        pool.close()


def test_prober_skips_browned_out_replica_but_probes_demoted():
    from gofr_tpu.service.replica_pool import ReplicaPool

    m = brownout_metrics()
    nominal = FakeReplica("nominal")
    browned = FakeReplica("browned", level=1)
    pool = ReplicaPool([nominal, browned], probe_interval_s=0, metrics=m)
    try:
        results = pool.probe_once()
        assert nominal.probes == 1
        assert browned.probes == 0
        assert results["browned"] == "skipped: brownout"
        assert counter_value(
            m, "app_tpu_brownout_actions_total", action="skip_probe"
        ) == 1.0
        # The skip ALTERNATES: the next sweep probes, so a broken
        # dataplane hiding behind its own burn storm still produces
        # restart-on-evidence within two sweeps.
        assert pool.probe_once()["browned"] == "pass"
        assert browned.probes == 1
        assert pool.probe_once()["browned"] == "skipped: brownout"
        assert browned.probes == 1
        # A DEMOTED replica always probes — re-admission requires a
        # clean pass through the dataplane, brownout or not.
        browned.probe_failed = True
        pool.probe_once()
        assert browned.probes == 2
        # A REMOTE replica always probes too: its probe is a health
        # GET (no generation) and the ONLY path that refreshes its
        # cached brownout/compliance advertisement — skipping it would
        # freeze a recovered pod at its last advertised level.
        remote = FakeReplica("remote", level=3)
        remote.remote = True
        pool2 = ReplicaPool([nominal, remote], probe_interval_s=0)
        try:
            pool2.probe_once()
            assert remote.probes == 1
        finally:
            pool2.close()
    finally:
        pool.close()


def test_hedge_suppressed_against_browned_out_primary():
    from gofr_tpu.service.replica_pool import ReplicaPool

    m = brownout_metrics()
    primary = FakeReplica("primary", level=1)
    pool = ReplicaPool([primary], probe_interval_s=0, metrics=m)
    try:
        assert pool._hedge_suppressed([primary]) is True
        assert counter_value(
            m, "app_tpu_brownout_actions_total", action="suppress_hedge"
        ) == 1.0
        primary._level = 0
        assert pool._hedge_suppressed([primary]) is False
        primary._level = None
        assert pool._hedge_suppressed([primary]) is False
    finally:
        pool.close()


def test_scaler_treats_sustained_l2_as_pressure():
    from gofr_tpu.service.pool_scaler import PoolScaler
    from gofr_tpu.service.replica_pool import ReplicaPool

    clock = FakeClock(0.0)
    browned = FakeReplica("browned", level=2)
    pool = ReplicaPool([browned], probe_interval_s=0)
    try:
        spawned = []

        def spawn():
            replica = FakeReplica(f"spawned-{len(spawned)}")
            spawned.append(replica)
            return replica

        scaler = PoolScaler(
            pool, spawn, max_replicas=2, scale_up_wait_s=10.0,
            interval_s=0, clock=clock,
        )
        assert scaler.evaluate() == "steady"   # pressure noted, not acted
        clock.advance(10.1)
        assert scaler.evaluate() == "up"       # sustained L2+ → spawn
        assert len(spawned) == 1
        # The knob off: L2 alone is not pressure.
        browned2 = FakeReplica("b2", level=2)
        pool2 = ReplicaPool([browned2], probe_interval_s=0)
        try:
            scaler2 = PoolScaler(
                pool2, spawn, max_replicas=2, scale_up_wait_s=10.0,
                interval_s=0, clock=clock, up_on_brownout=False,
            )
            assert scaler2.evaluate() == "steady"
            clock.advance(60.0)
            assert scaler2.evaluate() == "steady"
        finally:
            pool2.close()
    finally:
        pool.close()


def test_advertisement_through_engine_replica_and_http_probe():
    from gofr_tpu.service.replica_pool import EngineReplica, HTTPReplica

    eng = make_engine()
    try:
        replica = EngineReplica("r0", eng)
        assert replica.brownout_level() == 0
        assert replica.slo_compliant() is True
        eng._brownout.force_level(3)
        assert replica.brownout_level() == 3
        # L3 folds into the routing bit even while the burn gauges are
        # momentarily clean.
        assert replica.slo_compliant() is False
        desc = replica.describe()
        assert desc["brownout_level"] == 3
        health = eng.health_check()
        assert health["details"]["brownout"]["level"] == 3
        assert health["details"]["brownout"]["routable"] is False
        assert eng.capacity_report()["brownout"]["level"] == 3
        assert eng.brownout_report()["level"] == 3
        eng._brownout.force_level(0)
    finally:
        eng.close()

    class FakeService:
        def health_check(self):
            return {
                "status": "UP",
                "details": {
                    "slo": {"compliant": True},
                    "brownout": {"level": 3, "routable": False},
                },
            }

    remote = HTTPReplica("remote", FakeService(), stream=False)
    verdict, _ = remote.probe(timeout_s=1.0)
    assert verdict == "pass"
    assert remote.brownout_level() == 3
    assert remote.slo_compliant() is False  # L3 folds in over the wire


def test_remote_brownout_clamp_field_survives_the_hop():
    """A remote replica's clamp advertisement ("brownout": true on the
    OpenAI wire) must reach the routing pool's client — multi-host
    pools keep the 'truncation was deliberate' contract."""
    from gofr_tpu.service.replica_pool import HTTPReplica

    class FakeResp:
        status_code = 200
        body = b""

        def json(self):
            return {
                "choices": [{
                    "text": "cut", "finish_reason": "length",
                    "brownout": True,
                }],
                "usage": {"prompt_tokens": 3},
            }

        def get_header(self, name):
            return None

    class FakeSvc:
        def post(self, path, json=None, headers=None):
            return FakeResp()

    remote = HTTPReplica("r", FakeSvc(), stream=False)
    req = remote.submit("hi", max_new_tokens=4)
    result = req.future.result(timeout=10)
    assert result.finish_reason == "length"
    assert result.brownout is True


# ----------------------------------------------------------------------
# THE storm acceptance path
# ----------------------------------------------------------------------


def test_overload_storm_climbs_sheds_batch_first_and_descends():
    """The deterministic overload storm (acceptance criteria): a
    fault-injected slow-decode storm — modeled as sustained
    SLO-breaching observations under stated clocks — climbs the ladder
    L0→L1→L2; at L2 batch traffic is shed (429 reason=brownout, positive
    Retry-After) while interactive goodput continues; when the storm
    stops, the TTFT burn recovers below the exit threshold, the ladder
    descends with hysteresis back to L0, and no admitted request saw a
    5xx anywhere."""
    m = brownout_metrics()
    clock = FakeClock(100_000.0)
    eng = make_engine(
        metrics=m,
        queue_max_tokens=200,
        slo_ttft_ms=60_000.0,          # real traffic is always good
        brownout_enter=2.0,
        brownout_exit=1.0,
        brownout_sustain_s=5.0,
        brownout_exit_sustain_s=5.0,
        brownout_max_new=64,
    )
    errors_5xx = []
    try:
        # Stated time for the burn windows AND the ladder.
        eng._slo._clock = clock
        eng._brownout._clock = clock

        def storm(n=30):
            # The slow-decode fault: every observation misses the TTFT
            # objective by 100×, so the 5m burn pegs far above enter.
            for _ in range(n):
                eng._slo.observe("ok", {"ttft_s": 6_000.0})

        def interactive(prompt):
            try:
                return eng.generate_sync(
                    prompt, max_new_tokens=8, temperature=0.0,
                    stop_on_eos=False, slo_class="interactive",
                    timeout=300,
                ).token_ids
            except ErrorTooManyRequests:
                return None
            except Exception as exc:  # noqa: BLE001 — the zero-5xx assertion
                errors_5xx.append(exc)
                raise

        assert eng.brownout_level() == 0
        reference = interactive("storm baseline")
        assert reference

        # -- climb: L0 → L1 → L2, one sustained rung at a time --------
        storm()
        # The scheduler must anchor the over-signal at the CURRENT
        # stated time before it advances — one bad tick alone flips
        # nothing (the sustain window is the point).
        wait_for(lambda: eng._brownout._over_since is not None)
        assert eng.brownout_level() == 0
        clock.advance(6.0)
        wait_for(lambda: eng.brownout_level() >= 1)
        clock.advance(6.0)
        wait_for(lambda: eng.brownout_level() >= 2)
        assert eng._slo.worst_burn("5m") > 2.0

        # -- at L2: batch shed first, interactive keeps flowing -------
        # Batch cost ~120 tokens: over batch's 0.25 × 200 = 50-token
        # allowance, so the hog's batch burst sheds...
        batch_sheds = 0
        for i in range(3):
            try:
                eng.submit_generate(
                    "B" * 60, max_new_tokens=60,
                    temperature=0.0, stop_on_eos=False,
                    slo_class="batch", tenant="hog",
                )
            except ErrorTooManyRequests as exc:
                batch_sheds += 1
                assert "brownout" in str(exc)
                assert exc.retry_after_s >= 1
        assert batch_sheds == 3
        # ...while interactive goodput keeps flowing through the storm
        # (cost ~46, inside interactive's 0.5 × 200 = 100 allowance).
        assert interactive("interactive storm " + "I" * 20)
        assert eng._brownout.shed_count("batch") == 3
        assert eng._brownout.shed_count("interactive") == 0
        assert counter_value(
            m, "app_tpu_requests_shed_total", reason="brownout"
        ) == 3.0

        # -- recovery: storm ends, the 5m window ages out -------------
        clock.advance(360.0)
        assert eng._slo.worst_burn("5m") == 0.0   # below exit
        # Hysteresis on the way down too: the first clear tick only
        # anchors the exit-sustain window; each further sustained-clear
        # period descends ONE rung.
        wait_for(lambda: eng._brownout._clear_since is not None)
        assert eng.brownout_level() == 2
        clock.advance(6.0)
        wait_for(lambda: eng.brownout_level() == 1)
        clock.advance(6.0)
        wait_for(lambda: eng.brownout_level() == 0)
        assert eng._brownout.budget_factor == 1.0
        # Clean descent shows in the transition counters: two up, two
        # down, and the ladder is exactly where it started.
        assert counter_value(
            m, "app_tpu_brownout_transitions_total", direction="up"
        ) == 2.0
        assert counter_value(
            m, "app_tpu_brownout_transitions_total", direction="down"
        ) == 2.0
        # Post-storm interactive streams are byte-identical to the
        # pre-storm baseline (L0 is byte-identically off).
        assert interactive("storm baseline") == reference
        # Zero 5xx throughout: every admitted request resolved, every
        # rejection was a 429.
        assert errors_5xx == []
    finally:
        eng.close()
