"""Integration tests against REAL service backends (reference CI idiom:
``/root/reference/.github/workflows/go.yml:55-116`` boots real Kafka,
Redis, MySQL and Zipkin containers for the example tests).

Everything in this file is gated on ``REAL_BACKENDS=1`` — the default test
run (and this sandbox) uses the in-proc fakes (miniredis, fake
reader/writer); CI's optional ``real-backends`` job boots the service
containers and flips the flag so the wire clients are validated against
real peers.

Env knobs: REDIS_HOST/REDIS_PORT (default localhost:6379),
KAFKA_BROKER (default localhost:9092).
"""

from __future__ import annotations

import os
import time
import uuid

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("REAL_BACKENDS") != "1",
    reason="REAL_BACKENDS=1 not set (CI real-backends job only)",
)


def test_redis_client_against_real_server():
    """The from-scratch RESP client (datasource/redis/client.py) against a
    real Redis: strings, hashes, lists, expiry, pipeline."""
    from gofr_tpu.datasource.redis.client import Redis

    r = Redis(
        os.environ.get("REDIS_HOST", "localhost"),
        int(os.environ.get("REDIS_PORT", "6379")),
    )
    key = f"gofr-it-{uuid.uuid4().hex[:8]}"
    assert r.ping() == "PONG"
    assert r.set(key, "v1") == "OK"
    assert r.get(key) == "v1"
    assert r.incr(key + ":n") == 1
    assert r.incr(key + ":n") == 2
    assert r.hset(key + ":h", "a", "1", "b", "2") == 2
    assert r.hgetall(key + ":h") == {"a": "1", "b": "2"}
    assert r.rpush(key + ":l", "x", "y") == 2
    assert r.expire(key, 60) == 1
    assert 0 < r.ttl(key) <= 60
    assert r.delete(key, key + ":n", key + ":h", key + ":l") == 4


def test_redis_health_check_against_real_server():
    from gofr_tpu.datasource.redis.client import Redis

    r = Redis(
        os.environ.get("REDIS_HOST", "localhost"),
        int(os.environ.get("REDIS_PORT", "6379")),
    )
    health = r.health_check()
    assert health["status"] == "UP"


def test_kafka_publish_subscribe_roundtrip():
    """The Kafka client with the real kafka-python driver wiring
    (datasource/pubsub/kafka.py `kafka_from_config`) against a real
    broker: create topic, publish, subscribe, commit."""
    pytest.importorskip("kafka")
    from gofr_tpu.config import MockConfig
    from gofr_tpu.datasource.pubsub.kafka import new_kafka_from_config

    topic = f"gofr-it-{uuid.uuid4().hex[:8]}"
    client = new_kafka_from_config(MockConfig({
        "KAFKA_BROKER": os.environ.get("KAFKA_BROKER", "localhost:9092"),
        "KAFKA_CONSUMER_GROUP": f"gofr-it-{uuid.uuid4().hex[:8]}",
        "KAFKA_OFFSET": "earliest",
    }))
    try:
        client.create_topic(topic)
        payload = b'{"n": 42}'
        client.publish(topic, payload)
        deadline = time.time() + 30
        msg = None
        while msg is None and time.time() < deadline:
            msg = client.subscribe(topic, timeout=2.0)
        assert msg is not None, "no message within 30s"
        assert msg.value == payload
        msg.commit()
        client.delete_topic(topic)
    finally:
        client.close()


def _sql_db(dialect: str, **env):
    from gofr_tpu.config import MockConfig
    from gofr_tpu.datasource.sql import new_sql_from_config
    from gofr_tpu.logging import Level, Logger

    cfg = {"DB_DIALECT": dialect, **env}
    db = new_sql_from_config(MockConfig(cfg), Logger(level=Level.ERROR))
    assert db is not None, f"no {dialect} driver/connection"
    return db


def _sql_roundtrip(db, serial: str):
    """Shared DDL/DML/tx/reflective-select exercise (the reference example
    CI runs its example tests against real MySQL, go.yml:55-116)."""
    table = f"gofr_it_{uuid.uuid4().hex[:8]}"
    db.exec(f"CREATE TABLE {table} (id {serial}, name VARCHAR(64), n INT)")
    try:
        db.exec(f"INSERT INTO {table} (name, n) VALUES (?, ?)", "alice", 1)
        db.exec(f"INSERT INTO {table} (name, n) VALUES (?, ?)", "bob", 2)
        rows = db.query(f"SELECT name, n FROM {table} ORDER BY n")
        assert [(r["name"], r["n"]) for r in rows] == [("alice", 1), ("bob", 2)]
        # Transaction rollback leaves the table untouched.
        tx = db.begin()
        tx.exec(f"INSERT INTO {table} (name, n) VALUES (?, ?)", "carol", 3)
        tx.rollback()
        # Transaction commit lands.
        tx = db.begin()
        tx.exec(f"INSERT INTO {table} (name, n) VALUES (?, ?)", "dave", 4)
        tx.commit()
        names = {r["name"] for r in db.query(f"SELECT name FROM {table}")}
        assert names == {"alice", "bob", "dave"}
        health = db.health_check()
        assert health["status"] == "UP", health
    finally:
        db.exec(f"DROP TABLE {table}")
        db.close()


def test_mysql_real_server_roundtrip():
    pytest.importorskip("pymysql")
    db = _sql_db(
        "mysql",
        DB_HOST=os.environ.get("MYSQL_HOST", "localhost"),
        DB_PORT=os.environ.get("MYSQL_PORT", "3306"),
        DB_USER=os.environ.get("MYSQL_USER", "root"),
        DB_PASSWORD=os.environ.get("MYSQL_PASSWORD", "password"),
        DB_NAME=os.environ.get("MYSQL_DB", "test"),
    )
    _sql_roundtrip(db, "INT PRIMARY KEY AUTO_INCREMENT")


def test_postgres_real_server_roundtrip():
    pytest.importorskip("psycopg2")
    db = _sql_db(
        "postgres",
        DB_HOST=os.environ.get("PG_HOST", "localhost"),
        DB_PORT=os.environ.get("PG_PORT", "5432"),
        DB_USER=os.environ.get("PG_USER", "postgres"),
        DB_PASSWORD=os.environ.get("PG_PASSWORD", "password"),
        DB_NAME=os.environ.get("PG_DB", "test"),
    )
    _sql_roundtrip(db, "SERIAL PRIMARY KEY")


def test_migrations_against_real_mysql():
    """The migration runner (SQL tracking table + tx rollback) against a
    real MySQL — the reference's migration example runs in its container
    CI job."""
    pytest.importorskip("pymysql")
    from gofr_tpu.migration import Migrate, run

    db = _sql_db(
        "mysql",
        DB_HOST=os.environ.get("MYSQL_HOST", "localhost"),
        DB_PORT=os.environ.get("MYSQL_PORT", "3306"),
        DB_USER=os.environ.get("MYSQL_USER", "root"),
        DB_PASSWORD=os.environ.get("MYSQL_PASSWORD", "password"),
        DB_NAME=os.environ.get("MYSQL_DB", "test"),
    )
    table = f"gofr_mig_{uuid.uuid4().hex[:8]}"

    from gofr_tpu.logging import Level, Logger

    class _C:
        sql = db
        redis = None
        pubsub = None
        logger = Logger(level=Level.ERROR)

    try:
        run({
            1: Migrate(up=lambda d: d.sql.exec(
                f"CREATE TABLE {table} (id INT PRIMARY KEY)"
            )),
            2: Migrate(up=lambda d: d.sql.exec(
                f"INSERT INTO {table} (id) VALUES (7)"
            )),
        }, _C())
        rows = db.query(f"SELECT id FROM {table}")
        assert [r["id"] for r in rows] == [7]
        done = {
            r["version"]
            for r in db.query("SELECT version FROM gofr_migrations")
        }
        assert {1, 2} <= done
    finally:
        db.exec(f"DROP TABLE IF EXISTS {table}")
        db.close()


def test_google_pubsub_roundtrip_against_emulator():
    """The Google Pub/Sub client with the real google-cloud-pubsub driver
    against the official emulator (PUBSUB_EMULATOR_HOST) — the reference
    treats GCP as a first-class backend; the emulator is the hermetic
    stand-in its own client library honors natively."""
    pytest.importorskip("google.cloud.pubsub_v1")
    if not os.environ.get("PUBSUB_EMULATOR_HOST"):
        pytest.skip("PUBSUB_EMULATOR_HOST not set (emulator CI job only)")
    from gofr_tpu.config import MockConfig
    from gofr_tpu.datasource.pubsub.google import new_google_from_config

    topic = f"gofr-it-{uuid.uuid4().hex[:8]}"
    client = new_google_from_config(MockConfig({
        "GOOGLE_PROJECT_ID": os.environ.get("GOOGLE_PROJECT_ID", "gofr-it"),
        "GOOGLE_SUBSCRIPTION_NAME": f"gofr-it-{uuid.uuid4().hex[:8]}",
    }))
    try:
        payload = b'{"n": 7}'
        # Subscribe once BEFORE publishing: Pub/Sub subscriptions only
        # receive messages published after they exist, and the client
        # auto-creates the subscription on first subscribe. Retry the
        # priming call while the emulator finishes booting (creation
        # errors are no longer cached, so retrying works).
        prime_deadline = time.time() + 60
        while True:
            try:
                client.subscribe(topic, timeout=0.5)
                break
            except Exception:  # noqa: BLE001 — emulator still booting
                if time.time() > prime_deadline:
                    raise
                time.sleep(2)
        client.publish(topic, payload)
        deadline = time.time() + 30
        msg = None
        while msg is None and time.time() < deadline:
            msg = client.subscribe(topic, timeout=2.0)
        assert msg is not None, "no message from emulator within 30s"
        assert msg.value == payload
        msg.commit()
        health = client.health_check()
        assert health["status"] == "UP", health
        client.delete_topic(topic)
    finally:
        client.close()


def test_zipkin_exporter_against_real_collector():
    """The Zipkin exporter against a REAL collector (reference example CI
    boots Zipkin, go.yml:110-116): an HTTP request through a full App's
    middleware chain exports a span that round-trips through Zipkin's
    query API. The last wire protocol previously only tested against an
    in-proc fake (r4 VERDICT missing #1)."""
    import asyncio
    import http.client
    import json
    import threading

    from gofr_tpu import App
    from gofr_tpu.config import MockConfig

    zipkin = os.environ.get("ZIPKIN_HOST", "localhost")
    svc = f"zipkin-it-{uuid.uuid4().hex[:8]}"
    app = App(config=MockConfig({
        "APP_NAME": svc,
        "HTTP_PORT": "0",
        "METRICS_PORT": "0",
        "TRACE_EXPORTER": "zipkin",
        "TRACER_URL": f"http://{zipkin}:9411/api/v2/spans",
    }))

    @app.get("/traced")
    async def traced(ctx):  # noqa: ANN001
        with ctx.trace("custom-work"):
            pass
        return "ok"

    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=60)
    try:
        c = http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=30)
        c.request("GET", "/traced")
        assert c.getresponse().status == 200
    finally:
        # stop() shuts the tracer down, flushing the span batch.
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)

    # The span must be queryable from the collector.
    found = []
    for _ in range(30):
        q = http.client.HTTPConnection(zipkin, 9411, timeout=10)
        q.request(
            "GET",
            f"/api/v2/traces?serviceName={svc}&limit=10&lookback=600000",
        )
        resp = q.getresponse()
        body = resp.read()
        if resp.status == 200:
            traces = json.loads(body)
            if traces:
                found = traces
                break
        time.sleep(1)
    assert found, f"no trace for service {svc} arrived at Zipkin"
    names = {s["name"] for t in found for s in t}
    assert any("traced" in n for n in names), names
    assert any("custom-work" in n for n in names), names
