"""Integration tests against REAL service backends (reference CI idiom:
``/root/reference/.github/workflows/go.yml:55-116`` boots real Kafka,
Redis, MySQL and Zipkin containers for the example tests).

Everything in this file is gated on ``REAL_BACKENDS=1`` — the default test
run (and this sandbox) uses the in-proc fakes (miniredis, fake
reader/writer); CI's optional ``real-backends`` job boots the service
containers and flips the flag so the wire clients are validated against
real peers.

Env knobs: REDIS_HOST/REDIS_PORT (default localhost:6379),
KAFKA_BROKER (default localhost:9092).
"""

from __future__ import annotations

import os
import time
import uuid

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("REAL_BACKENDS") != "1",
    reason="REAL_BACKENDS=1 not set (CI real-backends job only)",
)


def test_redis_client_against_real_server():
    """The from-scratch RESP client (datasource/redis/client.py) against a
    real Redis: strings, hashes, lists, expiry, pipeline."""
    from gofr_tpu.datasource.redis.client import Redis

    r = Redis(
        os.environ.get("REDIS_HOST", "localhost"),
        int(os.environ.get("REDIS_PORT", "6379")),
    )
    key = f"gofr-it-{uuid.uuid4().hex[:8]}"
    assert r.ping() == "PONG"
    assert r.set(key, "v1") == "OK"
    assert r.get(key) == "v1"
    assert r.incr(key + ":n") == 1
    assert r.incr(key + ":n") == 2
    assert r.hset(key + ":h", "a", "1", "b", "2") == 2
    assert r.hgetall(key + ":h") == {"a": "1", "b": "2"}
    assert r.rpush(key + ":l", "x", "y") == 2
    assert r.expire(key, 60) == 1
    assert 0 < r.ttl(key) <= 60
    assert r.delete(key, key + ":n", key + ":h", key + ":l") == 4


def test_redis_health_check_against_real_server():
    from gofr_tpu.datasource.redis.client import Redis

    r = Redis(
        os.environ.get("REDIS_HOST", "localhost"),
        int(os.environ.get("REDIS_PORT", "6379")),
    )
    health = r.health_check()
    assert health["status"] == "UP"


def test_kafka_publish_subscribe_roundtrip():
    """The Kafka client with the real kafka-python driver wiring
    (datasource/pubsub/kafka.py `kafka_from_config`) against a real
    broker: create topic, publish, subscribe, commit."""
    pytest.importorskip("kafka")
    from gofr_tpu.config import MockConfig
    from gofr_tpu.datasource.pubsub.kafka import new_kafka_from_config

    topic = f"gofr-it-{uuid.uuid4().hex[:8]}"
    client = new_kafka_from_config(MockConfig({
        "KAFKA_BROKER": os.environ.get("KAFKA_BROKER", "localhost:9092"),
        "KAFKA_CONSUMER_GROUP": f"gofr-it-{uuid.uuid4().hex[:8]}",
        "KAFKA_OFFSET": "earliest",
    }))
    try:
        client.create_topic(topic)
        payload = b'{"n": 42}'
        client.publish(topic, payload)
        deadline = time.time() + 30
        msg = None
        while msg is None and time.time() < deadline:
            msg = client.subscribe(topic, timeout=2.0)
        assert msg is not None, "no message within 30s"
        assert msg.value == payload
        msg.commit()
        client.delete_topic(topic)
    finally:
        client.close()
