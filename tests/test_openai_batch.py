"""OpenAI Files + Batches API: upload a JSONL of requests, run them as a
batch through the app's own router, poll to completion, download
OpenAI-shaped output/error files. Batch outputs must equal direct
online calls (same engine, same code path)."""

from __future__ import annotations

import asyncio
import http.client
import json
import threading
import time

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig
from gofr_tpu.serving.openai_batch import add_openai_batch_routes
from gofr_tpu.serving.openai_compat import add_openai_routes


@pytest.fixture(scope="module")
def batch_app():
    app = App(config=MockConfig({
        "APP_NAME": "batch-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "4", "TPU_MAX_LEN": "128",
        "TPU_EMBED_MODEL": "bert-tiny",
    }))
    add_openai_routes(app)
    app.batch_store = add_openai_batch_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=120)
    yield app
    asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
    loop.call_soon_threadsafe(loop.stop)


def _call(app, method, path, body=None, headers=None):
    c = http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=120)
    if isinstance(body, (dict, list)):
        body = json.dumps(body)
    c.request(method, path, body=body, headers=headers or {})
    r = c.getresponse()
    data = r.read()
    if "json" not in (r.getheader("Content-Type") or ""):
        return r.status, data  # raw download (file content)
    try:
        return r.status, json.loads(data)
    except json.JSONDecodeError:
        return r.status, data


def _upload(app, content: bytes, purpose: str = "batch"):
    boundary = "testboundary42"
    body = (
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="purpose"\r\n\r\n'
        f"{purpose}\r\n"
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="file"; '
        f'filename="reqs.jsonl"\r\n'
        f"Content-Type: application/jsonl\r\n\r\n"
    ).encode() + content + f"\r\n--{boundary}--\r\n".encode()
    return _call(
        app, "POST", "/v1/files", body=body,
        headers={"Content-Type": f"multipart/form-data; boundary={boundary}"},
    )


def _wait_batch(app, bid, timeout=120.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        st, b = _call(app, "GET", f"/v1/batches/{bid}")
        assert st == 200
        if b["status"] in ("completed", "failed", "cancelled"):
            return b
        time.sleep(0.3)
    raise AssertionError("batch did not finish")


def test_file_upload_and_content(batch_app):
    st, meta = _upload(batch_app, b'{"x": 1}\n')
    assert st == 200
    assert meta["object"] == "file" and meta["purpose"] == "batch"
    assert meta["bytes"] == len(b'{"x": 1}\n')
    st, got = _call(batch_app, "GET", f"/v1/files/{meta['id']}")
    assert st == 200 and got["id"] == meta["id"]
    st, content = _call(batch_app, "GET", f"/v1/files/{meta['id']}/content")
    assert st == 200 and content == b'{"x": 1}\n'
    st, _ = _call(batch_app, "GET", "/v1/files/file-nope")
    assert st == 404
    st, err = _upload(batch_app, b"x", purpose="fine-tune")
    assert st == 400


def test_batch_completions_match_online(batch_app):
    prompts = ["hello there", "general kenobi", "a third prompt"]
    lines = "\n".join(
        json.dumps({
            "custom_id": f"req-{i}",
            "method": "POST",
            "url": "/v1/completions",
            "body": {
                "model": "llama-tiny", "prompt": p, "max_tokens": 8,
                "temperature": 0,
            },
        })
        for i, p in enumerate(prompts)
    ).encode()
    st, meta = _upload(batch_app, lines)
    assert st == 200
    st, batch = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": meta["id"],
        "endpoint": "/v1/completions",
        "completion_window": "24h",
        "metadata": {"suite": "test"},
    })
    assert st == 200 and batch["object"] == "batch"
    done = _wait_batch(batch_app, batch["id"])
    assert done["status"] == "completed"
    assert done["request_counts"] == {
        "total": 3, "completed": 3, "failed": 0,
    }
    assert done["error_file_id"] is None
    st, out = _call(
        batch_app, "GET", f"/v1/files/{done['output_file_id']}/content"
    )
    assert st == 200
    rows = [json.loads(x) for x in out.decode().splitlines()]
    assert {r["custom_id"] for r in rows} == {"req-0", "req-1", "req-2"}
    by_id = {r["custom_id"]: r for r in rows}
    for i, p in enumerate(prompts):
        st, direct = _call(batch_app, "POST", "/v1/completions", {
            "model": "llama-tiny", "prompt": p, "max_tokens": 8,
            "temperature": 0,
        })
        assert st == 200
        got = by_id[f"req-{i}"]["response"]
        assert got["status_code"] == 200
        assert (
            got["body"]["choices"][0]["text"]
            == direct["choices"][0]["text"]
        )


def test_batch_error_lines_and_chat(batch_app):
    lines = "\n".join([
        json.dumps({
            "custom_id": "good",
            "method": "POST",
            "url": "/v1/chat/completions",
            "body": {
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4, "temperature": 0,
            },
        }),
        json.dumps({
            "custom_id": "bad-model",
            "method": "POST",
            "url": "/v1/chat/completions",
            "body": {
                "model": "missing-model",
                "messages": [{"role": "user", "content": "hi"}],
            },
        }),
        json.dumps({
            "custom_id": "bad-stream",
            "method": "POST",
            "url": "/v1/chat/completions",
            "body": {
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
            },
        }),
        json.dumps({"custom_id": "bad-url", "url": "/v1/embeddings",
                    "body": {}}),
    ]).encode()
    st, meta = _upload(batch_app, lines)
    st, batch = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": meta["id"], "endpoint": "/v1/chat/completions",
    })
    assert st == 200
    done = _wait_batch(batch_app, batch["id"])
    assert done["status"] == "completed"
    assert done["request_counts"]["completed"] == 1
    assert done["request_counts"]["failed"] == 3
    st, err = _call(
        batch_app, "GET", f"/v1/files/{done['error_file_id']}/content"
    )
    rows = {json.loads(x)["custom_id"]: json.loads(x)
            for x in err.decode().splitlines()}
    assert rows["bad-model"]["response"]["status_code"] == 404
    assert rows["bad-stream"]["error"]["message"].startswith(
        "stream is not supported"
    )
    st, out = _call(
        batch_app, "GET", f"/v1/files/{done['output_file_id']}/content"
    )
    good = json.loads(out.decode().splitlines()[0])
    assert good["custom_id"] == "good"
    msg = good["response"]["body"]["choices"][0]["message"]
    assert msg["role"] == "assistant"


def test_batch_embeddings_endpoint(batch_app):
    lines = json.dumps({
        "custom_id": "emb-0",
        "method": "POST",
        "url": "/v1/embeddings",
        "body": {"input": "embed me", "model": "bert-tiny"},
    }).encode()
    st, meta = _upload(batch_app, lines)
    st, batch = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": meta["id"], "endpoint": "/v1/embeddings",
    })
    assert st == 200
    done = _wait_batch(batch_app, batch["id"])
    assert done["status"] == "completed"
    assert done["request_counts"]["completed"] == 1
    st, out = _call(
        batch_app, "GET", f"/v1/files/{done['output_file_id']}/content"
    )
    row = json.loads(out.decode().splitlines()[0])
    emb = row["response"]["body"]["data"][0]["embedding"]
    assert isinstance(emb, list) and len(emb) > 8


def test_batch_validation_and_listing(batch_app):
    st, err = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": "file-nope", "endpoint": "/v1/completions",
    })
    assert st == 400
    st, err = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": "x", "endpoint": "/v2/other",
    })
    assert st == 400
    st, meta = _upload(batch_app, b"not json at all {{{")
    st, batch = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": meta["id"], "endpoint": "/v1/completions",
    })
    assert st == 200
    done = _wait_batch(batch_app, batch["id"])
    assert done["status"] == "failed"
    assert done["errors"]["data"][0]["code"] == "invalid_jsonl"
    # A valid-JSON-but-not-object line must fail that LINE, not hang the
    # batch (the runner used to die on AttributeError → stuck in_progress).
    st, meta2 = _upload(batch_app, b"42\n")
    st, b2 = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": meta2["id"], "endpoint": "/v1/completions",
    })
    done2 = _wait_batch(batch_app, b2["id"])
    assert done2["status"] == "completed"
    assert done2["request_counts"]["failed"] == 1
    st, _ = _call(batch_app, "GET", "/v1/batches?limit=abc")
    assert st == 400
    st, listing = _call(batch_app, "GET", "/v1/batches")
    assert st == 200 and listing["object"] == "list"
    assert any(b["id"] == batch["id"] for b in listing["data"])
    st, _ = _call(batch_app, "GET", "/v1/batches/batch_nope")
    assert st == 404


def test_batch_cancel(batch_app):
    # Deterministic mid-flight cancel: every dispatch waits on a gate the
    # test holds closed until the cancel response has landed, so lines
    # beyond the runner's concurrency window are provably never issued.
    store = batch_app.batch_store
    gate: dict = {}
    orig = store._dispatch_line

    async def gated(batch, line):
        if "event" not in gate:
            gate["event"] = asyncio.Event()
            gate["loop"] = asyncio.get_running_loop()
        await gate["event"].wait()
        return await orig(batch, line)

    store._dispatch_line = gated
    try:
        lines = "\n".join(
            json.dumps({
                "custom_id": f"slow-{i}",
                "method": "POST",
                "url": "/v1/completions",
                "body": {"prompt": "x", "max_tokens": 8, "temperature": 0},
            })
            for i in range(48)  # > the 32-concurrency window
        ).encode()
        st, meta = _upload(batch_app, lines)
        assert st == 200
        st, batch = _call(batch_app, "POST", "/v1/batches", {
            "input_file_id": meta["id"], "endpoint": "/v1/completions",
        })
        assert st == 200
        st, b = _call(batch_app, "POST", f"/v1/batches/{batch['id']}/cancel")
        assert st == 200 and b["status"] in ("cancelling", "cancelled")
        # Open the gate AFTER the cancel landed: gated in-flight lines
        # proceed, the 16 still queued at the semaphore are skipped.
        t0 = time.time()
        while "event" not in gate and time.time() - t0 < 30:
            time.sleep(0.05)
        assert "event" in gate, "runner never reached the gate"
        gate["loop"].call_soon_threadsafe(gate["event"].set)
        done = _wait_batch(batch_app, batch["id"])
        assert done["status"] == "cancelled"
        assert 0 < done["request_counts"]["completed"] < 48
    finally:
        store._dispatch_line = orig


def test_batch_pagination_and_file_delete(batch_app):
    # Create 3 tiny batches so pagination is self-contained regardless
    # of which other tests ran.
    for _ in range(3):
        st, meta = _upload(batch_app, json.dumps({
            "custom_id": "p", "method": "POST", "url": "/v1/completions",
            "body": {"prompt": "x", "max_tokens": 2, "temperature": 0},
        }).encode())
        st, b = _call(batch_app, "POST", "/v1/batches", {
            "input_file_id": meta["id"], "endpoint": "/v1/completions",
        })
        _wait_batch(batch_app, b["id"])
    st, page1 = _call(batch_app, "GET", "/v1/batches?limit=2")
    assert st == 200 and len(page1["data"]) == 2
    assert page1["last_id"] == page1["data"][-1]["id"]
    st, page2 = _call(
        batch_app, "GET", f"/v1/batches?limit=2&after={page1['last_id']}"
    )
    assert st == 200
    ids1 = {b["id"] for b in page1["data"]}
    ids2 = {b["id"] for b in page2["data"]}
    assert not ids1 & ids2  # no overlap: the cursor advanced
    st, _ = _call(batch_app, "GET", "/v1/batches?after=batch_bogus")
    assert st == 400

    st, meta = _upload(batch_app, b'{"y": 2}\n')
    fid = meta["id"]
    st, gone = _call(batch_app, "DELETE", f"/v1/files/{fid}")
    assert st == 200 and gone["deleted"] is True
    st, _ = _call(batch_app, "GET", f"/v1/files/{fid}")
    assert st == 404
    st, _ = _call(batch_app, "DELETE", f"/v1/files/{fid}")
    assert st == 404


def test_batch_forwards_auth_headers():
    """On an authenticated app the internal line dispatch re-runs the
    middleware chain — the creator's credentials must ride along or
    every line 401s."""
    app = App(config=MockConfig({
        "APP_NAME": "batch-auth", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
    }))
    add_openai_routes(app)
    app.batch_store = add_openai_batch_routes(app)
    app.enable_api_key_auth("sekrit")
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=120)
    try:
        auth = {"X-API-KEY": "sekrit"}
        line = json.dumps({
            "custom_id": "a", "method": "POST", "url": "/v1/completions",
            "body": {"prompt": "hi", "max_tokens": 4, "temperature": 0},
        }).encode()
        boundary = "tb9"
        body = (
            f"--{boundary}\r\n"
            f'Content-Disposition: form-data; name="purpose"\r\n\r\nbatch\r\n'
            f"--{boundary}\r\n"
            f'Content-Disposition: form-data; name="file"; '
            f'filename="r.jsonl"\r\n\r\n'
        ).encode() + line + f"\r\n--{boundary}--\r\n".encode()
        st, meta = _call(
            app, "POST", "/v1/files", body=body,
            headers={
                "Content-Type": f"multipart/form-data; boundary={boundary}",
                **auth,
            },
        )
        assert st == 200
        st, batch = _call(app, "POST", "/v1/batches", {
            "input_file_id": meta["id"], "endpoint": "/v1/completions",
        }, headers=auth)
        assert st == 200
        t0 = time.time()
        while time.time() - t0 < 60:
            st, b = _call(
                app, "GET", f"/v1/batches/{batch['id']}", headers=auth
            )
            if b["status"] in ("completed", "failed", "cancelled"):
                break
            time.sleep(0.3)
        assert b["status"] == "completed"
        assert b["request_counts"] == {
            "total": 1, "completed": 1, "failed": 0,
        }
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)


def test_store_caps_and_retention(batch_app, monkeypatch):
    """The in-memory store is bounded: oversize uploads 413, a full
    store 413s further uploads, and terminal batches past retention are
    evicted together with their files (ADVICE r4: an exposed /v1/files
    must not let clients exhaust host memory)."""
    store = batch_app.batch_store
    # The module-scoped app accumulates files from earlier tests; this
    # test's quotas are tiny, so start from a clean store.
    store.files.clear()
    store.batches.clear()
    monkeypatch.setattr(store, "max_file_bytes", 64)
    monkeypatch.setattr(store, "max_store_bytes", 160)

    st, err = _upload(batch_app, b"x" * 65)
    assert st == 413, err
    st, meta1 = _upload(batch_app, b"y" * 60)
    assert st == 200
    st, meta2 = _upload(batch_app, b"y" * 60)
    assert st == 200
    st, err = _upload(batch_app, b"y" * 60)  # 180 > 160 total
    assert st == 413, err
    # Deleting frees quota.
    st, _ = _call(batch_app, "DELETE", f"/v1/files/{meta1['id']}")
    assert st == 200
    st, meta3 = _upload(batch_app, b"y" * 60)
    assert st == 200
    for m in (meta2, meta3):
        _call(batch_app, "DELETE", f"/v1/files/{m['id']}")

    # Retention: a completed batch + its files vanish once its terminal
    # timestamp ages past the window; fresh files survive.
    monkeypatch.setattr(store, "max_file_bytes", 4096)
    monkeypatch.setattr(store, "max_store_bytes", 65536)
    line = json.dumps({
        "custom_id": "r", "method": "POST", "url": "/v1/completions",
        "body": {"prompt": "hi", "max_tokens": 2, "temperature": 0},
    }).encode()
    st, meta = _upload(batch_app, line)
    assert st == 200
    st, batch = _call(batch_app, "POST", "/v1/batches", {
        "input_file_id": meta["id"], "endpoint": "/v1/completions",
    })
    assert st == 200
    done = _wait_batch(batch_app, batch["id"])
    assert done["status"] == "completed" and done["output_file_id"]
    # Age the batch out and trigger eviction via the next mutation.
    store.batches[batch["id"]].completed_at -= store.retention_s + 10
    for f in store.files.values():
        f.created_at -= store.retention_s + 10
    st, fresh = _upload(batch_app, b"fresh")
    assert st == 200
    assert batch["id"] not in store.batches
    assert meta["id"] not in store.files
    assert done["output_file_id"] not in store.files
    assert fresh["id"] in store.files  # the trigger upload survives
    _call(batch_app, "DELETE", f"/v1/files/{fresh['id']}")
