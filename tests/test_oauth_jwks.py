"""RS256 OAuth end to end (reference ``oauth.go:53-194``): a real JWKS
endpoint served in-proc, `App.enable_oauth` wiring, RS256 signature
verification, and every rejection path (bad signature, unknown kid,
expired token, unsupported alg, malformed token). The HS256 shared-secret
path is covered in test_parity_misc; this pins the production RSA path
the JWKSProvider exists for."""

from __future__ import annotations

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

cryptography = pytest.importorskip("cryptography")

from cryptography.hazmat.primitives.asymmetric import padding, rsa  # noqa: E402
from cryptography.hazmat.primitives import hashes  # noqa: E402

from tests.test_http_server import AppHarness, make_app  # noqa: E402


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _int_b64url(n: int) -> str:
    return _b64url(n.to_bytes((n.bit_length() + 7) // 8, "big"))


@pytest.fixture(scope="module")
def rsa_key():
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


@pytest.fixture(scope="module")
def jwks_server(rsa_key):
    pub = rsa_key.public_key().public_numbers()
    jwks = {
        "keys": [
            {"kty": "oct", "kid": "sym"},  # non-RSA: must be skipped
            {"kty": "RSA", "kid": "bad", "n": "!!!", "e": "AQAB"},  # bad jwk
            {
                "kty": "RSA",
                "kid": "test-key",
                "n": _int_b64url(pub.n),
                "e": _int_b64url(pub.e),
            },
        ]
    }

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(jwks).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}/jwks.json"
    srv.shutdown()


def _jwt(rsa_key, kid="test-key", alg="RS256", exp=None, claims=None):
    header = {"alg": alg, "kid": kid}
    payload = {"sub": "user-1", **(claims or {})}
    if exp is not None:
        payload["exp"] = exp
    h = _b64url(json.dumps(header).encode())
    p = _b64url(json.dumps(payload).encode())
    sig = rsa_key.sign(
        f"{h}.{p}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    return f"{h}.{p}.{_b64url(sig)}"


@pytest.fixture(scope="module")
def oauth_app(jwks_server):
    app = make_app()

    @app.get("/claims")
    def claims(ctx):
        return {"sub": ctx.get("JWTClaims")["sub"]}

    app.enable_oauth(jwks_server, refresh_interval_s=3600.0)
    with AppHarness(app) as harness:
        yield harness


def _get(harness, token):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    return harness.request("GET", "/claims", headers=headers)


def test_valid_rs256_token_passes_claims(oauth_app, rsa_key):
    status, _, body = _get(oauth_app, _jwt(rsa_key))
    assert status == 200
    assert json.loads(body)["data"]["sub"] == "user-1"


def test_missing_and_malformed_tokens_401(oauth_app, rsa_key):
    status, _, body = _get(oauth_app, None)
    assert status == 401 and b"missing" in body
    status, _, body = _get(oauth_app, "not.a.jwt")
    assert status == 401 and b"malformed" in body


def test_unknown_kid_401(oauth_app, rsa_key):
    status, _, body = _get(oauth_app, _jwt(rsa_key, kid="nope"))
    assert status == 401 and b"unknown key id" in body


def test_tampered_signature_401(oauth_app, rsa_key):
    token = _jwt(rsa_key)
    h, p, s = token.split(".")
    forged = json.loads(base64.urlsafe_b64decode(p + "=="))
    forged["sub"] = "attacker"
    tampered = f"{h}.{_b64url(json.dumps(forged).encode())}.{s}"
    status, _, body = _get(oauth_app, tampered)
    assert status == 401 and b"invalid signature" in body


def test_expired_token_401(oauth_app, rsa_key):
    status, _, body = _get(oauth_app, _jwt(rsa_key, exp=time.time() - 60))
    assert status == 401 and b"expired" in body
    status, _, _ = _get(oauth_app, _jwt(rsa_key, exp=time.time() + 3600))
    assert status == 200


def test_unsupported_alg_401(oauth_app, rsa_key):
    status, _, body = _get(oauth_app, _jwt(rsa_key, alg="none"))
    assert status == 401 and b"unsupported alg" in body


def test_health_probe_exempt(oauth_app):
    status, _, _ = oauth_app.request("GET", "/.well-known/alive")
    assert status == 200


def test_provider_survives_dead_endpoint(rsa_key):
    from gofr_tpu.http.middleware import JWKSProvider
    from gofr_tpu.testutil.mock_logger import MockLogger

    logger = MockLogger()
    provider = JWKSProvider(
        "http://127.0.0.1:1/nope", refresh_interval_s=3600.0, logger=logger
    )
    provider.refresh()  # must not raise
    assert provider.key("anything") is None
    provider.stop()
