"""The ops surface on the metrics port (net-new; nearest reference
analog is pprof-on-metrics-port which the reference does not ship):
/debug/threads (live stack dump), /debug/engine (engine health without
the app port), /debug/tpu-trace (bounded profiler capture), plus the
graceful _run_async stop path."""

from __future__ import annotations

import asyncio
import http.client
import json
import threading
import time

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig


@pytest.fixture(scope="module")
def debug_app():
    app = App(config=MockConfig({
        "APP_NAME": "debug-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
        # Generous objectives so /debug/slo is populated AND compliant
        # regardless of CI machine speed.
        "TPU_SLO_TTFT_MS": "600000", "TPU_SLO_AVAILABILITY": "0.999",
    }))
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=120)
    yield app
    asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
    loop.call_soon_threadsafe(loop.stop)


def _metrics_get(app, path):
    c = http.client.HTTPConnection(
        "127.0.0.1", app.metrics_port, timeout=60
    )
    c.request("GET", path)
    r = c.getresponse()
    body = r.read()
    c.close()
    return r.status, body


def test_alive_and_404_on_metrics_port(debug_app):
    st, body = _metrics_get(debug_app, "/.well-known/alive")
    assert st == 200 and json.loads(body)["status"] == "UP"
    st, _ = _metrics_get(debug_app, "/debug/nope")
    assert st == 404


def test_debug_threads_dumps_live_stacks(debug_app):
    st, body = _metrics_get(debug_app, "/debug/threads")
    assert st == 200
    text = body.decode()
    assert "Thread" in text
    # The engine's scheduler thread must be visible in a serving app.
    assert "tpu-scheduler" in text


def test_debug_engine_reports_health(debug_app):
    st, body = _metrics_get(debug_app, "/debug/engine")
    assert st == 200
    stats = json.loads(body)
    assert "tpu" in stats
    assert stats["tpu"]["status"] in ("UP", "DOWN")
    assert stats["tpu"]["details"]["model"] == "llama-tiny"


def test_debug_flight_serves_request_timelines(debug_app):
    """/debug/flight (docs/advanced-guide/observability.md): after one
    generation the flight recorder serves its timeline — phase
    durations, token counts, trace id — on the ops port."""
    result = debug_app.container.tpu.generate_sync(
        "flight recorder", max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    st, body = _metrics_get(debug_app, "/debug/flight")
    assert st == 200
    flights = json.loads(body)
    assert flights["tpu"]["enabled"] is True
    entries = flights["tpu"]["records"] + flights["tpu"]["pinned"]
    match = [
        e for e in entries
        if e["outcome"] == "ok"
        and e["output_tokens"] == len(result.token_ids)
    ]
    assert match, entries
    entry = match[-1]
    assert entry["trace_id"]
    for phase in ("queue_wait_s", "prefill_s", "ttft_s", "e2e_s"):
        assert phase in entry["phases"], entry["phases"]


def test_debug_capacity_reports_device_resources(debug_app):
    """/debug/capacity (docs/advanced-guide/observability.md
    "Device-resource signals"): the HBM ledger, XLA compile counts,
    and the steady-state recompile counter on the ops port."""
    st, body = _metrics_get(debug_app, "/debug/capacity")
    assert st == 200
    caps = json.loads(body)
    report = caps["tpu"]
    assert report["model"] == "llama-tiny"
    comps = report["hbm"]["components"]
    assert comps["params"] > 0 and comps["kv_pool"] > 0
    assert report["hbm"]["total_bytes"] == sum(comps.values())
    assert 0.0 <= report["hbm"]["headroom_ratio"] <= 1.0
    assert report["compiles"]["steady_state_recompiles"] == 0


def test_debug_tenants_serves_attribution_table(debug_app):
    """/debug/tenants (docs/advanced-guide/observability.md "Tenant
    attribution & SLOs"): the FULL unclamped per-tenant table — tokens
    by phase, KV-block·seconds, outcome counts — on the ops port."""
    result = debug_app.container.tpu.generate_sync(
        "tenant table", max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, tenant="acme", timeout=120,
    )
    st, body = _metrics_get(debug_app, "/debug/tenants")
    assert st == 200
    report = json.loads(body)["tpu"]
    assert report["enabled"] is True
    acme = report["tenants"]["acme"]
    assert acme["decode_tokens"] >= len(result.token_ids)
    assert acme["requests"]["ok"] >= 1
    assert acme["prefill_tokens"] > 0
    # Conservation anchor rides the table.
    assert report["pool_kv_block_seconds"] >= sum(
        t["kv_block_seconds"] for t in report["tenants"].values()
    ) - 1e-3
    assert report["label_max"] >= 1


def test_debug_slo_serves_burn_state(debug_app):
    """/debug/slo: per-objective multi-window burn rates and the
    compliance bit on the ops port."""
    debug_app.container.tpu.generate_sync(
        "slo probe", max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    st, body = _metrics_get(debug_app, "/debug/slo")
    assert st == 200
    report = json.loads(body)["tpu"]
    assert report["enabled"] is True and report["compliant"] is True
    for slo in ("ttft", "availability"):
        windows = report["slos"][slo]["windows"]
        assert set(windows) == {"5m", "1h"}
        for w in windows.values():
            assert w["total"] >= 1 and w["burn_rate"] == 0.0


def test_debug_brownout_serves_ladder_state(debug_app):
    """/debug/brownout (docs/advanced-guide/resilience.md "Brownout &
    overload control"): the degradation-ladder level, AIMD budget
    factor, thresholds, and per-action counters on the ops port — the
    actuator's state next to /debug/slo's signal."""
    st, body = _metrics_get(debug_app, "/debug/brownout")
    assert st == 200
    report = json.loads(body)["tpu"]
    assert report["enabled"] is True
    assert report["level"] == 0
    assert report["budget_factor"] == 1.0
    assert report["enter_burn"] > report["exit_burn"]
    assert report["sustain_s"] > 0 and report["exit_sustain_s"] > 0
    assert report["projected_recovery_s"] >= 1.0
    assert set(report["class_admit_fraction"]) == {
        "interactive", "standard", "batch"
    }
    assert report["transitions"] == {"up": 0, "down": 0}


def test_debug_loop_serves_phase_stats_and_anomalies(debug_app):
    """/debug/loop (docs/advanced-guide/observability.md
    "Scheduler-loop signals"): per-phase rolling stats, loop
    utilization, the host-overhead ratio, and the (bounded) anomaly
    rings on the ops port."""
    debug_app.container.tpu.generate_sync(
        "loop probe", max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    st, body = _metrics_get(debug_app, "/debug/loop")
    assert st == 200
    report = json.loads(body)["tpu"]
    assert report["enabled"] is True
    assert report["passes"] >= 1
    assert 0.0 <= report["utilization"] <= 1.0
    assert 0.0 <= report["host_overhead_ratio"] <= 1.0
    assert report["stall_s"] > 0 and report["stall_factor"] > 0
    assert report["self_overhead_s"] >= 0.0
    for phase in ("reap", "prefill", "emit_flush"):
        stats = report["phases"][phase]
        assert stats["count"] >= 1 and stats["total_s"] >= 0.0
        assert stats["p95_ms"] >= stats["p50_ms"] >= 0.0
    assert isinstance(report["anomalies"], list)
    assert isinstance(report["pinned_anomalies"], list)


def test_ops_tier_import_endpoint_shapes(debug_app):
    """POST /ops/tier-import (docs/advanced-guide/resilience.md
    "Disaggregated prefill/decode", wire leg): GET is a 405, an
    unparseable body is a 400 ``rejected``, and a well-framed payload
    that cannot alias here (this app has no paged pool) is a 200
    ``fused`` — never a 5xx on any input."""
    import http.client

    import numpy as np

    from gofr_tpu.ops.kv_cache import KVBlockPayload, payload_checksum, \
        payload_to_wire

    def _post(body):
        c = http.client.HTTPConnection(
            "127.0.0.1", debug_app.metrics_port, timeout=60
        )
        c.request("POST", "/ops/tier-import", body=body)
        r = c.getresponse()
        out = r.read()
        c.close()
        return r.status, out

    st, body = _metrics_get(debug_app, "/ops/tier-import")
    assert st == 405
    st, body = _post(b"not a payload")
    assert st == 400
    assert json.loads(body)["result"] == "rejected"
    k = np.zeros((1, 1, 1, 8, 4), dtype=np.float32)
    payload = KVBlockPayload(
        block=8, token_ids=tuple(range(8)), k=k, v=k,
        src="shape-test", checksum=payload_checksum(k, k),
        geometry=(1, 1, 8, 4, "float32", False),
    )
    st, body = _post(payload_to_wire(payload))
    assert st == 200
    report = json.loads(body)
    assert report["result"] == "fused"  # no paged pool on this app
    assert report["blocks"] == 1


def test_debug_tpu_trace_validates_and_captures(debug_app):
    st, body = _metrics_get(debug_app, "/debug/tpu-trace?ms=nope")
    assert st == 400 and b"integer" in body
    st, body = _metrics_get(debug_app, "/debug/tpu-trace?ms=50")
    out = json.loads(body)
    # 200 with a trace dir, or a clean 500 if the profiler backend is
    # unavailable in this environment — never a hang or a raw crash.
    assert st in (200, 500), out
    if st == 200:
        assert out["captured_ms"] == 50 and out["trace_dir"]


def test_debug_control_reports_the_control_plane(debug_app):
    """/debug/control (docs/advanced-guide/resilience.md): the control
    plane is default-on, so the ops port serves its full snapshot —
    per-signal guard status, per-loop mode, the bounded decision log."""
    st, body = _metrics_get(debug_app, "/debug/control")
    assert st == 200
    snap = json.loads(body)["tpu"]
    assert snap["enabled"] is True
    assert snap["passes"] >= 1 or snap["passes"] == 0  # shape, not timing
    assert set(snap["signals"]) >= {
        "tenant_burn", "queue_depth", "throughput",
    }
    for sig in snap["signals"].values():
        assert sig["status"] in ("ok", "last_good", "observe_only", "init")
        assert 0.0 <= sig["health"] <= 1.0
    loops = snap["loops"]
    assert loops["tenant_brownout"]["mode"] in (
        "off", "observe_only", "active"
    )
    assert "pressure" in loops["host_pressure"]
    assert "depth_threshold" in loops["predictive"]
    assert isinstance(snap["decisions"], list)


def test_debug_lockgraph_diffs_runtime_against_static(debug_app):
    """/debug/lockgraph: the runtime lock-order graph (what lockcheck
    actually witnessed) diffed against graftlint's static GL021 model —
    runtime_only edges are blind spots in the static model, static_only
    edges are paths this process never exercised."""
    st, body = _metrics_get(debug_app, "/debug/lockgraph")
    assert st == 200
    report = json.loads(body)
    assert set(report) >= {"runtime", "static", "diff", "violations"}
    # TPU_LOCKCHECK is not set in this app: the runtime side says so
    # explicitly instead of masquerading as "no edges observed".
    assert report["runtime"]["enabled"] is False
    assert report["runtime"]["edges"] == {}
    static = report["static"]
    assert isinstance(static["edges"], list)
    for edge in static["edges"]:
        assert " -> " in edge
    diff = report["diff"]
    assert isinstance(diff["runtime_only"], list)
    assert isinstance(diff["static_only"], list)
    # With runtime observation off, nothing can be runtime-only.
    assert diff["runtime_only"] == []
    assert isinstance(report["violations"], list)


def test_debug_async_reports_disabled_when_off(debug_app):
    """/debug/async with TPU_ASYNC unset: the plane was never built
    and the surface says so instead of 404ing."""
    st, body = _metrics_get(debug_app, "/debug/async")
    assert st == 200
    assert json.loads(body) == {"enabled": False}


def test_debug_async_serves_plane_state():
    """/debug/async with TPU_ASYNC=1 (docs/advanced-guide/resilience.md
    "Async serving & delivery semantics"): topics, knobs, lag,
    in-flight leases, the delivery counters, and the dedup ledger's
    occupancy — the operator's one read for "is async healthy"."""
    app = App(config=MockConfig({
        "APP_NAME": "async-debug-test", "HTTP_PORT": "0",
        "METRICS_PORT": "0", "TPU_MODEL": "llama-tiny",
        "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
        "TPU_ASYNC": "1", "TPU_ASYNC_POLL_S": "0.01",
    }))
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=120)
    try:
        plane = app._async_plane
        assert plane is not None and plane.report()["running"] is True
        plane.broker.publish(plane.request_topic, json.dumps({
            "prompt": "async debug", "max_new_tokens": 2,
            "temperature": 0.0, "stop_on_eos": False,
        }))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if plane.counters["published"] >= 1:
                break
            time.sleep(0.02)
        st, body = _metrics_get(app, "/debug/async")
        assert st == 200
        report = json.loads(body)
        assert report["enabled"] is True
        assert report["model"] == "llama-tiny"
        assert report["request_topic"] == "tpu.requests"
        assert report["reply_topic"] == "tpu.replies"
        assert report["dlq_topic"] == "tpu.dlq"
        assert report["counters"]["published"] >= 1
        assert report["counters"]["consumed"] >= 1
        assert report["counters"]["dead_lettered"] == 0
        assert report["dedup_ledger"]["size"] >= 1
        for key in ("redelivery_max", "lease_s", "max_inflight",
                    "deadline_s", "lag", "inflight_leases", "inflight",
                    "draining"):
            assert key in report, key
        # The reply actually landed on the reply topic.
        assert plane.broker.size(plane.reply_topic) == 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=60)
        loop.call_soon_threadsafe(loop.stop)


def test_run_async_stops_on_stop_event():
    """The signal-driven run loop: start → stop_event → graceful stop
    (the path run() drives under SIGINT/SIGTERM)."""
    app = App(config=MockConfig({
        "APP_NAME": "runloop-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
    }))

    async def scenario():
        task = asyncio.get_running_loop().create_task(app._run_async())
        for _ in range(200):
            if getattr(app, "_stop_event", None) is not None:
                break
            await asyncio.sleep(0.02)
        assert app._stop_event is not None, "run loop never started"
        app._stop_event.set()
        await asyncio.wait_for(task, timeout=30)

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(scenario())
    finally:
        loop.close()
