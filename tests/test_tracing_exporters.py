"""Trace exporter wire formats against an in-proc HTTP collector:
Zipkin JSON (reference ``exporter.go:58-96``) and OTLP/HTTP JSON — the
jaeger sink is a DISTINCT protocol, not a zipkin alias (reference treats
jaeger as its own OTLP exporter, ``gofr.go:277-286``; VERDICT r2 #2)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.tracing import (
    NoopExporter,
    OTLPExporter,
    ZipkinExporter,
    exporter_from_config,
)
from gofr_tpu.tracing.tracer import Span


@pytest.fixture
def collector():
    """In-proc HTTP sink capturing (path, body) of every POST."""
    received: list[tuple[str, bytes]] = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            received.append((self.path, body))
            self.send_response(202)
            self.end_headers()

        def log_message(self, *args):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}", received
    srv.shutdown()


def _span(**kw) -> Span:
    defaults = dict(
        name="GET /hello",
        trace_id="0af7651916cd43dd8448eb211c80319c",
        span_id="b7ad6b7169203331",
        parent_id="00f067aa0ba902b7",
        start_ns=1_700_000_000_000_000_000,
        end_ns=1_700_000_000_005_000_000,
        attributes={"http.status": 200},
    )
    defaults.update(kw)
    return Span(**defaults)


def test_zipkin_wire_format(collector):
    url, received = collector
    exp = ZipkinExporter(url + "/api/v2/spans", flush_interval_s=0.05)
    exp.export(_span(), "svc-a")
    exp.shutdown()
    assert received
    batch = json.loads(received[0][1])
    assert isinstance(batch, list)
    span = batch[0]
    assert span["traceId"] == "0af7651916cd43dd8448eb211c80319c"
    assert span["parentId"] == "00f067aa0ba902b7"
    assert span["duration"] == 5000
    assert span["localEndpoint"] == {"serviceName": "svc-a"}
    assert span["tags"] == {"http.status": "200"}


def test_otlp_wire_format(collector):
    url, received = collector
    exp = OTLPExporter(url + "/v1/traces", flush_interval_s=0.05)
    exp.export(_span(), "svc-b")
    exp.export(_span(span_id="c000000000000001", status="ERROR"), "svc-b")
    exp.shutdown()
    assert received
    body = json.loads(received[0][1])
    rs = body["resourceSpans"]
    assert len(rs) == 1
    res_attrs = rs[0]["resource"]["attributes"]
    assert {"key": "service.name", "value": {"stringValue": "svc-b"}} in res_attrs
    spans = rs[0]["scopeSpans"][0]["spans"]
    assert len(spans) == 2
    s0 = spans[0]
    assert s0["traceId"] == "0af7651916cd43dd8448eb211c80319c"
    assert s0["parentSpanId"] == "00f067aa0ba902b7"
    assert s0["startTimeUnixNano"] == "1700000000000000000"
    assert s0["endTimeUnixNano"] == "1700000000005000000"
    assert {"key": "http.status", "value": {"stringValue": "200"}} in s0["attributes"]
    assert s0["status"] == {"code": 1}
    assert s0["kind"] == 1  # has a parent → INTERNAL, not SERVER
    assert spans[1]["status"] == {"code": 2}
    assert "_service" not in s0


def test_otlp_root_span_is_server_kind(collector):
    url, received = collector
    exp = OTLPExporter(url + "/v1/traces", flush_interval_s=0.05)
    exp.export(_span(parent_id=None), "svc")
    exp.shutdown()
    span = json.loads(received[0][1])["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert span["kind"] == 2
    assert "parentSpanId" not in span


def test_exporter_selection():
    assert isinstance(
        exporter_from_config(MockConfig({
            "TRACE_EXPORTER": "jaeger", "TRACER_URL": "http://j:4318/v1/traces",
        })),
        OTLPExporter,
    )
    assert isinstance(
        exporter_from_config(MockConfig({
            "TRACE_EXPORTER": "otlp", "TRACER_URL": "http://j:4318/v1/traces",
        })),
        OTLPExporter,
    )
    assert isinstance(
        exporter_from_config(MockConfig({
            "TRACE_EXPORTER": "zipkin", "TRACER_URL": "http://z:9411/api/v2/spans",
        })),
        ZipkinExporter,
    )
    assert isinstance(
        exporter_from_config(MockConfig({"TRACE_EXPORTER": "jaeger"})),
        NoopExporter,  # no URL
    )


def test_export_survives_dead_collector():
    exp = OTLPExporter("http://127.0.0.1:1/v1/traces", flush_interval_s=0.05)
    exp.export(_span(), "svc")
    exp.shutdown()  # no raise
