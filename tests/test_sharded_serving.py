"""GSPMD-sharded serving data-plane suite (ISSUE 9 acceptance gate).

The tp-invariance contract, pinned: on the 8 virtual CPU devices the
conftest forces, a ``tp=2`` engine (params Megatron-sharded, the paged
KV pool's head axis sharded over the mesh) produces BYTE-IDENTICAL
greedy (and seeded-sampled) streams to an unsharded ``tp=1`` engine —
including prefix-cache hits, disaggregated-tier KV-block transfers
between two differently-placed sharded pods, and a mid-stream replica
failover. This is the trimmed tp-serving subset of the multichip dryrun
(``__graft_entry__.dryrun_multichip`` step 5), wired as a named CI step
so sharded-serving token-identity regresses loudly.

Also covered: the pod layout (dp across replicas, tp within — the
backend carves DISJOINT device slices per in-proc replica), mesh
topology advertising (health probes, replica descriptors,
``/debug/flight``, the ``app_tpu_mesh_devices`` gauge), and the
``tpu.shard_init`` boot span.

Determinism: engines share the default seed; faults fire on exact hit
counts through ``gofr_tpu/faults``; supervisor backoff sleeps are
recorded, not slept.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.config import MockConfig
from gofr_tpu.container import Container
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.supervisor import EngineSupervisor
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.replica_pool import EngineReplica, ReplicaPool
from gofr_tpu.tracing import Tracer, get_tracer, set_tracer

#: 96 tokens = exactly 3 full 32-token KV blocks, so prefix hits,
#: tier transfers, and the COW boundary all engage.
PROMPT = list(range(2, 200, 3)) + [7] * 30
assert len(PROMPT) == 96

#: Every engine in this suite uses the same serving geometry, so the
#: jitted programs compile once per (mesh placement) and are shared.
ENG_KW = dict(
    n_slots=4, max_len=256, window_k=4, pipeline_depth=1,
    prefill_chunk=32, kv_block=32, auto_prefix=True,
)


def _device_slices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 4, "suite needs the conftest's 8 virtual devices"
    return devs[:2], devs[2:4]


@pytest.fixture(scope="module")
def metrics():
    # The container's registered instrument set — what production
    # records into (includes app_tpu_mesh_devices).
    return Container.create(MockConfig({"APP_NAME": "shard-test"})).metrics


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _make_engine(metrics, devices=None, tp=0, **kw):
    eng = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(), metrics=metrics,
        tp=tp, devices=devices, **{**ENG_KW, **kw},
    )
    eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def engines(metrics):
    """The shared pair: an unsharded tp=1 reference and a tp=2 engine
    on the first device slice. Module-scoped — construction and
    first-dispatch GSPMD compiles dominate this suite's wall clock."""
    slice0, _ = _device_slices()
    ref = _make_engine(metrics)
    tp2 = _make_engine(metrics, devices=slice0, tp=2)
    yield ref, tp2
    faults.reset()
    for eng in (ref, tp2):
        eng.close()


def _drain_stream(req, timeout=120.0):
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _counter_total(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    total = 0.0
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            total += value
    return total


def _gauge(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            return value
    return None


# ----------------------------------------------------------------------
# the sharded engine IS sharded (not silently replicated)
# ----------------------------------------------------------------------


def test_tp2_engine_shards_params_and_paged_pool(engines):
    _, tp2 = engines
    assert tp2.tp == 2
    topo = tp2.mesh_topology()
    assert topo["axes"] == {"tp": 2}
    assert topo["n_devices"] == 2
    # The paged KV pool's planes actually SPAN both chips (the head
    # axis shards over tp) — a silently-replicated cache would defeat
    # the HBM-scaling point of the tentpole.
    assert len(tp2.cache.k.sharding.device_set) == 2
    assert len(tp2.cache.v.sharding.device_set) == 2
    # Megatron-sharded params: a column-parallel projection spans both
    # chips too.
    wq = tp2.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 2
    # Host logic stays device-count-agnostic: the block table is
    # per-LOGICAL-block, identical in shape to the unsharded engine's.
    ref, _ = engines
    assert tp2.cache.block_table.shape == ref.cache.block_table.shape
    assert tp2.cache.n_blocks == ref.cache.n_blocks


# ----------------------------------------------------------------------
# tp-invariance: byte-identical streams, cold and prefix-cache-warm
# ----------------------------------------------------------------------


def test_tp2_greedy_streams_byte_identical_including_prefix_hits(engines):
    ref, tp2 = engines
    params = dict(max_new_tokens=16, temperature=0.0, stop_on_eos=False)

    # COLD: first sight of this prompt on both engines.
    want = ref.generate_sync(PROMPT, timeout=240, **params)
    req = tp2.submit_generate(PROMPT, **params)
    toks = _drain_stream(req)
    got = req.future.result(timeout=5)
    assert toks == got.token_ids == want.token_ids
    assert got.finish_reason == want.finish_reason

    # WARM: the retired prompt's full blocks are radix-indexed; the
    # repeat admission-aliases them zero-copy — on the SHARDED pool
    # exactly as on the unsharded one — with strictly fewer prefill
    # chunk dispatches and a byte-identical stream.
    hits0, chunks0 = tp2._prefix_hit_tokens, tp2._prefill_chunk_steps
    ref_hits0 = ref._prefix_hit_tokens
    want_warm = ref.generate_sync(PROMPT, timeout=240, **params)
    got_warm = tp2.generate_sync(PROMPT, timeout=240, **params)
    assert got_warm.token_ids == want_warm.token_ids == want.token_ids
    assert tp2._prefix_hit_tokens > hits0
    assert tp2._prefix_hit_tokens - hits0 == ref._prefix_hit_tokens - ref_hits0
    assert tp2._prefill_chunk_steps - chunks0 < chunks0


def test_tp2_seeded_sampled_stream_byte_identical(engines):
    ref, tp2 = engines
    params = dict(
        max_new_tokens=24, temperature=0.9, seed=4242, stop_on_eos=False,
    )
    want = ref.generate_sync("sharded sampling", timeout=240, **params)
    got = tp2.generate_sync("sharded sampling", timeout=240, **params)
    assert got.token_ids == want.token_ids
    assert len(want.token_ids) == 24


# ----------------------------------------------------------------------
# disaggregated tiers over sharded pods: the export/import seam at tp=2
# ----------------------------------------------------------------------


def test_tier_transfer_between_sharded_pods_byte_identical(
    metrics, engines
):
    """Prefill pod on devices[0:2] ships its finished KV blocks to a
    decode pod on devices[2:4] — the payload leaves one mesh and lands
    on ANOTHER (different device placement), through the same
    per-logical-block host-bounce seam as tp=1. Stream byte-identical
    to the unsharded reference, transfer result "ok"."""
    ref, tp2 = engines
    slice0, slice1 = _device_slices()
    dc = _make_engine(metrics, devices=slice1, tp=2)
    pool = ReplicaPool(
        [
            EngineReplica("pf", tp2, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        probe_interval_s=0,
        probe_timeout_s=60.0,
        hedge_delay_s=300.0,
        transfer_retries=2,
        transfer_backoff_s=0.01,
        sleep=lambda s: None,
        rng=random.Random(7),
        metrics=metrics,
    )
    try:
        params = dict(max_new_tokens=12, temperature=0.0, stop_on_eos=False)
        want = ref.generate_sync(PROMPT, timeout=240, **params)
        ok0 = _counter_total(
            metrics, "app_tpu_tier_transfers_total", result="ok"
        )
        req = pool.submit_generate(PROMPT, **params)
        toks = _drain_stream(req)
        result = req.future.result(timeout=5)
        assert toks == result.token_ids == want.token_ids
        assert _counter_total(
            metrics, "app_tpu_tier_transfers_total", result="ok"
        ) == ok0 + 1
        # The decode pod imported the blocks into ITS sharded pool and
        # admission aliased them (zero-copy radix hit, tp>1; the whole
        # prompt is cached, so the COW boundary re-writes the final
        # position — 95 of 96 prompt tokens count as hit).
        assert dc._prefix_hit_tokens >= 3 * 32 - 1
    finally:
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)
            replica.set_tier_exporter(None)
        tp2.tier_role = "fused"
        dc.close()


# ----------------------------------------------------------------------
# mid-stream failover between sharded pods stays byte-identical
# ----------------------------------------------------------------------


def test_mid_stream_failover_between_sharded_pods_byte_identical(metrics):
    """Two tp=2 pods on disjoint device slices behind a pool; pod A's
    device dies mid-stream and exhausts its restart budget — the pool
    hands the live request to pod B, and the client's GREEDY stream is
    byte-identical to a fault-free run (the dryrun contract, now
    surviving a replica loss)."""
    slice0, slice1 = _device_slices()

    def supervised(devices):
        eng = InferenceEngine(
            "llama-tiny", tokenizer=ByteTokenizer(), metrics=metrics,
            tp=2, devices=devices, **ENG_KW,
        )
        sup = EngineSupervisor(
            eng, max_restarts=1, backoff_s=0.25, backoff_reset_s=60.0,
            rng=random.Random(1234), sleep=lambda s: None, metrics=metrics,
        ).start()
        eng.start_sync()
        return eng, sup

    eng_a, sup_a = supervised(slice0)
    eng_b, sup_b = supervised(slice1)
    pool = ReplicaPool(
        [EngineReplica("a", eng_a), EngineReplica("b", eng_b)],
        probe_interval_s=0, probe_timeout_s=60.0,
        rng=random.Random(7), metrics=metrics,
    )
    params = dict(max_new_tokens=24, temperature=0.0, stop_on_eos=False)
    try:
        failovers0 = _counter_total(metrics, "app_tpu_failovers_total")
        ref_b = eng_b.generate_sync(PROMPT, timeout=240, **params)
        ref_a = eng_a.generate_sync(PROMPT, timeout=240, **params)
        assert ref_a.token_ids == ref_b.token_ids
        assert len(ref_b.token_ids) == 24

        a_hits = {"n": 0}

        def crash_a(engine=None, **kw):
            if engine is eng_a:
                a_hits["n"] += 1
                if a_hits["n"] >= 5:
                    raise RuntimeError("injected: sharded pod A device loss")

        faults.arm("scheduler.device_step", action=crash_a)
        req = pool.submit_generate(PROMPT, **params)
        pre = [req.stream.get(timeout=120) for _ in range(3)]
        assert all(t is not None for t in pre)
        rest = _drain_stream(req)
        result = req.future.result(timeout=120)
        assert pre + rest == ref_b.token_ids
        assert result.token_ids == ref_b.token_ids
        assert _counter_total(
            metrics, "app_tpu_failovers_total"
        ) == failovers0 + 1
    finally:
        faults.reset()
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)
        sup_a.stop()
        sup_b.stop()
        eng_a.stop_sync()
        eng_b.stop_sync()


# ----------------------------------------------------------------------
# the pod layout: dp across replicas, tp within (config seam)
# ----------------------------------------------------------------------


def test_pool_carves_disjoint_tp_pods_and_serves_token_identical(engines):
    """TPU_TP=2 × TPU_REPLICAS=2 through the container seam: each
    in-proc replica is one sharded pod on its OWN device slice (the
    dryrun's dp=2 × tp=2 pod-serving topology, production-shaped), and
    pool-served greedy output is token-identical to unsharded."""
    from gofr_tpu.serving.backend import new_tpu_from_config

    ref, _ = engines
    pool = new_tpu_from_config(MockConfig({
        "TPU_MODEL": "llama-tiny",
        "TPU_TP": "2",
        "TPU_REPLICAS": "2",
        "TPU_POOL_MAX_REPLICAS": "3",
        "TPU_KV_SLOTS": "4",
        "TPU_MAX_LEN": "256",
        "TPU_DECODE_WINDOW": "4",
        "TPU_PIPELINE_DEPTH": "1",
        "TPU_PREFILL_CHUNK": "32",
        "TPU_KV_BLOCK": "32",
        "TPU_AUTO_PREFIX": "true",
    }))
    assert isinstance(pool, ReplicaPool)
    try:
        sets = [
            frozenset(r.mesh_topology()["devices"]) for r in pool.replicas
        ]
        assert len(sets) == 2
        assert sets[0].isdisjoint(sets[1])
        for replica in pool.replicas:
            replica.engine.start_sync()
        params = dict(max_new_tokens=12, temperature=0.0, stop_on_eos=False)
        want = ref.generate_sync(PROMPT, timeout=240, **params)
        got = pool.generate_sync(PROMPT, timeout=240, **params)
        assert got.token_ids == want.token_ids
        # A scaled-up pod lands on a FREE device slice, not on top of a
        # live replica's (the scaler's spawn factory scans held slices,
        # it does not count spawns).
        assert pool.scaler is not None
        scaled = pool.scaler.spawn()
        try:
            scaled_set = frozenset(scaled.mesh_topology()["devices"])
            assert scaled_set.isdisjoint(sets[0] | sets[1])
        finally:
            scaled.engine.close()
    finally:
        pool.close()


# ----------------------------------------------------------------------
# observability: topology advertised, shard-init span emitted
# ----------------------------------------------------------------------


def test_mesh_topology_advertised_everywhere(metrics, engines):
    ref, tp2 = engines
    # Health probes carry the pod shape; unsharded engines carry none.
    assert tp2.health_check()["details"]["mesh"]["axes"] == {"tp": 2}
    assert "mesh" not in ref.health_check()["details"]
    assert ref.mesh_topology() is None
    # The per-axis device gauge: 2 for the sharded engine's tp axis,
    # 1 advertised by the unsharded one.
    assert _gauge(metrics, "app_tpu_mesh_devices", axis="tp") == 2.0
    # Replica descriptors and /debug/flight records stamp the mesh.
    pool = ReplicaPool(
        [EngineReplica("sharded", tp2), EngineReplica("plain", ref)],
        probe_interval_s=0, metrics=metrics,
    )
    try:
        desc = pool.health_check()["details"]["replicas"]
        assert desc["sharded"]["mesh"]["axes"] == {"tp": 2}
        assert desc["plain"]["mesh"] is None
        records = pool.flight_records()["replicas"]
        assert records["sharded"]["mesh"]["n_devices"] == 2
        assert records["plain"]["mesh"] is None
    finally:
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)


def test_partition_devices_layout_and_undersized_error():
    from gofr_tpu.parallel.mesh import partition_devices

    devs = list(range(8))
    assert partition_devices(devs, 2, 3) == [[0, 1], [2, 3], [4, 5]]
    # Overflow groups past the last full slice share slice 0.
    assert partition_devices(devs, 4, 3) == [
        [0, 1, 2, 3], [4, 5, 6, 7], [0, 1, 2, 3],
    ]
    # Fewer devices than ONE group fails loudly here, not inside
    # make_mesh with misleading context.
    with pytest.raises(ValueError):
        partition_devices(devs[:1], 2, 1)


def test_remote_replica_mesh_cache_clears_when_pod_unshards():
    """A remote pod that restarts UNSHARDED must stop advertising its
    old tp topology — the probe assigns the cached mesh
    unconditionally from the health payload."""
    from gofr_tpu.service.replica_pool import HTTPReplica

    class _Resp:
        status_code = 200

        def __init__(self, details):
            self._details = details

        def json(self):
            return {"status": "UP", "details": self._details}

    class _Svc:
        def __init__(self):
            self.details = {"mesh": {"axes": {"tp": 2}, "n_devices": 2,
                                     "devices": ["a", "b"]}}

        def get(self, path):
            return _Resp(self.details)

    svc = _Svc()
    replica = HTTPReplica("remote", svc, stream=False)
    assert replica.probe(timeout_s=1.0)[0] == "pass"
    assert replica.mesh_topology()["axes"] == {"tp": 2}
    svc.details = {}  # pod restarted unsharded: no mesh key at all
    assert replica.probe(timeout_s=1.0)[0] == "pass"
    assert replica.mesh_topology() is None


class _CaptureExporter:
    """In-memory span sink; ``is_noop`` absent → the tracer is ACTIVE."""

    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()

    def export(self, span, service_name):
        with self._lock:
            self.spans.append(span)

    def by_name(self, name):
        with self._lock:
            return [s for s in self.spans if s.name == name]


def test_shard_init_span_covers_mesh_build_and_param_sharding():
    old = get_tracer()
    cap = _CaptureExporter()
    set_tracer(Tracer(service_name="shard-test", exporter=cap))
    try:
        slice0, _ = _device_slices()
        InferenceEngine(
            "llama-tiny", tokenizer=ByteTokenizer(),
            tp=2, devices=slice0, **ENG_KW,
        )
        spans = cap.by_name("tpu.shard_init")
        assert len(spans) == 1
        span = spans[0]
        assert span.attributes["tpu.mesh_axes"] == "tp=2"
        assert span.attributes["tpu.mesh_devices"] == 2
        assert span.end_ns > span.start_ns  # real duration, not instant
    finally:
        set_tracer(old)
