"""SubscriptionManager semantics (reference ``subscriber.go:27-84``):
commit ONLY on handler success, panic recovery logs-and-continues,
broker read errors back off instead of hot-looping, sync and async
handlers both run, and stop() cancels the loops cleanly. The example
tests cover the happy path through a real broker; these pin the error
paths with a scripted fake."""

from __future__ import annotations

import asyncio

from gofr_tpu.subscriber import SubscriptionManager
from gofr_tpu.testutil.mock_logger import MockLogger


class FakeMsg:
    def __init__(self, topic: str, data: bytes = b"x") -> None:
        self.topic = topic
        self.data = data
        self.committed = 0

    def commit(self) -> None:
        self.committed += 1


class FakeSubscriber:
    """Returns scripted items per subscribe() call: a FakeMsg, None
    (poll timeout), or an Exception (raised)."""

    def __init__(self, script: list) -> None:
        self.script = list(script)

    def subscribe(self, topic: str, timeout: float):
        if not self.script:
            return None
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        return item


class FakeContainer:
    def __init__(self, sub) -> None:
        self._sub = sub
        self.logger = MockLogger()

    def get_subscriber(self):
        return self._sub


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _drive(manager, until, timeout=10.0):
    manager.start()
    deadline = asyncio.get_running_loop().time() + timeout
    while not until():
        if asyncio.get_running_loop().time() > deadline:
            await manager.stop()
            raise AssertionError("condition never reached")
        await asyncio.sleep(0.01)
    await manager.stop()


def test_commit_only_on_success_sync_and_async():
    ok1, ok2 = FakeMsg("t"), FakeMsg("t")
    rejected = FakeMsg("t")
    sub = FakeSubscriber([ok1, rejected, None, ok2])
    container = FakeContainer(sub)
    manager = SubscriptionManager(container)
    seen = []

    async def handler(ctx):
        seen.append(ctx.request)
        if ctx.request is rejected:
            return False  # handler failure → must NOT commit
        return True

    manager.register("t", handler)
    assert manager.topics == ["t"]
    _run(_drive(manager, lambda: ok2.committed))
    assert ok1.committed == 1 and ok2.committed == 1
    assert rejected.committed == 0
    assert seen == [ok1, rejected, ok2]

    # Sync handler path (runs in the executor).
    ok3 = FakeMsg("t")
    sub.script.append(ok3)
    manager2 = SubscriptionManager(container)
    manager2.register("t", lambda ctx: True)
    _run(_drive(manager2, lambda: ok3.committed))
    assert ok3.committed == 1


def test_handler_panic_recovers_without_commit():
    boom, ok = FakeMsg("t"), FakeMsg("t")
    container = FakeContainer(FakeSubscriber([boom, ok]))
    manager = SubscriptionManager(container)

    async def handler(ctx):
        if ctx.request is boom:
            raise RuntimeError("handler exploded")
        return True

    manager.register("t", handler)
    _run(_drive(manager, lambda: ok.committed))
    assert boom.committed == 0  # panic → no commit
    logs = [r for r in container.logger.logs if "panicked" in str(r)]
    assert logs, container.logger.logs


def test_broker_error_backs_off_and_continues():
    ok = FakeMsg("t")
    container = FakeContainer(
        FakeSubscriber([ConnectionError("broker away"), ok])
    )
    manager = SubscriptionManager(container)
    manager.register("t", lambda ctx: True)
    _run(_drive(manager, lambda: ok.committed))
    assert ok.committed == 1  # loop survived the read error
    logs = [
        r for r in container.logger.logs
        if "error while reading" in str(r)
    ]
    assert logs


def test_no_subscriber_configured_waits_then_stops():
    container = FakeContainer(None)
    container.get_subscriber = lambda: None
    manager = SubscriptionManager(container)
    manager.register("t", lambda ctx: True)

    async def scenario():
        manager.start()
        await asyncio.sleep(0.05)  # loop idles on the None subscriber
        await manager.stop()  # must cancel cleanly, not hang

    _run(scenario())
    assert manager._tasks == []


def test_none_error_commits():
    """A handler returning None (the common bare-return) counts as
    success — reference handlers rarely return anything."""
    ok = FakeMsg("t")
    container = FakeContainer(FakeSubscriber([ok]))
    manager = SubscriptionManager(container)
    manager.register("t", lambda ctx: None)
    _run(_drive(manager, lambda: ok.committed))
    assert ok.committed == 1
