"""Native C++ BPE core vs the pure-Python oracle, plus serving integration."""

from __future__ import annotations

import random

import pytest

from gofr_tpu.serving.native_tokenizer import (
    NativeBPE,
    PyBPE,
    build_native,
    byte_vocab_with_merges,
    load_bpe,
    write_bpe_files,
)

MERGES = [
    (b"t", b"h"),       # th
    (b"th", b"e"),      # the
    (b" ", b"the"),     # ␣the
    (b"i", b"n"),       # in
    (b"a", b"n"),       # an
    (b"an", b"d"),      # and
    (b" ", b"and"),     # ␣and
    (b"e", b"r"),       # er
]


@pytest.fixture(scope="module")
def bpe_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("bpe")
    vocab = byte_vocab_with_merges(MERGES)
    return write_bpe_files(vocab, MERGES, str(d))


def test_python_core_merges(bpe_files):
    py = PyBPE(*bpe_files)
    ids = py.encode_bytes(b"the thin and")
    # "the" must collapse to the single 'the' merge token (id 256+1).
    assert py.id_to_token[ids[0]] == b"the"
    assert b" and" in [py.id_to_token[i] for i in ids]
    assert py.decode_bytes(ids) == b"the thin and"


def test_native_builds_and_matches_python(bpe_files):
    so = build_native()
    assert so is not None, "g++ is baked into this image; build must succeed"
    nat = NativeBPE(*bpe_files, so_path=so)
    py = PyBPE(*bpe_files)
    assert nat.vocab_size == py.vocab_size

    rng = random.Random(0)
    corpus = [
        b"the quick brown fox jumps over the lazy dog",
        b"and then there were none",
        "héllo wörld — ünïcode".encode("utf-8"),
        b"",
        b"a",
        bytes(rng.randrange(256) for _ in range(512)),
    ]
    for data in corpus:
        assert nat.encode_bytes(data) == py.encode_bytes(data), data
        assert nat.decode_bytes(py.encode_bytes(data)) == data


def test_tokenizer_protocol_roundtrip(bpe_files):
    tok = load_bpe(*bpe_files)
    ids = tok.encode("the thin and")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids + [tok.eos_id, tok.pad_id]) == "the thin and"


def test_fallback_when_native_unavailable(bpe_files, monkeypatch):
    import gofr_tpu.serving.native_tokenizer as nt

    monkeypatch.setattr(nt, "build_native", lambda force=False: None)
    tok = nt.load_bpe(*bpe_files)
    assert not tok.is_native
    assert tok.decode(tok.encode("the end")) == "the end"


def test_native_tokenizer_drives_serving_engine(bpe_files):
    """The BPE tokenizer slots into the engine exactly like ByteTokenizer —
    vocab_size 267 fits the tiny models' 512 vocab."""
    from gofr_tpu.serving.engine import InferenceEngine

    tok = load_bpe(*bpe_files)
    assert tok.is_native
    engine = InferenceEngine("llama-tiny", n_slots=2, max_len=64, tokenizer=tok)
    engine.start_sync()
    try:
        out = engine.generate_sync(
            "the and", max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        assert len(out.token_ids) == 4
        assert isinstance(out.text, str)
    finally:
        engine.stop_sync()
