"""True multi-host disaggregation suite (ISSUE 19 acceptance gate).

PR 14's transfer ladder stopped at device/wire/host inside one failure
domain. This suite pins the two planes that make the tiers genuinely
multi-host:

* **the dma leg** (new top rung): the exporter stages wire bytes on its
  process-local transfer server and ships only a ``KVH1`` claim ticket;
  the importer redeems it over a raw TCP fetch with layered budgets and
  post-fetch checksum/geometry/token verification. On CI jax (no
  ``jax.experimental.transfer``) the loopback emulation IS the backend,
  which is exactly what makes the matrix runnable without a pod;
* **streaming prefill sources** (the pull plane): a prefill-role remote
  advertising ``tier_source`` in health is asked for blocks it already
  computed (``POST /ops/tier-export`` — the tier-import codec run in
  reverse), dma ticket first, inline wire body one rung down, local
  prefill as the terminal rung;
* **the failure matrix on the new rungs** — each cell falls exactly ONE
  rung, byte-identical to the fused reference, zero 5xx, one trace id:
  stale/replayed/expired handles and checksum-geometry drift read as
  ``stale`` (never aliased as garbage), a dead transfer server is
  ``connect`` (next source, not next rung), slow-loris trips the read
  budget inside the request's own deadline, an armed ``offer`` bans the
  dma rung and the SAME target retries one rung down, and — the
  acceptance path — a REAL subprocess pod ``kill -9``'d mid-DMA (serve
  thread parked via the ``transfer.dma.serve`` seam) degrades
  dma → wire → local with zero leaked staged bodies or pool blocks on
  the surviving side.

The subprocess half (``@pytest.mark.slow``) boots
``tests/multihost_child.py`` pods on live ephemeral ports; everything
else is deterministic — faults fire on exact hit counts, TTL clocks are
injected, and no test sleeps as synchronization.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.ops.kv_cache import (
    HANDLE_MAGIC,
    WIRE_MAGIC,
    KVHandlePayload,
    handle_from_wire,
    handle_to_wire,
)
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.dma import (
    DmaError,
    DmaTransferServer,
    dma_fetch,
    get_transfer_server,
    jax_transfer_available,
    reset_transfer_server,
)
from gofr_tpu.service.replica_pool import (
    EngineReplica,
    HTTPReplica,
    ReplicaPool,
)

TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

COUNTERS = (
    "app_tpu_tier_transfers_total",
    "app_tpu_tier_transfer_bytes_total",
    "app_tpu_tier_sources_total",
    "app_tpu_failovers_total",
    "app_tpu_requests_replayed_total",
    "app_tpu_tokens_generated",
    "app_tpu_prefix_lookup_total",
    "app_tpu_prefix_hit_tokens_total",
)
GAUGES = (
    "app_tpu_tier_mode",
    "app_tpu_engine_state",
    "app_tpu_replica_state",
    "app_tpu_pool_replicas",
    "app_tpu_queue_depth",
    "app_tpu_kv_slots_in_use",
    "app_tpu_kv_blocks_free",
    "app_tpu_prefix_cached_blocks",
    "app_tpu_hbm_used_bytes",
)
HISTOGRAMS = (
    "app_tpu_tier_transfer_seconds",
    "app_tpu_infer_latency",
    "app_tpu_batch_size",
    "app_tpu_spec_tokens_per_step",
)


def _metrics_manager():
    m = new_metrics_manager()
    for name in COUNTERS:
        m.new_counter(name)
    for name in GAUGES:
        m.new_gauge(name)
    for name in HISTOGRAMS:
        m.new_histogram(name)
    return m


def counter_total(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    total = 0.0
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            total += value
    return total


def _prompt(tag: int):
    """96 tokens = exactly 3 full 32-token blocks, distinct per tag so
    every test pulls/ships COLD content (a collision would alias
    against an earlier test's import and skip the rung under test)."""
    return [2 + (i * 7 + tag * 13) % 200 for i in range(95)] + [tag % 200]


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _dma_hygiene():
    """Every test that touched the process-global transfer server
    leaves the NEXT test a fresh one (new ephemeral port, empty staging
    dict) — a leaked staged body here would mask the zero-leak
    assertions of whichever test runs after."""
    yield
    reset_transfer_server()


def _make_engine(metrics, **kw):
    kw.setdefault("kv_block", 32)
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, window_k=4,
        pipeline_depth=1, prefill_chunk=32, auto_prefix=True,
        tokenizer=ByteTokenizer(), metrics=metrics, **kw,
    )
    eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def engines(metrics):
    """One prefill + one decode engine shared by the suite (compile
    cost), plus a fused single-engine reference for byte-identity."""
    pf = _make_engine(metrics)
    dc = _make_engine(metrics)
    ref = _make_engine(metrics)
    yield pf, dc, ref
    faults.reset()
    for eng in (pf, dc, ref):
        eng.close()


def _pool(replicas, metrics, **kw):
    sleeps: list = []
    kw.setdefault("probe_interval_s", 0)
    kw.setdefault("probe_timeout_s", 60.0)
    kw.setdefault("hedge_delay_s", 300.0)
    kw.setdefault("transfer_retries", 2)
    kw.setdefault("transfer_backoff_s", 0.01)
    kw.setdefault("sleep", sleeps.append)
    kw.setdefault("rng", random.Random(7))
    pool = ReplicaPool(replicas, metrics=metrics, **kw)
    pool._test_sleeps = sleeps
    return pool


def _close_pool(pool):
    pool.stop_prober()
    for replica in pool.replicas:
        replica.set_handoff(None)
        replica.set_tier_exporter(None)


def _drain(req, timeout=120.0):
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _legs(req):
    tl = req.timeline
    assert tl is not None
    return [(result, leg) for _, _, _, _, result, leg in tl.transfers]


def _export_payload(engine, tag, *, new_tokens=1):
    """A REAL host-bounce payload off ``engine``'s radix: generate to
    cache the prompt's blocks, then export the cached prefix — the
    exact production staging path, not a hand-built fixture."""
    ids = _prompt(tag)
    engine.generate_sync(ids, max_new_tokens=new_tokens, temperature=0.0,
                         timeout=120.0)
    payload = engine.export_cached(ids, timeout_s=10.0)
    assert payload is not None
    return ids, payload


# ----------------------------------------------------------------------
# KVH1 claim-ticket codec units
# ----------------------------------------------------------------------


def test_handle_codec_roundtrip():
    handle = KVHandlePayload(
        address="127.0.0.1:4321", key="a" * 32, block=32,
        token_ids=tuple(range(64)), src="pf", checksum=0xDEADBEEF,
        geometry=(4, 2, 32, 8), nbytes_hint=4096,
    )
    wire = handle_to_wire(handle)
    assert wire[:4] == HANDLE_MAGIC
    back = handle_from_wire(wire)
    assert back == handle
    assert back.n_blocks == 2
    assert back.nbytes() == 4096
    assert back.verify()


def test_handle_codec_rejects_malformed():
    handle = KVHandlePayload(
        address="127.0.0.1:1", key="k", block=32,
        token_ids=tuple(range(32)),
    )
    wire = handle_to_wire(handle)
    for bad in (b"", b"KVH", b"XXXX" + wire[4:], wire[:7], wire[:-3],
                HANDLE_MAGIC + b"\x00\x00\x00\x05notjs"):
        with pytest.raises(ValueError):
            handle_from_wire(bad)
    # First-4-byte dispatch: a handle is never confusable with an
    # inline body (the import endpoint branches on exactly this).
    assert wire[:4] != WIRE_MAGIC


def test_loopback_is_the_ci_backend():
    """The CI jax has no ``jax.experimental.transfer``; the gate must
    say so (the dma leg then runs entirely on the loopback emulation —
    which is the point: the matrix runs without a pod)."""
    assert jax_transfer_available() is False


# ----------------------------------------------------------------------
# loopback transfer-server units: staging, single-use, TTL, budgets
# ----------------------------------------------------------------------


def test_offer_fetch_roundtrip_and_single_use(metrics, engines):
    pf, _, _ = engines
    _, payload = _export_payload(pf, 30)
    server = DmaTransferServer(ttl_s=30.0).start()
    try:
        handle = server.offer(payload, src="pf")
        assert handle.address == server.address
        assert handle.checksum == payload.checksum
        assert server.staged_count() == 1
        fetched = dma_fetch(handle)
        assert fetched.token_ids == payload.token_ids
        assert fetched.checksum == payload.checksum
        assert fetched.verify()
        assert server.staged_count() == 0  # zero leaked staged bodies
        # Single-use: a replayed claim is STALE, never a re-ship of
        # blocks whose radix entries may since have been evicted.
        with pytest.raises(DmaError) as err:
            dma_fetch(handle)
        assert err.value.kind == "stale"
    finally:
        server.stop()


def test_ttl_expiry_reads_as_stale(metrics, engines):
    pf, _, _ = engines
    _, payload = _export_payload(pf, 31)
    now = [100.0]
    server = DmaTransferServer(ttl_s=5.0, clock=lambda: now[0]).start()
    try:
        handle = server.offer(payload)
        now[0] += 6.0  # past the TTL: the staged body is gone
        with pytest.raises(DmaError) as err:
            dma_fetch(handle)
        assert err.value.kind == "stale"
        server.offer(payload)  # the sweep on offer reaps the corpse
        assert server.staged_count() == 1
    finally:
        server.stop()


def test_fetch_failure_kinds(metrics, engines):
    """Every transport failure is typed so the ladder can tell "the
    source is GONE" (connect → next source) from "this rung broke"
    (read/stale/proto → one rung down)."""
    pf, _, _ = engines
    _, payload = _export_payload(pf, 32)
    server = DmaTransferServer(ttl_s=30.0).start()
    handle = server.offer(payload)
    server.stop()
    # connect: nothing listening on the advertised port.
    with pytest.raises(DmaError) as err:
        dma_fetch(handle, connect_timeout_s=0.5)
    assert err.value.kind == "connect"
    # proto: an address that is not host:port at all.
    bogus = dataclasses.replace(handle, address="not-an-address")
    with pytest.raises(DmaError) as err:
        dma_fetch(bogus)
    assert err.value.kind == "proto"


def test_checksum_and_geometry_drift_read_as_stale(metrics, engines):
    """The fetched bytes must be the bytes the handle promised — a
    transfer server restarted into a new staging namespace (or drifted
    pod geometry) is caught BEFORE the importer touches its pool."""
    pf, _, _ = engines
    _, payload = _export_payload(pf, 33)
    server = DmaTransferServer(ttl_s=30.0).start()
    try:
        for drift in (
            {"checksum": payload.checksum ^ 1},
            {"geometry": tuple([*payload.geometry[:-1],
                                payload.geometry[-1] + 1])},
            {"token_ids": tuple([*payload.token_ids[:-1], 0])},
        ):
            handle = dataclasses.replace(server.offer(payload), **drift)
            with pytest.raises(DmaError) as err:
                dma_fetch(handle)
            assert err.value.kind == "stale"
    finally:
        server.stop()


def test_slow_loris_trips_the_read_budget(metrics, engines):
    """A stalled exporter (the ``transfer.dma.serve`` seam parked mid-
    transfer) cannot pin the importer: EVERY socket read carries the
    budget, so the fetch dies ``read`` inside it."""
    pf, _, _ = engines
    _, payload = _export_payload(pf, 34)
    server = DmaTransferServer(ttl_s=30.0).start()
    gate = threading.Event()
    try:
        handle = server.offer(payload)
        t0 = time.monotonic()
        with faults.armed("transfer.dma.serve",
                          action=lambda **_kw: gate.wait(30.0)):
            with pytest.raises(DmaError) as err:
                dma_fetch(handle, read_timeout_s=0.3)
        assert err.value.kind == "read"
        assert time.monotonic() - t0 < 5.0  # the budget cut it, not TTL
    finally:
        gate.set()
        server.stop()


# ----------------------------------------------------------------------
# the dma rung in the push ladder (in-proc, pinned)
# ----------------------------------------------------------------------


def test_pinned_dma_leg_byte_identical_greedy_and_seeded(metrics, engines):
    """``TPU_TRANSFER_LEG=dma`` pins the new top rung even in-process:
    the finished prefill stages on the loopback server and the decode
    replica redeems the ticket over a real TCP fetch — byte-identical
    to the fused reference for greedy AND seeded-sampled streams,
    result=ok leg=dma, zero staged bodies left behind."""
    pf, dc, ref = engines
    pool = _pool(
        [EngineReplica("pf", pf, role="prefill"),
         EngineReplica("dc", dc, role="decode")],
        metrics, transfer_leg="dma",
    )
    try:
        ok0 = counter_total(metrics, "app_tpu_tier_transfers_total",
                            result="ok", leg="dma")
        bytes0 = counter_total(metrics, "app_tpu_tier_transfer_bytes_total",
                               leg="dma")
        for tag, params in ((35, {"temperature": 0.0}),
                            (36, {"temperature": 0.8, "seed": 7})):
            prompt = _prompt(tag)
            want = ref.generate_sync(prompt, max_new_tokens=8,
                                     timeout=120.0, **params)
            req = pool.submit_generate(prompt, max_new_tokens=8, **params)
            toks = _drain(req)
            assert toks == want.token_ids
            assert req.future.result(timeout=5).token_ids == want.token_ids
            assert _legs(req) == [("ok", "dma")]
        assert counter_total(metrics, "app_tpu_tier_transfers_total",
                             result="ok", leg="dma") == ok0 + 2
        assert counter_total(metrics, "app_tpu_tier_transfer_bytes_total",
                             leg="dma") > bytes0
        assert get_transfer_server().staged_count() == 0
    finally:
        _close_pool(pool)


class _StubEngine:
    family = "llm"
    tier_role = "fused"
    model_name = "stub"
    kv_block = 0

    def set_replica_handoff(self, h):
        pass

    def set_tier_exporter(self, e):
        pass

    @property
    def state(self):
        return "SERVING"


def test_transfer_leg_validation_accepts_dma():
    with pytest.raises(ValueError):
        ReplicaPool(
            [EngineReplica("x", _StubEngine())], transfer_leg="rdma"
        )
    pool = ReplicaPool(
        [EngineReplica("x", _StubEngine())], transfer_leg="dma",
        probe_interval_s=0,
    )
    try:
        assert pool.transfer_leg == "dma"
    finally:
        pool.stop_prober()


# ----------------------------------------------------------------------
# the dma rung against a REAL remote app (live sockets) + its ladder
# ----------------------------------------------------------------------


class _Harness:
    """Boot a gofr_tpu App on ephemeral ports (httptest.Server role)."""

    def __init__(self, app):
        import asyncio

        self.app = app
        self._asyncio = asyncio
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )

    def __enter__(self):
        self._thread.start()
        self._asyncio.run_coroutine_threadsafe(
            self.app.start(), self._loop
        ).result(120)
        return self

    def __exit__(self, *exc):
        self._asyncio.run_coroutine_threadsafe(
            self.app.stop(), self._loop
        ).result(30)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    @property
    def address(self):
        return f"http://127.0.0.1:{self.app.http_port}"

    @property
    def ops_address(self):
        return f"http://127.0.0.1:{self.app.metrics_port}"


@pytest.fixture(scope="module")
def remote_app():
    """A REAL remote pod in-process: OpenAI SSE on the HTTP port, the
    tier-import AND tier-export endpoints on the ops port. It plays
    decode target for the push tests and prefill SOURCE for the pull
    tests — one pod, both directions of the same ops-port seam."""
    from gofr_tpu import App
    from gofr_tpu.config import MockConfig
    from gofr_tpu.serving.openai_compat import add_openai_routes

    app = App(config=MockConfig({
        "APP_NAME": "mh-remote", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "4",
        "TPU_MAX_LEN": "256", "TPU_KV_BLOCK": "32",
        "TPU_AUTO_PREFIX": "true", "TPU_PREFILL_CHUNK": "32",
    }))
    add_openai_routes(app)
    with _Harness(app) as harness:
        yield app, harness


def _remote_replica(name, harness, tokenizer, metrics, *, role,
                    ops_address=None):
    from gofr_tpu.service import new_http_service

    return HTTPReplica(
        name,
        new_http_service(harness.address),
        tokenizer=tokenizer,
        role=role,
        import_service=new_http_service(ops_address or harness.ops_address),
        metrics=metrics,
    )


@pytest.fixture()
def dma_push_pool(metrics, engines, remote_app):
    """1 in-proc prefill + 1 REMOTE decode replica whose probe saw the
    ``tier_source.dma`` advertisement — the automatic ladder's top rung
    for this target is dma."""
    pf, _, _ = engines
    _, harness = remote_app
    remote = _remote_replica("dc-remote", harness, pf.tokenizer, metrics,
                             role="decode")
    pool = _pool(
        [EngineReplica("pf", pf, role="prefill"), remote], metrics,
    )
    pool.probe_once()
    assert remote.supports_dma_import  # probe-fed capability
    yield pool
    _close_pool(pool)
    remote.close()


def test_remote_dma_leg_byte_identical_one_trace(metrics, engines,
                                                 remote_app, dma_push_pool):
    """THE remote dma path: a KVH1 ticket POSTed to the remote ops
    port, the remote redeeming it back over a live TCP fetch, the
    request streamed over OpenAI SSE — byte-identical to the fused
    reference, result=ok leg=dma, the remote's flight recorder showing
    the request under the CALLER's trace id."""
    _, _, ref = engines
    app, _ = remote_app
    prompt = _prompt(40)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    ok0 = counter_total(metrics, "app_tpu_tier_transfers_total",
                        result="ok", leg="dma")
    req = dma_push_pool.submit_generate(
        prompt, max_new_tokens=8, temperature=0.0, traceparent=TRACEPARENT,
    )
    toks = _drain(req)
    assert toks == req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("ok", "dma")]
    assert counter_total(metrics, "app_tpu_tier_transfers_total",
                         result="ok", leg="dma") == ok0 + 1
    assert get_transfer_server().staged_count() == 0
    flights = app.container.tpu.flight_records()
    assert any(
        e["trace_id"] == "ab" * 16
        for e in flights.get("records", []) + flights.get("pinned", [])
    )


def test_remote_dma_offer_failure_falls_one_rung_to_wire(
        metrics, engines, dma_push_pool):
    """An armed staging failure bans the dma rung and the SAME target
    retries one rung down (dma → wire) — byte-identical, zero 5xx."""
    _, _, ref = engines
    prompt = _prompt(41)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    with faults.armed("transfer.dma.offer",
                      raises=RuntimeError("staging plane down"), times=1):
        req = dma_push_pool.submit_generate(prompt, max_new_tokens=8,
                                            temperature=0.0)
        toks = _drain(req)
    assert toks == want.token_ids
    assert req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("ok", "wire")]


def test_remote_dma_fetch_failure_falls_one_rung_to_wire(
        metrics, engines, dma_push_pool):
    """The remote failing to redeem the ticket (fetch dies mid-DMA) is
    a LEG failure, not an adoption: the pool re-ships the SAME blocks
    over the inline wire body — never a silent fused re-prefill."""
    _, _, ref = engines
    prompt = _prompt(42)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    with faults.armed("transfer.dma.fetch",
                      raises=DmaError("reset mid-DMA", kind="read"),
                      times=1):
        req = dma_push_pool.submit_generate(prompt, max_new_tokens=8,
                                            temperature=0.0)
        toks = _drain(req)
    assert toks == want.token_ids
    assert req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("ok", "wire")]


# ----------------------------------------------------------------------
# streaming prefill sources: the pull plane (live sockets)
# ----------------------------------------------------------------------


@pytest.fixture()
def source_pool(metrics, engines, remote_app):
    """1 LOCAL decode replica + the remote pod as a prefill SOURCE:
    before admitting a fresh request locally, the pool pulls the
    remote's cached blocks through /ops/tier-export."""
    _, dc, _ = engines
    app, harness = remote_app
    source = _remote_replica("pf-source", harness, dc.tokenizer, metrics,
                             role="prefill")
    pool = _pool(
        [EngineReplica("dc", dc, role="decode"), source], metrics,
        source_timeout_s=5.0,
    )
    pool.probe_once()
    assert source.supports_tier_source  # probe-fed advertisement
    assert pool.tier_sources() == [source]
    yield app, pool
    _close_pool(pool)
    source.close()


def test_source_warm_hit_fewer_chunks_one_trace(metrics, engines,
                                                source_pool):
    """THE pull acceptance path: the remote already prefilled the
    prompt; the local decode replica pulls its blocks (dma ticket +
    TCP fetch), admission-aliases them, and dispatches STRICTLY fewer
    prefill chunk steps than a cold run — byte-identical, source_hit
    on the dma rung, ONE trace id across the pull and the stream."""
    _, dc, ref = engines
    app, pool = source_pool
    # Cold yardstick: a prompt NOBODY cached costs the full chunk walk
    # (and records an authoritative source_miss — re-asking via wire
    # cannot hit, so the descent stops at one note).
    cold_prompt = _prompt(50)
    s0 = dc._prefill_chunk_steps
    req = pool.submit_generate(cold_prompt, max_new_tokens=4,
                               temperature=0.0)
    cold_toks = _drain(req)
    cold_steps = dc._prefill_chunk_steps - s0
    assert cold_steps >= 3
    assert _legs(req) == [("source_miss", "dma")]
    assert cold_toks == ref.generate_sync(
        cold_prompt, max_new_tokens=4, temperature=0.0, timeout=120.0
    ).token_ids
    # Warm the SOURCE (not the local engine), then pull.
    warm_prompt = _prompt(51)
    app.container.tpu.generate_sync(warm_prompt, max_new_tokens=1,
                                    temperature=0.0, timeout=120.0)
    want = ref.generate_sync(warm_prompt, max_new_tokens=8,
                             temperature=0.0, timeout=120.0)
    hit0 = counter_total(metrics, "app_tpu_tier_sources_total", kind="hit")
    s1 = dc._prefill_chunk_steps
    req = pool.submit_generate(warm_prompt, max_new_tokens=8,
                               temperature=0.0, traceparent=TRACEPARENT)
    toks = _drain(req)
    warm_steps = dc._prefill_chunk_steps - s1
    assert toks == req.future.result(timeout=5).token_ids == want.token_ids
    assert warm_steps < cold_steps
    assert _legs(req) == [("source_hit", "dma")]
    assert req.timeline.trace_id == "ab" * 16
    assert counter_total(metrics, "app_tpu_tier_sources_total",
                         kind="hit") == hit0 + 1
    assert counter_total(metrics, "app_tpu_tier_transfer_bytes_total",
                         leg="dma") > 0
    assert get_transfer_server().staged_count() == 0


def test_source_seeded_sampled_byte_identical(metrics, engines,
                                              source_pool):
    _, _, ref = engines
    app, pool = source_pool
    prompt = _prompt(52)
    app.container.tpu.generate_sync(prompt, max_new_tokens=1,
                                    temperature=0.0, timeout=120.0)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.8,
                             seed=7, timeout=120.0)
    req = pool.submit_generate(prompt, max_new_tokens=8, temperature=0.8,
                               seed=7)
    toks = _drain(req)
    assert toks == want.token_ids
    assert _legs(req) == [("source_hit", "dma")]


def test_source_stale_handle_descends_to_wire(metrics, engines,
                                              source_pool):
    """A genuinely stale ticket (redeemed out from under the importer —
    the transfer server replies length 0) falls ONE rung: the same
    source re-asked for the inline wire body, which hits."""
    _, _, ref = engines
    app, pool = source_pool
    prompt = _prompt(53)
    app.container.tpu.generate_sync(prompt, max_new_tokens=1,
                                    temperature=0.0, timeout=120.0)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)

    def _poach(key="", **_kw):
        get_transfer_server().redeem(key)  # the claim is now stale

    with faults.armed("transfer.dma.fetch", action=_poach, times=1):
        req = pool.submit_generate(prompt, max_new_tokens=8,
                                   temperature=0.0)
        toks = _drain(req)
    assert toks == req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("source_error", "dma"), ("source_hit", "wire")]


def test_source_connect_refused_skips_the_source(metrics, engines,
                                                 remote_app, free_port):
    """A dead export port is ``connect``-kind: the source is GONE, so
    the pull breaks to the next source (none here) — local prefill,
    byte-identical, zero 5xx, one error note."""
    _, dc, ref = engines
    app, harness = remote_app
    source = _remote_replica(
        "pf-dead-ops", harness, dc.tokenizer, metrics, role="prefill",
        ops_address=f"http://127.0.0.1:{free_port()}",
    )
    pool = _pool(
        [EngineReplica("dc", dc, role="decode"), source], metrics,
        source_timeout_s=5.0,
    )
    try:
        pool.probe_once()  # health (live) advertises; the ops port lies dead
        assert pool.tier_sources() == [source]
        prompt = _prompt(54)
        want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                                 timeout=120.0)
        err0 = counter_total(metrics, "app_tpu_tier_sources_total",
                             kind="error")
        req = pool.submit_generate(prompt, max_new_tokens=8,
                                   temperature=0.0)
        toks = _drain(req)
        assert toks == want.token_ids
        assert req.future.result(timeout=5).token_ids == want.token_ids
        assert _legs(req) == [("source_error", "dma")]
        assert counter_total(metrics, "app_tpu_tier_sources_total",
                             kind="error") == err0 + 1
    finally:
        _close_pool(pool)
        source.close()


def test_source_slow_loris_expires_inside_the_budget(metrics, engines,
                                                     source_pool):
    """Partition/stall mid-pull (the serve thread parked) trips the
    read budget, and the EXPIRED pull budget then stops the descent —
    the terminal rung is local prefill, inside TPU_SOURCE_TIMEOUT_S,
    with the stream byte-identical and zero 5xx."""
    _, dc, ref = engines
    app, pool = source_pool
    # A tighter budget than the fixture's: the stall must cut inside it.
    pool.source_timeout_s = 1.2
    gate = threading.Event()
    try:
        prompt = _prompt(55)
        app.container.tpu.generate_sync(prompt, max_new_tokens=1,
                                        temperature=0.0, timeout=120.0)
        want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                                 timeout=120.0)
        exp0 = counter_total(metrics, "app_tpu_tier_sources_total",
                             kind="expired")
        t0 = time.monotonic()
        with faults.armed("transfer.dma.serve",
                          action=lambda **_kw: gate.wait(30.0)):
            req = pool.submit_generate(prompt, max_new_tokens=8,
                                       temperature=0.0)
            toks = _drain(req)
        assert time.monotonic() - t0 < 10.0
        assert toks == want.token_ids
        assert req.future.result(timeout=5).token_ids == want.token_ids
        assert _legs(req) == [("source_error", "dma")]
        assert counter_total(metrics, "app_tpu_tier_sources_total",
                             kind="expired") == exp0 + 1
    finally:
        gate.set()
        pool.source_timeout_s = 5.0


def test_source_geometry_drift_rejected_locally(metrics, engines,
                                                remote_app):
    """A source whose pod geometry drifted (kv_block 32 vs a local 16)
    survives the fetch — the bytes match the ticket — but the IMPORT
    rejects before touching the pool: source_rejected, no wire retry
    (it would reject identically), local prefill byte-identical."""
    _, _, ref = engines
    app, harness = remote_app
    dc16 = _make_engine(metrics, kv_block=16)
    source = _remote_replica("pf-drift", harness, dc16.tokenizer, metrics,
                             role="prefill")
    pool = _pool(
        [EngineReplica("dc16", dc16, role="decode"), source], metrics,
        source_timeout_s=5.0,
    )
    try:
        pool.probe_once()
        prompt = _prompt(56)
        app.container.tpu.generate_sync(prompt, max_new_tokens=1,
                                        temperature=0.0, timeout=120.0)
        want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                                 timeout=120.0)
        rej0 = counter_total(metrics, "app_tpu_tier_sources_total",
                             kind="rejected")
        req = pool.submit_generate(prompt, max_new_tokens=8,
                                   temperature=0.0)
        toks = _drain(req)
        assert toks == want.token_ids
        assert req.future.result(timeout=5).token_ids == want.token_ids
        assert _legs(req) == [("source_rejected", "dma")]
        assert counter_total(metrics, "app_tpu_tier_sources_total",
                             kind="rejected") == rej0 + 1
    finally:
        _close_pool(pool)
        source.close()
        dc16.close()


def test_source_pull_never_fires_when_locally_warm(metrics, engines,
                                                   source_pool):
    """The ``radix.peek`` gate: content already warm locally skips the
    pull entirely — no socket, no note, no counter."""
    _, dc, ref = engines
    app, pool = source_pool
    prompt = _prompt(57)
    app.container.tpu.generate_sync(prompt, max_new_tokens=1,
                                    temperature=0.0, timeout=120.0)
    dc.generate_sync(prompt, max_new_tokens=1, temperature=0.0,
                     timeout=120.0)  # locally warm
    total0 = counter_total(metrics, "app_tpu_tier_sources_total")
    req = pool.submit_generate(prompt, max_new_tokens=4, temperature=0.0)
    toks = _drain(req)
    assert toks == ref.generate_sync(
        prompt, max_new_tokens=4, temperature=0.0, timeout=120.0
    ).token_ids
    assert _legs(req) == []
    assert counter_total(metrics, "app_tpu_tier_sources_total") == total0


# ----------------------------------------------------------------------
# subprocess pods: kill -9 mid-DMA, warm hit across real processes
# ----------------------------------------------------------------------


class _ChildPod:
    """A REAL separate-process pod (tests/multihost_child.py)."""

    def __init__(self, *, stall=False):
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # The child runs by script path, so ITS sys.path gets tests/,
        # not the repo root — gofr_tpu must come in via PYTHONPATH.
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        if stall:
            env["MULTIHOST_CHILD_STALL"] = "1"
        self.proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "multihost_child.py")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=repo_root, env=env, text=True,
        )
        self.lines: list[str] = []
        self.ready = threading.Event()
        self.stalled = threading.Event()
        self.http_port = 0
        self.ops_port = 0
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self):
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            line = line.strip()
            self.lines.append(line)
            if line.startswith("READY "):
                parts = dict(p.split("=") for p in line.split()[1:])
                self.http_port = int(parts["http"])
                self.ops_port = int(parts["ops"])
                self.ready.set()
            elif line == "DMA-SERVE-STALLED":
                self.stalled.set()

    def wait_ready(self, timeout=240.0):
        assert self.ready.wait(timeout), (
            f"child pod never came up:\n" + "\n".join(self.lines[-30:])
        )

    def warm(self, token_ids, *, timeout=120.0):
        """Prefill+cache ``token_ids`` on the child via its OpenAI
        endpoint (prompt-as-token-ids is in the API)."""
        conn = http.client.HTTPConnection("127.0.0.1", self.http_port,
                                          timeout=timeout)
        try:
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({
                    "model": "llama-tiny", "prompt": list(token_ids),
                    "max_tokens": 1, "temperature": 0,
                }),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 200, body[:300]
        finally:
            conn.close()

    def metric(self, name):
        conn = http.client.HTTPConnection("127.0.0.1", self.ops_port,
                                          timeout=10.0)
        try:
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
        finally:
            conn.close()
        total = 0.0
        seen = False
        for line in text.splitlines():
            if line.startswith(name) and not line.startswith("#"):
                total += float(line.rsplit(None, 1)[-1])
                seen = True
        return total if seen else None

    def kill9(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def close(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


def _stable_metric(child, name, *, timeout=30.0):
    """A gauge read only after it stops moving (two consecutive equal
    samples): slot retirement on the child lags the HTTP reply by a
    scheduler tick, and a mid-retirement sample would fake a leak."""
    deadline = time.monotonic() + timeout
    prev = child.metric(name)
    while time.monotonic() < deadline:
        time.sleep(0.2)
        cur = child.metric(name)
        if cur == prev and cur is not None:
            return cur
        prev = cur
    return prev


def _child_source_pool(child, dc, metrics, *, source_timeout_s):
    from gofr_tpu.service import new_http_service

    source = HTTPReplica(
        "pf-pod",
        new_http_service(f"http://127.0.0.1:{child.http_port}"),
        tokenizer=dc.tokenizer,
        role="prefill",
        import_service=new_http_service(
            f"http://127.0.0.1:{child.ops_port}"
        ),
        metrics=metrics,
    )
    pool = _pool(
        [EngineReplica("dc", dc, role="decode"), source], metrics,
        source_timeout_s=source_timeout_s,
    )
    pool.probe_once()
    return pool, source


@pytest.mark.slow
def test_subprocess_source_warm_hit_zero_leak_both_sides(metrics, engines):
    """Cross-PROCESS pull: a real child pod (own interpreter, own JAX
    runtime, own transfer server) prefills a prompt; this process pulls
    its blocks over live sockets and admission-aliases them — fewer
    chunk dispatches, byte-identical, one trace id, and ZERO leaked
    blocks on EITHER side (the child's free-block gauge returns to its
    pre-export value; our staging dict is empty)."""
    _, dc, ref = engines
    child = _ChildPod()
    pool = source = None
    try:
        child.wait_ready()
        prompt = _prompt(60)
        child.warm(prompt)
        free_before = _stable_metric(child, "app_tpu_kv_blocks_free")
        pool, source = _child_source_pool(child, dc, metrics,
                                          source_timeout_s=10.0)
        assert pool.tier_sources() == [source]
        want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                                 timeout=120.0)
        s0 = dc._prefill_chunk_steps
        req = pool.submit_generate(prompt, max_new_tokens=8,
                                   temperature=0.0,
                                   traceparent=TRACEPARENT)
        toks = _drain(req)
        assert toks == req.future.result(timeout=5).token_ids
        assert toks == want.token_ids
        assert dc._prefill_chunk_steps - s0 < 3  # aliased, not re-prefilled
        assert _legs(req) == [("source_hit", "dma")]
        assert req.timeline.trace_id == "ab" * 16
        # Zero leak, both sides: the child exported COPIES (its pool is
        # untouched), and its transfer server redeemed the single-use
        # staging entry, so nothing is pinned on either host.
        free_after = _stable_metric(child, "app_tpu_kv_blocks_free")
        assert free_after == free_before
        assert get_transfer_server().staged_count() == 0
    finally:
        if pool is not None:
            _close_pool(pool)
        if source is not None:
            source.close()
        child.close()


@pytest.mark.slow
def test_subprocess_kill9_mid_dma_degrades_one_rung_at_a_time(metrics,
                                                              engines):
    """THE acceptance path: the child pod is ``kill -9``'d while its
    serve thread is parked MID-DMA (our fetch blocked inside its read
    budget). The pull degrades exactly one rung at a time — dma dies
    ``read``, the wire re-ask dies ``connect`` (the pod is gone), the
    terminal rung is local prefill — and the request completes
    byte-identically (greedy AND seeded-sampled on the follow-up
    request against the corpse), zero 5xx, one trace id, zero leaked
    staged bodies or slots on the surviving side."""
    _, dc, ref = engines
    child = _ChildPod(stall=True)
    pool = source = None
    try:
        child.wait_ready()
        prompt = _prompt(61)
        child.warm(prompt)
        pool, source = _child_source_pool(child, dc, metrics,
                                          source_timeout_s=30.0)
        assert pool.tier_sources() == [source]
        want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                                 timeout=120.0)
        box: dict = {}

        def _submit():
            box["req"] = pool.submit_generate(
                prompt, max_new_tokens=8, temperature=0.0,
                traceparent=TRACEPARENT,
            )
            box["toks"] = _drain(box["req"])

        worker = threading.Thread(target=_submit, daemon=True)
        worker.start()
        # The child prints the marker the instant our fetch lands on
        # its parked serve thread: the transfer is now mid-flight.
        assert child.stalled.wait(60.0), "\n".join(child.lines[-30:])
        child.kill9()
        worker.join(timeout=120.0)
        assert not worker.is_alive()
        req, toks = box["req"], box["toks"]
        assert toks == req.future.result(timeout=5).token_ids  # zero 5xx
        assert toks == want.token_ids
        assert _legs(req) == [
            ("source_error", "dma"),   # the fetch died mid-read
            ("source_error", "wire"),  # the re-ask found nobody listening
        ]
        assert req.timeline.trace_id == "ab" * 16
        # Seeded follow-up against the corpse: the connect-refused pull
        # degrades straight to local prefill, still byte-identical.
        prompt2 = _prompt(62)
        want2 = ref.generate_sync(prompt2, max_new_tokens=8,
                                  temperature=0.8, seed=7, timeout=120.0)
        req2 = pool.submit_generate(prompt2, max_new_tokens=8,
                                    temperature=0.8, seed=7)
        toks2 = _drain(req2)
        assert toks2 == want2.token_ids
        assert _legs(req2) == [("source_error", "dma")]
        # Surviving side leaks nothing: no staged bodies, no pinned
        # slots once the streams retired.
        assert get_transfer_server().staged_count() == 0
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(s is None for s in dc._slots):
                break
            time.sleep(0.05)
        assert all(s is None for s in dc._slots)
    finally:
        if pool is not None:
            _close_pool(pool)
        if source is not None:
            source.close()
        child.close()
