"""Config layer tests (behavioral parity with reference ``config/godotenv_test.go``)."""


from gofr_tpu.config import MockConfig, new_env_file


def _write(path, content):
    with open(path, "w") as fp:
        fp.write(content)


def test_env_file_loads_and_reads(tmp_path, monkeypatch):
    monkeypatch.delenv("APP_ENV", raising=False)
    monkeypatch.delenv("TEST_KEY_A", raising=False)
    _write(tmp_path / ".env", "TEST_KEY_A=hello\n# comment\nTEST_KEY_B=1\n")
    cfg = new_env_file(str(tmp_path))
    assert cfg.get("TEST_KEY_A") == "hello"
    assert cfg.get_or_default("MISSING_KEY_XYZ", "fallback") == "fallback"
    monkeypatch.delenv("TEST_KEY_A", raising=False)
    monkeypatch.delenv("TEST_KEY_B", raising=False)


def test_process_env_wins_over_base_file(tmp_path, monkeypatch):
    monkeypatch.delenv("APP_ENV", raising=False)
    monkeypatch.setenv("TEST_KEY_C", "from-process")
    _write(tmp_path / ".env", "TEST_KEY_C=from-file\n")
    cfg = new_env_file(str(tmp_path))
    assert cfg.get("TEST_KEY_C") == "from-process"


def test_local_env_overlay_overrides(tmp_path, monkeypatch):
    """Overlay semantics: .local.env overrides .env (godotenv.go:50-63)."""
    monkeypatch.delenv("APP_ENV", raising=False)
    monkeypatch.delenv("TEST_KEY_D", raising=False)
    _write(tmp_path / ".env", "TEST_KEY_D=base\n")
    _write(tmp_path / ".local.env", "TEST_KEY_D=local\n")
    cfg = new_env_file(str(tmp_path))
    assert cfg.get("TEST_KEY_D") == "local"
    monkeypatch.delenv("TEST_KEY_D", raising=False)


def test_app_env_overlay(tmp_path, monkeypatch):
    monkeypatch.setenv("APP_ENV", "stage")
    monkeypatch.delenv("TEST_KEY_E", raising=False)
    _write(tmp_path / ".env", "TEST_KEY_E=base\n")
    _write(tmp_path / ".stage.env", "TEST_KEY_E=stage\n")
    _write(tmp_path / ".local.env", "TEST_KEY_E=local\n")
    cfg = new_env_file(str(tmp_path))
    assert cfg.get("TEST_KEY_E") == "stage"
    monkeypatch.delenv("TEST_KEY_E", raising=False)


def test_quotes_and_export_prefix(tmp_path, monkeypatch):
    monkeypatch.delenv("APP_ENV", raising=False)
    for k in ("TEST_KEY_F", "TEST_KEY_G"):
        monkeypatch.delenv(k, raising=False)
    _write(tmp_path / ".env", 'export TEST_KEY_F="quoted value"\nTEST_KEY_G=plain # trailing\n')
    cfg = new_env_file(str(tmp_path))
    assert cfg.get("TEST_KEY_F") == "quoted value"
    assert cfg.get("TEST_KEY_G") == "plain"
    for k in ("TEST_KEY_F", "TEST_KEY_G"):
        monkeypatch.delenv(k, raising=False)


def test_mock_config():
    cfg = MockConfig({"A": "1"})
    assert cfg.get("A") == "1"
    assert cfg.get("B") is None
    assert cfg.get_or_default("B", "x") == "x"
