"""Disaggregated prefill/decode tier chaos suite (ISSUE 8 acceptance
gate).

Everything is deterministic: faults fire on exact hit counts through
``gofr_tpu/faults`` (``tier.prefill_done`` / ``tier.transfer`` /
``tier.import``), backoff sleeps go through a recording hook, deadlines
ride injectable clocks, and the prober never runs as a thread. Engines
share the default seed, so the transfer failure matrix's byte-identical
contract is checkable against a fused single-engine reference.

Covered:

* tiered happy path: a greedy AND a seeded-sampled stream served
  prefill-on-A → KV-block ship → decode-on-B are byte-identical to the
  fused reference, with ``app_tpu_tier_transfers_total{result="ok"}``,
  a ``tpu.transfer`` timeline annotation, ONE trace id, and the flight
  record in the ORIGIN replica's recorder;
* transfer retry with jittered backoff (one flaky attempt → success,
  sleep recorded — graftlint GL013's contract, lived);
* THE acceptance path: the prefill replica dying mid-transfer (every
  transfer attempt fails) → the request fails over WITHOUT its blocks
  to the decode replica, which re-prefills — byte-identical stream,
  zero 5xx, one trace id, ``result="failed_over"`` == 1;
* decode-side import rejection (``tier.import`` raise: pool pressure /
  version mismatch) → same fused fallback;
* corrupt / short payloads → ``"fused"`` import (re-prefill on the
  decode replica), never a wrong answer;
* deadline expiry and caller cancellation mid-transfer → the request
  is reaped within one window and leaks zero pool blocks on either
  engine;
* tier collapse: draining the only prefill replica flips
  ``app_tpu_tier_mode`` to fused with requests still served;
* import dedupe: re-shipping already-cached content allocates nothing.
"""

from __future__ import annotations

import dataclasses
import random
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.errors import ErrorDeadlineExceeded, ErrorRequestCancelled
from gofr_tpu.ops.kv_cache import export_blocks
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.lifecycle import Deadline
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.serving.types import _GenRequest
from gofr_tpu.service.replica_pool import EngineReplica, ReplicaPool

TIER_COUNTERS = (
    "app_tpu_tier_transfers_total",
    "app_tpu_tier_transfer_bytes_total",
    "app_tpu_failovers_total",
    "app_tpu_requests_replayed_total",
    "app_tpu_requests_cancelled_total",
    "app_tpu_deadline_exceeded_total",
    "app_tpu_requests_shed_total",
    "app_tpu_tokens_generated",
    "app_tpu_prefix_lookup_total",
    "app_tpu_prefix_hit_tokens_total",
    "app_tpu_probe_failures_total",
    "app_tpu_hedged_requests_total",
)
TIER_GAUGES = (
    "app_tpu_tier_mode",
    "app_tpu_engine_state",
    "app_tpu_replica_state",
    "app_tpu_pool_replicas",
    "app_tpu_queue_depth",
    "app_tpu_kv_slots_in_use",
    "app_tpu_kv_blocks_free",
    "app_tpu_prefix_cached_blocks",
    "app_tpu_hbm_used_bytes",
)
TIER_HISTOGRAMS = (
    "app_tpu_tier_transfer_seconds",
    "app_tpu_infer_latency",
    "app_tpu_batch_size",
    "app_tpu_spec_tokens_per_step",
)

#: 96 tokens = exactly 3 full 32-token KV blocks — the whole-prompt-
#: cached edge (COW boundary) rides every transfer.
PROMPT = list(range(2, 200, 3)) + [7] * 30
assert len(PROMPT) == 96


def _metrics_manager():
    m = new_metrics_manager()
    for name in TIER_COUNTERS:
        m.new_counter(name)
    for name in TIER_GAUGES:
        m.new_gauge(name)
    for name in TIER_HISTOGRAMS:
        m.new_histogram(name)
    return m


def counter_total(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    total = 0.0
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            total += value
    return total


def gauge_value(metrics, name):
    inst = {i.name: i for i in metrics.instruments()}[name]
    values = list(inst.collect().values())
    return values[0] if values else None


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _make_engine(metrics, **kw):
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, window_k=4,
        pipeline_depth=1, prefill_chunk=32, kv_block=32, auto_prefix=True,
        tokenizer=ByteTokenizer(), metrics=metrics, **kw,
    )
    eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def engines(metrics):
    """One prefill + one decode engine shared by the suite (compile
    cost), plus a fused single-engine reference for byte-identity.
    Every test that wounds something restores it before finishing."""
    pf = _make_engine(metrics)
    dc = _make_engine(metrics)
    ref = _make_engine(metrics)
    yield pf, dc, ref
    faults.reset()
    for eng in (pf, dc, ref):
        eng.close()


@pytest.fixture()
def tier_pool(metrics, engines):
    """A fresh 1-prefill + 1-decode pool around the shared engines with
    recording backoff sleeps; hedging is parked far out so unary calls
    never race a second attempt into the determinism assertions."""
    pf, dc, _ = engines
    sleeps: list[float] = []
    pool = ReplicaPool(
        [
            EngineReplica("pf", pf, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        probe_interval_s=0,
        probe_timeout_s=60.0,
        hedge_delay_s=300.0,
        transfer_retries=2,
        transfer_backoff_s=0.01,
        sleep=sleeps.append,
        rng=random.Random(7),
        metrics=metrics,
    )
    pool._test_sleeps = sleeps
    yield pool
    pool.stop_prober()
    for replica in pool.replicas:
        replica.set_handoff(None)
        replica.set_tier_exporter(None)


def _drain_stream(req, timeout=120.0):
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _wait_idle(eng, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if (
            all(s is None for s in eng._slots)
            and not eng._prefilling
            and eng._pending.empty()
        ):
            return
        time.sleep(0.01)
    raise AssertionError("engine did not go idle")


def _engine_block_invariant(eng):
    """Every pool block is free, or accounted for by exactly its
    referencing slot tables plus the radix index (the zero-leak
    contract the cancel/deadline-mid-transfer tests pin)."""
    refs: dict[int, int] = {}
    for row in eng._slot_blocks:
        for bid in row:
            refs[bid] = refs.get(bid, 0) + 1
    for bid in eng._radix.cached_block_ids():
        refs[bid] = refs.get(bid, 0) + 1
    alloc = eng._allocator
    free = set(alloc.free_blocks)
    assert len(free) == len(alloc.free_blocks)
    for bid in range(1, alloc.n_blocks):
        expected = refs.get(bid, 0)
        assert alloc.refcount(bid) == expected, (bid,)
        assert (bid in free) == (expected == 0), (bid,)


def _reference(engines, **kw):
    _, _, ref = engines
    return ref.generate_sync(PROMPT, timeout=120.0, **kw)


# ----------------------------------------------------------------------
# happy path: tiered serving is byte-identical and observable
# ----------------------------------------------------------------------


def test_tiered_greedy_stream_byte_identical(metrics, engines, tier_pool):
    pf, dc, _ = engines
    want = _reference(engines, max_new_tokens=12, temperature=0.0)
    ok0 = counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok"
    )
    req = tier_pool.submit_generate(
        PROMPT, max_new_tokens=12, temperature=0.0
    )
    toks = _drain_stream(req)
    result = req.future.result(timeout=5)  # zero 5xx: resolves cleanly
    assert toks == result.token_ids == want.token_ids
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok"
    ) == ok0 + 1
    # The transfer rides the request's ONE timeline: same trace id end
    # to end, with a tpu.transfer hop naming both replicas.
    tl = req.timeline
    assert tl is not None and len(tl.trace_id) == 32
    assert [(s, d, r) for s, d, _, _, r, _ in tl.transfers] == [
        ("pf", "dc", "ok")
    ]
    # The flight record lands ONCE, in the ORIGIN (prefill) replica's
    # recorder, with the transfer annotation.
    records = pf.flight_records()
    entries = [
        e for e in records["records"] + records["pinned"]
        if e["rid"] == tl.rid
    ]
    assert len(entries) == 1
    assert entries[0]["transfers"] == [{
        "source": "pf", "target": "dc",
        "duration_s": entries[0]["transfers"][0]["duration_s"],
        "result": "ok",
        "leg": entries[0]["transfers"][0]["leg"],
    }]
    assert entries[0]["transfers"][0]["leg"] in ("device", "host")
    assert entries[0]["outcome"] == "ok"
    # The shipped blocks live in the DECODE replica's radix index now.
    assert dc._radix.n_cached_blocks >= 3
    _wait_idle(pf)
    _wait_idle(dc)
    _engine_block_invariant(pf)
    _engine_block_invariant(dc)


def test_tiered_seeded_sampled_stream_byte_identical(engines, tier_pool):
    want = _reference(engines, max_new_tokens=10, temperature=0.8, seed=42)
    req = tier_pool.submit_generate(
        PROMPT, max_new_tokens=10, temperature=0.8, seed=42
    )
    toks = _drain_stream(req)
    assert toks == want.token_ids
    assert req.future.result(timeout=5).token_ids == want.token_ids


def test_import_dedupes_already_cached_content(metrics, engines, tier_pool):
    """Re-shipping content the decode replica already caches allocates
    zero new blocks — the lookup-first import path."""
    pf, dc, _ = engines
    # Warm: first transfer populates dc's radix.
    req = tier_pool.submit_generate(PROMPT, max_new_tokens=6, temperature=0.0)
    _drain_stream(req)
    _wait_idle(dc)
    cached = dc._radix.n_cached_blocks
    free = dc._allocator.n_free
    req2 = tier_pool.submit_generate(PROMPT, max_new_tokens=6, temperature=0.0)
    _drain_stream(req2)
    _wait_idle(dc)
    assert dc._radix.n_cached_blocks == cached
    assert dc._allocator.n_free == free
    _engine_block_invariant(dc)


# ----------------------------------------------------------------------
# the transfer failure matrix
# ----------------------------------------------------------------------


def test_transfer_retry_with_jittered_backoff(metrics, engines, tier_pool):
    """One flaky transfer attempt → a recorded backoff sleep → success
    on the retry. The stream is byte-identical either way."""
    want = _reference(engines, max_new_tokens=8, temperature=0.0)
    ok0 = counter_total(metrics, "app_tpu_tier_transfers_total", result="ok")
    tier_pool._test_sleeps.clear()
    with faults.armed(
        "tier.transfer", raises=RuntimeError("flaky leg"), times=1
    ):
        req = tier_pool.submit_generate(
            PROMPT, max_new_tokens=8, temperature=0.0
        )
        toks = _drain_stream(req)
    assert toks == want.token_ids
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok"
    ) == ok0 + 1
    assert len(tier_pool._test_sleeps) == 1  # one backoff before the retry
    assert tier_pool._test_sleeps[0] > 0.0


def test_prefill_death_mid_transfer_fails_over_byte_identically(
    metrics, engines, tier_pool
):
    """THE acceptance path: the prefill replica dies mid-transfer
    (every ship attempt fails), so the request fails over WITHOUT its
    blocks to the decode replica, which re-prefills — the client
    stream is byte-identical to the fault-free run, zero 5xx, one
    trace id, and ``result="failed_over"`` counts exactly 1."""
    want = _reference(engines, max_new_tokens=12, temperature=0.0)
    fo0 = counter_total(
        metrics, "app_tpu_tier_transfers_total", result="failed_over"
    )
    with faults.armed(
        "tier.transfer", raises=RuntimeError("prefill replica lost")
    ):
        req = tier_pool.submit_generate(
            PROMPT, max_new_tokens=12, temperature=0.0
        )
        toks = _drain_stream(req)
    result = req.future.result(timeout=5)  # zero 5xx
    assert toks == result.token_ids == want.token_ids
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="failed_over"
    ) == fo0 + 1
    tl = req.timeline
    assert tl is not None
    # One trace: the failover annotation and the abandoned transfer ride
    # the same timeline (same trace id) the prefill phase recorded.
    assert [(s, r) for s, _, _, _, r, _ in tl.transfers] == [
        ("pf", "failed_over")
    ]
    assert any(name == "tpu.failover" for name, _, _ in tl.annotations)


def test_decode_import_rejection_falls_back_to_fused(
    metrics, engines, tier_pool
):
    """The decode replica rejecting every import (pool pressure /
    version mismatch modeled by the ``tier.import`` raise) degrades to
    the same fused fallback, byte-identically."""
    want = _reference(engines, max_new_tokens=8, temperature=0.0)
    fo0 = counter_total(
        metrics, "app_tpu_tier_transfers_total", result="failed_over"
    )
    with faults.armed(
        "tier.import", raises=RuntimeError("importer said no")
    ):
        req = tier_pool.submit_generate(
            PROMPT, max_new_tokens=8, temperature=0.0
        )
        toks = _drain_stream(req)
    assert toks == want.token_ids
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="failed_over"
    ) == fo0 + 1


def test_corrupt_and_stale_payloads_degrade_to_fused_import(engines):
    """A corrupt (checksum-broken) or geometry-stale payload is never
    aliased: ``handoff_prefilled`` downgrades to ``"fused"`` and the
    request re-prefills on the decode replica, byte-identically."""
    pf, dc, _ = engines
    want = _reference(engines, max_new_tokens=8, temperature=0.0)
    _wait_idle(dc)
    cached0 = dc._radix.n_cached_blocks
    payload = export_blocks(
        pf.cache, [1, 2, 3], PROMPT, src="unit"
    )
    corrupt = dataclasses.replace(payload, checksum=payload.checksum ^ 1)
    stale = dataclasses.replace(payload, geometry=("bogus",))
    for bad in (corrupt, stale):
        req = _GenRequest(
            prompt_ids=list(PROMPT), max_new_tokens=8, temperature=0.0,
            stop_on_eos=True,
        )
        assert dc.handoff_prefilled(req, bad) == "fused"
        toks = _drain_stream(req)
        assert toks == want.token_ids
    _wait_idle(dc)
    # Neither bad payload may have landed blocks under its content keys
    # beyond what the re-prefill retirement itself caches.
    _engine_block_invariant(dc)
    assert dc._radix.n_cached_blocks >= cached0


def test_deadline_expired_mid_transfer_reaps_without_leaks(
    metrics, engines, tier_pool
):
    """A request whose deadline expires DURING the transfer is not
    shipped: it is released to the scheduler's reap (one window), the
    caller gets the deadline error (504 — the caller's budget, not a
    replica 5xx), and zero pool blocks leak on either engine."""
    pf, dc, _ = engines
    clk = [0.0]
    deadline = Deadline(60.0, clock=lambda: clk[0])

    def expire(**ctx):
        clk[0] = 120.0

    exp0 = counter_total(
        metrics, "app_tpu_tier_transfers_total", result="expired"
    )
    with faults.armed("tier.transfer", action=expire):
        req = tier_pool.submit_generate(
            PROMPT, max_new_tokens=8, temperature=0.0, deadline=deadline
        )
        with pytest.raises(ErrorDeadlineExceeded):
            req.future.result(timeout=60)
    assert _drain_stream(req) == []
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="expired"
    ) == exp0 + 1
    _wait_idle(pf)
    _wait_idle(dc)
    _engine_block_invariant(pf)
    _engine_block_invariant(dc)


def test_cancel_mid_transfer_leaks_zero_blocks(metrics, engines, tier_pool):
    """Satellite regression: a caller cancelling mid-transfer is reaped
    on whichever side holds the request, and every pool block on both
    engines is freed or accounted for — zero leaks."""
    pf, dc, _ = engines

    def cancel(**ctx):
        ctx["request"].cancel.cancel()

    with faults.armed("tier.transfer", action=cancel):
        req = tier_pool.submit_generate(
            PROMPT, max_new_tokens=8, temperature=0.0
        )
        with pytest.raises(ErrorRequestCancelled):
            req.future.result(timeout=60)
    assert _drain_stream(req) == []
    _wait_idle(pf)
    _wait_idle(dc)
    _engine_block_invariant(pf)
    _engine_block_invariant(dc)


# ----------------------------------------------------------------------
# tier collapse → fused degradation
# ----------------------------------------------------------------------


def test_draining_last_prefill_replica_collapses_to_fused(
    metrics, engines, tier_pool
):
    """Draining the only prefill replica flips ``app_tpu_tier_mode`` to
    fused (0) with requests still served — on the surviving decode
    replica, byte-identically."""
    pf_replica = tier_pool.replicas[0]
    want = _reference(engines, max_new_tokens=8, temperature=0.0)
    assert tier_pool.tier_mode == "tiered"
    assert gauge_value(metrics, "app_tpu_tier_mode") == 1.0
    pf_replica.draining = True
    tier_pool._publish_tier_mode()
    try:
        assert tier_pool.tier_mode == "fused"
        assert gauge_value(metrics, "app_tpu_tier_mode") == 0.0
        req = tier_pool.submit_generate(
            PROMPT, max_new_tokens=8, temperature=0.0
        )
        toks = _drain_stream(req)
        assert toks == want.token_ids
        # Served fused on the decode replica — no transfer involved.
        assert req.timeline is None or req.timeline.transfers == []
    finally:
        pf_replica.draining = False
    assert tier_pool.tier_mode == "tiered"
    assert gauge_value(metrics, "app_tpu_tier_mode") == 1.0


def test_probe_requests_never_transfer(engines, tier_pool):
    """A synthetic probe pinned to the prefill replica must measure
    THAT replica end to end — prefill AND decode run locally."""
    pf, _, _ = engines
    before = [r for r in (pf._obs.recorder,)]  # recorder exists
    assert before
    result = pf.synthetic_probe(timeout_s=60.0)
    assert len(result.token_ids) == 1
    _wait_idle(pf)


def test_tier_routing_prefers_prefill_replicas(metrics, engines, tier_pool):
    """While tiered, fresh submits land on the prefill tier; pick()
    only falls through to other roles when the preferred tier has no
    routable replica."""
    assert tier_pool.pick(prefer_roles=("prefill",)).name == "pf"
    assert tier_pool.pick(prefer_roles=("decode",)).name == "dc"
    # Preference dissolves instead of 502ing when the tier is empty.
    pf_replica = tier_pool.replicas[0]
    pf_replica.draining = True
    try:
        assert tier_pool.pick(prefer_roles=("prefill",)).name == "dc"
    finally:
        pf_replica.draining = False
