"""Logging tests (parity with reference ``logging/logger_test.go`` patterns:
assert on captured output, level filtering, stdout/stderr split)."""

import io
import json

import pytest

from gofr_tpu.logging import Level, Logger, level_from_string, new_file_logger


def make_logger(level=Level.INFO, terminal=False):
    out, err = io.StringIO(), io.StringIO()
    return Logger(level=level, out=out, err=err, is_terminal=terminal), out, err


def test_json_output_and_level_filtering():
    log, out, err = make_logger(Level.INFO)
    log.debug("hidden")
    log.info("visible", 42)
    lines = out.getvalue().strip().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["level"] == "INFO"
    assert rec["message"] == "visible 42"
    assert err.getvalue() == ""


def test_error_goes_to_stderr():
    log, out, err = make_logger()
    log.error("boom")
    assert out.getvalue() == ""
    assert json.loads(err.getvalue())["level"] == "ERROR"


def test_formatting_variants():
    log, out, _ = make_logger()
    log.infof("x=%d y=%s", 7, "a")
    assert json.loads(out.getvalue())["message"] == "x=7 y=a"


def test_structured_payload_serialized():
    class Payload:
        def __init__(self):
            self.query = "SELECT 1"
            self.duration = 3

    log, out, _ = make_logger()
    log.info(Payload())
    msg = json.loads(out.getvalue())["message"]
    assert msg == {"query": "SELECT 1", "duration": 3}


def test_pretty_print_on_terminal():
    class Payload:
        def pretty_print(self, fp):
            fp.write("PRETTY!\n")

    log, out, _ = make_logger(terminal=True)
    log.info(Payload())
    assert "PRETTY!" in out.getvalue()
    assert "INFO" in out.getvalue()


def test_change_level():
    log, out, _ = make_logger(Level.ERROR)
    log.info("nope")
    log.change_level(Level.DEBUG)
    log.debug("yes")
    assert "nope" not in out.getvalue()
    assert "yes" in out.getvalue()


def test_fatal_raises_system_exit():
    log, _, err = make_logger()
    with pytest.raises(SystemExit):
        log.fatal("dying")
    assert "dying" in err.getvalue()


def test_level_from_string():
    assert level_from_string("debug") == Level.DEBUG
    assert level_from_string("WARN") == Level.WARN
    assert level_from_string("bogus") == Level.INFO
    assert level_from_string(None) == Level.INFO


def test_file_logger(tmp_path):
    path = tmp_path / "cmd.log"
    log = new_file_logger(str(path))
    log.info("to file")
    log._out.flush()
    assert "to file" in path.read_text()


def test_silent_file_logger_when_no_path():
    log = new_file_logger("")
    log.info("discarded")  # must not raise


def test_remote_level_logger_uses_instrumented_client():
    """The level poll rides service.HTTPService: the level hot-swaps AND
    the client's response histogram records the framework's own fetch
    (reference dynamicLevelLogger.go:58 builds on service.NewHTTPService)."""
    import http.server
    import threading

    from gofr_tpu.logging import RemoteLevelLogger
    from gofr_tpu.metrics import new_metrics_manager

    class LevelHandler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({
                "data": [{"serviceName": "t",
                          "logLevel": {"LOG_LEVEL": "DEBUG"}}]
            }).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), LevelHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        log, _, _ = make_logger(Level.INFO)
        metrics = new_metrics_manager(log)
        metrics.new_histogram(
            "app_http_service_response", "outbound client response time"
        )
        rl = RemoteLevelLogger(
            log, f"http://127.0.0.1:{srv.server_address[1]}/level",
            metrics=metrics,
        )
        rl.fetch_and_update()
        assert log.level == Level.DEBUG
        from gofr_tpu.metrics.exposition import render_prometheus

        assert "app_http_service_response" in render_prometheus(metrics)
    finally:
        srv.shutdown()
        srv.server_close()


def test_remote_level_background_loop_and_failure_paths():
    """The poller's BACKGROUND thread hot-swaps the level on its
    interval; a dead endpoint or empty payload never kills the loop or
    changes the level; start() without a URL is a no-op; stop() ends
    the thread (reference dynamicLevelLogger.go:23-106)."""
    import http.server
    import threading
    import time as _time

    from gofr_tpu.logging import RemoteLevelLogger

    payload = {"data": [
        {"serviceName": "t", "logLevel": {"LOG_LEVEL": "ERROR"}}
    ]}

    class LevelHandler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), LevelHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        log, _, _ = make_logger(Level.INFO)
        rl = RemoteLevelLogger(
            log, f"http://127.0.0.1:{srv.server_address[1]}/level",
            interval_s=0.05,
        )
        rl.start()
        deadline = _time.time() + 10
        while log.level != Level.ERROR and _time.time() < deadline:
            _time.sleep(0.02)
        assert log.level == Level.ERROR  # hot-swapped by the thread
        # Empty data → keep the current level, keep polling.
        payload["data"] = []
        _time.sleep(0.2)
        assert log.level == Level.ERROR
        rl.stop()

        # Dead endpoint: fetch must swallow the error, not raise.
        log2, _, _ = make_logger(Level.INFO)
        dead = RemoteLevelLogger(log2, "http://127.0.0.1:1/level")
        dead.fetch_and_update()
        assert log2.level == Level.INFO
        dead.stop()

        # No URL configured → start() is a no-op (no thread).
        log3, _, _ = make_logger(Level.INFO)
        off = RemoteLevelLogger(log3, "")
        off.start()
        assert off._thread is None
    finally:
        srv.shutdown()
