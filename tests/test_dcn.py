"""DCN tier: the non-no-op multi-host path of ``parallel/dcn.py``
exercised by two real processes on one machine (CPU backend, localhost
coordinator) — VERDICT r2 next #8. Each child initializes via
``initialize_multihost``, runs a cross-process allgather, and routes a
request across hosts through the service client + circuit breaker."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.parallel.dcn import initialize_multihost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_no_config_is_single_host_noop():
    assert initialize_multihost(MockConfig({})) is False


def test_two_process_dcn_runtime_and_service_hop():
    coord, http = _free_port(), _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    tmpdir = os.path.join(REPO, ".pytest_cache", f"dcn-{coord}")
    os.makedirs(tmpdir, exist_ok=True)
    child = os.path.join(REPO, "tests", "dcn_child.py")
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", child, str(pid), str(coord), str(http), tmpdir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode("utf-8", "replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("DCN children timed out:\n" + "\n".join(
            p.stdout.read().decode("utf-8", "replace") for p in procs
        ))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("DCN_RESULT "):
                r = json.loads(line[len("DCN_RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, outs
    for r in results.values():
        assert r["topo"]["process_count"] == 2
        assert r["allgather_sum"] == 3.0  # 1.0 + 2.0 across processes
    assert results[0]["served_peer"] is True
    assert results[1]["hop"]["process_count"] == 2

    # Multi-host serving: the tp=2-over-DCN engine generation must agree
    # BETWEEN processes (SPMD consistency) and WITH a single-process
    # engine at the same seed/geometry (the collectives changed the
    # placement, not the math).
    toks0, toks1 = results[0]["engine_tokens"], results[1]["engine_tokens"]
    assert toks0 == toks1 and len(toks0) == 16, (toks0, toks1)
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    ref = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(), seed=0,
    )
    ref.start_sync()
    try:
        base = ref.generate_sync(
            "dcn serving smoke", max_new_tokens=16, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        ref.stop_sync()
    assert toks0 == [int(t) for t in base.token_ids], (toks0, base.token_ids)

    # dp-over-processes × tp-within-process (DCN × ICI composed): same
    # SPMD-consistency + math-unchanged contract for the pod topology.
    dp0 = results[0]["engine_dp_tp_tokens"]
    dp1 = results[1]["engine_dp_tp_tokens"]
    assert dp0 == dp1 and len(dp0) == 16, (dp0, dp1)
    assert dp0 == [int(t) for t in base.token_ids], (dp0, base.token_ids)
