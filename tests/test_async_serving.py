"""Durable event-driven inference (gofr_tpu/pubsub +
serving/async_serving.py; docs/advanced-guide/resilience.md "Async
serving & delivery semantics").

Four layers, all deterministic (stated clocks, jitter pinned to 0,
``step()``-driven pump — the background thread adds liveness, never
semantics):

* **broker unit** — lease/ack/nack lifecycle, lease-expiry redelivery,
  budget refunds, idempotent publish per pinned id, and the durable
  journal's crash-replay contract (unacked → ready, attempts
  preserved, torn tail lines skipped, compaction state-preserving);
* **the delivery contract** — THE acceptance path: ``pubsub.*`` faults
  armed and the consumer killed mid-inference, every message either
  answered exactly once or parked in the DLQ with its redelivery
  history — zero lost, zero duplicated, the dedup ledger proving the
  lost-ack replay never double-publishes;
* **integration with the real engine** — trace-id continuity
  broker→engine→reply, expired async messages reaped within one window
  with zero leaked leases and zero leaked KV blocks, brownout sheds
  async (batch-class) first while interactive goodput holds, and the
  sync path is byte-identical with the plane attached;
* **control plane** — sustained consumer lag asserts scale pressure
  through the same hysteretic sustain discipline as every other loop.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import Future
from types import SimpleNamespace

import pytest

from gofr_tpu import faults
from gofr_tpu.config import MockConfig
from gofr_tpu.pubsub import DurableBroker, InMemoryBroker, make_broker
from gofr_tpu.pubsub.durable import _topic_file
from gofr_tpu.serving.async_serving import (
    AsyncServingPlane,
    new_async_plane_from_config,
)
from gofr_tpu.serving.control_plane import ControlPlane
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.options import RetryConfig

REQUEST, REPLY, DLQ = "tpu.requests", "tpu.replies", "tpu.dlq"


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class FakeEngine:
    """The engine facade seam the plane drives: ``submit_generate``
    returning a handle with a ``future``. ``auto=False`` leaves the
    future unresolved (work 'stuck on the device') so tests control
    exactly when inference finishes."""

    model_name = "fake-llm"

    def __init__(self, auto: bool = True, raises: Exception = None):
        self.auto = auto
        self.raises = raises
        self.calls = []
        self.reqs = []

    def submit_generate(self, prompt, **kw):
        if self.raises is not None:
            raise self.raises
        req = SimpleNamespace(future=Future(), timeline=None)
        self.calls.append((prompt, dict(kw)))
        self.reqs.append(req)
        if self.auto:
            req.future.set_result(SimpleNamespace(
                text="ok", token_ids=[1, 2, 3], finish_reason="stop",
                prompt_tokens=2,
            ))
        return req


def no_jitter_retry(backoff_s: float = 1.0) -> RetryConfig:
    return RetryConfig(backoff_s=backoff_s, jitter=0.0, max_backoff_s=60.0)


def make_plane(engine=None, clock=None, **kw):
    clock = clock or FakeClock(1000.0)
    broker = kw.pop("broker", None) or InMemoryBroker(clock=clock)
    defaults = dict(
        request_topic=REQUEST, reply_topic=REPLY, dlq_topic=DLQ,
        redelivery_max=2, lease_s=30.0, max_inflight=4,
        retry=no_jitter_retry(), clock=clock,
    )
    defaults.update(kw)
    plane = AsyncServingPlane(
        engine if engine is not None else FakeEngine(), broker, **defaults
    )
    return plane, broker, clock


def req_json(prompt: str = "hi", **kw) -> str:
    return json.dumps({"prompt": prompt, **kw})


def wait_for(predicate, timeout_s: float = 60.0) -> None:
    """Bound a poll on a real scheduler thread observing a condition —
    the OUTCOME is deterministic, only the interleaving isn't."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    assert predicate(), "condition never became true"


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


# ----------------------------------------------------------------------
# broker unit: the lease lifecycle on a stated clock
# ----------------------------------------------------------------------


def test_broker_lease_ack_lifecycle_is_fifo():
    clock = FakeClock()
    b = InMemoryBroker(clock=clock)
    sub = b.subscribe(REQUEST, lease_s=30.0)
    first = b.publish(REQUEST, "a")
    second = b.publish(REQUEST, "b")
    assert b.depth(REQUEST) == 2 and b.inflight(REQUEST) == 0
    m1 = sub.lease()
    assert m1.id == first and m1.attempt == 1 and m1.value == "a"
    assert b.depth(REQUEST) == 1 and sub.inflight() == 1
    assert sub.ack(m1.id) is True
    assert b.size(REQUEST) == 1          # acked for good
    assert sub.ack(m1.id) is False       # unknown id now
    m2 = sub.lease()
    assert m2.id == second
    assert sub.lease() is None           # nothing ready


def test_broker_lease_expiry_redelivers_and_counts_the_attempt():
    clock = FakeClock()
    b = InMemoryBroker(clock=clock)
    sub = b.subscribe(REQUEST, lease_s=10.0)
    b.publish(REQUEST, "v")
    m1 = sub.lease()
    assert sub.lease() is None           # leased, not ready
    clock.advance(10.1)                  # consumer died; lease ran out
    m2 = sub.lease()
    assert m2.id == m1.id and m2.attempt == 2
    events = [h["event"] for h in m2.history]
    assert "lease_expired" in events
    # The dead consumer's stale ack bounces (the id was re-leased and
    # will be re-acked by whoever holds it now).
    assert sub.ack(m2.id) is True


def test_broker_nack_delay_and_drain_refund():
    clock = FakeClock()
    b = InMemoryBroker(clock=clock)
    sub = b.subscribe(REQUEST, lease_s=30.0)
    b.publish(REQUEST, "v")
    m = sub.lease()
    assert sub.nack(m.id, delay_s=5.0, note="boom") is True
    assert sub.lease() is None           # backoff holds it back
    clock.advance(5.0)
    m2 = sub.lease()
    assert m2.attempt == 2               # a penalized nack burns budget
    # Drain refund: penalize=False hands the delivery back.
    sub.nack(m2.id, delay_s=0.0, note="drain", penalize=False)
    m3 = sub.lease()
    assert m3.attempt == 2               # refunded, re-burned by this lease


def test_broker_publish_is_idempotent_per_pinned_id():
    b = InMemoryBroker(clock=FakeClock())
    a = b.publish(REPLY, "r1", message_id="reply-x")
    c = b.publish(REPLY, "DIFFERENT", message_id="reply-x")
    assert a == c == "reply-x"
    msgs = b.peek_all(REPLY)
    assert len(msgs) == 1 and msgs[0].value == "r1"


# ----------------------------------------------------------------------
# durable broker: crash-safe resumption off the journal
# ----------------------------------------------------------------------


def test_durable_replay_restores_unacked_with_attempts(tmp_path):
    clock = FakeClock()
    b = DurableBroker(str(tmp_path), clock=clock)
    sub = b.subscribe(REQUEST, lease_s=30.0)
    b.publish(REQUEST, "acked")
    b.publish(REQUEST, "leased-then-crash")
    b.publish(REQUEST, "never-touched")
    sub.ack(sub.lease().id)              # first: consumed for good
    leased = sub.lease()                 # second: lease dies with us
    assert leased.value == "leased-then-crash"
    b.close()                            # crash (no ack, no nack)

    b2 = DurableBroker(str(tmp_path), clock=clock)
    assert b2.depth(REQUEST) == 2        # leases are volatile → ready
    sub2 = b2.subscribe(REQUEST, lease_s=30.0)
    m1 = sub2.lease()
    # Delivery count survived the crash: the in-flight lease is
    # remembered, so a crash-looping consumer still exhausts budget.
    assert m1.value == "leased-then-crash" and m1.attempt == 2
    m2 = sub2.lease()
    assert m2.value == "never-touched" and m2.attempt == 1
    b2.close()


def test_durable_replay_skips_torn_tail_line(tmp_path):
    b = DurableBroker(str(tmp_path), clock=FakeClock())
    b.publish(REQUEST, "whole")
    b.close()
    with open(_topic_file(str(tmp_path), REQUEST), "a") as f:
        f.write('{"op":"pub","id":"half')  # power loss mid-append
    b2 = DurableBroker(str(tmp_path), clock=FakeClock())
    assert [m.value for m in b2.peek_all(REQUEST)] == ["whole"]
    b2.close()


def test_durable_compact_preserves_live_state(tmp_path):
    clock = FakeClock()
    b = DurableBroker(str(tmp_path), clock=clock)
    sub = b.subscribe(REQUEST, lease_s=5.0)
    for i in range(3):
        b.publish(REQUEST, f"v{i}")
    sub.ack(sub.lease().id)              # v0 gone
    sub.lease()                          # v1 at attempt 1
    clock.advance(5.1)                   # ... lease expires
    assert b.compact(REQUEST) == 2
    b.close()
    b2 = DurableBroker(str(tmp_path), clock=clock)
    by_value = {m.value: m.attempt for m in b2.peek_all(REQUEST)}
    assert by_value == {"v1": 1, "v2": 0}
    b2.close()


def test_make_broker_kinds(tmp_path):
    assert type(make_broker("memory")) is InMemoryBroker
    assert isinstance(make_broker("file", dir=str(tmp_path)), DurableBroker)
    with pytest.raises(ValueError):
        make_broker("file")              # dir is mandatory
    with pytest.raises(ValueError):
        make_broker("kafkaesque")


# ----------------------------------------------------------------------
# the delivery contract (fake engine, stated clock)
# ----------------------------------------------------------------------


def test_happy_path_publishes_reply_then_acks():
    plane, broker, _ = make_plane()
    mid = broker.publish(
        REQUEST, req_json(max_new_tokens=4), {"tenant": "acme"}
    )
    plane.step()                         # lease + submit (auto-resolves)
    plane.step()                         # complete: publish, ledger, ack
    replies = broker.peek_all(REPLY)
    assert len(replies) == 1
    body = json.loads(replies[0].value)
    assert body["id"] == mid and body["token_ids"] == [1, 2, 3]
    assert body["attempt"] == 1 and body["finish_reason"] == "stop"
    assert replies[0].headers["tenant"] == "acme"
    assert broker.size(REQUEST) == 0     # acked for good
    assert mid in plane.dedup_ledger()
    assert plane.counters["published"] == 1
    assert plane.counters["consumed"] == 1
    # Engine saw the batch SLO class and the tenant attribution.
    kw = plane.engine.calls[0][1]
    assert kw["slo_class"] == "batch" and kw["tenant"] == "acme"
    assert kw["max_new_tokens"] == 4


def test_consumer_killed_mid_inference_redelivers_exactly_one_reply():
    """THE at-least-once half: a consumer crash loses nothing — the
    lease expires, a second consumer redelivers, one reply lands."""
    clock = FakeClock(1000.0)
    broker = InMemoryBroker(clock=clock)
    stuck = FakeEngine(auto=False)       # inference never finishes
    plane1, _, _ = make_plane(stuck, clock=clock, broker=broker)
    broker.publish(REQUEST, req_json())
    plane1.step()
    assert plane1.inflight_count() == 1
    plane1.kill()                        # crash: no nack, lease leaks
    assert broker.inflight(REQUEST) == 1
    clock.advance(30.1)                  # the lease clock is the recovery
    plane2, _, _ = make_plane(clock=clock, broker=broker)
    plane2.step()
    plane2.step()
    assert plane2.counters["redelivered"] == 1
    assert broker.size(REQUEST) == 0
    assert len(broker.peek_all(REPLY)) == 1
    assert json.loads(broker.peek_all(REPLY)[0].value)["attempt"] == 2


def test_lost_ack_replay_is_deduped_never_double_published():
    """THE exactly-once-publish half: die between publish and ack and
    the replay acks off the dedup ledger — no second reply."""
    plane, broker, clock = make_plane()
    mid = broker.publish(REQUEST, req_json())
    with faults.armed("pubsub.ack", raises=RuntimeError("died"), times=1):
        plane.step()
        plane.step()                     # publish OK, ledger OK, ack dies
    assert plane.counters["ack_errors"] == 1
    assert broker.inflight(REQUEST) == 1     # lease survived
    assert len(broker.peek_all(REPLY)) == 1  # the reply DID land
    clock.advance(30.1)                  # lease expires → redelivery
    plane.step()
    assert plane.counters["deduped"] == 1
    assert broker.size(REQUEST) == 0     # replay acked, not re-run
    assert len(broker.peek_all(REPLY)) == 1  # STILL exactly one
    assert plane.counters["published"] == 1
    assert mid in plane.dedup_ledger()


def test_poison_message_parks_in_dlq_with_annotated_history():
    plane, broker, clock = make_plane(redelivery_max=1)
    mid = broker.publish(REQUEST, "this is not json")
    plane.step()                         # attempt 1 → nack (backoff 1s)
    assert plane.counters["nacked"] == 1
    clock.advance(1.0)
    plane.step()                         # attempt 2 = budget → DLQ
    assert broker.size(REQUEST) == 0
    dlq = broker.peek_all(DLQ)
    assert len(dlq) == 1
    parked = json.loads(dlq[0].value)
    assert parked["id"] == mid and parked["attempts"] == 2
    assert "ValueError" in parked["error"] or "JSON" in parked["error"]
    assert parked["value"] == "this is not json"
    events = [h["event"] for h in parked["history"]]
    assert "nacked" in events            # the redelivery record rode along
    assert plane.counters["dead_lettered"] == 1


def test_tenant_quota_parks_overflow_in_dlq_with_quota_annotation():
    """TPU_ASYNC_TENANT_QUEUE_MAX: one tenant's flood stops at its
    quota — the overflow parks immediately in the DLQ with a quota
    annotation (redelivering it would re-collide with the same full
    backlog), and OTHER tenants' messages still admit."""
    stuck = FakeEngine(auto=False)       # leases stay in flight
    plane, broker, _ = make_plane(stuck, tenant_queue_max=2)
    mids = [
        broker.publish(REQUEST, req_json(), {"tenant": "acme"})
        for _ in range(3)
    ]
    broker.publish(REQUEST, req_json(), {"tenant": "zen"})
    plane.step()
    assert plane.inflight_count() == 3   # 2× acme + 1× zen admitted
    assert plane.counters["quota_rejected"] == 1
    dlq = broker.peek_all(DLQ)
    assert len(dlq) == 1
    parked = json.loads(dlq[0].value)
    assert parked["id"] == mids[2]
    assert parked["quota"] == {"tenant": "acme", "max": 2}
    assert "quota" in parked["error"]
    assert plane.report()["tenant_backlog"] == {
        "max": 2, "tenants": {"acme": 2, "zen": 1},
    }


def test_tenant_quota_slot_frees_after_terminal_ack():
    """The backlog entry leaves at the terminal ack: once a message's
    reply is published and acked, the tenant's next message admits."""
    plane, broker, _ = make_plane(tenant_queue_max=1)
    broker.publish(REQUEST, req_json(), {"tenant": "acme"})
    plane.step()                         # admits: the slot is taken
    plane.step()                         # completes: publish + ack frees it
    assert len(broker.peek_all(REPLY)) == 1
    broker.publish(REQUEST, req_json(), {"tenant": "acme"})
    plane.step()                         # the freed slot admits again
    plane.step()
    assert len(broker.peek_all(REPLY)) == 2
    assert plane.counters["quota_rejected"] == 0
    assert broker.peek_all(DLQ) == []
    assert plane.report()["tenant_backlog"]["tenants"] == {}


def test_tenant_quota_redelivery_is_not_double_counted():
    """A redelivery is the same logical message: it must re-enter its
    own backlog slot, not consume a second one or self-collide."""
    plane, broker, clock = make_plane(
        tenant_queue_max=1, redelivery_max=1,
    )
    broker.publish(REQUEST, "poison", {"tenant": "acme"})
    plane.step()                         # attempt 1 → nack (slot kept)
    assert plane.counters["nacked"] == 1
    clock.advance(1.0)
    plane.step()                         # attempt 2: same slot, budget DLQ
    assert plane.counters["quota_rejected"] == 0
    assert plane.counters["dead_lettered"] == 1
    assert "quota" not in json.loads(broker.peek_all(DLQ)[0].value)
    # The terminal ack cleared the slot.
    assert plane.report()["tenant_backlog"]["tenants"] == {}


def test_redelivery_backoff_is_exponential_and_gates_readiness():
    plane, broker, clock = make_plane(redelivery_max=5)
    broker.publish(REQUEST, "poison")
    plane.step()                         # attempt 1 → delay 1.0
    assert plane.step() == 0             # not ready yet
    clock.advance(1.0)
    plane.step()                         # attempt 2 → delay 2.0
    clock.advance(1.0)
    assert plane.step() == 0             # exponential: 2s, not 1s
    clock.advance(1.0)
    assert plane.step() == 1
    assert plane.counters["deliver_errors"] == 3


def test_broker_fault_points_flap_and_recover():
    """deliver and publish each raise once (flap); the message rides
    the redelivery path and still lands exactly once."""
    plane, broker, clock = make_plane()
    broker.publish(REQUEST, req_json())
    with faults.armed("pubsub.deliver", raises=OSError("read"), times=1):
        plane.step()
    assert plane.counters["deliver_errors"] == 1
    clock.advance(1.0)
    with faults.armed("pubsub.publish", raises=OSError("write"), times=1):
        plane.step()                     # redelivered, submitted
        plane.step()                     # reply publish dies → nack
    assert plane.counters["publish_errors"] == 1
    assert len(broker.peek_all(REPLY)) == 0
    # The reply was NOT ledgered — the retry must republish for real.
    assert plane.dedup_ledger() == {}
    clock.advance(2.0)
    plane.step()
    plane.step()
    assert len(broker.peek_all(REPLY)) == 1
    assert broker.size(REQUEST) == 0     # zero lost, zero duplicated
    assert plane.counters["redelivered"] == 2


def test_acceptance_chaos_every_message_answered_or_parked():
    """THE acceptance path: pubsub.* faults armed AND a consumer killed
    mid-batch — every message is either answered exactly once or parked
    in the DLQ with its history. Zero lost, zero duplicated."""
    clock = FakeClock(1000.0)
    broker = InMemoryBroker(clock=clock)
    stuck = FakeEngine(auto=False)
    plane1, _, _ = make_plane(stuck, clock=clock, broker=broker)
    ids = [broker.publish(REQUEST, req_json(f"p{i}")) for i in range(4)]
    poison = broker.publish(REQUEST, "poison pill")
    plane1.step()                        # everything leased / nacked once
    plane1.kill()                        # crash with 4 inference in flight
    clock.advance(30.1)
    plane2, _, _ = make_plane(clock=clock, broker=broker)
    faults.arm("pubsub.deliver", raises=OSError("flaky read"), times=1)
    faults.arm("pubsub.ack", raises=OSError("flaky ack"), times=1)
    for _ in range(40):                  # drive to quiescence
        if plane2.step() == 0:
            clock.advance(31.0)          # backoffs AND lost-ack leases
    assert broker.size(REQUEST) == 0     # nothing in limbo
    replies = {
        json.loads(m.value)["id"] for m in broker.peek_all(REPLY)
    }
    assert replies == set(ids)           # answered exactly once each...
    assert len(broker.peek_all(REPLY)) == len(ids)
    parked = [json.loads(m.value) for m in broker.peek_all(DLQ)]
    assert [p["id"] for p in parked] == [poison]  # ...or parked
    assert parked[0]["attempts"] >= 3
    assert plane2.counters["dead_lettered"] == 1


def test_graceful_drain_nacks_unstarted_leases_with_budget_refund():
    stuck = FakeEngine(auto=False)
    plane, broker, _ = make_plane(stuck)
    broker.publish(REQUEST, req_json())
    plane.step()
    assert broker.inflight(REQUEST) == 1
    plane.stop(drain_s=0.0)              # in-flight work can't finish
    assert plane.inflight_count() == 0
    assert broker.inflight(REQUEST) == 0
    msgs = broker.peek_all(REQUEST)
    assert len(msgs) == 1                # handed back, not dropped
    assert msgs[0].attempt == 0          # penalize=False refunded it
    assert msgs[0].history[-1]["note"] == "drain"
    # The engine-side work was cancelled so the device isn't wedged.
    assert stuck.calls[0][1]["cancel"].cancelled
    # Draining plane leases nothing new.
    assert plane.step() == 0


def test_submit_rejection_takes_the_redelivery_path():
    shedding = FakeEngine(raises=RuntimeError("queue full"))
    plane, broker, clock = make_plane(shedding, redelivery_max=1)
    broker.publish(REQUEST, req_json())
    plane.step()
    assert plane.counters["nacked"] == 1
    clock.advance(1.0)
    plane.step()                         # budget exhausted → DLQ
    assert len(broker.peek_all(DLQ)) == 1
    assert "queue full" in json.loads(
        broker.peek_all(DLQ)[0].value
    )["error"]


def test_dedup_ledger_is_bounded():
    plane, broker, clock = make_plane(dedup_max=3)
    for i in range(5):
        broker.publish(REQUEST, req_json(f"p{i}"))
        plane.step()
        plane.step()
    assert len(plane.dedup_ledger()) == 3    # oldest two evicted
    assert plane.report()["dedup_ledger"] == {"size": 3, "max": 3}


def test_report_shape_for_debug_surface():
    plane, broker, _ = make_plane()
    broker.publish(REQUEST, req_json())
    report = plane.report()
    assert report["enabled"] is True
    assert report["request_topic"] == REQUEST
    assert report["lag"] == 1 and report["inflight_leases"] == 0
    for key in ("redelivery_max", "lease_s", "max_inflight", "counters",
                "running", "draining", "inflight", "dedup_ledger"):
        assert key in report


# ----------------------------------------------------------------------
# config seam
# ----------------------------------------------------------------------


def test_async_off_builds_nothing():
    cfg = MockConfig({"TPU_ASYNC": "0"})
    assert new_async_plane_from_config(cfg, FakeEngine()) is None
    assert new_async_plane_from_config(MockConfig({}), FakeEngine()) is None
    # Enabled but no engine: still nothing (metrics-only apps).
    assert new_async_plane_from_config(
        MockConfig({"TPU_ASYNC": "1"}), None
    ) is None


def test_config_knobs_reach_the_plane(tmp_path):
    cfg = MockConfig({
        "TPU_ASYNC": "1",
        "TPU_ASYNC_BROKER": "file",
        "TPU_ASYNC_BROKER_DIR": str(tmp_path),
        "TPU_ASYNC_REQUEST_TOPIC": "in",
        "TPU_ASYNC_REPLY_TOPIC": "out",
        "TPU_ASYNC_DLQ_TOPIC": "dead",
        "TPU_ASYNC_REDELIVERY_MAX": "7",
        "TPU_ASYNC_LEASE_S": "12",
        "TPU_ASYNC_MAX_INFLIGHT": "2",
        "TPU_ASYNC_DEADLINE_S": "9",
    })
    plane = new_async_plane_from_config(cfg, FakeEngine())
    try:
        assert isinstance(plane.broker, DurableBroker)
        assert (plane.request_topic, plane.reply_topic, plane.dlq_topic) \
            == ("in", "out", "dead")
        assert plane.redelivery_max == 7 and plane.lease_s == 12.0
        assert plane.max_inflight == 2 and plane.deadline_s == 9.0
    finally:
        plane.broker.close()


# ----------------------------------------------------------------------
# real engine: trace continuity, deadline reap, brownout, byte-identity
# ----------------------------------------------------------------------


def _make_engine(**kw):
    defaults = dict(
        n_slots=2, max_len=128, kv_block=16,
        tokenizer=ByteTokenizer(), seed=0,
    )
    defaults.update(kw)
    eng = InferenceEngine("llama-tiny", **defaults)
    eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def engine():
    eng = _make_engine()
    eng.generate_sync("warm", max_new_tokens=2, temperature=0.0,
                      stop_on_eos=False, timeout=300)
    yield eng
    eng.stop_sync()


def _pump(plane, done, timeout_s: float = 120.0) -> None:
    """Drive step() until ``done()`` — the deterministic alternative to
    the background thread when a real scheduler is in the loop."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        plane.step()
        if done():
            return
        time.sleep(0.01)
    assert done(), "plane never reached the expected state"


def test_one_trace_id_from_broker_to_reply(engine):
    plane, broker, _ = make_plane(engine, clock=FakeClock())
    trace = "ab" * 16
    broker.publish(
        REQUEST, req_json("trace me", max_new_tokens=3,
                          temperature=0.0, stop_on_eos=False),
        {"traceparent": f"00-{trace}-{'cd' * 8}-01", "tenant": "t1"},
    )
    _pump(plane, lambda: len(broker.peek_all(REPLY)) == 1)
    reply = broker.peek_all(REPLY)[0]
    # The engine's timeline adopted the broker message's trace id and
    # the reply carries it out — one trace broker→engine→reply.
    assert reply.headers["traceparent"].split("-")[1] == trace
    assert reply.headers["tenant"] == "t1"
    body = json.loads(reply.value)
    assert len(body["token_ids"]) == 3


def test_expired_async_message_reaped_within_window_no_leaks(engine):
    """Satellite regression: an async admission carries Deadline +
    CancelToken, so the scheduler reap retires it like any interactive
    request — the lease is nacked back (not leaked) and the paged KV
    pool is whole again."""
    clock = FakeClock(1000.0)
    free0 = len(engine._free_blocks)
    plane, broker, _ = make_plane(engine, clock=clock, lease_s=1e9)
    broker.publish(REQUEST, req_json(
        "deadline", max_new_tokens=100, temperature=0.0,
        stop_on_eos=False, deadline_s=3600,
    ))
    plane.step()
    assert plane.inflight_count() == 1
    clock.advance(7200.0)                # the deadline's stated clock
    _pump(plane, lambda: plane.counters["nacked"] == 1)
    assert plane.inflight_count() == 0
    assert broker.inflight(REQUEST) == 0     # lease handed back, not leaked
    assert broker.size(REQUEST) == 1         # queued for redelivery
    assert "ErrorDeadlineExceeded" in \
        broker.peek_all(REQUEST)[0].history[-1]["note"]
    wait_for(lambda: len(engine._free_blocks) == free0)


def test_brownout_storm_sheds_async_first_interactive_holds():
    eng = _make_engine(
        queue_max_tokens=400, slo_availability=0.999,
        brownout_exit_sustain_s=100_000.0,
    )
    try:
        eng._brownout.force_level(2)
        plane, broker, clock = make_plane(eng)
        # Cost ~ prompt + max_new ≈ 150: over batch's L2 allowance,
        # within interactive's — the async plane IS batch class.
        broker.publish(REQUEST, req_json(
            "B" * 10, max_new_tokens=140, temperature=0.0,
            stop_on_eos=False,
        ))
        plane.step()
        assert plane.counters["nacked"] == 1     # shed → redelivery path
        assert eng._brownout.shed_count("batch") == 1
        assert len(broker.peek_all(REPLY)) == 0
        # Interactive goodput holds through the same storm.
        res = eng.submit_generate(
            "I" * 10, max_new_tokens=140, temperature=0.0,
            stop_on_eos=False, slo_class="interactive",
        ).future.result(timeout=300)
        assert res.token_ids                 # admitted and served
        assert eng._brownout.shed_count("interactive") == 0
        # The shed message is still owed a redelivery, not lost.
        clock.advance(1.0)
        assert broker.depth(REQUEST) == 1
    finally:
        eng.close()


def test_sync_path_byte_identical_with_plane_attached(engine):
    def greedy():
        return engine.generate_sync(
            "byte identical", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False, timeout=300,
        ).token_ids

    reference = greedy()
    cfg = MockConfig({"TPU_ASYNC": "1", "TPU_ASYNC_POLL_S": "0.01"})
    plane = new_async_plane_from_config(cfg, engine)
    assert plane is not None
    plane.start()
    try:
        assert greedy() == reference     # idle plane: zero interference
    finally:
        plane.stop(drain_s=1.0)
        plane.broker.close()
    assert greedy() == reference         # and clean after detach


# ----------------------------------------------------------------------
# control plane: sustained consumer lag → scale pressure
# ----------------------------------------------------------------------


def test_async_lag_loop_sustained_hysteresis():
    clock = FakeClock(1000.0)
    cp = ControlPlane(
        "m", async_lag_depth=10.0, async_lag_sustain_s=5.0, clock=clock,
    )
    lag = [20.0]
    cp.register("async_lag", lambda: lag[0])
    cp.evaluate(now=clock.t)             # over: anchor only
    assert cp.scale_pressure() == 0
    cp.evaluate(now=clock.advance(4.9))  # inside the sustain
    assert cp.scale_pressure() == 0
    cp.evaluate(now=clock.advance(0.2))  # sustained → pressure
    assert cp.scale_pressure() == 1
    snap = cp.snapshot()["loops"]["async_lag"]
    assert snap["mode"] == "active" and snap["pressure"] is True
    assert snap["last_lag"] == 20.0
    # The dead band (between exit 5.0 and enter 10.0) holds pressure.
    lag[0] = 7.0
    cp.evaluate(now=clock.advance(100.0))
    assert cp.scale_pressure() == 1
    # Below the exit threshold, sustained → clears.
    lag[0] = 2.0
    cp.evaluate(now=clock.advance(1.0))
    cp.evaluate(now=clock.advance(5.1))
    assert cp.scale_pressure() == 0


def test_engine_attach_async_lag_feeds_scale_pressure():
    eng = _make_engine(control_plane=True)
    try:
        # sustain_s must be a small POSITIVE value: the attach seam
        # treats 0 as "keep the default" (30s — a real half-minute on
        # the engine's wall clock).
        assert eng.attach_async_lag(
            lambda: 100.0, depth=10.0, sustain_s=0.05
        ) is True
        assert eng._control.async_loop.depth == 10.0
        wait_for(lambda: eng._control.scale_pressure() == 1)
    finally:
        eng.close()
    # Control-off engines skip the signal (None-guarded).
    eng2 = _make_engine(control_plane=False)
    try:
        assert eng2.attach_async_lag(lambda: 0.0) is False
    finally:
        eng2.close()
