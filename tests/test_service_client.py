"""Inter-service HTTP client tests (reference ``service/*_test.go`` patterns:
httptest servers, circuit breaker state transitions)."""

from __future__ import annotations

import asyncio
import base64
import threading

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig
from gofr_tpu.service import (
    APIKeyConfig,
    BasicAuthConfig,
    CircuitBreakerConfig,
    CircuitOpenError,
    DefaultHeaders,
    HealthConfig,
    RetryConfig,
    new_http_service,
)


class ServerHarness:
    """Boots a gofr_tpu App to play the httptest.Server role."""

    def __init__(self, app: App) -> None:
        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)

    def __enter__(self):
        self._thread.start()
        asyncio.run_coroutine_threadsafe(self.app.start(), self._loop).result(10)
        return self

    def __exit__(self, *exc):
        asyncio.run_coroutine_threadsafe(self.app.stop(), self._loop).result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.app.http_port}"


@pytest.fixture
def upstream():
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    state = {"fail": False, "hits": 0}

    @app.get("/data")
    def data(ctx):
        state["hits"] += 1
        if state["fail"]:
            raise RuntimeError("boom")
        return {"value": 42}

    @app.get("/echo-headers")
    def echo(ctx):
        return {
            "api_key": ctx.header("X-API-KEY"),
            "auth": ctx.header("Authorization"),
            "custom": ctx.header("X-Custom"),
            "traceparent": ctx.header("traceparent"),
        }

    with ServerHarness(app) as harness:
        harness.state = state
        yield harness


def test_basic_get_and_traceparent(upstream):
    svc = new_http_service(upstream.address)
    resp = svc.get("/data")
    assert resp.status_code == 200
    assert resp.json()["data"]["value"] == 42

    resp = svc.get("/echo-headers")
    tp = resp.json()["data"]["traceparent"]
    assert tp and len(tp.split("-")) == 4  # W3C traceparent injected
    svc.close()


def test_health_check_and_override(upstream):
    svc = new_http_service(upstream.address)
    assert svc.health_check()["status"] == "UP"

    svc2 = new_http_service(upstream.address, None, None, HealthConfig("/data"))
    assert svc2.health_check()["status"] == "UP"

    svc3 = new_http_service("http://127.0.0.1:1")
    assert svc3.health_check()["status"] == "DOWN"

    # Order-independence: HealthConfig must land on the base client even
    # when another option has already wrapped it.
    svc4 = new_http_service(
        upstream.address, None, None, APIKeyConfig("k"), HealthConfig("/data")
    )
    assert svc4.health_check()["status"] == "UP"
    from gofr_tpu.service.wrapper import innermost

    assert innermost(svc4).health_endpoint == "data"


def test_auth_options_inject_headers(upstream):
    svc = new_http_service(
        upstream.address, None, None,
        APIKeyConfig("sekrit"), DefaultHeaders({"X-Custom": "v1"}),
    )
    got = svc.get("/echo-headers").json()["data"]
    assert got["api_key"] == "sekrit"
    assert got["custom"] == "v1"

    svc2 = new_http_service(
        upstream.address, None, None, BasicAuthConfig("user", "pass")
    )
    got = svc2.get("/echo-headers").json()["data"]
    assert got["auth"] == "Basic " + base64.b64encode(b"user:pass").decode()


def test_circuit_breaker_opens_and_recovers(upstream):
    # Health probe aimed at the failing endpoint so an app-level failure
    # keeps the circuit open (with the default liveness probe, an
    # alive-but-erroring upstream closes it again — reference behavior).
    svc = new_http_service(
        upstream.address, None, None,
        HealthConfig("/data"),
        CircuitBreakerConfig(threshold=2, interval_s=60),
    )
    upstream.state["fail"] = True
    # Opens after exactly `threshold` consecutive failures.
    for _ in range(2):
        assert svc.get("/data").status_code == 500
    with pytest.raises(CircuitOpenError):
        svc.get("/data")

    # Request-path recovery probe: upstream healthy again → circuit closes.
    upstream.state["fail"] = False
    resp = svc.get("/data")
    assert resp.status_code == 200
    assert svc.get("/data").status_code == 200  # stays closed


def test_retry_config(upstream):
    calls_before = upstream.state["hits"]
    upstream.state["fail"] = True
    svc = new_http_service(
        upstream.address, None, None, RetryConfig(max_retries=2, backoff_s=0.01)
    )
    resp = svc.get("/data")
    assert resp.status_code == 500
    assert upstream.state["hits"] - calls_before == 3  # initial + 2 retries
    upstream.state["fail"] = False


def test_registered_service_in_container_health(upstream):
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    app.add_http_service("upstream", upstream.address)
    health = app.container.health()
    assert health["details"]["service:upstream"]["status"] == "UP"
    svc = app.container.get_http_service("upstream")
    assert svc.get("/data").json()["data"]["value"] == 42


def test_oauth2_client_credentials_token_flow(upstream):
    """OAuthConfig performs the client-credentials grant against a real
    token endpoint, injects Bearer tokens, caches until near expiry,
    and re-fetches once expired (reference service auth decorators)."""
    import time as _time

    from gofr_tpu.service import OAuthConfig

    tokens = {"issued": 0}
    app = upstream.app

    @app.post("/token")
    def token(ctx):
        body = ctx.request.form() if hasattr(ctx.request, "form") else {}
        tokens["issued"] += 1
        tokens["last_grant"] = dict(body or {})
        from gofr_tpu.http.response import Raw

        return Raw({
            "access_token": f"tok-{tokens['issued']}",
            "expires_in": 31,  # cache refreshes 30s before expiry → ~1s
        })

    svc = new_http_service(
        upstream.address, None, None,
        OAuthConfig(
            token_url=f"{upstream.address}/token",
            client_id="cid", client_secret="sec", scopes=("a", "b"),
        ),
    )
    got = svc.get("/echo-headers").json()["data"]
    assert got["auth"] == "Bearer tok-1"
    got = svc.get("/echo-headers").json()["data"]
    assert got["auth"] == "Bearer tok-1"  # cached, not re-fetched
    assert tokens["issued"] == 1
    _time.sleep(1.2)  # past expiry-30s → refresh
    got = svc.get("/echo-headers").json()["data"]
    assert got["auth"] == "Bearer tok-2"
    assert tokens["issued"] == 2


def test_retry_on_connection_error():
    """The retry loop's CONNECTION-error branch: a dead upstream raises
    after max_retries+1 attempts instead of hanging or succeeding."""
    svc = new_http_service(
        "http://127.0.0.1:1", None, None,
        RetryConfig(max_retries=2, backoff_s=0.01),
    )
    with pytest.raises(Exception):
        svc.get("/data")
