"""Inter-service HTTP client tests (reference ``service/*_test.go`` patterns:
httptest servers, circuit breaker state transitions)."""

from __future__ import annotations

import asyncio
import base64
import threading

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig
from gofr_tpu.service import (
    APIKeyConfig,
    BasicAuthConfig,
    CircuitBreakerConfig,
    CircuitOpenError,
    DefaultHeaders,
    HealthConfig,
    RetryConfig,
    new_http_service,
)


class ServerHarness:
    """Boots a gofr_tpu App to play the httptest.Server role."""

    def __init__(self, app: App) -> None:
        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)

    def __enter__(self):
        self._thread.start()
        asyncio.run_coroutine_threadsafe(self.app.start(), self._loop).result(10)
        return self

    def __exit__(self, *exc):
        asyncio.run_coroutine_threadsafe(self.app.stop(), self._loop).result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.app.http_port}"


@pytest.fixture
def upstream():
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    state = {"fail": False, "hits": 0}

    @app.get("/data")
    def data(ctx):
        state["hits"] += 1
        if state["fail"]:
            raise RuntimeError("boom")
        return {"value": 42}

    @app.get("/echo-headers")
    def echo(ctx):
        return {
            "api_key": ctx.header("X-API-KEY"),
            "auth": ctx.header("Authorization"),
            "custom": ctx.header("X-Custom"),
            "traceparent": ctx.header("traceparent"),
        }

    with ServerHarness(app) as harness:
        harness.state = state
        yield harness


def test_basic_get_and_traceparent(upstream):
    svc = new_http_service(upstream.address)
    resp = svc.get("/data")
    assert resp.status_code == 200
    assert resp.json()["data"]["value"] == 42

    resp = svc.get("/echo-headers")
    tp = resp.json()["data"]["traceparent"]
    assert tp and len(tp.split("-")) == 4  # W3C traceparent injected
    svc.close()


def test_health_check_and_override(upstream):
    svc = new_http_service(upstream.address)
    assert svc.health_check()["status"] == "UP"

    svc2 = new_http_service(upstream.address, None, None, HealthConfig("/data"))
    assert svc2.health_check()["status"] == "UP"

    svc3 = new_http_service("http://127.0.0.1:1")
    assert svc3.health_check()["status"] == "DOWN"

    # Order-independence: HealthConfig must land on the base client even
    # when another option has already wrapped it.
    svc4 = new_http_service(
        upstream.address, None, None, APIKeyConfig("k"), HealthConfig("/data")
    )
    assert svc4.health_check()["status"] == "UP"
    from gofr_tpu.service.wrapper import innermost

    assert innermost(svc4).health_endpoint == "data"


def test_auth_options_inject_headers(upstream):
    svc = new_http_service(
        upstream.address, None, None,
        APIKeyConfig("sekrit"), DefaultHeaders({"X-Custom": "v1"}),
    )
    got = svc.get("/echo-headers").json()["data"]
    assert got["api_key"] == "sekrit"
    assert got["custom"] == "v1"

    svc2 = new_http_service(
        upstream.address, None, None, BasicAuthConfig("user", "pass")
    )
    got = svc2.get("/echo-headers").json()["data"]
    assert got["auth"] == "Basic " + base64.b64encode(b"user:pass").decode()


def test_circuit_breaker_opens_and_recovers(upstream):
    # Health probe aimed at the failing endpoint so an app-level failure
    # keeps the circuit open (with the default liveness probe, an
    # alive-but-erroring upstream closes it again — reference behavior).
    svc = new_http_service(
        upstream.address, None, None,
        HealthConfig("/data"),
        CircuitBreakerConfig(threshold=2, interval_s=60),
    )
    upstream.state["fail"] = True
    # Opens after exactly `threshold` consecutive failures.
    for _ in range(2):
        assert svc.get("/data").status_code == 500
    with pytest.raises(CircuitOpenError):
        svc.get("/data")

    # Request-path recovery probe: upstream healthy again → circuit closes.
    upstream.state["fail"] = False
    resp = svc.get("/data")
    assert resp.status_code == 200
    assert svc.get("/data").status_code == 200  # stays closed


def test_retry_config(upstream):
    calls_before = upstream.state["hits"]
    upstream.state["fail"] = True
    svc = new_http_service(
        upstream.address, None, None, RetryConfig(max_retries=2, backoff_s=0.01)
    )
    resp = svc.get("/data")
    assert resp.status_code == 500
    assert upstream.state["hits"] - calls_before == 3  # initial + 2 retries
    upstream.state["fail"] = False


def test_registered_service_in_container_health(upstream):
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    app.add_http_service("upstream", upstream.address)
    health = app.container.health()
    assert health["details"]["service:upstream"]["status"] == "UP"
    svc = app.container.get_http_service("upstream")
    assert svc.get("/data").json()["data"]["value"] == 42


def test_oauth2_client_credentials_token_flow(upstream):
    """OAuthConfig performs the client-credentials grant against a real
    token endpoint, injects Bearer tokens, caches until near expiry,
    and re-fetches once expired (reference service auth decorators)."""
    import time as _time

    from gofr_tpu.service import OAuthConfig

    tokens = {"issued": 0}
    app = upstream.app

    @app.post("/token")
    def token(ctx):
        body = ctx.request.form() if hasattr(ctx.request, "form") else {}
        tokens["issued"] += 1
        tokens["last_grant"] = dict(body or {})
        from gofr_tpu.http.response import Raw

        return Raw({
            "access_token": f"tok-{tokens['issued']}",
            "expires_in": 31,  # cache refreshes 30s before expiry → ~1s
        })

    svc = new_http_service(
        upstream.address, None, None,
        OAuthConfig(
            token_url=f"{upstream.address}/token",
            client_id="cid", client_secret="sec", scopes=("a", "b"),
        ),
    )
    got = svc.get("/echo-headers").json()["data"]
    assert got["auth"] == "Bearer tok-1"
    got = svc.get("/echo-headers").json()["data"]
    assert got["auth"] == "Bearer tok-1"  # cached, not re-fetched
    assert tokens["issued"] == 1
    _time.sleep(1.2)  # past expiry-30s → refresh
    got = svc.get("/echo-headers").json()["data"]
    assert got["auth"] == "Bearer tok-2"
    assert tokens["issued"] == 2


def test_retry_on_connection_error():
    """The retry loop's CONNECTION-error branch: a dead upstream raises
    after max_retries+1 attempts instead of hanging or succeeding."""
    svc = new_http_service(
        "http://127.0.0.1:1", None, None,
        RetryConfig(max_retries=2, backoff_s=0.01),
    )
    with pytest.raises(Exception):
        svc.get("/data")


def test_retry_backoff_jitter_bounds():
    """Jittered exponential backoff: every delay stays within
    base·2^attempt scaled by [1 - jitter, 1 + jitter], capped at
    max_backoff_s — and a pinned rng makes the draw deterministic."""
    import random as _random

    rng = _random.Random(7)
    cfg = RetryConfig(
        max_retries=3, backoff_s=0.1, jitter=0.5, max_backoff_s=0.3,
        rng=rng.random,
    )
    for attempt in range(6):
        base = min(0.1 * (2 ** attempt), 0.3)
        for _ in range(50):
            delay = cfg.delay_s(attempt)
            assert base * 0.5 <= delay <= base * 1.5, (attempt, delay)
    # Jitter actually varies the delay (fixed delays synchronize herds).
    draws = {round(cfg.delay_s(0), 6) for _ in range(20)}
    assert len(draws) > 1
    # jitter=0 degrades to the fixed exponential schedule.
    fixed = RetryConfig(backoff_s=0.1, jitter=0.0, rng=rng.random)
    assert fixed.delay_s(0) == pytest.approx(0.1)
    assert fixed.delay_s(2) == pytest.approx(0.4)
    # Out-of-range jitter configs clamp instead of going negative.
    weird = RetryConfig(backoff_s=0.1, jitter=5.0, rng=lambda: 0.0)
    assert weird.delay_s(0) == pytest.approx(0.0)  # clamped to jitter=1


def test_circuit_breaker_close_stops_probe_ticker(upstream):
    """The probe ticker must die with the client — it used to keep
    probing a dead address forever — and breaker state lands on the
    app_http_service_circuit_open gauge."""
    import threading as _threading

    from gofr_tpu.metrics import new_metrics_manager

    metrics = new_metrics_manager()
    metrics.new_gauge("app_http_service_circuit_open")
    svc = new_http_service(
        upstream.address, None, metrics,
        HealthConfig("/data"),
        CircuitBreakerConfig(threshold=1, interval_s=0.05),
    )
    upstream.state["fail"] = True
    try:
        assert svc.get("/data").status_code == 500  # opens the breaker
        gauge = {
            i.name: i for i in metrics.instruments()
        }["app_http_service_circuit_open"].collect()
        assert list(gauge.values()) == [1.0]
        ticker = svc._ticker
        assert ticker is not None and ticker.is_alive()
        svc.close()
        assert not any(
            t.name == "circuit-breaker-probe" and t.is_alive()
            for t in _threading.enumerate()
        )
        assert svc._ticker is None
    finally:
        upstream.state["fail"] = False


def test_circuit_breaker_half_opens_on_probe_success(upstream):
    """Regression (replica-pool composition): a breaker stuck open on a
    replica that has RETURNED to serving must half-open on the next
    successful synthetic probe (``note_probe_success``) instead of
    waiting out the full probe interval — with no traffic and a long
    ticker, the old behavior kept a healthy replica dark for minutes."""
    svc = new_http_service(
        upstream.address, None, None,
        HealthConfig("/data"),
        # interval_s huge: the background ticker can never be the thing
        # that closes the circuit inside this test.
        CircuitBreakerConfig(threshold=1, interval_s=3600),
    )
    upstream.state["fail"] = True
    assert svc.get("/data").status_code == 500  # opens the breaker
    assert svc.is_open
    with pytest.raises(CircuitOpenError):
        svc.get("/data")
    # The upstream recovers, but NO requests arrive to trigger the
    # request-path probe: without the hook the circuit stays open until
    # the 1-hour ticker fires.
    upstream.state["fail"] = False
    assert svc.is_open
    svc.note_probe_success()  # the pool's synthetic probe passed
    assert not svc.is_open
    assert svc.get("/data").status_code == 200
    svc.close()


def test_replica_probe_half_opens_breaker_through_option_chain(upstream):
    """The pool reaches the breaker through however many option
    wrappers compose the service: HTTPReplica.note_probe_success walks
    the ``_inner`` chain."""
    from gofr_tpu.service.replica_pool import HTTPReplica

    svc = new_http_service(
        upstream.address, None, None,
        HealthConfig("/data"),
        CircuitBreakerConfig(threshold=1, interval_s=3600),
        DefaultHeaders({"X-Custom": "wrapped"}),  # breaker is now inner
    )
    upstream.state["fail"] = True
    assert svc.get("/data").status_code == 500
    breaker = svc._inner  # the DefaultHeaders wrapper wraps the breaker
    assert breaker.is_open
    upstream.state["fail"] = False
    replica = HTTPReplica("r0", svc)
    verdict, _ = replica.probe(timeout_s=5.0)
    assert verdict == "pass"
    replica.note_probe_success()
    assert not breaker.is_open
    svc.close()


def test_circuit_breaker_recovery_clears_state_gauge(upstream):
    from gofr_tpu.metrics import new_metrics_manager

    metrics = new_metrics_manager()
    metrics.new_gauge("app_http_service_circuit_open")
    svc = new_http_service(
        upstream.address, None, metrics,
        HealthConfig("/data"),
        CircuitBreakerConfig(threshold=1, interval_s=60),
    )
    upstream.state["fail"] = True
    assert svc.get("/data").status_code == 500
    upstream.state["fail"] = False
    assert svc.get("/data").status_code == 200  # request-path recovery
    gauge = {
        i.name: i for i in metrics.instruments()
    }["app_http_service_circuit_open"].collect()
    assert list(gauge.values()) == [0.0]
    svc.close()
