"""Real-weights ingestion: HF Llama safetensors → our param pytree.

The oracle is the `transformers` LlamaForCausalLM itself (torch CPU): a
tiny random HF model is saved with safe_serialization and loaded by
``serving/hf_loader``; logits must match — which validates the name map,
the [out,in]→[in,out] transposes, the RoPE convention, and RMSNorm eps in
one shot (VERDICT r1 #5)."""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from gofr_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    transformer_forward,
)
from gofr_tpu.serving.hf_loader import (  # noqa: E402
    config_from_hf,
    is_hf_checkpoint,
    load_hf_llama,
    params_have_q8,
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf-llama")
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def _our_cfg(dtype=jnp.float32) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_len=128, rope_theta=10000.0, norm_eps=1e-6,
        dtype=dtype,
    )


def test_is_hf_checkpoint_and_config(hf_checkpoint):
    path, _ = hf_checkpoint
    assert is_hf_checkpoint(path)
    cfg = config_from_hf(path)
    assert cfg.d_model == 64
    assert cfg.n_kv_heads == 2
    assert not is_hf_checkpoint("/nonexistent")


def test_hf_llama_logit_parity(hf_checkpoint):
    path, model = hf_checkpoint
    cfg = _our_cfg()
    params = load_hf_llama(path, cfg)
    tokens = np.array([[1, 5, 9, 2, 7, 3, 11, 90]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_hf_llama_int8_load_coherent(hf_checkpoint):
    """int8-on-load: quantized params produce near-identical greedy
    next-token picks."""
    path, _ = hf_checkpoint
    cfg = _our_cfg()
    ref = load_hf_llama(path, cfg)
    q = load_hf_llama(path, cfg, quant="int8")
    assert params_have_q8(q)
    assert not params_have_q8(ref)
    tokens = np.array([[1, 5, 9, 2, 7, 3]], dtype=np.int32)
    lr = np.asarray(transformer_forward(ref, jnp.asarray(tokens), cfg))
    lq = np.asarray(transformer_forward(q, jnp.asarray(tokens), cfg))
    # Weight-only int8 keeps top-1 agreement on most positions.
    agree = (lr.argmax(-1) == lq.argmax(-1)).mean()
    assert agree >= 0.8


def test_hf_checkpoint_serves_through_engine(hf_checkpoint):
    """TPU_CHECKPOINT boot seam end to end: the engine boots from the HF
    dir and generates deterministically with real weights."""
    from gofr_tpu.config import MockConfig
    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.serving.engine import InferenceEngine

    path, _ = hf_checkpoint
    cfg = _our_cfg(dtype=jnp.float32)
    register_model(ModelSpec(
        name="hf-tiny-test", family="llm", config=cfg,
        init=lambda key, c: (_ for _ in ()).throw(
            AssertionError("engine must not random-init when params given")
        ),
    ))
    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "hf-tiny-test",
        "TPU_CHECKPOINT": path,
        "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "64",
    }))
    eng.start_sync()
    try:
        r1 = eng.generate_sync(
            [1, 5, 9], max_new_tokens=6, temperature=0.0, stop_on_eos=False
        )
        r2 = eng.generate_sync(
            [1, 5, 9], max_new_tokens=6, temperature=0.0, stop_on_eos=False
        )
        assert r1.token_ids == r2.token_ids
        assert len(r1.token_ids) == 6
    finally:
        eng.stop_sync()


def test_hf_llama_int4_load(hf_checkpoint):
    """W4A16 group-wise load: Q4 leaves, logits track the bf16 load."""
    from gofr_tpu.serving.hf_loader import params_quant_mode

    path, _ = hf_checkpoint
    cfg = _our_cfg()
    ref = load_hf_llama(path, cfg)
    q = load_hf_llama(path, cfg, quant="int4")
    assert params_quant_mode(q) == "int4"
    assert q["layers"]["wq"].q.dtype.name == "uint8"  # nibble-packed
    tokens = np.array([[1, 5, 9, 2, 7, 3]], dtype=np.int32)
    lr = np.asarray(transformer_forward(ref, jnp.asarray(tokens), cfg))
    lq = np.asarray(transformer_forward(q, jnp.asarray(tokens), cfg))
    corr = np.corrcoef(lr.ravel(), lq.ravel())[0, 1]
    assert corr >= 0.95  # group-wise 4-bit tracks closely


def test_hf_llama_loads_onto_mesh(hf_checkpoint):
    """mesh= places every leaf with its Megatron NamedSharding as it
    lands; logits must match the unsharded load exactly."""
    from gofr_tpu.parallel import make_mesh

    path, _ = hf_checkpoint
    cfg = _our_cfg()
    mesh = make_mesh({"tp": 2})
    ref = load_hf_llama(path, cfg)
    sharded = load_hf_llama(path, cfg, mesh=mesh)
    assert "tp" in str(sharded["layers"]["wq"].sharding.spec)
    assert "tp" in str(sharded["lm_head"].sharding.spec)
    tokens = np.array([[1, 5, 9, 2, 7, 3]], dtype=np.int32)
    lr = np.asarray(transformer_forward(ref, jnp.asarray(tokens), cfg))
    ls = np.asarray(transformer_forward(sharded, jnp.asarray(tokens), cfg))
    np.testing.assert_allclose(lr, ls, atol=1e-4, rtol=1e-4)


def test_hf_llama_int8_onto_mesh(hf_checkpoint):
    """The north-star trio minus the chip: real weights + int8 + tp mesh.
    Q8 scale vectors shard with the output-channel axis."""
    from gofr_tpu.parallel import make_mesh

    path, _ = hf_checkpoint
    cfg = _our_cfg()
    mesh = make_mesh({"tp": 2})
    ref = load_hf_llama(path, cfg, quant="int8")
    q = load_hf_llama(path, cfg, quant="int8", mesh=mesh)
    assert params_have_q8(q)
    assert "tp" in str(q["layers"]["wq"].q.sharding.spec)
    assert "tp" in str(q["layers"]["wq"].s.sharding.spec)
    tokens = np.array([[1, 5, 9, 2, 7, 3]], dtype=np.int32)
    lr = np.asarray(transformer_forward(ref, jnp.asarray(tokens), cfg))
    lq = np.asarray(transformer_forward(q, jnp.asarray(tokens), cfg))
    np.testing.assert_allclose(lr, lq, atol=1e-4, rtol=1e-4)


def test_config_mismatch_rejected(hf_checkpoint):
    path, _ = hf_checkpoint
    bad = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_len=128,
    )
    with pytest.raises(ValueError, match="d_model"):
        load_hf_llama(path, bad)


def test_tied_embeddings(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_len=64, rope_theta=10000.0, norm_eps=1e-6,
        dtype=jnp.float32,
    )
    params = load_hf_llama(str(tmp_path), cfg)
    tokens = np.array([[1, 5, 9, 2]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


@pytest.fixture(scope="module")
def hf_mixtral_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf-mixtral")
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = transformers.MixtralForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_mixtral_logit_parity(hf_mixtral_checkpoint):
    """MoE checkpoint: router + stacked expert weights load into our
    dense-einsum top-k formulation and match the HF Mixtral logits."""
    import dataclasses

    path, model = hf_mixtral_checkpoint
    cfg = dataclasses.replace(
        _our_cfg(), n_experts=4, n_experts_active=2
    )
    loaded = config_from_hf(path)
    assert loaded.n_experts == 4 and loaded.n_experts_active == 2
    params = load_hf_llama(path, cfg)
    assert params["layers"]["w_gate"].shape == (2, 4, 64, 128)
    tokens = np.array([[1, 5, 9, 2, 7, 3, 11, 90]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_hf_mixtral_int8_serves(hf_mixtral_checkpoint):
    """int8-quantized Mixtral weights (router kept bf16) generate
    through the engine."""
    import dataclasses

    from gofr_tpu.ops.quant import Q8
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    path, _ = hf_mixtral_checkpoint
    cfg = dataclasses.replace(_our_cfg(), n_experts=4, n_experts_active=2)
    params = load_hf_llama(path, cfg, quant="int8")
    assert isinstance(params["layers"]["w_gate"], Q8)
    assert not isinstance(params["layers"]["router"], Q8)

    from gofr_tpu.models.registry import ModelSpec, register_model

    register_model(ModelSpec(
        name="mixtral-test", family="llm", config=cfg,
        init=lambda key, c: params,
    ))
    eng = InferenceEngine(
        "mixtral-test", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        params=params,
    )
    eng.start_sync()
    try:
        r = eng.generate_sync(
            "hi", max_new_tokens=5, temperature=0.0, stop_on_eos=False
        )
        assert len(r.token_ids) == 5
    finally:
        eng.stop_sync()


@pytest.fixture(scope="module")
def hf_qwen2_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf-qwen2")
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = transformers.Qwen2ForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_qwen2_logit_parity(hf_qwen2_checkpoint):
    """Qwen2 = llama architecture + QKV projection bias; the torch model
    is the oracle for the bias plumbing through every forward path."""
    import dataclasses

    path, model = hf_qwen2_checkpoint
    cfg = config_from_hf(path)
    assert cfg.attn_bias
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = load_hf_llama(path, cfg)
    assert "wq_b" in params["layers"]
    tokens = np.array([[1, 5, 9, 2, 7, 3, 11, 90]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


@pytest.fixture(scope="module")
def hf_gemma_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf-gemma")
    # head_dim=32 deliberately differs from hidden/heads (64/4=16) to
    # exercise the override; Gemma always ties lm_head to the embedding.
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-6, hidden_act="gelu_pytorch_tanh",
        hidden_activation="gelu_pytorch_tanh",
    )
    torch.manual_seed(1)
    model = transformers.GemmaForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_gemma_logit_parity(hf_gemma_checkpoint):
    """Gemma vs torch oracle: validates the head_dim override, GeGLU,
    the (1+w) RMSNorm offset, sqrt(d_model) embedding scaling, and the
    tied lm_head in one shot."""
    import dataclasses

    path, model = hf_gemma_checkpoint
    cfg = config_from_hf(path)
    assert cfg.head_dim == 32 and cfg.act == "gelu"
    assert cfg.norm_offset and cfg.embed_scale
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = load_hf_llama(path, cfg)
    assert params["layers"]["wq"].shape == (2, 64, 4 * 32)
    tokens = np.array([[1, 5, 9, 2, 7, 3, 11, 90]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_hf_gemma_serves_through_engine(hf_gemma_checkpoint):
    """Gemma arch switches hold through prefill/decode/verify: greedy
    generation deterministic and identical between spec and plain
    engines (greedy spec is lossless)."""
    import dataclasses

    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    path, _ = hf_gemma_checkpoint
    cfg = dataclasses.replace(config_from_hf(path), dtype=jnp.float32)
    register_model(ModelSpec(
        name="gemma-test", family="llm", config=cfg,
        init=lambda key, c: load_hf_llama(path, c), eos_token=1,
    ))
    outs = []
    for spec_tokens in (0, 2):
        eng = InferenceEngine(
            "gemma-test", n_slots=2, max_len=96, window_k=4,
            tokenizer=ByteTokenizer(), params=load_hf_llama(path, cfg),
            spec_tokens=spec_tokens,
        )
        eng.start_sync()
        try:
            outs.append(eng.generate_sync(
                "ab", max_new_tokens=10, temperature=0.0, stop_on_eos=False,
                timeout=120,
            ).token_ids)
        finally:
            eng.stop_sync()
    assert outs[0] == outs[1] and len(outs[0]) == 10


@pytest.fixture(scope="module")
def hf_neox_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf-neox")
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=128, rotary_pct=0.25,
        rotary_emb_base=10000.0, layer_norm_eps=1e-5,
        use_parallel_residual=True, hidden_act="gelu",
        tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    model = transformers.GPTNeoXForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_neox_logit_parity(hf_neox_checkpoint):
    """GPT-NeoX vs torch oracle: validates the fused-QKV split, the
    LayerNorm+bias pairs, parallel residual, partial rotary (25% of
    head_dim), the non-gated erf-gelu MLP, and every dense bias."""
    import dataclasses

    path, model = hf_neox_checkpoint
    cfg = config_from_hf(path)
    assert cfg.norm == "ln" and cfg.parallel_residual
    assert cfg.rotary_pct == 0.25 and cfg.ffn == "mlp"
    assert cfg.rope_dims == 4  # head_dim 16 × 0.25
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = load_hf_llama(path, cfg)
    assert params["layers"]["wq"].shape == (2, 64, 64)
    assert "attn_norm_b" in params["layers"]
    assert "final_norm_b" in params
    tokens = np.array([[1, 5, 9, 2, 7, 3, 11, 90]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_hf_neox_serves_through_engine(hf_neox_checkpoint):
    """NeoX arch switches hold through prefill/decode/verify: greedy
    generation deterministic and identical between spec and plain
    engines."""
    import dataclasses

    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    path, _ = hf_neox_checkpoint
    cfg = dataclasses.replace(config_from_hf(path), dtype=jnp.float32)
    register_model(ModelSpec(
        name="neox-test", family="llm", config=cfg,
        init=lambda key, c: load_hf_llama(path, c), eos_token=0,
    ))
    outs = []
    for spec_tokens in (0, 2):
        eng = InferenceEngine(
            "neox-test", n_slots=2, max_len=96, window_k=4,
            tokenizer=ByteTokenizer(), params=load_hf_llama(path, cfg),
            spec_tokens=spec_tokens,
        )
        eng.start_sync()
        try:
            outs.append(eng.generate_sync(
                "ab", max_new_tokens=10, temperature=0.0, stop_on_eos=False,
                timeout=120,
            ).token_ids)
        finally:
            eng.stop_sync()
    assert outs[0] == outs[1] and len(outs[0]) == 10


@pytest.fixture(scope="module")
def hf_gpt2_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf-gpt2")
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=128,
        n_inner=None, layer_norm_epsilon=1e-5,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    torch.manual_seed(3)
    model = transformers.GPT2LMHeadModel(hf_cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_gpt2_logit_parity(hf_gpt2_checkpoint):
    """GPT-2 vs torch oracle: validates the learned position table, the
    Conv1D [in, out] no-transpose layout, the contiguous c_attn q/k/v
    split, LayerNorm pairs, tanh-gelu MLP, and the tied lm_head."""
    import dataclasses

    path, model = hf_gpt2_checkpoint
    cfg = config_from_hf(path)
    assert cfg.pos_emb == "learned" and cfg.norm == "ln"
    assert cfg.d_ff == 256  # n_inner None → 4*n_embd
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = load_hf_llama(path, cfg)
    assert params["pos_embed"].shape == (128, 64)
    tokens = np.array([[1, 5, 9, 2, 7, 3, 11, 90]], dtype=np.int32)
    ours = np.asarray(transformer_forward(params, jnp.asarray(tokens), cfg))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_hf_gpt2_serves_through_engine(hf_gpt2_checkpoint):
    """Learned positions hold through chunked prefill + decode + verify
    (positions come from cache lengths, not rope tables): deterministic,
    spec-lossless generation."""
    import dataclasses

    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    path, _ = hf_gpt2_checkpoint
    cfg = dataclasses.replace(config_from_hf(path), dtype=jnp.float32)
    register_model(ModelSpec(
        name="gpt2-test", family="llm", config=cfg,
        init=lambda key, c: load_hf_llama(path, c),
    ))
    outs = []
    for spec_tokens in (0, 2):
        eng = InferenceEngine(
            "gpt2-test", n_slots=2, max_len=96, window_k=4,
            tokenizer=ByteTokenizer(), params=load_hf_llama(path, cfg),
            spec_tokens=spec_tokens,
        )
        eng.start_sync()
        try:
            outs.append(eng.generate_sync(
                "ab", max_new_tokens=10, temperature=0.0, stop_on_eos=False,
                timeout=120,
            ).token_ids)
        finally:
            eng.stop_sync()
    assert outs[0] == outs[1] and len(outs[0]) == 10


def test_hf_qwen2_serves_through_engine(hf_qwen2_checkpoint):
    """Decode + prefill + (speculative) verify paths all apply the bias:
    engine generation from the qwen2 checkpoint must be deterministic and
    equal between the spec and plain engines (greedy spec is lossless)."""
    import dataclasses

    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    path, _ = hf_qwen2_checkpoint
    cfg = dataclasses.replace(config_from_hf(path), dtype=jnp.float32)
    register_model(ModelSpec(
        name="qwen2-test", family="llm", config=cfg,
        init=lambda key, c: load_hf_llama(path, c),
    ))
    outs = []
    for spec_tokens in (0, 2):
        eng = InferenceEngine(
            "qwen2-test", n_slots=2, max_len=96, window_k=4,
            tokenizer=ByteTokenizer(), params=load_hf_llama(path, cfg),
            spec_tokens=spec_tokens,
        )
        eng.start_sync()
        try:
            outs.append(eng.generate_sync(
                "ab", max_new_tokens=10, temperature=0.0, stop_on_eos=False,
                timeout=120,
            ).token_ids)
        finally:
            eng.stop_sync()
    assert outs[0] == outs[1] and len(outs[0]) == 10


def test_gpt2_learned_pos_guards(hf_gpt2_checkpoint):
    """max_len beyond the learned position table is rejected at load
    (the clip in _embed would silently reuse the last row)."""
    import dataclasses

    path, model = hf_gpt2_checkpoint
    cfg = dataclasses.replace(
        config_from_hf(path), dtype=jnp.float32, max_len=4096
    )
    with pytest.raises(ValueError, match="position table"):
        load_hf_llama(path, cfg)


def test_gpt2_untied_head_wins(hf_gpt2_checkpoint, tmp_path):
    """An untied fine-tune's own lm_head.weight overrides the wte
    transpose (safetensors dedups the tied case, so this copies the
    checkpoint and injects a distinct head)."""
    import dataclasses
    import shutil

    from safetensors.numpy import save_file
    from safetensors import safe_open

    path, _ = hf_gpt2_checkpoint
    dst = tmp_path / "untied"
    shutil.copytree(path, dst)
    st = next(iter(dst.glob("*.safetensors")))
    tensors = {}
    with safe_open(str(st), framework="numpy") as h:
        for name in h.keys():
            tensors[name] = h.get_tensor(name)
    rng = np.random.default_rng(7)
    wte_name = (
        "wte.weight" if "wte.weight" in tensors
        else "transformer.wte.weight"
    )
    head = rng.standard_normal(
        tensors[wte_name].shape
    ).astype(np.float32) * 0.02
    tensors["lm_head.weight"] = head
    save_file(tensors, str(st))

    cfg = dataclasses.replace(config_from_hf(str(dst)), dtype=jnp.float32)
    params = load_hf_llama(str(dst), cfg)
    np.testing.assert_allclose(
        np.asarray(params["lm_head"]), head.T, atol=1e-6
    )
