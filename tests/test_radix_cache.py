"""Automatic block-level prefix caching (ISSUE 5 acceptance gate).

Three layers, all deterministic on CPU:

* host units — the refcounted ``BlockAllocator`` and the
  ``RadixPrefixIndex`` (longest-prefix walk, insert adoption semantics,
  LRU eviction of unreferenced leaves, adapter purge);
* a fuzz-style churn test that interleaves admit/retire/evict/grow/
  restart against a host-side model of the scheduler's exact aliasing
  and COW logic, asserting after every step that each block is either
  free or accounted for by exactly its referencing tables + the index
  — and that a slot never writes a block with refcount > 1;
* engine integration — a warm repeated-prefix request admission-aliases
  cached blocks (``app_tpu_prefix_hit_tokens_total`` mirror > 0),
  dispatches STRICTLY fewer prefill chunk steps than the cold run, and
  emits a byte-identical stream; whole-prompt hits exercise the COW
  boundary; pool pressure evicts cached blocks instead of starving
  requests; LoRA unload purges the adapter's subtree; and a supervisor
  warm restart rebuilds a fresh index while replaying byte-identically.
"""

from __future__ import annotations

import random
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.ops.kv_cache import BlockAllocator
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.radix_cache import RadixPrefixIndex
from gofr_tpu.serving.tokenizer import ByteTokenizer


def _metrics_manager():
    m = new_metrics_manager()
    for name in (
        "app_tpu_prefix_lookup_total", "app_tpu_prefix_hit_tokens_total",
        "app_tpu_tokens_generated", "app_tpu_requests_shed_total",
        "app_tpu_requests_cancelled_total", "app_tpu_deadline_exceeded_total",
    ):
        m.new_counter(name)
    for name in (
        "app_tpu_prefix_cached_blocks", "app_tpu_kv_blocks_free",
        "app_tpu_kv_slots_in_use", "app_tpu_queue_depth",
        "app_tpu_hbm_used_bytes", "app_tpu_engine_state",
        "app_tpu_lora_adapters",
    ):
        m.new_gauge(name)
    for name in ("app_tpu_infer_latency", "app_tpu_batch_size"):
        m.new_histogram(name)
    return m


def _counter_total(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    total = 0.0
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            total += value
    return total


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


# ----------------------------------------------------------------------
# host units: allocator
# ----------------------------------------------------------------------


def test_allocator_refcount_lifecycle():
    alloc = BlockAllocator(5)  # blocks 1..4 usable; 0 parks
    assert alloc.n_free == 4
    a = alloc.alloc()
    assert a is not None and alloc.refcount(a) == 1
    assert alloc.incref(a) == 2
    assert alloc.decref(a) is False  # still referenced
    assert alloc.decref(a) is True  # refcount 0 → freed
    assert alloc.n_free == 4
    # Double-free / touch-free are programming errors, loudly.
    with pytest.raises(ValueError):
        alloc.decref(a)
    with pytest.raises(ValueError):
        alloc.incref(a)
    # Exhaustion returns None (no exception: callers defer or evict).
    got = [alloc.alloc() for _ in range(4)]
    assert None not in got and alloc.alloc() is None


# ----------------------------------------------------------------------
# host units: radix index
# ----------------------------------------------------------------------


def _fill(alloc: BlockAllocator, n: int) -> list[int]:
    out = []
    for _ in range(n):
        bid = alloc.alloc()
        assert bid is not None
        out.append(bid)
    return out


def _release(alloc: BlockAllocator, blocks: list[int]) -> None:
    """Drop the references a ``lookup`` returned holding (tests that
    only probe the index must not leak them into refcount asserts)."""
    for bid in blocks:
        alloc.decref(bid)


def test_radix_longest_prefix_walk_and_adoption():
    alloc = BlockAllocator(17)
    idx = RadixPrefixIndex(4, alloc)
    ids = [1, 2, 3, 4, 5, 6, 7, 8, 9]  # 2 full blocks + tail
    row = _fill(alloc, 2)
    flags = idx.insert(ids, row, aid=0)
    assert flags == [True, True]  # both references adopted
    assert idx.n_cached_blocks == 2

    # Full two-block match; the tail never matches (not a full block).
    blocks, matched = idx.lookup(ids + [9, 9, 9], aid=0)
    assert blocks == row and matched == 8
    # lookup returns holding one reference per block (index + ours).
    assert all(alloc.refcount(b) == 2 for b in blocks)
    _release(alloc, blocks)
    # Diverging second block → only the first matches.
    blocks, matched = idx.lookup([1, 2, 3, 4, 9, 9, 9, 9], aid=0)
    assert blocks == row[:1] and matched == 4
    _release(alloc, blocks)
    # Under three tokens of prefix — no full block — no match.
    assert idx.lookup([1, 2, 3], aid=0) == ([], 0)
    # Different adapter slot: blind to aid 0's entries.
    assert idx.lookup(ids, aid=1) == ([], 0)

    # Re-inserting the same content does NOT adopt (incumbent block
    # wins); the caller keeps — and here releases — its own refs.
    row2 = _fill(alloc, 2)
    flags = idx.insert(ids, row2, aid=0)
    assert flags == [False, False]
    for bid in row2:
        alloc.decref(bid)
    assert idx.n_cached_blocks == 2
    blocks, _ = idx.lookup(ids, aid=0)
    assert blocks == row
    _release(alloc, blocks)


def test_radix_lru_eviction_unreferenced_leaves_only():
    alloc = BlockAllocator(33)
    idx = RadixPrefixIndex(2, alloc)
    # Two chains under one root: [1,2]->[3,4] and [5,6].
    chain_a = _fill(alloc, 2)
    idx.insert([1, 2, 3, 4], chain_a, aid=0)
    chain_b = _fill(alloc, 1)
    idx.insert([5, 6], chain_b, aid=0)
    free0 = alloc.n_free

    # A lookup refreshes [1,2]'s chain; [5,6] becomes LRU.
    _release(alloc, idx.lookup([1, 2, 3, 4], aid=0)[0])
    assert idx.evict(1) == 1
    assert alloc.n_free == free0 + 1
    assert idx.lookup([5, 6], aid=0) == ([], 0)

    # A block aliased by a live table (refcount > 1) never evicts; the
    # leaf [3,4] (refcount 1) goes first, then the now-leaf [1,2] is
    # pinned by the external reference.
    alloc.incref(chain_a[0])
    assert idx.evict(4) == 1  # only [3,4] freed
    assert idx.n_cached_blocks == 1
    blocks, matched = idx.lookup([1, 2, 9, 9], aid=0)
    assert blocks == chain_a[:1] and matched == 2
    _release(alloc, blocks)
    alloc.decref(chain_a[0])
    assert idx.evict(4) == 1  # unpinned → evictable
    assert idx.n_cached_blocks == 0
    assert alloc.n_free == 32


def test_radix_purge_aid_drops_subtree_and_respects_live_refs():
    alloc = BlockAllocator(33)
    idx = RadixPrefixIndex(2, alloc)
    base = _fill(alloc, 2)
    idx.insert([1, 2, 3, 4], base, aid=0)
    lora = _fill(alloc, 2)
    idx.insert([1, 2, 3, 4], lora, aid=3)
    free0 = alloc.n_free

    alloc.incref(lora[0])  # a live slot still aliases one block
    assert idx.purge_aid(3) == 2
    assert idx.lookup([1, 2, 3, 4], aid=3) == ([], 0)
    # The shared block survives until its table releases it.
    assert alloc.n_free == free0 + 1
    alloc.decref(lora[0])
    assert alloc.n_free == free0 + 2
    # aid 0 untouched.
    blocks, matched = idx.lookup([1, 2, 3, 4], aid=0)
    assert matched == 4
    _release(alloc, blocks)
    assert idx.purge_aid(99) == 0


def test_lookup_refs_survive_concurrent_purge():
    alloc = BlockAllocator(9)
    idx = RadixPrefixIndex(2, alloc)
    row = _fill(alloc, 2)
    idx.insert([1, 2, 3, 4], row, aid=1)
    blocks, matched = idx.lookup([1, 2, 3, 4], aid=1)
    assert blocks == row and matched == 4
    # An adapter reload purges between the lookup and the table
    # aliasing: the lookup-held references must keep the blocks
    # allocated (taking refs AFTER lookup increfed a freed block here).
    idx.purge_aid(1)
    assert idx.n_cached_blocks == 0
    for bid in blocks:
        assert alloc.refcount(bid) == 1  # ours — purge could not free
    _release(alloc, blocks)
    assert alloc.n_free == 8  # fully reclaimed once we let go


def test_radix_max_blocks_cap_evicts_on_insert():
    alloc = BlockAllocator(33)
    idx = RadixPrefixIndex(2, alloc, max_blocks=2)
    idx.insert([1, 2, 3, 4], _fill(alloc, 2), aid=0)
    idx.insert([7, 8], _fill(alloc, 1), aid=0)
    assert idx.n_cached_blocks == 2  # LRU leaf [3,4] evicted at cap
    for probe in ([1, 2, 3, 4], [7, 8]):
        blocks, matched = idx.lookup(probe, aid=0)
        assert matched == 2
        _release(alloc, blocks)


# ----------------------------------------------------------------------
# fuzz churn: the refcount invariant under admit/retire/evict/restart
# ----------------------------------------------------------------------


class _SchedModel:
    """Host-side mirror of the scheduler's aliasing/COW/release logic
    (the same order of allocator and index operations), so the churn
    test can interleave every lifecycle transition thousands of times
    without compiling a model."""

    B = 4

    def __init__(self, n_blocks: int) -> None:
        self.alloc = BlockAllocator(n_blocks)
        self.idx = RadixPrefixIndex(self.B, self.alloc)
        self.rows: dict[int, list[int]] = {}
        self.meta: dict[int, list[int]] = {}

    def _alloc_block(self):
        bid = self.alloc.alloc()
        if bid is None and self.idx.evict(1):
            bid = self.alloc.alloc()
        return bid

    def admit(self, slot: int, ids: list[int]) -> bool:
        B = self.B
        # lookup returns with one reference per block already held
        # (taken under the index lock — the anti-purge-race contract);
        # each transfers to the slot row here.
        blocks, matched = self.idx.lookup(ids, 0)
        done = min(matched, len(ids) - 1)
        row: list[int] = list(blocks)
        if row and done < matched:  # COW the boundary block
            src = row[-1]
            dst = self._alloc_block()
            if dst is None:
                row.pop()
                self.alloc.decref(src)
                done = min(len(row) * B, len(ids) - 1)
            else:
                row[-1] = dst
                self.alloc.decref(src)
        target = (len(ids) + 1 + B - 1) // B
        ok = True
        while len(row) < target:
            bid = self._alloc_block()
            if bid is None:
                ok = False
                break
            row.append(bid)
        if not ok:  # defer: every reference dropped
            for bid in row:
                self.alloc.decref(bid)
            return False
        # THE decode/prefill write-safety invariant: every block this
        # slot will write (positions ≥ done) is exclusively owned.
        for j in range(done // B, len(row)):
            assert self.alloc.refcount(row[j]) == 1, (slot, j, row)
        self.rows[slot], self.meta[slot] = row, ids
        return True

    def grow(self, slot: int) -> None:
        bid = self._alloc_block()
        if bid is not None:
            assert self.alloc.refcount(bid) == 1
            self.rows[slot].append(bid)

    def retire(self, slot: int) -> None:
        ids, row = self.meta.pop(slot), self.rows.pop(slot)
        n_full = min(len(ids) // self.B, len(row))
        adopted: set[int] = set()
        if n_full > 0:
            flags = self.idx.insert(ids, row[:n_full], 0)
            adopted = {row[j] for j, f in enumerate(flags) if f}
        for bid in row:
            if bid not in adopted:
                self.alloc.decref(bid)

    def check_invariant(self) -> None:
        refs: dict[int, int] = {}
        for row in self.rows.values():
            for bid in row:
                refs[bid] = refs.get(bid, 0) + 1
        for bid in self.idx.cached_block_ids():
            refs[bid] = refs.get(bid, 0) + 1
        free = self.alloc.free_blocks
        free_set = set(free)
        assert len(free) == len(free_set)  # no double-free
        for bid in range(1, self.alloc.n_blocks):
            expected = refs.get(bid, 0)
            assert self.alloc.refcount(bid) == expected, (
                bid, self.alloc.refcount(bid), expected,
            )
            assert (bid in free_set) == (expected == 0), bid


def test_refcount_invariants_under_fuzzed_churn():
    rng = random.Random(0)
    model = _SchedModel(n_blocks=24)  # tight pool → real pressure
    slots = list(range(4))
    for step in range(2000):
        op = rng.random()
        free_slots = [s for s in slots if s not in model.rows]
        busy_slots = [s for s in slots if s in model.rows]
        if op < 0.45 and free_slots:
            # Small vocab + short prompts → heavy prefix collisions.
            n = rng.randint(1, 14)
            ids = [rng.randint(0, 2) for _ in range(n)]
            model.admit(rng.choice(free_slots), ids)
        elif op < 0.75 and busy_slots:
            model.retire(rng.choice(busy_slots))
        elif op < 0.85 and busy_slots:
            model.grow(rng.choice(busy_slots))
        elif op < 0.95:
            model.idx.evict(rng.randint(1, 3))
        else:
            # Warm restart: cache planes, allocator, and index are
            # rebuilt together; live rows die with the old scheduler.
            model = _SchedModel(n_blocks=24)
        model.check_invariant()
    # Drain: after retiring everything, every block is free or cached.
    for slot in list(model.rows):
        model.retire(slot)
    model.check_invariant()
    assert (
        model.alloc.n_free + model.idx.n_cached_blocks
        == model.alloc.n_blocks - 1
    )


# ----------------------------------------------------------------------
# engine integration (CPU, llama-tiny)
# ----------------------------------------------------------------------

_ENGINE_KW = dict(
    n_slots=4, max_len=256, window_k=4, pipeline_depth=1,
    prefill_chunk=32, kv_block=32, auto_prefix=True,
)


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(scope="module")
def engine(metrics):
    eng = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(), lora_slots=1,
        metrics=metrics, **_ENGINE_KW,
    )
    eng.start_sync()
    yield eng
    eng.stop_sync()


def _wait_idle(eng, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if (
            all(s is None for s in eng._slots)
            and not eng._prefilling
            and eng._pending.empty()
        ):
            return
        time.sleep(0.01)
    raise AssertionError("engine did not go idle")


def _engine_block_invariant(eng):
    """Every pool block is free, or accounted for by exactly its
    referencing slot tables plus the radix index."""
    refs: dict[int, int] = {}
    for row in eng._slot_blocks:
        for bid in row:
            refs[bid] = refs.get(bid, 0) + 1
    for bid in eng._radix.cached_block_ids():
        refs[bid] = refs.get(bid, 0) + 1
    alloc = eng._allocator
    free = set(alloc.free_blocks)
    assert len(free) == len(alloc.free_blocks)
    for bid in range(1, alloc.n_blocks):
        expected = refs.get(bid, 0)
        assert alloc.refcount(bid) == expected, (bid,)
        assert (bid in free) == (expected == 0), (bid,)


def test_warm_request_skips_prefill_chunks_byte_identically(
    engine, metrics
):
    engine._radix.clear()
    _wait_idle(engine)
    preamble = list(range(10, 80))  # 70 tokens = 2 full blocks + tail
    hit0 = engine._prefix_hit_tokens
    mhit0 = _counter_total(metrics, "app_tpu_prefix_hit_tokens_total")
    mmiss0 = _counter_total(
        metrics, "app_tpu_prefix_lookup_total", result="miss"
    )
    mhits0 = _counter_total(
        metrics, "app_tpu_prefix_lookup_total", result="hit"
    )

    s0 = engine._prefill_chunk_steps
    cold = engine.generate_sync(
        preamble + [100, 101, 102], max_new_tokens=6, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    _wait_idle(engine)
    cold_steps = engine._prefill_chunk_steps - s0
    assert engine._prefix_hit_tokens == hit0  # cold: no hit
    assert engine._radix.n_cached_blocks == 2  # retirement indexed B0,B1

    s1 = engine._prefill_chunk_steps
    warm = engine.generate_sync(
        preamble + [120, 121, 122], max_new_tokens=6, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    _wait_idle(engine)
    warm_steps = engine._prefill_chunk_steps - s1
    # The acceptance assertions: aliased tokens counted (host mirror AND
    # the exported counter), STRICTLY fewer chunk steps, and a
    # byte-identical stream vs a cold-cache run.
    assert engine._prefix_hit_tokens - hit0 == 64
    assert (
        _counter_total(metrics, "app_tpu_prefix_hit_tokens_total") - mhit0
        == 64
    )
    assert _counter_total(
        metrics, "app_tpu_prefix_lookup_total", result="miss"
    ) - mmiss0 >= 1
    assert _counter_total(
        metrics, "app_tpu_prefix_lookup_total", result="hit"
    ) - mhits0 >= 1
    assert warm_steps < cold_steps

    engine._radix.clear()
    s2 = engine._prefill_chunk_steps
    reference = engine.generate_sync(
        preamble + [120, 121, 122], max_new_tokens=6, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    _wait_idle(engine)
    assert engine._prefill_chunk_steps - s2 == cold_steps
    assert warm.token_ids == reference.token_ids
    _engine_block_invariant(engine)


def test_whole_prompt_hit_cows_boundary_block(engine):
    engine._radix.clear()
    _wait_idle(engine)
    prompt = list(range(5, 69))  # exactly 64 tokens = 2 full blocks
    first = engine.generate_sync(
        prompt, max_new_tokens=5, temperature=0.0, stop_on_eos=False,
        timeout=120,
    )
    _wait_idle(engine)
    hit0 = engine._prefix_hit_tokens
    second = engine.generate_sync(
        prompt, max_new_tokens=5, temperature=0.0, stop_on_eos=False,
        timeout=120,
    )
    _wait_idle(engine)
    # done = len-1: the finalize position was COW'd out of the shared
    # boundary block, everything before it aliased.
    assert engine._prefix_hit_tokens - hit0 == 63
    assert second.token_ids == first.token_ids
    # The COW'd copy was NOT re-indexed as a duplicate: the incumbent
    # blocks stay, the copy freed at retirement.
    assert engine._radix.n_cached_blocks == 2
    _engine_block_invariant(engine)


def test_sampled_warm_hit_stays_byte_identical(engine):
    engine._radix.clear()
    _wait_idle(engine)
    prompt = list(range(30, 100))  # 70 tokens
    kw = dict(
        max_new_tokens=6, temperature=0.9, seed=1234, stop_on_eos=False,
        timeout=120,
    )
    cold = engine.generate_sync(prompt, **kw)
    _wait_idle(engine)
    warm = engine.generate_sync(prompt, **kw)  # whole-prompt hit + COW
    _wait_idle(engine)
    assert warm.token_ids == cold.token_ids


def test_lora_unload_purges_adapter_entries(engine):
    import jax

    from gofr_tpu.models.transformer import lora_dims

    engine._radix.clear()
    _wait_idle(engine)
    leaves = {}
    for ti, t in enumerate(("wq", "wk", "wv", "wo")):
        d_in, d_out = lora_dims(engine.cfg, t)
        k1, k2 = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(9), ti), 2
        )
        leaves[t] = (
            0.02 * jax.random.normal(k1, (engine.cfg.n_layers, d_in, 16)),
            0.02 * jax.random.normal(k2, (engine.cfg.n_layers, 16, d_out)),
        )
    engine.load_lora("radix-ad", leaves)
    try:
        prompt = list(range(40, 110))  # 70 tokens
        base_hit0 = engine._prefix_hit_tokens
        engine.generate_sync(
            prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False,
            adapter="radix-ad", timeout=120,
        )
        _wait_idle(engine)
        cached_after_lora = engine._radix.n_cached_blocks
        assert cached_after_lora == 2
        # Base requests never reuse adapter-prefilled blocks.
        engine.generate_sync(
            prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False,
            timeout=120,
        )
        _wait_idle(engine)
        assert engine._prefix_hit_tokens == base_hit0
        assert engine._radix.n_cached_blocks == 4  # 2 per adapter slot
        hit1 = engine._prefix_hit_tokens
        # Same-adapter repeat DOES hit.
        engine.generate_sync(
            prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False,
            adapter="radix-ad", timeout=120,
        )
        _wait_idle(engine)
        assert engine._prefix_hit_tokens > hit1
    finally:
        engine.unload_lora("radix-ad")
    # Unload purged the adapter subtree; base entries survive.
    assert engine._radix.n_cached_blocks == 2
    _wait_idle(engine)
    _engine_block_invariant(engine)


def test_pool_pressure_evicts_cached_blocks_not_requests():
    # Pool of 8 usable blocks on 2 slots: cached prefixes must yield to
    # live admissions instead of deadlocking the queue.
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, window_k=4,
        pipeline_depth=1, prefill_chunk=32, kv_block=32,
        kv_pool_blocks=9, auto_prefix=True, tokenizer=ByteTokenizer(),
    )
    eng.start_sync()
    try:
        reqs = [
            eng.submit_generate(
                [200 + i] + list(range(60)), max_new_tokens=3,
                temperature=0.0, stop_on_eos=False,
            )
            for i in range(4)
        ]
        results = [r.future.result(timeout=180) for r in reqs]
        assert all(len(r.token_ids) == 3 for r in results)
        _wait_idle(eng)
        # Everything is free or cached; nothing leaked.
        assert (
            eng._allocator.n_free + eng._radix.n_cached_blocks == 8
        )
        _engine_block_invariant(eng)
    finally:
        eng.stop_sync()


def test_eviction_watermark_sweeps_ahead_of_admission():
    """TPU_PREFIX_EVICT_WM: the scheduler loop trims LRU cached blocks
    whenever the free list drops below the watermark, so admission
    under pressure finds free blocks waiting instead of paying the
    synchronous pre-evict scan inside its own grow."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, window_k=4,
        pipeline_depth=1, prefill_chunk=32, kv_block=32,
        kv_pool_blocks=9, auto_prefix=True, prefix_evict_watermark=5,
        tokenizer=ByteTokenizer(),
    )
    eng.start_sync()
    try:
        # Two distinct 2-full-block prompts: retiring both would cache
        # 4+ blocks and leave < watermark free; the sweep must trim the
        # LRU entries back down without any allocation shortfall.
        for base in (300, 600):
            eng.generate_sync(
                [base] + list(range(60)), max_new_tokens=2,
                temperature=0.0, stop_on_eos=False, timeout=180,
            )
        _wait_idle(eng)
        deadline = time.monotonic() + 10
        while (
            eng._allocator.n_free < 5 and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert eng._allocator.n_free >= 5
        _engine_block_invariant(eng)
    finally:
        eng.stop_sync()


def test_supervisor_restart_resets_index_and_replays_byte_identically():
    from gofr_tpu.serving.supervisor import EngineSupervisor

    eng = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(), **_ENGINE_KW,
    )
    EngineSupervisor(
        eng, max_restarts=3, backoff_s=0.01, rng=random.Random(7),
        sleep=lambda s: None,
    ).start()
    eng.start_sync()
    try:
        prompt = list(range(10, 80))  # 2 full blocks + tail
        # 24 tokens: with the warm index the faulted request prefills in
        # ONE chunk, so the budget must span enough decode windows that
        # the armed fault (hit 5) still lands mid-generation.
        ref = eng.generate_sync(
            prompt, max_new_tokens=24, temperature=0.0,
            stop_on_eos=False, timeout=120,
        )
        _wait_idle(eng)
        assert eng._radix.n_cached_blocks == 2
        radix_before = eng._radix

        # Device dies mid-generation; the supervisor warm-restarts and
        # replays. The radix index is rebuilt WITH the cache planes —
        # the old object must not survive into the new engine state.
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("injected device loss"),
            after=4, times=1,
        )
        req = eng.submit_generate(
            prompt, max_new_tokens=24, temperature=0.0, stop_on_eos=False,
        )
        got = req.future.result(timeout=120)
        assert got.token_ids == ref.token_ids  # replay: no gaps, no dupes
        assert eng._radix is not radix_before  # fresh index post-restart
        _wait_idle(eng)
        # The replayed request re-prefilled through normal admission, so
        # its retirement re-warmed the fresh index.
        assert eng._radix.n_cached_blocks == 2
        hit0 = eng._prefix_hit_tokens
        again = eng.generate_sync(
            prompt, max_new_tokens=24, temperature=0.0,
            stop_on_eos=False, timeout=120,
        )
        assert again.token_ids == ref.token_ids
        assert eng._prefix_hit_tokens > hit0  # cache-warm after replay
        _wait_idle(eng)
        _engine_block_invariant(eng)
    finally:
        eng.close()
