"""Device-to-device / over-the-wire tier-transfer suite (ISSUE 14
acceptance gate).

The PR 8 disaggregated tiers shipped every finished prefill HOST-BOUNCE;
this suite pins the leg-aware ladder that replaces it:

* **device leg** (shared JAX runtime): per-block jitted extraction +
  sharding-aware ``device_put`` + donated jitted ``paged_move_block`` —
  zero host copies, pinned byte-identical to the fused reference for
  greedy AND seeded-sampled streams, at tp=1 and across DISJOINT tp=2
  meshes (the 8-virtual-device conftest), with zero steady-state
  recompiles across repeated transfers after the warm-up fence;
* **wire leg** (remote decode replica): the exported payload rides a
  length-prefixed binary POST to the remote's ops-port import endpoint
  (a REAL gofr_tpu app over a live socket), then the request streams
  there over the ordinary OpenAI SSE — byte-identical, one trace id;
* **the failure matrix, per leg**: mid-POST death, corrupt body, and a
  stale geometry fingerprint all degrade to ``"fused"`` (re-prefill on
  the adopter) with zero 5xx and one trace id; a dead ops port excludes
  the target; a device-leg exception bans the leg and the SAME target
  retries one rung down (device → host) — any leg failure degrades to
  the next rung, terminally fused;
* **leg selection**: the automatic ladder picks device for in-proc
  targets and wire for remotes; ``TPU_TRANSFER_LEG`` pins exactly one;
* **per-SLO-class priority dequeue** (rode along): deterministic
  ordering under stated clocks — interactive jumps queued batch work,
  stable FIFO within a class, max-wait promotion as the starvation
  bound — and the engine wires it from ``TPU_QUEUE_CLASS_PROMOTE_S``.

Everything is deterministic: faults fire on exact hit counts, the
backoff sleeps record instead of sleeping, and the wire chaos rides the
``http.request`` fault point so no real packet is harmed.
"""

from __future__ import annotations

import asyncio
import http.client
import queue as queue_mod
import random
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.config import MockConfig
from gofr_tpu.errors import ErrorServiceUnavailable
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.ops.kv_cache import (
    KVBlockPayload,
    export_blocks,
    payload_from_wire,
    payload_to_wire,
)
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.lifecycle import ClassPriorityQueue
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.replica_pool import (
    EngineReplica,
    HTTPReplica,
    ReplicaPool,
)

TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

COUNTERS = (
    "app_tpu_tier_transfers_total",
    "app_tpu_tier_transfer_bytes_total",
    "app_tpu_failovers_total",
    "app_tpu_requests_replayed_total",
    "app_tpu_tokens_generated",
    "app_tpu_prefix_lookup_total",
    "app_tpu_prefix_hit_tokens_total",
)
GAUGES = (
    "app_tpu_tier_mode",
    "app_tpu_engine_state",
    "app_tpu_replica_state",
    "app_tpu_pool_replicas",
    "app_tpu_queue_depth",
    "app_tpu_kv_slots_in_use",
    "app_tpu_kv_blocks_free",
    "app_tpu_prefix_cached_blocks",
    "app_tpu_hbm_used_bytes",
)
HISTOGRAMS = (
    "app_tpu_tier_transfer_seconds",
    "app_tpu_infer_latency",
    "app_tpu_batch_size",
    "app_tpu_spec_tokens_per_step",
)


def _metrics_manager():
    m = new_metrics_manager()
    for name in COUNTERS:
        m.new_counter(name)
    for name in GAUGES:
        m.new_gauge(name)
    for name in HISTOGRAMS:
        m.new_histogram(name)
    return m


def counter_total(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    total = 0.0
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            total += value
    return total


def _prompt(tag: int):
    """96 tokens = exactly 3 full 32-token blocks, distinct per tag so
    every test's transfer ships COLD content (a collision would dedupe
    against the shared decode engine's radix and skip the leg under
    test)."""
    return [2 + (i * 7 + tag * 13) % 200 for i in range(95)] + [tag % 200]


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _make_engine(metrics, **kw):
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, window_k=4,
        pipeline_depth=1, prefill_chunk=32, kv_block=32, auto_prefix=True,
        tokenizer=ByteTokenizer(), metrics=metrics, **kw,
    )
    eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def engines(metrics):
    """One prefill + one decode engine shared by the suite (compile
    cost), plus a fused single-engine reference for byte-identity."""
    pf = _make_engine(metrics)
    dc = _make_engine(metrics)
    ref = _make_engine(metrics)
    yield pf, dc, ref
    faults.reset()
    for eng in (pf, dc, ref):
        eng.close()


def _pool(replicas, metrics, **kw):
    sleeps: list = []
    kw.setdefault("probe_interval_s", 0)
    kw.setdefault("probe_timeout_s", 60.0)
    kw.setdefault("hedge_delay_s", 300.0)
    kw.setdefault("transfer_retries", 2)
    kw.setdefault("transfer_backoff_s", 0.01)
    kw.setdefault("sleep", sleeps.append)
    kw.setdefault("rng", random.Random(7))
    pool = ReplicaPool(replicas, metrics=metrics, **kw)
    pool._test_sleeps = sleeps
    return pool


@pytest.fixture()
def tier_pool(metrics, engines):
    pf, dc, _ = engines
    pool = _pool(
        [
            EngineReplica("pf", pf, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        metrics,
    )
    yield pool
    pool.stop_prober()
    for replica in pool.replicas:
        replica.set_handoff(None)
        replica.set_tier_exporter(None)


def _drain(req, timeout=120.0):
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _legs(req):
    tl = req.timeline
    assert tl is not None
    return [(result, leg) for _, _, _, _, result, leg in tl.transfers]


# ----------------------------------------------------------------------
# device leg: byte-identity, observability, zero recompiles
# ----------------------------------------------------------------------


def test_device_leg_greedy_byte_identical(metrics, engines, tier_pool):
    """The automatic ladder picks the device leg for in-proc targets;
    the stream is byte-identical to the fused reference, the transfer
    is tagged leg="device" end to end (counter, bytes counter,
    timeline), and the decode replica's radix holds the blocks."""
    pf, dc, ref = engines
    prompt = _prompt(1)
    want = ref.generate_sync(prompt, max_new_tokens=10, temperature=0.0,
                             timeout=120.0)
    ok0 = counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok", leg="device"
    )
    bytes0 = counter_total(
        metrics, "app_tpu_tier_transfer_bytes_total", leg="device"
    )
    req = tier_pool.submit_generate(prompt, max_new_tokens=10,
                                    temperature=0.0)
    toks = _drain(req)
    result = req.future.result(timeout=5)  # zero 5xx
    assert toks == result.token_ids == want.token_ids
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok", leg="device"
    ) == ok0 + 1
    assert counter_total(
        metrics, "app_tpu_tier_transfer_bytes_total", leg="device"
    ) > bytes0
    assert _legs(req) == [("ok", "device")]
    tl = req.timeline
    assert len(tl.trace_id) == 32  # one trace end to end
    assert dc._radix.n_cached_blocks >= 3


def test_device_leg_seeded_sampled_byte_identical(engines, tier_pool):
    _, _, ref = engines
    prompt = _prompt(2)
    want = ref.generate_sync(
        prompt, max_new_tokens=10, temperature=0.8, seed=42, timeout=120.0
    )
    req = tier_pool.submit_generate(
        prompt, max_new_tokens=10, temperature=0.8, seed=42
    )
    toks = _drain(req)
    assert toks == want.token_ids
    assert req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("ok", "device")]


def test_host_pin_byte_identical(metrics, engines):
    """TPU_TRANSFER_LEG=host pins the PR 8 host bounce; same bytes,
    same stream, leg="host" in every signal."""
    pf, dc, ref = engines
    prompt = _prompt(3)
    pool = _pool(
        [
            EngineReplica("pf", pf, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        metrics, transfer_leg="host",
    )
    try:
        want = ref.generate_sync(prompt, max_new_tokens=10,
                                 temperature=0.0, timeout=120.0)
        req = pool.submit_generate(prompt, max_new_tokens=10,
                                   temperature=0.0)
        toks = _drain(req)
        assert toks == want.token_ids
        assert _legs(req) == [("ok", "host")]
    finally:
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)
            replica.set_tier_exporter(None)


def test_zero_steady_state_recompiles_repeated_device_transfers(
    metrics, engines, tier_pool
):
    """Repeated device-leg transfers after the PR 10 warm-up fence
    compile nothing: extract/move are one fixed-shape program per cache
    geometry, warmed by the suite's earlier transfers."""
    pf, dc, _ = engines
    pf.mark_steady_state()
    dc.mark_steady_state()
    for tag in (4, 5, 6):
        req = tier_pool.submit_generate(
            _prompt(tag), max_new_tokens=6, temperature=0.0
        )
        _drain(req)
        assert _legs(req) == [("ok", "device")]
    for eng in (pf, dc):
        assert eng.compile_stats()["steady_state_recompiles"] == 0


def test_device_leg_failure_degrades_to_host_rung(metrics, engines,
                                                  tier_pool):
    """A device-leg import blowing up bans the leg for that transfer
    and the SAME target retries one rung down (host bounce) — the
    ladder's any-leg-failure contract, still byte-identical, still one
    transfer counted (result=ok, leg=host)."""
    pf, dc, ref = engines
    prompt = _prompt(7)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    with faults.armed(
        "tier.import", raises=RuntimeError("device import died"), times=1
    ):
        req = tier_pool.submit_generate(prompt, max_new_tokens=8,
                                        temperature=0.0)
        toks = _drain(req)
    assert toks == want.token_ids
    assert req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("ok", "host")]


def test_tp2_device_leg_across_disjoint_meshes_byte_identical(metrics,
                                                              engines):
    """Prefill pod on devices[0:2], decode pod on devices[2:4]: the
    device leg reshards each block shard-to-shard with an explicit
    ``device_put`` — no host gather (GL018's lived contract) — and the
    stream stays byte-identical to the unsharded fused reference."""
    import jax

    _, _, ref = engines
    devs = list(jax.devices())
    if len(devs) < 4:
        pytest.skip("needs the conftest's 8 virtual devices")
    prompt = _prompt(8)
    pf2 = _make_engine(metrics, devices=devs[0:2], tp=2)
    dc2 = _make_engine(metrics, devices=devs[2:4], tp=2)
    pool = _pool(
        [
            EngineReplica("pf2", pf2, role="prefill"),
            EngineReplica("dc2", dc2, role="decode"),
        ],
        metrics,
    )
    try:
        want = ref.generate_sync(prompt, max_new_tokens=10,
                                 temperature=0.0, timeout=240.0)
        req = pool.submit_generate(prompt, max_new_tokens=10,
                                   temperature=0.0)
        toks = _drain(req, timeout=240.0)
        assert toks == want.token_ids
        assert _legs(req) == [("ok", "device")]
        assert dc2._radix.n_cached_blocks >= 3
    finally:
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)
            replica.set_tier_exporter(None)
        pf2.close()
        dc2.close()


# ----------------------------------------------------------------------
# wire leg: a real remote decode replica over a live socket
# ----------------------------------------------------------------------


class _Harness:
    """Boot a gofr_tpu App on ephemeral ports (httptest.Server role)."""

    def __init__(self, app):
        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )

    def __enter__(self):
        self._thread.start()
        asyncio.run_coroutine_threadsafe(
            self.app.start(), self._loop
        ).result(120)
        return self

    def __exit__(self, *exc):
        asyncio.run_coroutine_threadsafe(
            self.app.stop(), self._loop
        ).result(30)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    @property
    def address(self):
        return f"http://127.0.0.1:{self.app.http_port}"

    @property
    def ops_address(self):
        return f"http://127.0.0.1:{self.app.metrics_port}"


@pytest.fixture(scope="module")
def remote_app():
    """A REAL decode-replica app: OpenAI SSE on the HTTP port, the
    tier-import endpoint on the ops port. Same model/seed as the
    in-proc engines, so tiered streams are byte-identical."""
    from gofr_tpu import App
    from gofr_tpu.serving.openai_compat import add_openai_routes

    app = App(config=MockConfig({
        "APP_NAME": "remote-decode", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "4",
        "TPU_MAX_LEN": "256", "TPU_KV_BLOCK": "32",
        "TPU_AUTO_PREFIX": "true", "TPU_PREFILL_CHUNK": "32",
    }))
    add_openai_routes(app)
    with _Harness(app) as harness:
        yield app, harness


@pytest.fixture()
def wire_pool(metrics, engines, remote_app):
    """1 in-proc prefill + 1 REMOTE decode replica (wire-leg import
    service at the remote's ops port)."""
    from gofr_tpu.service import new_http_service

    pf, _, _ = engines
    app, harness = remote_app
    remote = HTTPReplica(
        "dc-remote",
        new_http_service(harness.address),
        tokenizer=pf.tokenizer,
        role="decode",
        import_service=new_http_service(harness.ops_address),
        metrics=metrics,
    )
    assert remote.supports_tier_import
    pool = _pool(
        [EngineReplica("pf", pf, role="prefill"), remote], metrics,
    )
    yield pool
    pool.stop_prober()
    for replica in pool.replicas:
        replica.set_handoff(None)
        replica.set_tier_exporter(None)
    remote.close()


def test_wire_leg_greedy_byte_identical_one_trace(metrics, engines,
                                                  remote_app, wire_pool):
    """THE wire acceptance path: blocks POSTed to the remote ops port,
    the request streamed over OpenAI SSE — byte-identical to the fused
    reference, result=ok leg=wire, the remote's radix warmed, and the
    remote's flight recorder shows the request under the CALLER's
    trace id (one trace across hosts)."""
    _, _, ref = engines
    app, _ = remote_app
    prompt = _prompt(20)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    cached0 = app.container.tpu._radix.n_cached_blocks
    ok0 = counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok", leg="wire"
    )
    req = wire_pool.submit_generate(
        prompt, max_new_tokens=8, temperature=0.0, traceparent=TRACEPARENT,
    )
    toks = _drain(req)
    result = req.future.result(timeout=5)  # zero 5xx
    assert toks == result.token_ids == want.token_ids
    assert counter_total(
        metrics, "app_tpu_tier_transfers_total", result="ok", leg="wire"
    ) == ok0 + 1
    assert _legs(req) == [("ok", "wire")]
    assert app.container.tpu._radix.n_cached_blocks >= cached0 + 3
    flights = app.container.tpu.flight_records()
    assert any(
        e["trace_id"] == "ab" * 16
        for e in flights.get("records", []) + flights.get("pinned", [])
    )


def test_wire_leg_seeded_sampled_byte_identical(engines, wire_pool):
    _, _, ref = engines
    prompt = _prompt(21)
    want = ref.generate_sync(
        prompt, max_new_tokens=8, temperature=0.8, seed=7, timeout=120.0
    )
    req = wire_pool.submit_generate(
        prompt, max_new_tokens=8, temperature=0.8, seed=7
    )
    toks = _drain(req)
    assert toks == want.token_ids
    assert _legs(req) == [("ok", "wire")]


def test_wire_mid_post_death_degrades_fused_zero_5xx(metrics, engines,
                                                     wire_pool):
    """The import POST dying mid-wire (read loss after the connection
    opened) degrades to fused adoption: the request still streams on
    the remote and re-prefills there — byte-identical, zero 5xx, one
    trace id, result=fused leg=wire."""
    _, _, ref = engines
    prompt = _prompt(22)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    exc = ErrorServiceUnavailable("mid-POST reset")
    exc.kind = "read"
    with faults.armed("http.request", raises=exc, times=1):
        req = wire_pool.submit_generate(prompt, max_new_tokens=8,
                                        temperature=0.0)
        toks = _drain(req)
    assert toks == want.token_ids
    assert req.future.result(timeout=5).token_ids == want.token_ids
    assert _legs(req) == [("fused", "wire")]


def test_wire_corrupt_body_rejected_then_fused(metrics, engines,
                                               remote_app, wire_pool):
    """A corrupt wire body is rejected by the remote (400, CRC/framing)
    and the transfer degrades to fused — never a wrong answer. Both
    halves pinned: the endpoint's verdict on actually-corrupt bytes,
    and the exporter's ladder on a canned rejection."""
    from gofr_tpu.service.client import Response

    _, _, ref = engines
    app, harness = remote_app
    # Half 1: real corrupt bytes at the real endpoint.
    pf_cache_engine = ref
    payload = export_blocks(
        pf_cache_engine.cache, [1], list(range(32)), src="test"
    )
    body = bytearray(payload_to_wire(payload))
    body[-3] ^= 0xFF  # flip one plane byte: CRC must catch it
    conn = http.client.HTTPConnection(
        "127.0.0.1", app.metrics_port, timeout=60
    )
    conn.request("POST", "/ops/tier-import", body=bytes(body),
                 headers={"Content-Type": "application/octet-stream"})
    resp = conn.getresponse()
    verdict = resp.read()
    conn.close()
    assert resp.status == 200  # framing parsed; CRC fails at validation
    assert b'"fused"' in verdict
    # Short/garbage framing is a 400 "rejected", never a 5xx.
    conn = http.client.HTTPConnection(
        "127.0.0.1", app.metrics_port, timeout=60
    )
    conn.request("POST", "/ops/tier-import", body=b"garbage")
    resp = conn.getresponse()
    verdict = resp.read()
    conn.close()
    assert resp.status == 400
    assert b'"rejected"' in verdict
    # Half 2: the exporter sees a rejection → fused adoption,
    # byte-identical stream.
    prompt = _prompt(23)
    want = ref.generate_sync(prompt, max_new_tokens=8, temperature=0.0,
                             timeout=120.0)
    with faults.armed(
        "http.request",
        action=lambda **ctx: Response(b'{"result":"rejected"}', 400, {}),
        times=1,
    ):
        req = wire_pool.submit_generate(prompt, max_new_tokens=8,
                                        temperature=0.0)
        toks = _drain(req)
    assert toks == want.token_ids
    assert _legs(req) == [("fused", "wire")]


def test_wire_stale_fingerprint_fused(remote_app):
    """A payload from a different cache geometry must never alias into
    the remote pool: the endpoint accepts the bytes, validation fails
    the fingerprint, the reply is "fused" (the request re-prefills)."""
    import numpy as np

    app, _ = remote_app
    k = np.zeros((2, 1, 2, 16, 4), dtype=np.float32)  # wrong geometry
    from gofr_tpu.ops.kv_cache import payload_checksum

    stale = KVBlockPayload(
        block=16, token_ids=tuple(range(16)), k=k, v=k,
        src="old-pod", checksum=payload_checksum(k, k),
        geometry=(2, 2, 16, 4, "float32", False),
    )
    conn = http.client.HTTPConnection(
        "127.0.0.1", app.metrics_port, timeout=60
    )
    conn.request("POST", "/ops/tier-import", body=payload_to_wire(stale))
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    assert resp.status == 200
    assert b'"fused"' in body


def test_wire_dead_ops_port_excludes_target(metrics, engines):
    """Nothing listening at the ops port (connect-refused) → the remote
    is excluded; with no other decode target the request decodes
    locally on the prefill replica (local_fused) — served either way."""
    from gofr_tpu.service import new_http_service

    pf, _, ref = engines
    prompt = _prompt(24)
    remote = HTTPReplica(
        "dc-dead",
        new_http_service("http://127.0.0.1:9"),
        tokenizer=pf.tokenizer, role="decode",
        import_service=new_http_service("http://127.0.0.1:9"),
    )
    pool = _pool(
        [EngineReplica("pf", pf, role="prefill"), remote], metrics,
    )
    try:
        exc = ErrorServiceUnavailable("refused")
        exc.kind = "connect"
        want = ref.generate_sync(prompt, max_new_tokens=6,
                                 temperature=0.0, timeout=120.0)
        lf0 = counter_total(
            metrics, "app_tpu_tier_transfers_total", result="local_fused"
        )
        with faults.armed("http.request", raises=exc):
            req = pool.submit_generate(prompt, max_new_tokens=6,
                                       temperature=0.0)
            toks = _drain(req)
        assert toks == want.token_ids
        assert counter_total(
            metrics, "app_tpu_tier_transfers_total", result="local_fused"
        ) == lf0 + 1
    finally:
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)
            replica.set_tier_exporter(None)
        remote.close()


# ----------------------------------------------------------------------
# leg selection
# ----------------------------------------------------------------------


def test_leg_selection_matrix(metrics, engines):
    """The ladder's static half: automatic selection prefers device
    for in-proc targets; pins restrict to exactly one leg; a pin no
    target can serve degrades to local fused serving (never a 5xx)."""
    pf, dc, ref = engines
    cases = [
        ("", "device"),      # auto → device for an in-proc sibling
        ("device", "device"),
        ("host", "host"),
    ]
    for tag, (pin, expected) in enumerate(cases, start=30):
        prompt = _prompt(tag)
        pool = _pool(
            [
                EngineReplica("pf", pf, role="prefill"),
                EngineReplica("dc", dc, role="decode"),
            ],
            metrics, transfer_leg=pin,
        )
        try:
            req = pool.submit_generate(prompt, max_new_tokens=4,
                                       temperature=0.0)
            _drain(req)
            assert _legs(req) == [("ok", expected)], (pin,)
        finally:
            pool.stop_prober()
            for replica in pool.replicas:
                replica.set_handoff(None)
                replica.set_tier_exporter(None)
    # A wire pin with only in-proc decode targets: no reachable
    # target, the prefill replica decodes locally — still served.
    prompt = _prompt(39)
    want = ref.generate_sync(prompt, max_new_tokens=4, temperature=0.0,
                             timeout=120.0)
    pool = _pool(
        [
            EngineReplica("pf", pf, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        metrics, transfer_leg="wire",
    )
    try:
        lf0 = counter_total(
            metrics, "app_tpu_tier_transfers_total", result="local_fused"
        )
        req = pool.submit_generate(prompt, max_new_tokens=4,
                                   temperature=0.0)
        toks = _drain(req)
        assert toks == want.token_ids
        assert counter_total(
            metrics, "app_tpu_tier_transfers_total", result="local_fused"
        ) == lf0 + 1
    finally:
        pool.stop_prober()
        for replica in pool.replicas:
            replica.set_handoff(None)
            replica.set_tier_exporter(None)


def test_pool_import_facade_prefers_decode_and_tries_siblings():
    """The wire endpoint's pool facade must land blocks where the
    companion request will DECODE: decode-role replicas first, and a
    rejecting (unpaged/stale) replica must not stop a sibling from
    importing."""
    calls: list = []

    class _Eng(_StubEngine):
        def __init__(self, name, verdict):
            self._name, self._verdict = name, verdict

        def import_payload(self, payload):
            calls.append(self._name)
            return self._verdict

    pf = EngineReplica("pf", _Eng("pf", "imported"), role="prefill")
    dc = EngineReplica("dc", _Eng("dc", "imported"), role="decode")
    pool = ReplicaPool([pf, dc])
    assert pool.import_payload(object()) == "imported"
    assert calls == ["dc"]  # decode tier first, prefill never touched
    # A fused-replying (unpaged) decode replica falls through to the
    # next importer instead of wasting the shipped bytes.
    calls.clear()
    dc_unpaged = EngineReplica("dc0", _Eng("dc0", "fused"), role="decode")
    dc_paged = EngineReplica("dc1", _Eng("dc1", "imported"), role="decode")
    pool2 = ReplicaPool([pf, dc_unpaged, dc_paged])
    assert pool2.import_payload(object()) == "imported"
    assert calls == ["dc0", "dc1"]


def test_transfer_leg_validation():
    with pytest.raises(ValueError):
        ReplicaPool(
            [EngineReplica("x", _StubEngine())], transfer_leg="carrier-pigeon"
        )


class _StubEngine:
    family = "llm"
    tier_role = "fused"
    model_name = "stub"
    kv_block = 0

    def set_replica_handoff(self, h):
        pass

    def set_tier_exporter(self, e):
        pass

    @property
    def state(self):
        return "SERVING"


# ----------------------------------------------------------------------
# wire codec units
# ----------------------------------------------------------------------


def test_wire_codec_roundtrip_and_framing_rejections(engines):
    _, _, ref = engines
    import numpy as np

    payload = export_blocks(ref.cache, [1, 2], list(range(64)), src="me")
    wire = payload_to_wire(payload)
    back = payload_from_wire(wire)
    assert back.verify()
    assert back.compatible_with(ref.cache)
    assert back.token_ids == payload.token_ids
    assert back.checksum == payload.checksum
    assert np.array_equal(back.k, payload.k)
    assert back.nbytes() == payload.nbytes()
    # Framing violations raise ValueError (the endpoint's 400 rung).
    for bad in (b"", b"NOPE", wire[:10], wire[:-5]):
        with pytest.raises(ValueError):
            payload_from_wire(bad)
    # Byte corruption inside a plane survives framing but fails the
    # re-computed CRC.
    corrupt = bytearray(wire)
    corrupt[-3] ^= 0xFF
    assert not payload_from_wire(bytes(corrupt)).verify()


# ----------------------------------------------------------------------
# per-SLO-class priority dequeue (satellite)
# ----------------------------------------------------------------------


class _Req:
    def __init__(self, name, slo_class):
        self.name = name
        self.slo_class = slo_class


def test_class_dequeue_deterministic_ordering():
    """Stated clocks: interactive jumps queued standard/batch work at
    pop time, stable FIFO within a class."""
    now = [0.0]
    q = ClassPriorityQueue(promote_after_s=10.0, clock=lambda: now[0])
    for name, cls in (
        ("b0", "batch"), ("s0", "standard"), ("i0", "interactive"),
        ("b1", "batch"), ("i1", "interactive"), ("s1", "standard"),
    ):
        q.put_nowait(_Req(name, cls))
        now[0] += 1.0
    order = [q.get_nowait().name for _ in range(q.qsize())]
    assert order == ["i0", "i1", "s0", "s1", "b0", "b1"]
    with pytest.raises(queue_mod.Empty):
        q.get_nowait()


def test_class_dequeue_starvation_bound_promotes_oldest():
    """A lower-class head past the promotion window pops first — among
    over-age heads the OLDEST wins regardless of class, so batch work
    is delayed by at most the window, never forever."""
    now = [0.0]
    q = ClassPriorityQueue(promote_after_s=5.0, clock=lambda: now[0])
    q.put_nowait(_Req("b0", "batch"))
    now[0] = 2.0
    q.put_nowait(_Req("s0", "standard"))
    now[0] = 8.0
    q.put_nowait(_Req("i0", "interactive"))
    # b0 waited 8s > 5s, s0 6s > 5s: oldest over-age head (b0) first,
    # then s0, then the interactive arrival.
    assert [q.get_nowait().name for _ in range(3)] == ["b0", "s0", "i0"]


def test_class_dequeue_off_is_strict_fifo_and_unknown_is_standard():
    q = ClassPriorityQueue(promote_after_s=0.0)
    q.put_nowait(_Req("b", "batch"))
    q.put_nowait(_Req("i", "interactive"))
    assert [q.get_nowait().name, q.get_nowait().name] == ["b", "i"]
    q2 = ClassPriorityQueue(promote_after_s=10.0)
    q2.put_nowait(_Req("w", "weird-class"))
    q2.put_nowait(_Req("i", "interactive"))
    # Unknown classes rank standard (never 400, never starved-first).
    assert [q2.get_nowait().name, q2.get_nowait().name] == ["i", "w"]


def test_class_dequeue_maxsize_and_queue_api():
    q = ClassPriorityQueue(maxsize=2)
    q.put_nowait(_Req("a", "standard"))
    q.put_nowait(_Req("b", "standard"))
    with pytest.raises(queue_mod.Full):
        q.put_nowait(_Req("c", "standard"))
    assert q.qsize() == 2 and not q.empty()
    assert q.maxsize == 2


def test_engine_wires_class_dequeue_from_config(engines):
    """The engine's admission queue IS the class queue, wired from
    TPU_QUEUE_CLASS_PROMOTE_S (default 5s, 0 = strict FIFO)."""
    pf, _, _ = engines
    assert isinstance(pf._pending, ClassPriorityQueue)
    assert pf._pending.promote_after_s == 5.0
    eng = InferenceEngine.from_config(
        MockConfig({
            "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
            "TPU_MAX_LEN": "64", "TPU_QUEUE_CLASS_PROMOTE_S": "12.5",
        })
    )
    assert eng._pending.promote_after_s == 12.5
