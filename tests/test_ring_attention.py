"""Context parallelism: ring attention and Ulysses vs dense reference.

Runs on the 8-virtual-device CPU mesh from conftest — the same mechanism
the driver uses to validate multi-chip sharding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.ring_attention import context_parallel_attention
from gofr_tpu.parallel import make_mesh


def _qkv(key, b=2, s=64, h=4, kv=4, d=16, dtype=jnp.float32):
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kv, d), dtype)
    v = jax.random.normal(kv_, (b, s, kv, d), dtype)
    return q, k, v


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 4}, devices=jax.devices()[:4])


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense(sp_mesh, impl, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    want = attention(q, k, v, causal=causal, kernel=False)
    got = context_parallel_attention(
        q, k, v, sp_mesh, axis_name="sp", impl=impl, causal=causal
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_gqa(sp_mesh, impl):
    # 8 query heads over 2 KV heads; KV heads don't divide the 4-way axis.
    q, k, v = _qkv(jax.random.PRNGKey(1), h=8, kv=2)
    want = attention(q, k, v, causal=True, kernel=False)
    got = context_parallel_attention(q, k, v, sp_mesh, impl=impl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_full_axis():
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(jax.random.PRNGKey(2), s=32)
    want = attention(q, k, v, causal=True, kernel=False)
    got = context_parallel_attention(q, k, v, mesh, impl="ring")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_under_jit_is_sharded(sp_mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(jax.random.PRNGKey(3))
    shard = NamedSharding(sp_mesh, P(None, "sp", None, None))
    q, k, v = (jax.device_put(x, shard) for x in (q, k, v))
    out = jax.jit(
        lambda q, k, v: context_parallel_attention(q, k, v, sp_mesh, impl="ring")
    )(q, k, v)
    assert out.sharding.spec == P(None, "sp", None, None)
    want = attention(q, k, v, causal=True, kernel=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


def test_ring_bf16():
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(jax.random.PRNGKey(4), dtype=jnp.bfloat16)
    want = attention(q, k, v, causal=True, kernel=False)
    got = context_parallel_attention(q, k, v, mesh, impl="ring")
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=0.1
    )


def test_bad_impl(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(5))
    with pytest.raises(ValueError, match="unknown context-parallel impl"):
        context_parallel_attention(q, k, v, sp_mesh, impl="nope")
