"""Device-resource observability suite (ISSUE 11 acceptance gate).

Deterministic throughout: real engines on the conftest's 8 virtual CPU
devices (no sleeps as synchronization — sweeps are driven directly with
the scheduler stopped), an in-memory span collector, and exact-count
assertions against the compile tracker.

Covered:

* the HBM ledger's component sum equals the engine's actual accounting
  — ``kv_pool`` is exactly ``cache.hbm_bytes()`` and ``params + lora``
  exactly ``quantized_bytes(engine.params)`` — at **tp=1 AND tp=2**
  (global bytes are tp-invariant; ``per_device_bytes`` divides);
* THE acceptance path: zero ``app_tpu_steady_state_recompiles_total``
  across a mixed cold + prefix-warm + seeded-sampled + LoRA workload
  after the warm-up fence;
* a genuinely new program variant AFTER the fence is detected and
  counted (the logit-bias compile choice);
* ``tpu.compile`` spans parent under the trace that was ambient at
  engine construction (a traced boot owns its warm-up compiles even
  though they fire on the scheduler thread);
* ``TPU_PREFIX_EVICT_HBM_FRAC`` derives the block watermark from the
  ledger, with ``TPU_PREFIX_EVICT_WM`` as the explicit override —
  both precedence orders — and the derived watermark actually sweeps
  the radix cache;
* ``/debug/capacity`` JSON shape, engine- and pool-shaped;
* headroom advertised through a pool probe (describe / flight
  records), admission's headroom floor, and the pool scaler's
  headroom-pressure scale-up.
"""

from __future__ import annotations

import threading

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.container import Container
from gofr_tpu.errors import ErrorTooManyRequests
from gofr_tpu.ops.quant import quantized_bytes
from gofr_tpu.serving.device_telemetry import HBMLedger
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.pool_scaler import PoolScaler
from gofr_tpu.service.replica_pool import EngineReplica, Replica, ReplicaPool
from gofr_tpu.tracing import Tracer, get_tracer, set_tracer

#: Shared serving geometry: one compile set per mesh placement.
ENG_KW = dict(
    n_slots=4, max_len=256, window_k=4, pipeline_depth=1,
    prefill_chunk=32, kv_block=32, auto_prefix=True,
)

#: 96 tokens = exactly 3 full 32-token KV blocks: retirement caches
#: full-block prefixes and a repeat hits the COW boundary.
PROMPT = list(range(2, 200, 3)) + [7] * 30
assert len(PROMPT) == 96


@pytest.fixture(scope="module")
def metrics():
    return Container.create(
        MockConfig({"APP_NAME": "devtel-test"})
    ).metrics


def _make_engine(metrics=None, start=True, **kw):
    eng = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(), metrics=metrics,
        **{**ENG_KW, **kw},
    )
    if start:
        eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def eng_lora(metrics):
    """The shared workhorse engine (module-scoped — engine boots and
    first-dispatch compiles dominate this suite's wall clock): paged +
    auto-prefix + one adapter slot. Tests on it are order-independent:
    compile assertions are delta-based and the ledger/capacity
    invariants hold whether or not another test generated first."""
    eng = _make_engine(metrics, lora_slots=1, lora_rank=4)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def lora_pool(eng_lora, metrics):
    pool = ReplicaPool(
        [EngineReplica("shared-0", eng_lora)], metrics=metrics
    )
    yield pool
    # Detach only: the engine belongs to its own fixture.
    eng_lora.set_replica_handoff(None)


def _gauge(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            return value
    return None


def _counter_total(metrics, name, **labels):
    inst = {i.name: i for i in metrics.instruments()}[name]
    total = 0.0
    for key, value in inst.collect().items():
        if all((k, str(v)) in key for k, v in labels.items()):
            total += value
    return total


class _CaptureExporter:
    """In-memory span sink; ``is_noop`` absent → the tracer is ACTIVE."""

    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()

    def export(self, span, service_name):
        with self._lock:
            self.spans.append(span)

    def by_name(self, name):
        with self._lock:
            return [s for s in self.spans if s.name == name]


# ----------------------------------------------------------------------
# the HBM ledger agrees with the engine's own accounting — tp=1 and tp=2
# ----------------------------------------------------------------------


def _assert_ledger_exact(eng):
    snap = eng.hbm_ledger()
    comps = snap["components"]
    # The pool component IS the cache's own accounting, to the byte.
    assert comps["kv_pool"] == eng.cache.hbm_bytes()
    # params + adapter planes == the whole quantized weight tree.
    assert comps["params"] + comps.get("lora", 0) == quantized_bytes(
        eng.params
    )
    assert snap["total_bytes"] == sum(comps.values())
    assert comps["workspace"] > 0
    assert 0.0 <= snap["headroom_ratio"] <= 1.0
    return snap


def test_hbm_ledger_matches_engine_accounting_tp1(eng_lora, metrics):
    snap = _assert_ledger_exact(eng_lora)
    assert snap["mesh_devices"] == 1
    assert snap["per_device_bytes"] == snap["total_bytes"]
    assert snap["components"]["lora"] > 0
    # With no platform memory_stats and no TPU_HBM_BYTES the budget
    # falls back to the ledger's own footprint.
    assert snap["budget_source"] == "ledger"
    assert snap["budget_bytes"] == snap["per_device_bytes"]
    # Per-component gauges exported at boot (every ENG_KW engine shares
    # the pool geometry, so the kv_pool gauge is stable across the
    # suite's engines regardless of test order).
    assert _gauge(
        metrics, "app_tpu_hbm_bytes", component="kv_pool"
    ) == snap["components"]["kv_pool"]
    assert _gauge(
        metrics, "app_tpu_hbm_headroom_ratio", model="llama-tiny"
    ) is not None


def test_hbm_ledger_matches_engine_accounting_tp2(metrics):
    import jax

    devs = jax.devices()
    assert len(devs) >= 2, "suite needs the conftest's 8 virtual devices"
    eng = _make_engine(metrics, tp=2, devices=devs[:2])
    try:
        snap = _assert_ledger_exact(eng)  # global bytes: tp-invariant
        assert snap["mesh_devices"] == 2
        # Sharded components divide across the mesh; replicated
        # workspace does not — per-device strictly between total/2 and
        # total.
        assert (
            snap["total_bytes"] // 2
            <= snap["per_device_bytes"]
            < snap["total_bytes"]
        )
    finally:
        eng.close()


def test_explicit_budget_wins_and_headroom_uses_it():
    eng = _make_engine(hbm_budget_bytes=1 << 30, start=False)
    try:
        snap = eng.hbm_ledger()
        assert snap["budget_source"] == "env"
        assert snap["budget_bytes"] == 1 << 30
        # A huge budget over a tiny engine: headroom ≈ 1.
        assert eng.hbm_headroom_ratio() > 0.99
    finally:
        eng.close()


# ----------------------------------------------------------------------
# compile tracker: THE acceptance path — zero steady-state recompiles
# across a mixed workload after warm-up
# ----------------------------------------------------------------------


def test_zero_steady_state_recompiles_across_mixed_workload(
    eng_lora, metrics
):
    from gofr_tpu.models.transformer import lora_dims
    import jax

    eng = eng_lora
    leaves = {}
    for ti, t in enumerate(("wq", "wk", "wv", "wo")):
        d_in, d_out = lora_dims(eng.cfg, t)
        k1, k2 = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(9), ti), 2
        )
        leaves[t] = (
            0.02 * jax.random.normal(k1, (eng.cfg.n_layers, d_in, 4)),
            0.02 * jax.random.normal(k2, (eng.cfg.n_layers, 4, d_out)),
        )
    eng.load_lora("mixed-test", leaves)

    def run(prompt, **kw):
        return eng.generate_sync(
            prompt, max_new_tokens=6, stop_on_eos=False, **kw
        )

    # Warm-up: one request per program variant the mixed workload
    # will exercise — cold greedy, seeded sampled, LoRA, and an
    # IDENTICAL repeat (whole-prompt prefix hit → the COW boundary
    # compiles paged_copy_block).
    run(PROMPT, temperature=0.0)
    run(PROMPT, temperature=0.0)
    run(PROMPT, temperature=0.8, seed=7)
    run(PROMPT, temperature=0.0, adapter="mixed-test")
    warm_stats = eng.compile_stats()
    assert warm_stats["total"] >= 2
    assert not warm_stats["warm"]

    steady_before = _counter_total(
        metrics, "app_tpu_steady_state_recompiles_total"
    )
    eng.mark_steady_state()
    assert eng.compile_stats()["warm"]

    # The mixed steady-state workload: a NEW cold prompt, the warm
    # repeat (prefix alias + COW), seeded sampling, LoRA — all
    # through the already-compiled fixed-shape programs.
    cold = list(range(3, 150, 2))
    run(cold, temperature=0.0)
    run(PROMPT, temperature=0.0)
    run(PROMPT, temperature=0.9, seed=11)
    run(PROMPT, temperature=0.0, adapter="mixed-test")

    stats = eng.compile_stats()
    assert stats["steady_state_recompiles"] == 0, stats
    assert stats["total"] == warm_stats["total"], stats
    assert _counter_total(
        metrics, "app_tpu_steady_state_recompiles_total"
    ) == steady_before
    # Total compiles exported per program.
    assert _counter_total(
        metrics, "app_tpu_compiles_total", model="llama-tiny"
    ) >= stats["total"]


def test_steady_state_recompile_detected_and_counted(metrics):
    eng = _make_engine(metrics)
    try:
        eng.generate_sync(
            PROMPT, max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        before = _counter_total(
            metrics, "app_tpu_steady_state_recompiles_total"
        )
        eng.mark_steady_state()
        # A program VARIANT never exercised during warm-up: logit_bias
        # flips the use_bias static arg — a genuinely new compile, the
        # exact bug class the fence exists to catch.
        eng.generate_sync(
            PROMPT, max_new_tokens=4, temperature=0.0, stop_on_eos=False,
            logit_bias={1: 5.0},
        )
        stats = eng.compile_stats()
        assert stats["steady_state_recompiles"] >= 1, stats
        assert _counter_total(
            metrics, "app_tpu_steady_state_recompiles_total"
        ) > before
        # The flight surface carries the headline too.
        assert eng.flight_records()["steady_state_recompiles"] >= 1
    finally:
        eng.close()


def test_compile_span_parents_under_boot_trace():
    old = get_tracer()
    cap = _CaptureExporter()
    set_tracer(Tracer(service_name="devtel-test", exporter=cap))
    try:
        tracer = get_tracer()
        boot = tracer.start_span("tpu.boot")
        try:
            # Tracker construction captures the ambient boot span…
            eng = _make_engine()
        finally:
            boot.end()
        try:
            # …and the compiles fire LATER, on the scheduler thread
            # (no ambient span there) — they must still join the boot
            # trace.
            eng.generate_sync(
                PROMPT, max_new_tokens=4, temperature=0.0,
                stop_on_eos=False,
            )
            spans = cap.by_name("tpu.compile")
            assert spans, [s.name for s in cap.spans]
            for span in spans:
                assert span.trace_id == boot.trace_id
                assert span.parent_id == boot.span_id
                assert span.attributes["tpu.steady_state"] is False
                assert span.attributes["tpu.program"]
                assert span.end_ns >= span.start_ns
        finally:
            eng.close()
    finally:
        set_tracer(old)


# ----------------------------------------------------------------------
# HBM-frac eviction watermark: derivation, precedence, behavior
# ----------------------------------------------------------------------


def test_ledger_derives_block_watermark_exactly():
    # Unit arithmetic, no engine: budget 1000, per-device total 700
    # (slack 300), 10-block pool at 50 B/block. frac=0.5 wants 500 B
    # free → 200 B beyond slack → ceil(200/50) = 4 blocks.
    ledger = HBMLedger(
        {"params": 600, "kv_pool": 100},
        block_bytes=50, n_blocks=10, budget_bytes=1000,
    )
    assert ledger.per_device_bytes == 700
    assert ledger.derive_block_watermark(0.5) == 4
    # Slack already covers the target → no blocks needed.
    assert ledger.derive_block_watermark(0.3) == 0
    # Impossible target clamps to the pool minus the parking block.
    assert ledger.derive_block_watermark(5.0) == 9
    assert ledger.derive_block_watermark(0.0) == 0
    # Headroom: slack 300 + 2 free blocks × 50 = 400 over 1000.
    assert ledger.headroom_ratio(free_blocks=2) == pytest.approx(0.4)


def test_watermark_precedence_both_orders():
    # Explicit only.
    eng = _make_engine(prefix_evict_watermark=3, start=False)
    try:
        assert eng.effective_evict_watermark == 3
    finally:
        eng.close()
    # Frac only → derived from the ledger (> 0: frac 1.0 of the budget
    # can only be covered by freeing pool blocks).
    eng = _make_engine(prefix_evict_hbm_frac=1.0, start=False)
    try:
        derived = eng._ledger.derive_block_watermark(1.0)
        assert derived > 0
        assert eng.effective_evict_watermark == derived
    finally:
        eng.close()
    # Both set → the explicit block count wins (the carried ROADMAP
    # contract: TPU_PREFIX_EVICT_WM stays the override).
    eng = _make_engine(
        prefix_evict_watermark=2, prefix_evict_hbm_frac=1.0, start=False
    )
    try:
        assert eng.effective_evict_watermark == 2
    finally:
        eng.close()
    # Neither → off.
    eng = _make_engine(start=False)
    try:
        assert eng.effective_evict_watermark == 0
    finally:
        eng.close()


def test_hbm_frac_watermark_sweeps_radix_under_pressure(metrics):
    # frac=1.0: the whole budget must be free-able → the derived
    # watermark clamps to every allocatable block, so ANY radix-cached
    # block is pressure the sweep must relieve.
    eng = _make_engine(metrics, prefix_evict_hbm_frac=1.0)
    try:
        total = eng.cache.n_blocks - 1
        assert eng.effective_evict_watermark == total
        eng.generate_sync(
            PROMPT, max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        eng.stop_sync()  # drive the sweep directly, no scheduler race
        # Retirement inserted the prompt's full blocks into the radix…
        # (the running scheduler may already have swept them — run one
        # explicit sweep either way and assert the watermark HOLDS).
        eng._radix_watermark_sweep()
        assert eng._radix.n_cached_blocks == 0
        assert eng._allocator.n_free == total
        assert eng.hbm_headroom_ratio() == pytest.approx(
            eng._ledger.headroom_ratio(total)
        )
    finally:
        eng.close()


# ----------------------------------------------------------------------
# /debug/capacity shapes + headroom through the pool
# ----------------------------------------------------------------------


def test_capacity_report_shape_engine_and_pool(eng_lora, lora_pool):
    eng, pool = eng_lora, lora_pool
    report = eng.capacity_report()
    assert report["model"] == "llama-tiny"
    assert set(report["hbm"]["components"]) >= {
        "params", "kv_pool", "workspace",
    }
    assert report["compiles"]["total"] >= 0
    assert "steady_state_recompiles" in report["compiles"]
    pool_kv = report["kv_pool"]
    assert pool_kv["total_blocks"] == eng.cache.n_blocks - 1
    assert (
        pool_kv["free_blocks"] + pool_kv["used_blocks"]
        == pool_kv["total_blocks"]
    )
    assert pool_kv["evict_watermark_source"] == "off"

    agg = pool.capacity_report()
    entry = agg["replicas"]["shared-0"]
    assert entry["state"] == "SERVING"
    assert entry["role"] == "fused"
    assert entry["hbm"]["total_bytes"] == report["hbm"]["total_bytes"]
    assert 0.0 < entry["hbm_headroom"] <= 1.0
    assert agg["tier_mode"] == "fused"


def test_headroom_advertised_through_pool_probe(eng_lora, lora_pool):
    eng, pool = eng_lora, lora_pool
    assert pool.probe_once() == {"shared-0": "pass"}
    replica = pool.replicas[0]
    desc = replica.describe()
    assert 0.0 < desc["hbm_headroom"] <= 1.0
    # Health carries the compact ledger (what a remote pool's
    # probe lifts into ITS descriptor over the wire).
    details = eng.health_check()["details"]
    assert details["hbm_ledger"]["headroom_ratio"] == pytest.approx(
        desc["hbm_headroom"], abs=1e-4
    )
    assert details["hbm_ledger"]["components"]["kv_pool"] > 0
    assert details["compiles"]["steady_state_recompiles"] == 0
    # Flight records stamp the headline per replica.
    flights = pool.flight_records()
    assert (
        0.0 < flights["replicas"]["shared-0"]["hbm_headroom"] <= 1.0
    )


def test_admission_sheds_below_headroom_floor(metrics):
    # A floor above 1.0 is unreachable → every submit sheds 429 with
    # the hbm_headroom reason (the real-world case — a nearly-full
    # pool — just moves the ratio, not the mechanism).
    eng = _make_engine(metrics, admit_min_headroom=1.1)
    try:
        before = _counter_total(
            metrics, "app_tpu_requests_shed_total", reason="hbm_headroom"
        )
        with pytest.raises(ErrorTooManyRequests):
            eng.submit_generate(PROMPT, max_new_tokens=4)
        assert _counter_total(
            metrics, "app_tpu_requests_shed_total", reason="hbm_headroom"
        ) == before + 1
    finally:
        eng.close()


# ----------------------------------------------------------------------
# pool scaler reads the same headroom signal
# ----------------------------------------------------------------------


class _HeadroomStub(Replica):
    supports_stream = True

    def __init__(self, name, load=0, headroom=None):
        super().__init__(name)
        self.load_value = load
        self.headroom_value = headroom

    def state(self):
        return "SERVING"

    def load(self):
        return self.load_value

    def headroom(self):
        return self.headroom_value

    def set_handoff(self, handoff):
        pass


def test_scaler_scales_up_on_sustained_low_headroom(metrics):
    spawned = []

    def spawn():
        replica = _HeadroomStub(f"scaled-{len(spawned)}", headroom=0.9)
        spawned.append(replica)
        return replica

    # Queue looks SHALLOW (load 0) but the pool is nearly out of HBM —
    # the exact pressure the queue-depth signal never sees.
    a = _HeadroomStub("a", load=0, headroom=0.02)
    pool = ReplicaPool([a], metrics=metrics)
    scaler = PoolScaler(
        pool, spawn, min_replicas=1, max_replicas=3,
        up_headroom_floor=0.1, scale_up_wait_s=10.0, interval_s=0,
        sleep=lambda s: None, metrics=metrics,
    )
    # Sustain window applies to headroom pressure exactly like load.
    assert scaler.evaluate(now=0.0) == "steady"
    assert scaler.evaluate(now=9.9) == "steady"
    assert scaler.evaluate(now=10.0) == "up"
    assert len(pool.replicas) == 2
    # The spawned replica's healthy headroom lifts the worst-of above
    # the floor → steady.
    a.headroom_value = 0.9
    assert scaler.evaluate(now=20.0) == "steady"
    # None-advertising replicas (remotes pre-probe) are not pressure.
    a.headroom_value = None
    spawned[0].headroom_value = None
    assert scaler.evaluate(now=30.0) == "steady"
    pool.close()


def test_scaler_headroom_floor_off_by_default(metrics):
    a = _HeadroomStub("a", load=0, headroom=0.0)
    pool = ReplicaPool([a], metrics=metrics)
    scaler = PoolScaler(
        pool, lambda: _HeadroomStub("x"), min_replicas=1, max_replicas=3,
        scale_up_wait_s=10.0, interval_s=0, sleep=lambda s: None,
    )
    for t in (0.0, 10.0, 20.0):
        assert scaler.evaluate(now=t) == "steady"
    assert len(pool.replicas) == 1
    pool.close()
