"""Sliding-window attention (Mistral): every query attends only the
last `sliding_window` positions. Oracles: the torch MistralForCausalLM
with an ACTIVE window (seq > window), window >= seq == full attention,
and cross-path consistency — the engine's chunked-prefill + split-decode
stream must reproduce a step-by-step full-forward greedy rollout."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.registry import ModelSpec, get_model, register_model
from gofr_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_forward,
)

SWA_CFG = TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_len=128, rope_theta=10000.0, dtype=jnp.float32,
    sliding_window=8,
)


def test_window_geq_seq_equals_full():
    """A window at least as long as the sequence is exactly full causal
    attention."""
    full = dataclasses.replace(SWA_CFG, sliding_window=0)
    wide = dataclasses.replace(SWA_CFG, sliding_window=64)
    params = init_transformer(jax.random.PRNGKey(0), full)
    toks = jnp.arange(1, 33, dtype=jnp.int32)[None, :]
    lf = np.asarray(transformer_forward(params, toks, full))
    lw = np.asarray(transformer_forward(params, toks, wide))
    np.testing.assert_allclose(lf, lw, atol=1e-6)
    # An ACTIVE window must change late-position logits.
    nw = np.asarray(transformer_forward(params, toks, SWA_CFG))
    assert not np.allclose(lf[:, -1], nw[:, -1], atol=1e-3)
    # ...but positions inside the window are identical.
    np.testing.assert_allclose(lf[:, :8], nw[:, :8], atol=1e-6)


def test_swa_matches_torch_mistral_oracle():
    """Active-window logit parity against MistralForCausalLM (seq 24,
    window 8): pins the (q_pos-window, q_pos] masking convention."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from gofr_tpu.serving.hf_loader import config_from_hf, load_hf_llama

    import tempfile

    with tempfile.TemporaryDirectory() as path:
        hf_cfg = transformers.MistralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            rope_theta=10000.0, rms_norm_eps=1e-6, sliding_window=8,
            tie_word_embeddings=False, attention_dropout=0.0,
        )
        torch.manual_seed(5)
        model = transformers.MistralForCausalLM(hf_cfg)
        model.eval()
        model.save_pretrained(path, safe_serialization=True)

        cfg = config_from_hf(path)
        assert cfg.sliding_window == 8
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        params = load_hf_llama(path, cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(1, 128, size=(1, 24)).astype(np.int32)
        ours = np.asarray(
            transformer_forward(params, jnp.asarray(tokens), cfg)
        )
        with torch.no_grad():
            theirs = model(
                torch.tensor(tokens, dtype=torch.long)
            ).logits.numpy()
        np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def _rollout_reference(params, cfg, prompt_ids, n_new):
    """Greedy rollout via repeated FULL forwards — the cross-path oracle
    for the engine's chunked-prefill + split-decode stream."""
    ids = list(prompt_ids)
    for _ in range(n_new):
        logits = transformer_forward(
            params, jnp.asarray([ids], dtype=jnp.int32), cfg
        )
        ids.append(int(np.asarray(logits)[0, -1].argmax()))
    return ids[len(prompt_ids):]


def test_engine_swa_matches_full_forward_rollout():
    """The serving stream (chunked prefill, split-cache decode, and the
    speculative verify path) must equal the full-forward greedy rollout
    when generation CROSSES the window boundary."""
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    params = init_transformer(jax.random.PRNGKey(3), SWA_CFG)
    register_model(ModelSpec(
        name="swa-test", family="llm", config=SWA_CFG,
        init=lambda key, c: params,
    ))
    prompt = [ord(c) for c in "sliding windows"]  # 15 tokens > window 8
    want = _rollout_reference(params, SWA_CFG, prompt, 12)
    # kv_block=8 makes the paged pool's block axis equal the window — the
    # shape that used to zero the window in decode_attention (the pool's
    # shape[2] is the BLOCK axis, not capacity) and attend beyond it.
    for spec_tokens, kv_block in ((0, 0), (2, 0), (0, 8)):
        eng = InferenceEngine(
            "swa-test", n_slots=2, max_len=128, window_k=4,
            prefill_chunk=16, tokenizer=ByteTokenizer(), params=params,
            spec_tokens=spec_tokens, kv_block=kv_block,
        )
        eng.start_sync()
        try:
            got = eng.generate_sync(
                prompt, max_new_tokens=12, temperature=0.0,
                stop_on_eos=False, timeout=120,
            ).token_ids
        finally:
            eng.stop_sync()
        assert got == want, f"spec_tokens={spec_tokens} kv_block={kv_block}"


def test_engine_swa_mega_parity():
    """Mega-window dispatch honors the sliding window identically."""
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    params = init_transformer(jax.random.PRNGKey(4), SWA_CFG)
    register_model(ModelSpec(
        name="swa-mega-test", family="llm", config=SWA_CFG,
        init=lambda key, c: params,
    ))
    outs = []
    for mega in (0, 4):
        eng = InferenceEngine(
            "swa-mega-test", n_slots=2, max_len=128, window_k=4,
            mega_windows=mega, tokenizer=ByteTokenizer(), params=params,
        )
        eng.start_sync()
        try:
            outs.append(eng.generate_sync(
                "abcdefghij", max_new_tokens=16, temperature=0.0,
                stop_on_eos=False, timeout=120,
            ).token_ids)
        finally:
            eng.stop_sync()
    assert outs[0] == outs[1] and len(outs[0]) == 16


def test_mistral_registry_carries_window():
    cfg = get_model("mistral-7b").config
    assert cfg.sliding_window == 4096
    assert cfg.max_len == 8192  # context can exceed the window now
