"""MySQL / Postgres dialect branches exercised end to end via the in-proc
DB-API fakes (``datasource/sql/fakedb.py`` — the miniredis idiom; VERDICT
r2 missing #1). The reference validates these with sqlmock + real CI
containers (``sql/sql_mock.go:13-33``, ``go.yml:86-87``)."""

from __future__ import annotations

import dataclasses
import io

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.datasource.sql import (
    delete_by_query,
    insert_query,
    new_sql_from_config,
    register_sql_driver,
    select_by_query,
    update_by_query,
)
from gofr_tpu.datasource.sql.db import _DRIVER_REGISTRY
from gofr_tpu.datasource.sql.fakedb import (
    connect_fake_mysql,
    connect_fake_postgres,
)
from gofr_tpu.logging import Level, Logger


@pytest.fixture(autouse=True)
def _fake_drivers():
    register_sql_driver("mysql", connect_fake_mysql)
    register_sql_driver("postgres", connect_fake_postgres)
    yield
    _DRIVER_REGISTRY.clear()


@dataclasses.dataclass
class Book:
    id: int
    title: str
    pages: int


def _db(dialect: str):
    db = new_sql_from_config(MockConfig({
        "DB_DIALECT": dialect, "DB_HOST": "fake", "DB_NAME": "testdb",
    }))
    assert db is not None and db.dialect() == dialect
    return db


@pytest.mark.parametrize("dialect,ddl", [
    ("mysql",
     "CREATE TABLE `book` (`id` INT PRIMARY KEY AUTO_INCREMENT, "
     "`title` VARCHAR(64), `pages` INT)"),
    ("postgres",
     'CREATE TABLE "book" ("id" SERIAL PRIMARY KEY, '
     '"title" VARCHAR(64), "pages" INT)'),
])
def test_dialect_crud_roundtrip(dialect, ddl):
    """The query-builder statements (backticks+? vs quotes+$n) execute
    against the dialect peer: insert → select → update → delete."""
    db = _db(dialect)
    db.exec(ddl)
    res = db.exec(
        insert_query(dialect, "book", ["title", "pages"]), "Dune", 412
    )
    if dialect == "mysql":
        # Real postgres has no lastrowid (needs INSERT ... RETURNING);
        # only assert insert-id semantics where real drivers provide them.
        assert res.last_insert_id == 1
    db.exec(insert_query(dialect, "book", ["title", "pages"]), "Hyperion", 482)

    rows = db.select(Book, select_by_query(dialect, "book", "id"), 1)
    assert rows == [Book(id=1, title="Dune", pages=412)]

    res = db.exec(
        update_by_query(dialect, "book", ["pages"], "title"), 500, "Dune"
    )
    assert res.rows_affected == 1
    assert db.query_row(
        select_by_query(dialect, "book", "id"), 1
    )["pages"] == 500

    res = db.exec(delete_by_query(dialect, "book", "title"), "Hyperion")
    assert res.rows_affected == 1
    assert len(db.select(dict, f"SELECT * FROM {'`book`' if dialect == 'mysql' else chr(34) + 'book' + chr(34)}")) == 1


@pytest.mark.parametrize("dialect", ["mysql", "postgres"])
def test_dialect_transaction_commit_and_rollback(dialect):
    db = _db(dialect)
    db.exec("CREATE TABLE kv (k TEXT, v TEXT)")
    tx = db.begin()
    tx.exec(insert_query(dialect, "kv", ["k", "v"]), "a", "1")
    tx.commit()
    tx = db.begin()
    tx.exec(insert_query(dialect, "kv", ["k", "v"]), "b", "2")
    tx.rollback()
    assert [r["k"] for r in db.query("SELECT k FROM kv")] == ["a"]


@pytest.mark.parametrize("dialect", ["mysql", "postgres"])
def test_dialect_health_check(dialect):
    assert _db(dialect).health_check()["status"] == "UP"


def test_migrations_on_postgres_dialect():
    """The migration tracker writes dialect-aware SQL ($n bindvars)."""
    from gofr_tpu.container import Container
    from gofr_tpu.migration import Migrate, run

    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    c = Container.create(
        MockConfig({"DB_DIALECT": "postgres", "DB_NAME": "testdb"}),
        logger=logger,
    )
    assert c.sql is not None and c.sql.dialect() == "postgres"
    run({
        1: Migrate(up=lambda ds: ds.sql.exec(
            'CREATE TABLE "t" ("id" SERIAL PRIMARY KEY)'
        )),
    }, c)
    rows = c.sql.query("SELECT version FROM gofr_migrations")
    assert [r["version"] for r in rows] == [1]


def test_missing_driver_logs_and_returns_none():
    _DRIVER_REGISTRY.clear()  # no fakes, no real drivers in this image
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    db = new_sql_from_config(
        MockConfig({"DB_DIALECT": "postgres"}), logger=logger
    )
    if db is not None:  # a real psycopg2 exists in this environment
        pytest.skip("real postgres driver importable")
    assert "no driver" in out.getvalue()


def test_pyformat_adapter_translates_real_driver_params():
    """Real pymysql/psycopg2 speak %s pyformat, not ?/$n — the adapter
    must translate query text (and reorder args for repeated $n)."""
    from gofr_tpu.datasource.sql.db import _PyformatCursor

    class Capture:
        def execute(self, q, a):
            self.q, self.a = q, a

    cap = Capture()
    _PyformatCursor(cap, "mysql").execute(
        "INSERT INTO `b` (`t`, `p`) VALUES (?, ?)", ("x", 1)
    )
    assert cap.q == "INSERT INTO `b` (`t`, `p`) VALUES (%s, %s)"
    assert cap.a == ("x", 1)

    cap = Capture()
    _PyformatCursor(cap, "postgres").execute(
        'UPDATE "b" SET "p" = $2 WHERE "t" = $1 OR "u" = $1', ("x", 9)
    )
    assert cap.q == 'UPDATE "b" SET "p" = %s WHERE "t" = %s OR "u" = %s'
    assert cap.a == (9, "x", "x")


def test_pyformat_adapter_is_literal_aware():
    """?/$n inside quoted strings are data; raw % must escape to %% so
    pyformat can't trip on LIKE patterns."""
    from gofr_tpu.datasource.sql.db import _PyformatCursor

    class Capture:
        def execute(self, q, a):
            self.q, self.a = q, a

    cap = Capture()
    _PyformatCursor(cap, "mysql").execute(
        "SELECT * FROM t WHERE name LIKE '%a%' AND q = 'why?' AND id = ?",
        (5,),
    )
    assert cap.q == (
        "SELECT * FROM t WHERE name LIKE '%%a%%' AND q = 'why?' AND id = %s"
    )
    assert cap.a == (5,)

    cap = Capture()
    _PyformatCursor(cap, "postgres").execute(
        "SELECT * FROM t WHERE tag = 'cost $1' AND pct LIKE '5%' AND id = $1",
        (7,),
    )
    assert cap.q == (
        "SELECT * FROM t WHERE tag = 'cost $1' AND pct LIKE '5%%' AND id = %s"
    )
    assert cap.a == (7,)


def test_connect_failure_logs_and_returns_none():
    def boom(**_kw):
        raise ConnectionError("refused")

    register_sql_driver("mysql", boom)
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    db = new_sql_from_config(
        MockConfig({"DB_DIALECT": "mysql"}), logger=logger
    )
    assert db is None
    assert "could not connect" in out.getvalue()
