"""Metrics tests (parity with reference ``metrics/register_test.go`` behaviors)."""

import io

from gofr_tpu.logging import Level, Logger
from gofr_tpu.metrics import Manager, render_prometheus


def make_manager():
    out, err = io.StringIO(), io.StringIO()
    log = Logger(level=Level.DEBUG, out=out, err=err, is_terminal=False)
    return Manager(logger=log), out, err


def test_counter_roundtrip():
    m, _, _ = make_manager()
    m.new_counter("reqs", "request count")
    m.increment_counter("reqs", "path", "/hello", "method", "GET")
    m.increment_counter("reqs", "path", "/hello", "method", "GET")
    text = render_prometheus(m)
    assert 'reqs{method="GET",path="/hello"} 2.0' in text


def test_unregistered_metric_logs_error_not_raise():
    m, _, err = make_manager()
    m.increment_counter("nope")
    assert "not registered" in err.getvalue()


def test_duplicate_registration_logs_error():
    m, _, err = make_manager()
    m.new_counter("dup")
    m.new_counter("dup")
    assert "already registered" in err.getvalue()


def test_wrong_type_recording():
    m, _, err = make_manager()
    m.new_counter("c1")
    m.set_gauge("c1", 5.0)
    assert "not of type" in err.getvalue()


def test_odd_labels_logged():
    m, _, err = make_manager()
    m.new_counter("c2")
    m.increment_counter("c2", "only-key")
    assert "key/value" in err.getvalue()


def test_gauge_set_overwrites():
    m, _, _ = make_manager()
    m.new_gauge("hbm_used", "bytes")
    m.set_gauge("hbm_used", 10.0, "chip", "0")
    m.set_gauge("hbm_used", 20.0, "chip", "0")
    assert 'hbm_used{chip="0"} 20.0' in render_prometheus(m)


def test_updown_counter():
    m, _, _ = make_manager()
    m.new_updown_counter("inflight")
    m.delta_updown_counter("inflight", 2)
    m.delta_updown_counter("inflight", -1)
    assert "inflight 1.0" in render_prometheus(m)


def test_histogram_buckets_cumulative():
    m, _, _ = make_manager()
    m.new_histogram("lat", "latency", buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.5, 5.0, 50.0):
        m.record_histogram("lat", v)
    text = render_prometheus(m)
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1.0"} 2' in text
    assert 'lat_bucket{le="10.0"} 3' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text
    assert "lat_sum 55.55" in text


def test_histogram_le_inclusive():
    m, _, _ = make_manager()
    m.new_histogram("h2", buckets=[1.0, 2.0])
    m.record_histogram("h2", 1.0)  # exactly on a bound → le="1.0"
    assert 'h2_bucket{le="1.0"} 1' in render_prometheus(m)


def test_cardinality_warning():
    m, out, _ = make_manager()
    m.new_counter("wide")
    for i in range(25):
        m.increment_counter("wide", "id", str(i))
    assert "high cardinality" in out.getvalue()


def test_runtime_metrics_present():
    m, _, _ = make_manager()
    text = render_prometheus(m, app_name="test-app")
    assert "process_threads" in text
    assert 'app_info{app="test-app"' in text
