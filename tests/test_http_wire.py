"""Wire-level HTTP/1.1 robustness (the layer Go's net/http gives the
reference for free — ``http/proto.py`` implements it natively, so its
limits and error statuses need pinning against raw sockets: http.client
cannot send malformed requests)."""

from __future__ import annotations

import json
import socket

import pytest

from tests.test_http_server import AppHarness, make_app


@pytest.fixture(scope="module")
def wire_app():
    app = make_app()

    @app.post("/echo")
    def echo(ctx):
        return {"len": len(ctx.request.body or b"")}

    @app.get("/hello")
    def hello(ctx):
        return "hi"

    with AppHarness(app) as harness:
        yield harness


def _raw(harness, payload: bytes, recv_all=True) -> bytes:
    s = socket.create_connection(
        ("127.0.0.1", harness.app.http_port), timeout=10
    )
    try:
        s.sendall(payload)
        out = b""
        s.settimeout(10)
        while True:
            try:
                chunk = s.recv(65536)
            except socket.timeout:
                break
            if not chunk:
                break
            out += chunk
            if not recv_all and b"\r\n\r\n" in out:
                break
        return out
    finally:
        s.close()


def _status(resp: bytes) -> int:
    return int(resp.split(b" ", 2)[1])


def test_malformed_request_line_400(wire_app):
    assert _status(_raw(wire_app, b"GARBAGE\r\n\r\n")) == 400


def test_unsupported_version_505(wire_app):
    assert _status(_raw(wire_app, b"GET /hello HTTP/2.0\r\n\r\n")) == 505


def test_http10_is_accepted_and_closes_by_default(wire_app):
    resp = _raw(wire_app, b"GET /hello HTTP/1.0\r\n\r\n")
    assert _status(resp) == 200
    # HTTP/1.0 without keep-alive → server closes (Connection: close).
    assert b"Connection: close" in resp


def test_header_line_too_long_431(wire_app):
    big = b"x-big: " + b"a" * 9000
    resp = _raw(wire_app, b"GET /hello HTTP/1.1\r\n" + big + b"\r\n\r\n")
    assert _status(resp) == 431


def test_too_many_headers_431(wire_app):
    headers = b"".join(b"x-h%d: v\r\n" % i for i in range(150))
    resp = _raw(wire_app, b"GET /hello HTTP/1.1\r\n" + headers + b"\r\n")
    assert _status(resp) == 431


def test_malformed_header_400(wire_app):
    resp = _raw(wire_app, b"GET /hello HTTP/1.1\r\nno-colon-here\r\n\r\n")
    assert _status(resp) == 400


def test_bad_content_length_400(wire_app):
    resp = _raw(
        wire_app,
        b"POST /echo HTTP/1.1\r\ncontent-length: nope\r\n\r\n",
    )
    assert _status(resp) == 400
    resp = _raw(
        wire_app,
        b"POST /echo HTTP/1.1\r\ncontent-length: -5\r\n\r\n",
    )
    assert _status(resp) == 400


def test_oversized_content_length_413(wire_app):
    resp = _raw(
        wire_app,
        b"POST /echo HTTP/1.1\r\ncontent-length: 99999999999\r\n\r\n",
    )
    assert _status(resp) == 413


def test_chunked_body_roundtrip_with_trailers(wire_app):
    body = (
        b"POST /echo HTTP/1.1\r\n"
        b"transfer-encoding: chunked\r\n"
        b"content-type: application/json\r\n\r\n"
        b"5\r\nhello\r\n"
        b"6\r\n world\r\n"
        b"0\r\n"
        b"x-trailer: ignored\r\n"
        b"\r\n"
    )
    resp = _raw(wire_app, body)
    assert _status(resp) == 201  # POST envelope status
    payload = json.loads(resp.split(b"\r\n\r\n", 1)[1])
    assert payload["data"]["len"] == len(b"hello world")


def test_bad_chunk_size_400(wire_app):
    body = (
        b"POST /echo HTTP/1.1\r\n"
        b"transfer-encoding: chunked\r\n\r\n"
        b"zz\r\nhello\r\n0\r\n\r\n"
    )
    assert _status(_raw(wire_app, body)) == 400


def test_repeated_headers_comma_join(wire_app):
    app = wire_app.app

    @app.get("/hdr")
    def hdr(ctx):
        return {"via": ctx.request.headers.get("x-multi", "")}

    resp = _raw(
        wire_app,
        b"GET /hdr HTTP/1.1\r\nx-multi: a\r\nx-multi: b\r\n\r\n",
    )
    assert _status(resp) == 200
    payload = json.loads(resp.split(b"\r\n\r\n", 1)[1])
    assert payload["data"]["via"] == "a, b"
