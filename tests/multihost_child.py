"""Subprocess prefill-source pod for the multi-host chaos suite.

NOT a test module (no ``test_`` prefix): ``tests/test_tier_multihost.py``
spawns this as a REAL separate process — its own interpreter, its own
JAX runtime, its own transfer server — so the kill -9 cells sever live
sockets exactly like a dead pod, not like a mocked one.

Protocol (stdout, line-oriented, flushed):

* ``READY http=<port> ops=<port>`` once the app serves — the parent
  parses the ephemeral ports from this line;
* ``DMA-SERVE-STALLED`` the moment a dma fetch lands while
  ``MULTIHOST_CHILD_STALL=1`` — the parent's cue that the transfer is
  mid-flight and ``SIGKILL`` now is a genuine "died mid-DMA" cell.

The stall itself is the ordinary ``transfer.dma.serve`` fault seam with
a blocking action: the serve thread parks before sending one body byte,
pinning the importer inside its read budget.
"""

import asyncio
import os
import sys
import threading

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from gofr_tpu import App, faults  # noqa: E402
from gofr_tpu.config import MockConfig  # noqa: E402
from gofr_tpu.serving.openai_compat import add_openai_routes  # noqa: E402


def main() -> None:
    if os.environ.get("MULTIHOST_CHILD_STALL") == "1":
        def _stall(**_ctx) -> None:
            print("DMA-SERVE-STALLED", flush=True)
            threading.Event().wait(300.0)  # parked until SIGKILL

        faults.arm("transfer.dma.serve", action=_stall)

    app = App(config=MockConfig({
        "APP_NAME": "multihost-child", "HTTP_PORT": "0",
        "METRICS_PORT": "0", "TPU_MODEL": "llama-tiny",
        "TPU_KV_SLOTS": "4", "TPU_MAX_LEN": "256", "TPU_KV_BLOCK": "32",
        "TPU_AUTO_PREFIX": "true", "TPU_PREFILL_CHUNK": "32",
    }))
    add_openai_routes(app)

    loop = asyncio.new_event_loop()
    loop.run_until_complete(app.start())
    print(f"READY http={app.http_port} ops={app.metrics_port}", flush=True)
    try:
        loop.run_forever()  # only SIGKILL (or the parent's terminate) ends us
    finally:
        loop.run_until_complete(app.stop())
        loop.close()


if __name__ == "__main__":
    sys.exit(main())
