"""HTTP spine integration tests.

Mirrors the reference's example-app pattern (SURVEY §4): boot the real app
in-process on ephemeral ports and assert over real HTTP via http.client.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import threading
import time
from dataclasses import dataclass

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig
from gofr_tpu.errors import ErrorEntityNotFound
from gofr_tpu.http.response import File, Raw, Redirect


@dataclass
class Person:
    name: str = ""
    age: int = 0


class AppHarness:
    """Runs an App's asyncio lifecycle on a background thread."""

    def __init__(self, app: App) -> None:
        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)

    def __enter__(self) -> "AppHarness":
        self._thread.start()
        asyncio.run_coroutine_threadsafe(self.app.start(), self._loop).result(timeout=10)
        return self

    def __exit__(self, *exc) -> None:
        asyncio.run_coroutine_threadsafe(self.app.stop(), self._loop).result(timeout=10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    def request(self, method: str, path: str, body=None, headers=None, port=None):
        conn = http.client.HTTPConnection(
            "127.0.0.1", port or self.app.http_port, timeout=5
        )
        try:
            payload = None
            if body is not None:
                payload = json.dumps(body).encode() if not isinstance(body, bytes) else body
            conn.request(method, path, body=payload, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()


def make_app(**env) -> App:
    cfg = {"HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": "test-app", **env}
    return App(config=MockConfig(cfg))


@pytest.fixture
def app_harness():
    app = make_app()

    @app.get("/hello")
    def hello(ctx):
        return f"Hello {ctx.param('name') or 'World'}!"

    @app.get("/items/{id}")
    def item(ctx):
        return {"id": ctx.path_param("id")}

    @app.post("/people")
    def create_person(ctx):
        p = ctx.bind(Person)
        return {"name": p.name, "age": p.age}

    @app.delete("/items/{id}")
    def delete_item(ctx):
        return None

    @app.get("/missing")
    def missing(ctx):
        raise ErrorEntityNotFound("id", "42")

    @app.get("/crash")
    def crash(ctx):
        raise RuntimeError("kaboom")

    @app.get("/raw")
    def raw(ctx):
        return Raw([1, 2, 3])

    @app.get("/file")
    def file(ctx):
        return File(content=b"bytes!", content_type="text/plain")

    @app.get("/redirect")
    def redirect(ctx):
        return Redirect("/hello")

    @app.get("/async")
    async def async_handler(ctx):
        await asyncio.sleep(0.01)
        return "async ok"

    with AppHarness(app) as harness:
        yield harness


def test_hello_envelope(app_harness):
    status, headers, body = app_harness.request("GET", "/hello?name=TPU")
    assert status == 200
    assert json.loads(body) == {"data": "Hello TPU!"}
    assert headers.get("Content-Type") == "application/json"
    assert headers.get("X-Correlation-ID")  # trace id surfaced


def test_path_params(app_harness):
    status, _, body = app_harness.request("GET", "/items/abc123")
    assert status == 200
    assert json.loads(body) == {"data": {"id": "abc123"}}


def test_post_bind_and_201(app_harness):
    status, _, body = app_harness.request(
        "POST", "/people", body={"name": "Ada", "age": 36}
    )
    assert status == 201
    assert json.loads(body) == {"data": {"name": "Ada", "age": 36}}


def test_delete_204(app_harness):
    status, _, body = app_harness.request("DELETE", "/items/1")
    assert status == 204
    assert body == b""


def test_typed_error_maps_status(app_harness):
    status, _, body = app_harness.request("GET", "/missing")
    assert status == 404
    assert json.loads(body) == {"error": {"message": "No entity found with id: 42"}}


def test_panic_recovery_500(app_harness):
    status, _, body = app_harness.request("GET", "/crash")
    assert status == 500
    assert json.loads(body)["error"]["message"] == "some unexpected error has occurred"


def test_route_not_registered_404(app_harness):
    status, _, body = app_harness.request("GET", "/nope")
    assert status == 404
    assert "error" in json.loads(body)


def test_method_not_allowed_405(app_harness):
    status, _, _ = app_harness.request("PUT", "/hello")
    assert status == 405


def test_raw_file_redirect(app_harness):
    status, _, body = app_harness.request("GET", "/raw")
    assert (status, json.loads(body)) == (200, [1, 2, 3])

    status, headers, body = app_harness.request("GET", "/file")
    assert (status, body) == (200, b"bytes!")
    assert headers["Content-Type"] == "text/plain"

    status, headers, _ = app_harness.request("GET", "/redirect")
    assert status == 302
    assert headers["Location"] == "/hello"


def test_async_handler(app_harness):
    status, _, body = app_harness.request("GET", "/async")
    assert json.loads(body) == {"data": "async ok"}


def test_wellknown_health_and_alive(app_harness):
    status, _, body = app_harness.request("GET", "/.well-known/alive")
    assert (status, json.loads(body)["data"]["status"]) == (200, "UP")

    status, _, body = app_harness.request("GET", "/.well-known/health")
    data = json.loads(body)["data"]
    assert data["status"] == "UP"
    assert data["name"] == "test-app"


def test_cors_preflight(app_harness):
    status, headers, _ = app_harness.request("OPTIONS", "/hello")
    assert status == 200
    assert headers["Access-Control-Allow-Origin"] == "*"


def test_metrics_server_scrape(app_harness):
    app_harness.request("GET", "/hello")  # generate a sample
    status, headers, body = app_harness.request(
        "GET", "/metrics", port=app_harness.app.metrics_port
    )
    assert status == 200
    text = body.decode()
    assert "app_http_response_bucket" in text
    assert 'path="/hello"' in text
    assert "process_threads" in text


def test_keepalive_multiple_requests(app_harness):
    conn = http.client.HTTPConnection("127.0.0.1", app_harness.app.http_port, timeout=5)
    try:
        for _ in range(3):
            conn.request("GET", "/hello")
            resp = conn.getresponse()
            assert resp.status == 200
            resp.read()
    finally:
        conn.close()


def test_shutdown_drains_inflight_request():
    """A request mid-handler at shutdown still gets its response."""
    import concurrent.futures

    app = make_app()

    @app.get("/slow")
    async def slow(ctx):
        await asyncio.sleep(0.8)
        return "made it"

    harness = AppHarness(app)
    harness.__enter__()
    try:
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            fut = pool.submit(harness.request, "GET", "/slow")
            time.sleep(0.2)  # request is in-flight now
            stop = pool.submit(
                lambda: asyncio.run_coroutine_threadsafe(
                    harness.app.stop(), harness._loop
                ).result(timeout=15)
            )
            status, _, body = fut.result(timeout=15)
            stop.result(timeout=15)
        assert status == 200
        assert json.loads(body) == {"data": "made it"}
    finally:
        harness._loop.call_soon_threadsafe(harness._loop.stop)
        harness._thread.join(timeout=5)
        harness._loop.close()


def test_favicon(app_harness):
    status, headers, body = app_harness.request("GET", "/favicon.ico")
    assert status == 200
    assert headers["Content-Type"] == "image/x-icon"
    assert body[:4] == b"\x00\x00\x01\x00"


def test_debug_endpoints_on_metrics_port(app_harness):
    # /debug/threads: a live thread dump (the tool that diagnoses a
    # wedged device dispatch without restarting the server).
    status, _, body = app_harness.request(
        "GET", "/debug/threads", port=app_harness.app.metrics_port
    )
    assert status == 200
    assert b"Thread" in body or b"thread" in body
    # /debug/engine: no engine configured → empty JSON object.
    status, headers, body = app_harness.request(
        "GET", "/debug/engine", port=app_harness.app.metrics_port
    )
    assert status == 200
    assert json.loads(body) == {}


def test_multipart_binary_byte_fidelity():
    """The multipart parser strips exactly the delimiter CRLFs: file
    data containing interior AND trailing CR/LF bytes round-trips
    byte-exact (a JSONL upload keeps its trailing newline; a binary
    blob with \\r\\n sequences is untouched)."""
    from gofr_tpu.http.proto import RawRequest
    from gofr_tpu.http.request import Request

    payload = b"\r\nbinary\r\nwith\nnewlines\r\n\r\n"
    boundary = "bb7"
    body = (
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="purpose"\r\n\r\nbatch\r\n'
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="file"; '
        f'filename="blob.bin"\r\n'
        f"Content-Type: application/octet-stream\r\n\r\n"
    ).encode() + payload + f"\r\n--{boundary}--\r\n".encode()
    req = Request(RawRequest(
        method="POST", target="/up", version="HTTP/1.1",
        headers={
            "content-type": f"multipart/form-data; boundary={boundary}"
        },
        body=body,
    ))
    bound = req.bind({})
    assert bound["purpose"] == "batch"
    assert bound["file"].data == payload
    assert bound["file"].filename == "blob.bin"


def test_shutdown_drain_timeout_closes_stragglers():
    """A handler that outlives the drain window is forcibly closed and
    the timeout is logged — shutdown must never hang on one slow
    request (SURVEY §7 hard-part 5)."""
    import concurrent.futures

    app = make_app()

    @app.get("/stuck")
    async def stuck(ctx):
        await asyncio.sleep(30)
        return "never"

    harness = AppHarness(app)
    harness.__enter__()
    try:
        harness.app._http_server.drain_timeout_s = 0.3
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            fut = pool.submit(harness.request, "GET", "/stuck")
            time.sleep(0.2)  # in-flight now
            t0 = time.time()
            asyncio.run_coroutine_threadsafe(
                harness.app.stop(), harness._loop
            ).result(timeout=15)
            assert time.time() - t0 < 10  # did not wait the full 30s
            with pytest.raises(Exception):
                fut.result(timeout=15)  # connection was reset, not served
    finally:
        harness._loop.call_soon_threadsafe(harness._loop.stop)
        harness._thread.join(timeout=5)
        harness._loop.close()
