"""Int8 KV cache (VERDICT r2 next #9): quantized-cache attention matches
the bf16 cache within quantization tolerance, at every level — the
quantize/dequant ops, the flash kernels (interpret mode), the decode/
prefill steps, and the serving engine end to end."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.registry import get_model
from gofr_tpu.models.transformer import (
    transformer_decode_step,
    transformer_prefill_chunk,
)
from gofr_tpu.ops.attention import cache_chunk_attention, decode_attention
from gofr_tpu.ops.kv_cache import KVCache, quantize_kv
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer


def test_quantize_kv_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 64), jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (4, 2)
    recon = q.astype(jnp.float32) * s[..., None]
    np.testing.assert_allclose(recon, x, atol=float(jnp.abs(x).max()) / 120)


def test_kv_cache_create_int8_halves_bytes():
    bf16 = KVCache.create(2, 4, 128, 2, 64)
    q8 = KVCache.create(2, 4, 128, 2, 64, quant="int8")
    assert q8.quantized and not bf16.quantized
    assert q8.k.dtype == jnp.int8
    assert q8.hbm_bytes() < bf16.hbm_bytes()
    with pytest.raises(ValueError):
        KVCache.create(2, 4, 128, 2, 64, quant="int4")


def _filled_cache(key, b, n_kv, max_len, hd, lengths):
    """bf16 cache + its int8 twin holding the same values."""
    k = jax.random.normal(key, (b, n_kv, max_len, hd), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 1), k.shape, jnp.float32)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    rep8 = lambda s: jnp.broadcast_to(  # noqa: E731
        s[:, :, None, :], (b, n_kv, 8, max_len)
    ).astype(jnp.float32)
    return (
        k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
        kq, vq, rep8(ks), rep8(vs), jnp.asarray(lengths, jnp.int32),
    )


@pytest.mark.parametrize("kernel", [False, True])
def test_int8_decode_attention_matches_bf16(kernel):
    b, n_kv, max_len, hd, n_heads = 4, 2, 128, 64, 4
    k, v, kq, vq, ks, vs, lens = _filled_cache(
        jax.random.PRNGKey(2), b, n_kv, max_len, hd, [5, 64, 128, 1]
    )
    q = jax.random.normal(jax.random.PRNGKey(3), (b, n_heads, hd), jnp.bfloat16)
    want = decode_attention(q, k, v, lens, kernel=kernel)
    got = decode_attention(
        q, kq, vq, lens, k_scale=ks, v_scale=vs, kernel=kernel
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=0.05, rtol=0.05,
    )


@pytest.mark.parametrize("kernel", [False, True])
def test_int8_chunk_attention_matches_bf16(kernel):
    S, n_kv, max_len, hd, n_heads, P, c = 4, 2, 128, 64, 4, 2, 16
    k, v, kq, vq, ks, vs, _ = _filled_cache(
        jax.random.PRNGKey(4), S, n_kv, max_len, hd, [0] * S
    )
    q = jax.random.normal(
        jax.random.PRNGKey(5), (P, c, n_heads, hd), jnp.bfloat16
    )
    slots = jnp.asarray([0, 2], jnp.int32)
    starts = jnp.asarray([8, 32], jnp.int32)
    lens = jnp.asarray([16, 9], jnp.int32)
    want = cache_chunk_attention(q, k, v, slots, starts, lens, kernel=kernel)
    got = cache_chunk_attention(
        q, kq, vq, slots, starts, lens, k_scale=ks, v_scale=vs, kernel=kernel
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=0.05, rtol=0.05,
    )


def test_prefill_chunk_and_decode_steps_with_int8_cache():
    """Full steps write quantized K/V + scales and stay numerically close
    to the bf16-cache steps."""
    spec = get_model("llama-tiny")
    cfg = spec.config
    params = spec.init(jax.random.PRNGKey(0), cfg)
    S, max_len = 2, 64
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    mk = lambda q: KVCache.create(  # noqa: E731
        cfg.n_layers, S, max_len, cfg.n_kv_heads, cfg.head_dim,
        cfg.dtype, quant=q,
    )
    out = {}
    for mode in ("", "int8"):
        cache = mk(mode)
        logits, cache = transformer_prefill_chunk(
            params, tokens, cache,
            jnp.asarray([0], jnp.int32), jnp.asarray([0], jnp.int32),
            jnp.asarray([8], jnp.int32), cfg,
        )
        cache = cache._replace(lengths=cache.lengths.at[0].set(8))
        step_logits, cache = transformer_decode_step(
            params, jnp.asarray([9, 0], jnp.int32), cache,
            jnp.asarray([True, False]), cfg,
        )
        out[mode] = (np.asarray(logits), np.asarray(step_logits))
        if mode == "int8":
            assert cache.k.dtype == jnp.int8
            # Prompt positions got real scales; untouched tail stays 1.0.
            assert float(jnp.max(cache.k_s[0, 0, 0, 0, :8])) < 1.0
            assert float(cache.k_s[0, 0, 0, 0, -2]) == 1.0
    scale = np.abs(out[""][0]).max()
    np.testing.assert_allclose(
        out["int8"][0], out[""][0], atol=0.05 * scale, rtol=0.1
    )
    np.testing.assert_allclose(
        out["int8"][1][0], out[""][1][0], atol=0.05 * scale, rtol=0.1
    )


def test_engine_serves_with_int8_kv_cache():
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        kv_quant="int8",
    )
    assert eng.cache.quantized
    eng.start_sync()
    try:
        r1 = eng.generate_sync(
            "kv quant", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
        r2 = eng.generate_sync(
            "kv quant", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
    finally:
        eng.stop_sync()
    assert len(r1.token_ids) == 8
    assert r1.token_ids == r2.token_ids  # deterministic across slots/steps


def test_engine_int8_kv_from_config_with_mesh():
    """TPU_KV_QUANT composes with TPU_MESH_TP (+ weight int8): the full
    production stack boots and generates."""
    from gofr_tpu.config import MockConfig

    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
        "TPU_MESH_TP": "2", "TPU_QUANT": "int8", "TPU_KV_QUANT": "int8",
    }))
    assert eng.cache.quantized and eng.quant == "int8"
    assert "tp" in str(eng.cache.k_s.sharding.spec)
    eng.start_sync()
    try:
        r = eng.generate_sync(
            "all together", max_new_tokens=6, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        eng.stop_sync()
    assert len(r.token_ids) == 6
