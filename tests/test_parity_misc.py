"""CRUD generator, auth middleware, file/zip, testutil, checkpoint tests."""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import io
import json
import threading
import time
import zipfile
from dataclasses import dataclass

import pytest

from gofr_tpu import App
from gofr_tpu.config import MockConfig


@dataclass
class Book:
    id: int = 0
    title: str = ""
    author_name: str = ""


class Harness:
    def __init__(self, app):
        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)

    def __enter__(self):
        self._thread.start()
        asyncio.run_coroutine_threadsafe(self.app.start(), self._loop).result(10)
        return self

    def __exit__(self, *exc):
        asyncio.run_coroutine_threadsafe(self.app.stop(), self._loop).result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    def request(self, method, path, body=None, headers=None):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", self.app.http_port, timeout=10)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            hdrs = {"Content-Type": "application/json", **(headers or {})}
            conn.request(method, path, body=payload, headers=hdrs)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"null")
        finally:
            conn.close()


# ---------------- CRUD generator ----------------


def test_crud_full_lifecycle():
    app = App(config=MockConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "DB_DIALECT": "sqlite", "DB_NAME": ":memory:",
    }))
    app.container.sql.exec(
        "CREATE TABLE book (id INTEGER PRIMARY KEY, title TEXT, author_name TEXT)"
    )
    app.add_rest_handlers(Book)

    with Harness(app) as h:
        status, body = h.request(
            "POST", "/book", {"id": 1, "title": "Dune", "author_name": "Herbert"}
        )
        assert status == 201
        assert "successfully created" in body["data"]

        status, body = h.request("GET", "/book")
        assert status == 200
        assert body["data"] == [
            {"id": 1, "title": "Dune", "author_name": "Herbert"}
        ]

        status, body = h.request("GET", "/book/1")
        assert body["data"]["title"] == "Dune"

        status, body = h.request(
            "PUT", "/book/1", {"title": "Dune II", "author_name": "Herbert"}
        )
        assert "successfully updated" in body["data"]

        status, body = h.request("GET", "/book/99")
        assert status == 404

        status, body = h.request("DELETE", "/book/1")
        assert status == 204  # DELETE strips the body (responder.go:27-41)
        status, _ = h.request("GET", "/book/1")
        assert status == 404


def test_crud_scan_entity_and_snake_case():
    from gofr_tpu.crud import scan_entity, to_snake_case

    assert to_snake_case("AuthorName") == "author_name"
    assert to_snake_case("HTTPServer") == "http_server"
    table, cols, pk = scan_entity(Book)
    assert (table, pk) == ("book", "id")
    assert cols == ["id", "title", "author_name"]
    with pytest.raises(TypeError):
        scan_entity(dict)


# ---------------- auth middleware through the app ----------------


def test_basic_auth_enabled_app():
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    app.get("/secret", lambda ctx: "classified")
    app.enable_basic_auth({"admin": "pw123"})
    with Harness(app) as h:
        status, _ = h.request("GET", "/secret")
        assert status == 401
        token = base64.b64encode(b"admin:pw123").decode()
        status, body = h.request(
            "GET", "/secret", headers={"Authorization": f"Basic {token}"}
        )
        assert (status, body["data"]) == (200, "classified")
        # well-known stays open (reference validate.go:5-7)
        status, _ = h.request("GET", "/.well-known/alive")
        assert status == 200


def test_api_key_auth_enabled_app():
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    app.get("/secret", lambda ctx: "classified")
    app.enable_api_key_auth("key-1", "key-2")
    with Harness(app) as h:
        assert h.request("GET", "/secret")[0] == 401
        status, _ = h.request("GET", "/secret", headers={"X-API-KEY": "key-2"})
        assert status == 200


def test_oauth_hs256_jwt_middleware():
    from gofr_tpu.http.middleware import oauth_middleware

    secret = b"shh"
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))

    @app.get("/claims")
    def claims(ctx):
        return ctx.get("JWTClaims")

    app.use_middleware(oauth_middleware(hs_secret=secret))

    def make_jwt(payload: dict) -> str:
        def b64(b: bytes) -> str:
            return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

        header = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        body = b64(json.dumps(payload).encode())
        sig = hmac.new(secret, f"{header}.{body}".encode(), hashlib.sha256).digest()
        return f"{header}.{body}.{b64(sig)}"

    with Harness(app) as h:
        assert h.request("GET", "/claims")[0] == 401
        good = make_jwt({"sub": "ada", "exp": time.time() + 60})
        status, body = h.request(
            "GET", "/claims", headers={"Authorization": f"Bearer {good}"}
        )
        assert status == 200
        assert body["data"]["sub"] == "ada"

        expired = make_jwt({"sub": "ada", "exp": time.time() - 10})
        status, body = h.request(
            "GET", "/claims", headers={"Authorization": f"Bearer {expired}"}
        )
        assert status == 401
        assert "expired" in body["error"]["message"]

        tampered = good[:-4] + "AAAA"
        assert h.request(
            "GET", "/claims", headers={"Authorization": f"Bearer {tampered}"}
        )[0] == 401


# ---------------- file / zip ----------------


def test_zip_roundtrip_and_local_copies(tmp_path):
    from gofr_tpu.file import Zip

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("a.txt", "hello")
        zf.writestr("sub/b.txt", "world")
        zf.writestr("../evil.txt", "nope")
    z = Zip(buf.getvalue())
    assert z.files["a.txt"] == b"hello"
    written = z.create_local_copies(str(tmp_path))
    assert (tmp_path / "a.txt").read_text() == "hello"
    assert (tmp_path / "sub" / "b.txt").read_text() == "world"
    assert not (tmp_path.parent / "evil.txt").exists()
    assert len(written) == 2


def test_zip_bomb_guard():
    from gofr_tpu.file import Zip, ZipBombError

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("big.bin", b"\0" * (101 * 1024 * 1024))
    with pytest.raises(ZipBombError):
        Zip(buf.getvalue())


# ---------------- testutil ----------------


def test_testutil_capture_and_mock_logger():
    from gofr_tpu.logging import Level
    from gofr_tpu.testutil import (
        CustomError,
        MockLogger,
        stdout_output_for_func,
    )

    out = stdout_output_for_func(lambda: print("captured!"))
    assert out == "captured!\n"

    log = MockLogger()
    log.infof("x=%d", 5)
    log.error("bad")
    assert log.messages_at(Level.INFO) == ["x=5"]
    assert log.messages_at(Level.ERROR) == ["bad"]
    with pytest.raises(SystemExit):
        log.fatal("die")
    assert str(CustomError("msg")) == "msg"


# ---------------- checkpoint ----------------


def test_checkpoint_roundtrip(tmp_path):
    import jax
    import numpy as np

    from gofr_tpu.models.registry import get_model
    from gofr_tpu.serving.checkpoint import (
        maybe_restore_params,
        restore_checkpoint,
        save_checkpoint,
    )

    spec = get_model("llama-tiny")
    params = spec.init(jax.random.PRNGKey(7), spec.config)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = restore_checkpoint(path, like=params)
    np.testing.assert_array_equal(
        np.asarray(params["layers"]["wq"], dtype=np.float32),
        np.asarray(restored["layers"]["wq"], dtype=np.float32),
    )

    # Engine boot seam: TPU_CHECKPOINT swaps random init for the checkpoint.
    other = spec.init(jax.random.PRNGKey(8), spec.config)
    cfg = MockConfig({"TPU_CHECKPOINT": path})
    swapped = maybe_restore_params(cfg, other)
    np.testing.assert_array_equal(
        np.asarray(swapped["layers"]["wq"], dtype=np.float32),
        np.asarray(params["layers"]["wq"], dtype=np.float32),
    )
