"""Datasource tests (reference patterns: sqlmock for SQL, miniredis for
Redis, mocked brokers for pub/sub — SURVEY §4)."""

from __future__ import annotations

import io
from dataclasses import dataclass, field

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.datasource.pubsub import InProcBroker, new_pubsub_from_config
from gofr_tpu.datasource.redis import MiniRedis, Redis, new_redis_from_config
from gofr_tpu.datasource.sql import (
    delete_by_query,
    insert_query,
    new_sql_from_config,
    select_by_query,
    select_query,
    update_by_query,
)
from gofr_tpu.logging import Level, Logger


@dataclass
class Employee:
    id: int = 0
    name: str = ""
    dept_name: str = field(default="", metadata={"db": "department"})


# ---------------- SQL ----------------


@pytest.fixture
def db():
    cfg = MockConfig({"DB_DIALECT": "sqlite", "DB_NAME": ":memory:"})
    db = new_sql_from_config(cfg)
    assert db is not None
    db.exec("CREATE TABLE employee (id INTEGER PRIMARY KEY, name TEXT, department TEXT)")
    yield db
    db.close()


def test_sql_exec_query_roundtrip(db):
    res = db.exec("INSERT INTO employee (name, department) VALUES (?, ?)", "Ada", "eng")
    assert res.last_insert_id == 1
    rows = db.query("SELECT * FROM employee")
    assert rows == [{"id": 1, "name": "Ada", "department": "eng"}]
    assert db.query_row("SELECT name FROM employee WHERE id = ?", 1) == {"name": "Ada"}
    assert db.query_row("SELECT name FROM employee WHERE id = ?", 99) is None


def test_sql_select_binds_dataclass(db):
    db.exec("INSERT INTO employee (name, department) VALUES (?, ?)", "Ada", "eng")
    out = db.select(Employee, "SELECT * FROM employee")
    assert out == [Employee(id=1, name="Ada", dept_name="eng")]


def test_sql_transactions_commit_and_rollback(db):
    tx = db.begin()
    tx.exec("INSERT INTO employee (name) VALUES (?)", "A")
    tx.commit()
    assert len(db.query("SELECT * FROM employee")) == 1

    tx = db.begin()
    tx.exec("INSERT INTO employee (name) VALUES (?)", "B")
    tx.rollback()
    assert len(db.query("SELECT * FROM employee")) == 1


def test_sql_health(db):
    h = db.health_check()
    assert h["status"] == "UP"
    assert h["details"]["dialect"] == "sqlite"


def test_sql_unconfigured_returns_none():
    assert new_sql_from_config(MockConfig({})) is None


def test_query_builder_dialects():
    assert (
        insert_query("mysql", "user", ["id", "name"])
        == "INSERT INTO `user` (`id`, `name`) VALUES (?, ?)"
    )
    assert (
        insert_query("postgres", "user", ["id", "name"])
        == 'INSERT INTO "user" ("id", "name") VALUES ($1, $2)'
    )
    assert select_query("mysql", "user") == "SELECT * FROM `user`"
    assert (
        select_by_query("postgres", "user", "id") == 'SELECT * FROM "user" WHERE "id" = $1'
    )
    assert (
        update_by_query("mysql", "user", ["name"], "id")
        == "UPDATE `user` SET `name` = ? WHERE `id` = ?"
    )
    assert (
        delete_by_query("postgres", "user", "id") == 'DELETE FROM "user" WHERE "id" = $1'
    )


# ---------------- Redis ----------------


@pytest.fixture
def mini():
    server = MiniRedis().start()
    yield server
    server.stop()


@pytest.fixture
def redis_client(mini):
    client = Redis("127.0.0.1", mini.port)
    yield client
    client.close()


def test_redis_strings(redis_client):
    assert redis_client.set("k", "v") == "OK"
    assert redis_client.get("k") == "v"
    assert redis_client.get("missing") is None
    assert redis_client.delete("k") == 1
    assert redis_client.exists("k") == 0


def test_redis_incr_expire_ttl(redis_client):
    assert redis_client.incr("n") == 1
    assert redis_client.incr("n") == 2
    assert redis_client.expire("n", 100) == 1
    assert 0 < redis_client.ttl("n") <= 100


def test_redis_hashes(redis_client):
    redis_client.hset("h", "a", "1", "b", "2")
    assert redis_client.hget("h", "a") == "1"
    assert redis_client.hgetall("h") == {"a": "1", "b": "2"}
    assert redis_client.hdel("h", "a") == 1


def test_redis_lists_and_sets(redis_client):
    redis_client.rpush("l", "1", "2", "3")
    assert redis_client.lrange("l", 0, -1) == ["1", "2", "3"]
    redis_client.sadd("s", "x", "y", "x")
    assert sorted(redis_client.smembers("s")) == ["x", "y"]


def test_redis_tx_pipeline(redis_client):
    pipe = redis_client.tx_pipeline()
    pipe.set("a", "1").hset("h2", "f", "v")
    replies = pipe.exec()
    assert len(replies) == 2
    assert redis_client.get("a") == "1"


def test_redis_health_and_logging(mini):
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    cfg = MockConfig({"REDIS_HOST": "127.0.0.1", "REDIS_PORT": str(mini.port)})
    client = new_redis_from_config(cfg, logger=logger)
    assert client is not None
    assert client.health_check()["status"] == "UP"
    client.get("x")
    assert "REDIS" in out.getvalue()
    client.close()


def test_redis_unreachable_returns_none():
    cfg = MockConfig({"REDIS_HOST": "127.0.0.1", "REDIS_PORT": "1"})
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    assert new_redis_from_config(cfg, logger=logger) is None
    assert "could not connect" in out.getvalue()


# ---------------- PubSub ----------------


def test_inproc_publish_subscribe_commit():
    broker = InProcBroker()
    broker.publish("orders", b'{"id": 1}')
    msg = broker.subscribe("orders", timeout=1)
    assert msg is not None
    assert msg.topic == "orders"
    assert msg.json() == {"id": 1}
    assert msg.param("topic") == "orders"
    msg.commit()
    assert msg.committed


def test_inproc_subscribe_timeout_returns_none():
    broker = InProcBroker()
    assert broker.subscribe("empty", timeout=0.05) is None


def test_pubsub_factory():
    assert new_pubsub_from_config(MockConfig({})) is None
    broker = new_pubsub_from_config(MockConfig({"PUBSUB_BACKEND": "INPROC"}))
    assert isinstance(broker, InProcBroker)
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    assert new_pubsub_from_config(MockConfig({"PUBSUB_BACKEND": "KAFKA"}), logger) is None
    assert "KAFKA" in out.getvalue()


def test_message_bind():
    from gofr_tpu.datasource.pubsub.base import Message

    @dataclass
    class Order:
        id: int = 0
        item: str = ""

    msg = Message("t", b'{"id": 7, "item": "gpu"}')
    order = msg.bind(Order)
    assert order == Order(id=7, item="gpu")
