"""Mongo injection seam: Protocol conformance, CRUD double, container wiring."""

from __future__ import annotations

from gofr_tpu.config import MockConfig
from gofr_tpu.container import Container
from gofr_tpu.datasource.mongo import InMemoryMongo, Mongo


def test_inmemory_mongo_satisfies_protocol():
    assert isinstance(InMemoryMongo(), Mongo)


def test_crud_roundtrip():
    db = InMemoryMongo()
    uid = db.insert_one("users", {"name": "ada", "role": "admin"})
    db.insert_many("users", [{"name": "bo"}, {"name": "cy", "role": "admin"}])

    out: list = []
    db.find("users", {"role": "admin"}, out)
    assert {d["name"] for d in out} == {"ada", "cy"}

    one: dict = {}
    db.find_one("users", {"name": "bo"}, one)
    assert one["name"] == "bo"

    assert db.update_by_id("users", uid, {"$set": {"role": "owner"}}) == 1
    assert db.count_documents("users", {"role": "owner"}) == 1
    assert db.update_many("users", {}, {"$set": {"active": True}}) == 3
    db.update_one("users", {"name": "ada"}, {"$inc": {"logins": 2}})
    one: dict = {}
    one.clear(); db.find_one("users", {"name": "ada"}, one)
    assert one["logins"] == 2
    db.update_one("users", {"name": "ada"}, {"$unset": {"logins": ""}})
    # Operator-less updates are rejected like real MongoDB.
    import pytest as _pytest
    with _pytest.raises(ValueError, match="operators"):
        db.update_one("users", {"name": "ada"}, {"role": "boss"})
    assert db.delete_one("users", {"name": "bo"}) == 1
    assert db.delete_many("users", {}) == 2
    db.drop("users")
    assert db.count_documents("users", {}) == 0


def test_container_injection_and_health():
    c = Container(MockConfig({}))
    db = InMemoryMongo()
    c.use_mongo(db)
    assert c.mongo is db
    health = c.health()
    assert health["details"]["mongo"]["status"] == "UP"


def test_use_pubsub_injection():
    from gofr_tpu.datasource.pubsub import InProcBroker

    c = Container(MockConfig({}))
    broker = InProcBroker()
    c.use_pubsub(broker)
    assert c.get_publisher() is broker and c.get_subscriber() is broker
