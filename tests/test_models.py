"""Model correctness tests on the CPU backend.

The load-bearing test is prefill+decode vs. full-forward equivalence: the
serving path (KV cache, RoPE offsets, padding masks) must reproduce the
training path logits token for token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.bert import bert_embed, init_bert
from gofr_tpu.models.registry import get_model, list_models
from gofr_tpu.models.resnet import init_resnet, resnet_forward
from gofr_tpu.models.transformer import (
    init_transformer,
    transformer_decode_step,
    transformer_forward,
    transformer_prefill,
)
from gofr_tpu.ops.kv_cache import KVCache


@pytest.fixture(scope="module")
def tiny():
    spec = get_model("llama-tiny")
    cfg = spec.config
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_registry_contents():
    names = list_models()
    for expected in ("llama-3-8b", "llama-1b", "llama-tiny", "moe-tiny", "bert-base", "resnet-50"):
        assert expected in names
    with pytest.raises(KeyError):
        get_model("nope")


def test_forward_shapes_and_finiteness(tiny):
    cfg, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = transformer_forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_param_count_flagship_configs():
    cfg8b = get_model("llama-3-8b").config
    # Count without materializing: eval_shape.
    shapes = jax.eval_shape(lambda k: init_transformer(k, cfg8b), jax.random.PRNGKey(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(shapes))
    assert 7.5e9 < n < 8.7e9  # Llama-3-8B ballpark (incl. untied lm_head)


def test_prefill_decode_matches_full_forward():
    """Serving path == training path, token for token (f32 so the comparison
    is precision-tight; bf16 paths diverge only by rounding)."""
    import dataclasses

    cfg = dataclasses.replace(get_model("llama-tiny").config, dtype=jnp.float32)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    b, prompt_len, gen_len = 2, 10, 5
    total = prompt_len + gen_len
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (b, total), 0, cfg.vocab_size)

    # Ground truth: full causal forward over the whole sequence.
    full_logits = transformer_forward(params, tokens, cfg)

    # Serving path: prefill the prompt, then decode one token at a time
    # (teacher-forced with the same tokens so logits must match).
    cache = KVCache.create(
        cfg.n_layers, n_slots=4, max_len=64, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, dtype=cfg.dtype,
    )
    slots = jnp.array([0, 2])  # non-contiguous slots on purpose
    lengths = jnp.array([prompt_len, prompt_len])
    logits_p, cache = transformer_prefill(
        params, tokens[:, :prompt_len], lengths, cache, slots, cfg
    )
    np.testing.assert_allclose(
        np.asarray(logits_p),
        np.asarray(full_logits[:, prompt_len - 1]),
        rtol=1e-4, atol=1e-4,
    )

    # Decode runs over ALL slots; place each sequence's token at its slot and
    # mark only those slots active.
    active = jnp.zeros((4,), dtype=bool).at[slots].set(True)
    for step in range(gen_len):
        pos = prompt_len + step
        slot_tokens = jnp.zeros((4,), dtype=tokens.dtype).at[slots].set(tokens[:, pos])
        logits_d, cache = transformer_decode_step(
            params, slot_tokens, cache, active, cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits_d[slots]),
            np.asarray(full_logits[:, pos]),
            rtol=1e-4, atol=1e-4,
            err_msg=f"decode step {step} diverged from full forward",
        )
    assert cache.lengths[0] == prompt_len + gen_len
    assert cache.lengths[1] == 0  # inactive slot length untouched


def test_verify_step_matches_sequential_decode():
    """Speculative verify (c tokens, read-only cache, one pass) must produce
    the same logits as feeding those c tokens through sequential decode
    steps, and commit_chunk_kv must leave the same cache behind."""
    import dataclasses

    from gofr_tpu.models.transformer import (
        commit_chunk_kv,
        transformer_prefill,
        transformer_verify_step,
    )

    cfg = dataclasses.replace(get_model("llama-tiny").config, dtype=jnp.float32)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    b, prompt_len, c = 2, 9, 4
    key = jax.random.PRNGKey(5)
    tokens = jax.random.randint(key, (b, prompt_len + c), 0, cfg.vocab_size)

    def fresh_cache():
        cache = KVCache.create(
            cfg.n_layers, n_slots=4, max_len=64, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, dtype=cfg.dtype,
        )
        slots = jnp.array([0, 3])
        lengths = jnp.array([prompt_len, prompt_len])
        _, cache = transformer_prefill(
            params, tokens[:, :prompt_len], lengths, cache, slots, cfg
        )
        return cache, slots

    cache_v, slots = fresh_cache()
    active = jnp.zeros((4,), dtype=bool).at[slots].set(True)
    slot_tokens = jnp.zeros((4, c), dtype=tokens.dtype).at[slots].set(
        tokens[:, prompt_len:]
    )
    logits_v, nk, nv = transformer_verify_step(params, slot_tokens, cache_v, cfg)
    cache_v = commit_chunk_kv(cache_v, nk, nv, active, cfg)
    cache_v = cache_v._replace(
        lengths=cache_v.lengths + c * active.astype(jnp.int32)
    )

    cache_d, _ = fresh_cache()
    for j in range(c):
        step_tokens = jnp.zeros((4,), dtype=tokens.dtype).at[slots].set(
            tokens[:, prompt_len + j]
        )
        logits_d, cache_d = transformer_decode_step(
            params, step_tokens, cache_d, active, cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits_v[slots, j]),
            np.asarray(logits_d[slots]),
            rtol=1e-4, atol=1e-4,
            err_msg=f"verify position {j} diverged from sequential decode",
        )
    # Same cache contents at the written positions (and same lengths).
    np.testing.assert_array_equal(
        np.asarray(cache_v.lengths), np.asarray(cache_d.lengths)
    )
    span = slice(prompt_len, prompt_len + c)
    np.testing.assert_allclose(
        np.asarray(cache_v.k[:, slots, :, span]),
        np.asarray(cache_d.k[:, slots, :, span]),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(cache_v.v[:, slots, :, span]),
        np.asarray(cache_d.v[:, slots, :, span]),
        rtol=1e-5, atol=1e-5,
    )


def test_ngram_draft_lookup():
    from gofr_tpu.models.transformer import ngram_draft

    T = 16
    hist = jnp.zeros((3, T), dtype=jnp.int32)
    # Slot 0: "5 6 7 8 ... 5" → bigram (4,5)? history: 1 2 5 6 7 2 5 ; cur=5
    hist = hist.at[0, :7].set(jnp.array([1, 2, 5, 6, 7, 2, 5]))
    # Slot 1: no prior occurrence of cur.
    hist = hist.at[1, :4].set(jnp.array([3, 4, 5, 9]))
    # Slot 2: unigram fallback (length 1).
    hist = hist.at[2, :2].set(jnp.array([7, 7]))
    lengths = jnp.array([6, 3, 1])
    current = jnp.array([5, 8, 7])  # sits at history[lengths]
    draft = ngram_draft(hist, lengths, current, 3)
    # Slot 0: bigram (2,5) last matched at p=2 → draft = history[3:6] = 6 7 2.
    np.testing.assert_array_equal(np.asarray(draft[0]), [6, 7, 2])
    # Slot 1: no match → repeats current.
    np.testing.assert_array_equal(np.asarray(draft[1]), [8, 8, 8])
    # Slot 2: unigram 7 matched at p=0 → draft = history[1:4] = 7 0 0.
    np.testing.assert_array_equal(np.asarray(draft[2]), [7, 0, 0])


def test_prefill_respects_padding(tiny):
    """Right-padded short prompt must give same last-token logits as unpadded."""
    cfg, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0, cfg.vocab_size)
    cache = KVCache.create(cfg.n_layers, 2, 32, cfg.n_kv_heads, cfg.head_dim, cfg.dtype)
    logits_a, _ = transformer_prefill(
        params, tokens, jnp.array([6]), cache, jnp.array([0]), cfg
    )
    padded = jnp.pad(tokens, ((0, 0), (0, 4)))  # junk zeros after the prompt
    logits_b, _ = transformer_prefill(
        params, padded, jnp.array([6]), cache, jnp.array([1]), cfg
    )
    np.testing.assert_allclose(
        np.asarray(logits_a), np.asarray(logits_b), rtol=1e-4, atol=1e-4
    )


def test_moe_forward_runs():
    spec = get_model("moe-tiny")
    cfg = spec.config
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    logits = transformer_forward(params, tokens, cfg)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_bert_embed():
    spec = get_model("bert-tiny")
    cfg = spec.config
    params = init_bert(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    mask = jnp.ones((2, 16), dtype=jnp.int32)
    emb = bert_embed(params, tokens, mask, cfg)
    assert emb.shape == (2, cfg.d_model)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(emb), axis=-1), 1.0, rtol=1e-5
    )


def test_bert_mask_changes_output():
    spec = get_model("bert-tiny")
    cfg = spec.config
    params = init_bert(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    full = bert_embed(params, tokens, jnp.ones((1, 8), jnp.int32), cfg)
    half = bert_embed(
        params, tokens, jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32), cfg
    )
    assert not np.allclose(np.asarray(full), np.asarray(half), atol=1e-3)


def test_resnet_forward():
    spec = get_model("resnet-tiny")
    cfg = spec.config
    params = init_resnet(jax.random.PRNGKey(0), cfg)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    logits = resnet_forward(params, images, cfg)
    assert logits.shape == (2, cfg.num_classes)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_sampling():
    from gofr_tpu.ops.sampling import sample_logits

    logits = jnp.array([[0.0, 10.0, 0.0, 0.0], [0.0, 0.0, 0.0, 10.0]])
    greedy = sample_logits(logits, jax.random.PRNGKey(0), temperature=0.0)
    assert greedy.tolist() == [1, 3]
    sampled = sample_logits(
        logits, jax.random.PRNGKey(0), temperature=1.0, top_k=1
    )
    assert sampled.tolist() == [1, 3]  # top_k=1 → argmax regardless of temp


def test_llama_70b_registered_and_shardable_tp8():
    """Scale target sanity: llama-3-70b's param count matches the real
    model (~70.6B), every weight leaf divides a tp=8 mesh cleanly under
    its partition spec, and the int8/int4 per-chip weight bytes fit a
    16 GB v5e with room for cache — the capacity math behind serving
    70B on one v5e-8 slice."""
    import jax

    from gofr_tpu.models.registry import get_model
    from gofr_tpu.models.transformer import (
        kv_cache_specs,
        transformer_param_specs,
    )

    spec = get_model("llama-3-70b")
    cfg = spec.config
    shapes = jax.eval_shape(lambda k: spec.init(k, cfg), jax.random.PRNGKey(0))
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes)
    )
    assert 70e9 < n_params < 72e9, n_params

    TP = 8
    specs = transformer_param_specs(cfg)

    def check(leaf, s):
        for axis, entry in enumerate(s):
            if entry == "tp":
                assert leaf.shape[axis] % TP == 0, (leaf.shape, s)

    jax.tree_util.tree_map(
        check, shapes, specs,
        is_leaf=lambda x: hasattr(x, "shape") or x is None,
    )
    # KV cache shards its kv-head axis over tp: check via the cache's
    # own specs on a representative shape [L, slots, kv, len, hd].
    cache_shape = (cfg.n_layers, 8, cfg.n_kv_heads, 128, cfg.head_dim)
    for axis, entry in enumerate(kv_cache_specs().k):
        if entry == "tp":
            assert cache_shape[axis] % TP == 0, (cache_shape, axis)

    # Weight bytes per chip: int8 ≈ total params (1 B) / TP + scales.
    int8_per_chip = n_params / TP / 1e9
    assert int8_per_chip < 10, int8_per_chip  # < 10 GB of 16 GB HBM
    int4_per_chip = n_params / 2 / TP / 1e9
    assert int4_per_chip < 5, int4_per_chip


def test_mistral_7b_registered():
    from gofr_tpu.models.registry import get_model

    cfg = get_model("mistral-7b").config
    assert cfg.n_kv_heads == 8 and cfg.d_ff == 14336


def test_vit_forward_and_engine_classify():
    """ViT joins the vision family: forward shape and the engine's
    batched classify path (same surface ResNet serves)."""
    import jax

    from gofr_tpu.models.vit import vit_forward
    from gofr_tpu.serving.engine import InferenceEngine

    spec = get_model("vit-tiny")
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    img = jnp.ones((1, 32, 32, 3), jnp.float32)
    logits = vit_forward(params, img, spec.config)
    assert logits.shape == (1, 10)

    eng = InferenceEngine("vit-tiny", max_batch=4)
    eng.start_sync()
    try:
        out = eng.classify_sync(np.ones((32, 32, 3), np.float32))
        assert np.asarray(out).shape[-1] == 10
    finally:
        eng.stop_sync()


def test_vit_matches_torch_oracle():
    """Patchify + one-matmul patch embedding must equal the HF conv
    patch embedding, and the whole encoder must match
    ViTForImageClassification logits (validates q/k/v/o maps, pre-LN
    placement, CLS head)."""
    import dataclasses

    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from gofr_tpu.models.vit import ViTConfig, vit_forward

    hf_cfg = transformers.ViTConfig(
        image_size=32, patch_size=8, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128, num_labels=10,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=1e-12,
    )
    torch.manual_seed(4)
    model = transformers.ViTForImageClassification(hf_cfg)
    model.eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}

    cfg = dataclasses.replace(
        ViTConfig(
            image_size=32, patch_size=8, d_model=64, n_layers=2,
            n_heads=4, d_ff=128, num_classes=10,
        ),
        dtype=jnp.float32,
    )
    L = cfg.n_layers
    pre = "vit.encoder.layer.{}."

    def stack(fmt, transpose=False):
        a = np.stack([sd[fmt.format(i)] for i in range(L)])
        return jnp.asarray(
            np.swapaxes(a, -1, -2) if transpose else a, jnp.float32
        )

    conv_w = sd["vit.embeddings.patch_embeddings.projection.weight"]
    # HF conv kernel [D, 3, P, P] → our flattened [(P, P, 3) row-major, D].
    patch_proj = jnp.asarray(
        conv_w.transpose(2, 3, 1, 0).reshape(-1, conv_w.shape[0]),
        jnp.float32,
    )
    params = {
        "patch_proj": patch_proj,
        "patch_proj_b": jnp.asarray(
            sd["vit.embeddings.patch_embeddings.projection.bias"]
        ),
        "cls_token": jnp.asarray(sd["vit.embeddings.cls_token"]),
        "pos_embed": jnp.asarray(
            sd["vit.embeddings.position_embeddings"][0]
        ),
        "layers": {
            "ln1": stack(pre + "layernorm_before.weight"),
            "ln1_b": stack(pre + "layernorm_before.bias"),
            "wq": stack(pre + "attention.attention.query.weight", True),
            "wq_b": stack(pre + "attention.attention.query.bias"),
            "wk": stack(pre + "attention.attention.key.weight", True),
            "wk_b": stack(pre + "attention.attention.key.bias"),
            "wv": stack(pre + "attention.attention.value.weight", True),
            "wv_b": stack(pre + "attention.attention.value.bias"),
            "wo": stack(pre + "attention.output.dense.weight", True),
            "wo_b": stack(pre + "attention.output.dense.bias"),
            "ln2": stack(pre + "layernorm_after.weight"),
            "ln2_b": stack(pre + "layernorm_after.bias"),
            "w_up": stack(pre + "intermediate.dense.weight", True),
            "w_up_b": stack(pre + "intermediate.dense.bias"),
            "w_down": stack(pre + "output.dense.weight", True),
            "w_down_b": stack(pre + "output.dense.bias"),
        },
        "ln_f": jnp.asarray(sd["vit.layernorm.weight"]),
        "ln_f_b": jnp.asarray(sd["vit.layernorm.bias"]),
        "head": jnp.asarray(np.swapaxes(sd["classifier.weight"], 0, 1)),
        "head_b": jnp.asarray(sd["classifier.bias"]),
    }
    rng = np.random.default_rng(0)
    img = rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
    ours = np.asarray(vit_forward(params, jnp.asarray(img), cfg))
    with torch.no_grad():
        # HF expects NCHW.
        theirs = model(
            torch.tensor(img.transpose(0, 3, 1, 2))
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)
