"""Test harness configuration.

All tests run on CPU with 8 virtual XLA devices so multi-chip sharding
(dp/tp/sp/ep meshes) is exercised without TPU hardware — the
`xla_force_host_platform_device_count` trick the driver also uses for the
multi-chip dry run.
"""

import os

# The environment pre-sets JAX_PLATFORMS=axon and a sitecustomize that
# imports jax at interpreter startup, so env writes here are too late —
# force the CPU backend through jax.config instead (valid until the first
# backend is actually initialized, which no sitecustomize does).
os.environ["JAX_PLATFORMS"] = "cpu"  # belt-and-braces for subprocesses
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) has no jax_num_cpu_devices flag; the XLA_FLAGS
    # host-device-count override above already provides the 8 devices.
    pass

import socket

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: subprocess/multi-minute chaos tests (their own named CI "
        "step runs them; the default tier-1 sweep filters -m 'not slow')",
    )


from gofr_tpu.analysis import lockcheck

if lockcheck.enabled():
    # Lock-discipline validation (TPU_LOCKCHECK=1, e.g. the CI
    # lockcheck-chaos step): every test starts with a fresh order graph
    # and must end with zero recorded violations — an order inversion or
    # a device sync under an instrumented lock anywhere in the test
    # fails THAT test, with the acquisition stacks in the message.
    @pytest.fixture(autouse=True)
    def _lockcheck_clean():
        lockcheck.reset()
        yield
        lockcheck.assert_clean()


@pytest.fixture
def free_port():
    def _get():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    return _get


@pytest.fixture
def mock_config():
    from gofr_tpu.config import MockConfig

    def _make(values=None):
        return MockConfig(values or {})

    return _make
