"""Sharding/parallelism tests on the 8-device virtual CPU mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.registry import get_model
from gofr_tpu.models.transformer import (
    init_transformer,
    transformer_forward,
    transformer_param_specs,
)
from gofr_tpu.parallel import make_mesh, make_train_step, mesh_axis_sizes, shard_pytree


def test_mesh_construction():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh_axis_sizes(mesh) == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        make_mesh({"dp": 64, "tp": 4})


def test_sharded_params_match_replicated_forward():
    """tp-sharded forward must equal single-device forward (f32 so the
    comparison is tight; bf16 differs only by collective reduction order)."""
    import dataclasses

    import jax.numpy as jnp

    cfg = dataclasses.replace(get_model("llama-tiny").config, dtype=jnp.float32)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    expected = transformer_forward(params, tokens, cfg)

    mesh = make_mesh({"dp": 1, "tp": 2})
    specs = transformer_param_specs(cfg)
    sharded = shard_pytree(params, specs, mesh)
    got = transformer_forward(sharded, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(got), rtol=1e-4, atol=1e-4
    )


def test_train_step_dense_dp_tp():
    cfg = get_model("llama-tiny").config
    mesh = make_mesh({"dp": 2, "tp": 2})
    init_state, train_step, _ = make_train_step(cfg, mesh, sp=True)
    params, opt_state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    loss0, params, opt_state = train_step(params, opt_state, tokens)
    loss1, params, opt_state = train_step(params, opt_state, tokens)
    assert np.isfinite(float(loss0)) and np.isfinite(float(loss1))
    assert float(loss1) < float(loss0)  # same batch twice → loss must drop


def test_train_step_moe_ep():
    cfg = get_model("moe-tiny").config
    mesh = make_mesh({"dp": 2, "tp": 4})
    init_state, train_step, _ = make_train_step(cfg, mesh, sp=True, remat=True)
    params, opt_state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    loss, params, opt_state = train_step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    # Expert weights really are sharded over tp.
    w_gate = params["layers"]["w_gate"]
    spec = w_gate.sharding.spec
    assert spec[1] == "tp"


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 3
    g.dryrun_multichip(8)


def test_dcn_init_noop_without_config():
    from gofr_tpu.config import MockConfig
    from gofr_tpu.parallel import initialize_multihost, process_topology

    assert initialize_multihost(MockConfig({})) is False
    topo = process_topology()
    assert topo["process_count"] == 1
    assert topo["global_devices"] == 8  # the virtual CPU mesh
