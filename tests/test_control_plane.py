"""The fault-tolerant control plane (serving/control_plane.py;
docs/advanced-guide/resilience.md "Control plane").

Three layers of coverage, all deterministic:

* **loop math** — stated-clock units for the per-tenant ladder
  (hysteresis, AIMD, L0 byte-identity snap), the host-overhead
  pressure loop, and the predictive trend fit (which must fire while
  the depth itself is still far below the reactive threshold);
* **the signal guard** — fresh → last-good → observe-only transitions,
  NaN/type lies rejected as errors, and one chaos test per
  ``control.signal`` fault mode (stale / NaN / raise / flap), each
  ending with the loop observe-only and ZERO 5xx;
* **per-tenant acceptance** — a real flooding hog burns its own
  availability SLO and climbs ITS ladder while every other tenant's
  seeded greedy stream stays byte-identical and the pod ladder holds
  L0.

Plus the satellite regressions this PR's audit pinned: a None/NaN
headroom advertisement never counts as pressure anywhere (engine
admission, pool scaler, brownout controller), and the
prefix-hit-aware queue ordering is byte-identical when off."""

from __future__ import annotations

import math
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.errors import ErrorTooManyRequests
from gofr_tpu.metrics.manager import Manager
from gofr_tpu.serving.brownout import MAX_LEVEL, BrownoutController
from gofr_tpu.serving.control_plane import (
    ControlPlane,
    HostPressureLoop,
    PredictiveLoop,
)
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.lifecycle import ClassPriorityQueue
from gofr_tpu.serving.tokenizer import ByteTokenizer


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def control_metrics() -> Manager:
    m = Manager()
    m.new_counter("app_tpu_control_actions_total")
    for name in (
        "app_tpu_control_signal_health",
        "app_tpu_control_tenant_level",
        "app_tpu_control_scale_pressure",
    ):
        m.new_gauge(name)
    return m


def gauge_value(m: Manager, name: str, **labels: str) -> float:
    inst = [i for i in m.instruments() if i.name == name]
    if not inst:
        return float("nan")
    want = set(labels.items())
    for k, v in inst[0].collect().items():
        if want <= set(k):
            return v
    return float("nan")


def make_plane(**kw):
    clock = FakeClock(1000.0)
    defaults = dict(
        tenant_enter=2.0, tenant_exit=1.0, tenant_sustain_s=5.0,
        tenant_exit_sustain_s=20.0, tenant_max_new=8,
        tenant_aimd_cut=0.5, tenant_recover_per_s=0.05,
        host_ratio=0.85, host_util=0.75, host_sustain_s=5.0,
        predict_window_s=60.0, predict_horizon_s=30.0,
        predict_depth=64.0, predict_hold_s=10.0,
        clock=clock,
    )
    defaults.update(kw)
    return ControlPlane("m", **defaults), clock


def make_engine(**kw):
    defaults = dict(
        n_slots=2, max_len=128, kv_block=16,
        tokenizer=ByteTokenizer(), seed=0,
        slo_availability=0.999,
        control_plane=True,
        # Hold a reached level against the scheduler's continuous
        # re-evaluation (the brownout-test idiom): with burn 0 the
        # ladder would descend after the exit sustain.
        control_tenant_exit_sustain_s=100_000.0,
        # The POD ladder must hold L0 through the per-tenant tests —
        # the hog's sheds burn the GLOBAL availability SLO too, and
        # the isolation contract is per-tenant action, pod inaction.
        brownout_sustain_s=100_000.0,
    )
    defaults.update(kw)
    eng = InferenceEngine("llama-tiny", **defaults)
    eng.start_sync()
    return eng


def wait_for(predicate, timeout_s: float = 30.0) -> None:
    """Bound a poll on the scheduler thread observing a condition —
    the OUTCOME is deterministic, only the thread interleaving isn't."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    assert predicate(), "condition never became true"


def _greedy(eng, prompt: str = "byte identical", tenant: str = ""):
    return eng.generate_sync(
        prompt, max_new_tokens=8, temperature=0.0, stop_on_eos=False,
        tenant=tenant, timeout=300,
    ).token_ids


# ----------------------------------------------------------------------
# loop math: the per-tenant ladder (stated clock)
# ----------------------------------------------------------------------


def test_tenant_ladder_one_bad_tick_never_flips_a_level():
    cp, clock = make_plane()
    burns = {"hog": 50.0}
    cp.register("tenant_burn", lambda: burns, kind="map")
    cp.evaluate(now=clock.t)                    # over, anchor only
    assert cp.tenant_level("hog") == 0
    cp.evaluate(now=clock.advance(4.9))         # inside the sustain
    assert cp.tenant_level("hog") == 0
    cp.evaluate(now=clock.advance(0.2))         # sustained → L1
    assert cp.tenant_level("hog") == 1
    # One clean tick does NOT descend either (exit sustain).
    burns["hog"] = 0.0
    cp.evaluate(now=clock.advance(1.0))
    assert cp.tenant_level("hog") == 1


def test_tenant_ladder_climbs_per_sustain_caps_and_isolates():
    cp, clock = make_plane()
    burns = {"hog": 10.0, "clean": 0.0}
    cp.register("tenant_burn", lambda: burns, kind="map")
    cp.evaluate(now=clock.t)
    for expected in (1, 2, 3, 3):               # re-armed per rung; caps
        cp.evaluate(now=clock.advance(5.1))
        assert cp.tenant_level("hog") == expected
    assert cp.tenant_level("hog") == MAX_LEVEL
    # Isolation: the clean tenant never left L0, and its actuators are
    # byte-identically neutral.
    assert cp.tenant_level("clean") == 0
    assert cp.tenant_clamp_max_new("clean", 32) == 32
    assert all(cp.tenant_admit("clean", "standard") for _ in range(20))
    snap = cp.snapshot()
    assert snap["loops"]["tenant_brownout"]["tenants"]["hog"]["level"] == 3
    assert snap["loops"]["tenant_brownout"]["transitions"]["up"] == 3


def test_tenant_hysteresis_band_holds_and_exit_needs_sustained_clean():
    cp, clock = make_plane()
    burns = {"hog": 10.0}
    cp.register("tenant_burn", lambda: burns, kind="map")
    cp.evaluate(now=clock.t)
    cp.evaluate(now=clock.advance(5.1))
    assert cp.tenant_level("hog") == 1
    # Between exit (1.0) and enter (2.0): the band holds the level and
    # resets BOTH anchors — band time counts toward neither sustain.
    burns["hog"] = 1.5
    for _ in range(5):
        cp.evaluate(now=clock.advance(30.0))
        assert cp.tenant_level("hog") == 1
    # Clean signal: one rung only after a full exit-sustain period.
    burns["hog"] = 0.2
    cp.evaluate(now=clock.advance(0.0))
    cp.evaluate(now=clock.advance(19.9))
    assert cp.tenant_level("hog") == 1
    cp.evaluate(now=clock.advance(0.2))
    assert cp.tenant_level("hog") == 0


def test_tenant_aimd_cut_recovery_and_l0_snap():
    cp, clock = make_plane()
    burns = {"hog": 10.0}
    cp.register("tenant_burn", lambda: burns, kind="map")
    cp.evaluate(now=clock.t)
    cp.evaluate(now=clock.advance(5.1))         # L1: no budget action
    table = cp.tenant_loop.table
    assert table["hog"].budget_factor == 1.0
    cp.evaluate(now=clock.advance(5.1))         # L2: multiplicative cut
    assert table["hog"].budget_factor == pytest.approx(0.5)
    # Additive recovery while the signal is below enter: 10s at
    # 0.05/s → +0.5, capped at 1.0 only at L0.
    burns["hog"] = 0.0
    cp.evaluate(now=clock.advance(10.0))
    assert table["hog"].budget_factor == pytest.approx(1.0)
    # Descend to L0 (two exit-sustain periods) snaps the factor to
    # exactly 1.0 — the byte-identity contract.
    cp.evaluate(now=clock.advance(20.1))
    cp.evaluate(now=clock.advance(20.1))
    assert cp.tenant_level("hog") == 0
    burns.clear()
    cp.evaluate(now=clock.advance(1.0))
    # Fully-recovered idle entries leave the table (bounded memory).
    assert "hog" not in table


def test_l2_admission_credit_is_deterministic_and_class_aware():
    cp, _clock = make_plane()
    cp.force_tenant_level("hog", 2)
    ladder = cp.tenant_loop.table["hog"]
    assert ladder.budget_factor == pytest.approx(0.5)   # one AIMD cut
    # standard: 0.5 × 0.8 = 0.4 credit per submit, starting bank 1.0 —
    # the exact admit pattern is stated, not sampled.
    got = [cp.tenant_admit("hog", "standard") for _ in range(10)]
    assert got == [
        True, False, True, False, True, False, False, True, False, True,
    ]
    # interactive (fraction 1.0): 0.5 credit/call after the AIMD cut —
    # the starting bank of 1.0 buys two admissions up front, then one
    # in two.
    cp.force_tenant_level("ivy", 2)
    got = [cp.tenant_admit("ivy", "interactive") for _ in range(6)]
    assert got == [True, True, False, True, False, True]
    # L3: shed outright; L1: admit everything.
    cp.force_tenant_level("hog", 3)
    assert not any(cp.tenant_admit("hog", "interactive") for _ in range(5))
    cp.force_tenant_level("hog", 1)
    assert all(cp.tenant_admit("hog", "batch") for _ in range(5))


def test_tenant_recovery_floor_scales_with_level():
    cp, _clock = make_plane()
    assert cp.tenant_recovery_s("unknown") == 0.0
    cp.force_tenant_level("hog", 2)
    at_l2 = cp.tenant_recovery_s("hog")
    cp.force_tenant_level("hog", 3)
    at_l3 = cp.tenant_recovery_s("hog")
    assert at_l3 > at_l2 >= 1.0


def test_tenant_table_is_bounded_against_label_cardinality():
    cp, clock = make_plane(tenant_table_max=4)
    burns = {f"t{i}": 10.0 for i in range(32)}
    cp.register("tenant_burn", lambda: burns, kind="map")
    cp.evaluate(now=clock.t)
    cp.evaluate(now=clock.advance(5.1))
    assert len(cp.tenant_loop.table) == 4


# ----------------------------------------------------------------------
# loop math: host-overhead pressure + predictive scaling
# ----------------------------------------------------------------------


def test_host_pressure_needs_sustained_ratio_at_high_util():
    hl = HostPressureLoop(ratio=0.85, util=0.75, sustain_s=5.0)
    assert hl.evaluate(0.9, 0.8, 0.0) is False      # anchor only
    assert hl.evaluate(0.9, 0.8, 4.9) is False
    assert hl.evaluate(0.9, 0.8, 5.1) is True       # sustained
    # Hysteresis band (exit = enter − 0.1): holds, resets anchors.
    assert hl.evaluate(0.80, 0.8, 10.0) is True
    # Clean below the exit ratio: released only after the sustain.
    assert hl.evaluate(0.5, 0.8, 20.0) is True
    assert hl.evaluate(0.5, 0.8, 24.9) is True
    assert hl.evaluate(0.5, 0.8, 25.1) is False
    # High ratio at LOW utilization is not pressure (an idle loop's
    # bookkeeping share is large by construction).
    hl2 = HostPressureLoop(ratio=0.85, util=0.75, sustain_s=5.0)
    hl2.evaluate(0.99, 0.1, 0.0)
    assert hl2.evaluate(0.99, 0.1, 60.0) is False


def test_predictive_fires_on_trend_before_reactive_threshold():
    pl = PredictiveLoop(
        window_s=60.0, horizon_s=30.0, depth_threshold=64.0, hold_s=10.0
    )
    # Rising ~2 req/s: projected = depth + 2×30 crosses 64 while the
    # depth itself is only 6 — the LEAD the loop exists to provide.
    assert pl.evaluate(0.0, 0.0, 0.0) is False      # < MIN_SAMPLES
    assert pl.evaluate(2.0, 0.0, 1.0) is False
    assert pl.evaluate(4.0, 0.0, 2.0) is False
    assert pl.evaluate(6.0, 0.0, 3.0) is True
    assert 6.0 < pl.depth_threshold                 # fired early
    assert pl.last_slope == pytest.approx(2.0)
    # Hold-down: the trend breaking does not release before hold_s.
    assert pl.evaluate(0.0, 0.0, 4.0) is True
    # Past the hold with no projected breach: released.
    assert pl.evaluate(0.0, 0.0, 14.0) is False


def test_predictive_flat_backlog_below_threshold_never_fires():
    pl = PredictiveLoop(
        window_s=60.0, horizon_s=30.0, depth_threshold=64.0, hold_s=10.0
    )
    for t in range(10):
        assert pl.evaluate(20.0, 0.0, float(t)) is False
    assert pl.last_slope == pytest.approx(0.0)


def test_scale_pressure_follows_loops_and_modes():
    cp, clock = make_plane(
        host_sustain_s=1.0, predict_depth=8.0, predict_horizon_s=10.0
    )
    sensors = {"host_overhead_ratio": 0.95, "loop_utilization": 0.9}
    cp.register(
        "host_overhead_ratio", lambda: sensors["host_overhead_ratio"]
    )
    cp.register("loop_utilization", lambda: sensors["loop_utilization"])
    cp.evaluate(now=clock.t)
    assert cp.scale_pressure() == 0
    cp.evaluate(now=clock.advance(1.1))
    assert cp.scale_pressure() == 1
    # The signal dying moves the loop to observe-only → neutral, even
    # though the loop's internal latch still says pressure.
    sensors["host_overhead_ratio"] = float("nan")
    cp.evaluate(now=clock.advance(cp.stale_s + 1.0))
    assert cp.host_loop.pressure is True
    assert cp.scale_pressure() == 0
    assert cp.snapshot()["loops"]["host_pressure"]["mode"] == "observe_only"


# ----------------------------------------------------------------------
# the signal guard: fresh → last-good → observe-only
# ----------------------------------------------------------------------


def test_guard_walks_ok_last_good_observe_only_and_recovers():
    cp, clock = make_plane(stale_s=10.0)
    sensor = {"value": 5.0, "raise": False}

    def read():
        if sensor["raise"]:
            raise RuntimeError("sensor died")
        return sensor["value"]

    cp.register("queue_depth", read)
    cp.evaluate(now=clock.t)
    assert cp.signal_health() == {"queue_depth": 1.0}
    # Failure within the stale window: last-good, loop still active.
    sensor["raise"] = True
    cp.evaluate(now=clock.advance(5.0))
    assert cp.signal_health() == {"queue_depth": 0.5}
    snap = cp.snapshot()["signals"]["queue_depth"]
    assert snap["status"] == "last_good"
    assert "RuntimeError" in snap["last_error"]
    assert cp.snapshot()["loops"]["predictive"]["mode"] == "active"
    # Past the window: observe-only, the consuming loop goes neutral.
    cp.evaluate(now=clock.advance(10.1))
    assert cp.signal_health() == {"queue_depth": 0.0}
    assert cp.snapshot()["loops"]["predictive"]["mode"] == "observe_only"
    # Recovery is immediate on the next good sample.
    sensor["raise"] = False
    cp.evaluate(now=clock.advance(1.0))
    assert cp.signal_health() == {"queue_depth": 1.0}
    assert cp.snapshot()["signals"]["queue_depth"]["errors"] == 2


def test_nan_and_type_lies_are_errors_not_values():
    cp, clock = make_plane(stale_s=0.0)
    values = {"scalar": float("nan"), "map": {"hog": float("inf")}}
    cp.register("queue_depth", lambda: values["scalar"])
    cp.register("tenant_burn", lambda: values["map"], kind="map")
    cp.evaluate(now=clock.t)
    health = cp.signal_health()
    assert health["queue_depth"] == 0.0
    assert health["tenant_burn"] == 0.0
    # A map sensor answering a scalar (and vice versa) is an error too.
    values["map"] = 3.0
    values["scalar"] = {"not": 1.0}
    cp.evaluate(now=clock.advance(1.0))
    assert cp.signal_health() == {
        "queue_depth": 0.0, "tenant_burn": 0.0,
    }
    # Guarded failures are NOT controller bugs: eval_errors stays 0.
    assert cp.snapshot()["eval_errors"] == 0


def test_tenant_loop_observes_only_holds_table_on_dead_sensor():
    cp, clock = make_plane(stale_s=5.0)
    state = {"burns": {"hog": 10.0}, "fail": False}

    def read():
        if state["fail"]:
            raise RuntimeError("burn sensor gone")
        return state["burns"]

    cp.register("tenant_burn", read, kind="map")
    cp.evaluate(now=clock.t)
    cp.evaluate(now=clock.advance(5.1))
    assert cp.tenant_level("hog") == 1
    # Sensor dies past the stale window: the table HOLDS (no climbs,
    # no descents) and every actuator reads neutral.
    state["fail"] = True
    cp.evaluate(now=clock.advance(6.0))
    mode = cp.snapshot()["loops"]["tenant_brownout"]["mode"]
    assert mode == "observe_only"
    assert cp.tenant_loop.table["hog"].level == 1
    assert cp.tenant_clamp_max_new("hog", 32) == 32   # neutral at L1
    for _ in range(10):
        cp.evaluate(now=clock.advance(30.0))
    assert cp.tenant_loop.table["hog"].level == 1     # held, not moved


def test_evaluate_never_raises_even_on_controller_bugs():
    cp, clock = make_plane()
    cp.register("tenant_burn", lambda: {}, kind="map")
    # Sabotage the loop itself — not just a sensor — and evaluate must
    # still return (the scheduler pass survives; the bug is counted).
    cp.tenant_loop.evaluate = None  # type: ignore[assignment]
    cp.evaluate(now=clock.advance(1.0))
    assert cp.snapshot()["eval_errors"] == 1


def test_metrics_export_health_levels_and_pressure():
    m = control_metrics()
    cp, clock = make_plane(metrics=m, stale_s=0.0)
    state = {"burns": {"hog": 10.0}, "depth_ok": True}
    cp.register("tenant_burn", lambda: state["burns"], kind="map")
    cp.register(
        "queue_depth",
        lambda: 1.0 if state["depth_ok"] else float("nan"),
    )
    cp.evaluate(now=clock.t)
    cp.evaluate(now=clock.advance(5.1))
    assert gauge_value(
        m, "app_tpu_control_signal_health", signal="tenant_burn"
    ) == 1.0
    assert gauge_value(
        m, "app_tpu_control_tenant_level", tenant="hog"
    ) == 1.0
    assert gauge_value(
        m, "app_tpu_control_scale_pressure", source="predictive"
    ) == 0.0
    # The health gauge NAMES the degraded signal.
    state["depth_ok"] = False
    cp.evaluate(now=clock.advance(1.0))
    assert gauge_value(
        m, "app_tpu_control_signal_health", signal="queue_depth"
    ) == 0.0
    assert gauge_value(
        m, "app_tpu_control_signal_health", signal="tenant_burn"
    ) == 1.0
    # A tenant leaving the table zeroes its gauge (no stale levels).
    state["burns"] = {}
    burn_clock = clock.advance(100_000.0)
    for _ in range(4):
        burn_clock = clock.advance(100_000.0)
        cp.evaluate(now=burn_clock)
    assert gauge_value(
        m, "app_tpu_control_tenant_level", tenant="hog"
    ) == 0.0


# ----------------------------------------------------------------------
# chaos: the control.signal fault point, one test per failure mode
# ----------------------------------------------------------------------


def _plane_with_live_sensor():
    cp, clock = make_plane(stale_s=5.0)
    cp.register("queue_depth", lambda: 7.0)
    return cp, clock


def test_fault_stale_starves_one_signal_to_observe_only():
    cp, clock = _plane_with_live_sensor()
    cp.evaluate(now=clock.t)
    with faults.armed(
        "control.signal",
        action=lambda signal: "stale" if signal == "queue_depth" else None,
    ):
        cp.evaluate(now=clock.advance(1.0))
        assert cp.signal_health()["queue_depth"] == 0.5   # last-good
        cp.evaluate(now=clock.advance(10.0))
        assert cp.signal_health()["queue_depth"] == 0.0
        assert (
            cp.snapshot()["loops"]["predictive"]["mode"] == "observe_only"
        )
        assert cp.scale_pressure() == 0
    cp.evaluate(now=clock.advance(1.0))
    assert cp.signal_health()["queue_depth"] == 1.0       # recovered


def test_fault_nan_lie_is_rejected_not_consumed():
    cp, clock = _plane_with_live_sensor()
    cp.evaluate(now=clock.t)
    with faults.armed(
        "control.signal",
        action=lambda signal: (
            float("nan") if signal == "queue_depth" else None
        ),
    ):
        cp.evaluate(now=clock.advance(10.0))
        assert cp.signal_health()["queue_depth"] == 0.0
        snap = cp.snapshot()["signals"]["queue_depth"]
        assert "non-finite" in snap["last_error"]
    assert cp.snapshot()["eval_errors"] == 0


def test_fault_raise_is_absorbed_by_the_guard():
    cp, clock = _plane_with_live_sensor()
    cp.evaluate(now=clock.t)

    def blow_up(signal):
        if signal == "queue_depth":
            raise RuntimeError("sensor exploded")
        return None

    with faults.armed("control.signal", action=blow_up):
        cp.evaluate(now=clock.advance(10.0))   # never raises
        assert cp.signal_health()["queue_depth"] == 0.0
    assert cp.snapshot()["eval_errors"] == 0


def test_fault_flap_never_wedges_or_errors():
    cp, clock = _plane_with_live_sensor()
    cp.evaluate(now=clock.t)
    flap = {"n": 0}

    def flapping(signal):
        if signal != "queue_depth":
            return None
        flap["n"] += 1
        return "stale" if flap["n"] % 2 else None

    with faults.armed("control.signal", action=flapping):
        for _ in range(20):
            cp.evaluate(now=clock.advance(1.0))
            assert cp.signal_health()["queue_depth"] in (0.5, 1.0)
    assert cp.snapshot()["eval_errors"] == 0
    assert cp.snapshot()["passes"] >= 21


def test_engine_chaos_dead_burn_sensor_zero_5xx_observe_only():
    """The headline acceptance: arm the ``control.signal`` fault
    against a REAL engine's burn sensor mid-flight — no crash, no
    wedged scheduler, zero 5xx; the tenant loop parks observe-only
    (even a forced L3 admits — acting on a dead sensor is guessing),
    and the health surface names the lying signal."""
    eng = make_engine(control_stale_s=0.05)
    try:
        cp = eng._control
        assert cp is not None
        cp.force_tenant_level("hog", 3)

        def kill_burn(signal):
            if signal == "tenant_burn":
                raise RuntimeError("burn sensor died")
            return None

        with faults.armed("control.signal", action=kill_burn):
            wait_for(lambda: (
                eng.control_report()["signals"]["tenant_burn"]["status"]
                == "observe_only"
            ))
            # Zero 5xx: every tenant — the forced-L3 hog included —
            # serves normally while the loop observes only.
            for tenant in ("hog", "clean"):
                result = eng.generate_sync(
                    f"chaos {tenant}", max_new_tokens=4,
                    temperature=0.0, stop_on_eos=False, tenant=tenant,
                    timeout=300,
                )
                assert len(result.token_ids) == 4
            report = eng.control_report()
            assert report["loops"]["tenant_brownout"]["mode"] == (
                "observe_only"
            )
            assert report["signals"]["tenant_burn"]["health"] == 0.0
            assert eng.capacity_report()["control"][
                "degraded_signals"
            ] == ["tenant_burn"]
            assert report["eval_errors"] == 0
        # Disarmed: the sensor heals and the loop re-activates.
        wait_for(lambda: (
            eng.control_report()["signals"]["tenant_burn"]["status"]
            == "ok"
        ))
        wait_for(lambda: (
            eng.control_report()["loops"]["tenant_brownout"]["mode"]
            == "active"
        ))
    finally:
        eng.close()


# ----------------------------------------------------------------------
# engine integration: off-is-off, per-tenant actuation, acceptance
# ----------------------------------------------------------------------


def test_off_switch_and_neutral_plane_are_byte_identical():
    base = make_engine(control_plane=False, slo_availability=0.0)
    try:
        assert base._control is None
        assert base.control_report() == {"enabled": False}
        # Plane off = signal ABSENT (None), not "armed at 0".
        assert base.control_scale_pressure() is None
        reference = _greedy(base)
    finally:
        base.close()
    armed = make_engine()
    try:
        assert armed._control is not None
        assert armed.control_scale_pressure() == 0
        assert _greedy(armed) == reference
        report = armed.control_report()
        assert report["enabled"] is True
        assert set(report["signals"]) >= {
            "tenant_burn", "queue_depth", "throughput",
        }
    finally:
        armed.close()


def test_tenant_l1_clamps_only_the_burning_tenant():
    eng = make_engine(control_tenant_max_new=4)
    try:
        eng._control.force_tenant_level("hog", 1)
        hog = eng.generate_sync(
            "clamp me", max_new_tokens=32, temperature=0.0,
            stop_on_eos=False, tenant="hog", timeout=300,
        )
        assert len(hog.token_ids) == 4
        assert hog.brownout is True           # deliberate, advertised
        clean = eng.generate_sync(
            "clamp me", max_new_tokens=32, temperature=0.0,
            stop_on_eos=False, tenant="clean", timeout=300,
        )
        assert len(clean.token_ids) == 32
        assert clean.brownout is False
    finally:
        eng.close()


def test_tenant_l3_sheds_with_429_reason_and_retry_after():
    eng = make_engine()
    try:
        eng._control.force_tenant_level("hog", 3)
        with pytest.raises(ErrorTooManyRequests) as exc:
            eng.submit_generate(
                "shed me", max_new_tokens=4, temperature=0.0,
                stop_on_eos=False, tenant="hog",
            )
        assert "tenant_brownout" in str(exc.value)
        assert exc.value.retry_after_s >= 1
        # Everyone else admits untouched while the hog sheds.
        other = eng.generate_sync(
            "not the hog", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, tenant="clean", timeout=300,
        )
        assert len(other.token_ids) == 4
        assert eng.health_check()["details"]["control"][
            "tenants_browned_out"
        ] == 1
    finally:
        eng.close()


def test_acceptance_hog_burns_climb_its_ladder_others_byte_identical():
    """The per-tenant acceptance: a flooding hog's admission sheds
    burn ITS availability SLO, its ladder climbs, and the clean
    tenants' seeded greedy streams match a control-off run byte for
    byte while the POD ladder holds L0."""
    reference = {}
    base = make_engine(control_plane=False)
    try:
        for t in ("clean-a", "clean-b"):
            reference[t] = _greedy(base, f"isolation {t}", tenant=t)
    finally:
        base.close()
    eng = make_engine(
        queue_max_tokens=96,
        control_tenant_sustain_s=0.01,
    )
    try:
        hog_prompt = "H" * 40
        handles, sheds = [], 0
        for i in range(12):
            try:
                handles.append(eng.submit_generate(
                    hog_prompt + f" {i:02d}", max_new_tokens=16,
                    temperature=0.0, stop_on_eos=False, tenant="hog",
                ))
            except ErrorTooManyRequests:
                sheds += 1
        assert sheds >= 1               # the flood overran the queue
        # The hog's OWN availability burn drives ITS ladder.
        wait_for(lambda: eng._control.tenant_level("hog") >= 1)
        for h in handles:
            try:
                h.future.result(timeout=300)
            except ErrorTooManyRequests:
                sheds += 1              # L3 sheds count too
        burns = eng._slo.tenant_burns("5m")
        assert burns.get("hog", 0.0) > 2.0
        assert burns.get("clean-a", 0.0) == 0.0
        # Pod-level inaction: the hog degrades, the POD does not.
        assert eng.brownout_level() == 0
        for t in ("clean-a", "clean-b"):
            assert _greedy(eng, f"isolation {t}", tenant=t) == (
                reference[t]
            )
            assert eng._control.tenant_level(t) == 0
    finally:
        eng.close()


# ----------------------------------------------------------------------
# satellite: None/NaN headroom is never pressure (audit regressions)
# ----------------------------------------------------------------------


def test_brownout_none_or_nan_headroom_is_not_pressure():
    clock = FakeClock(0.0)
    bc = BrownoutController(
        "m", min_headroom=0.2, sustain_s=5.0, clock=clock,
    )
    for headroom in (None, float("nan")):
        bc.force_level(0)
        bc.evaluate(0.0, headroom=headroom)
        clock.advance(60.0)
        assert bc.evaluate(0.0, headroom=headroom) == 0
    # A real low advertisement still counts.
    bc.evaluate(0.0, headroom=0.05)
    clock.advance(5.1)
    assert bc.evaluate(0.0, headroom=0.05) == 1


def test_scaler_nan_headroom_is_not_pressure():
    from gofr_tpu.service.pool_scaler import PoolScaler
    from gofr_tpu.service.replica_pool import Replica, ReplicaPool

    class Stub(Replica):
        supports_stream = True

        def __init__(self, name, headroom):
            super().__init__(name)
            self._headroom = headroom

        def state(self):
            return "SERVING"

        def load(self):
            return 0

        def headroom(self):
            return self._headroom

        def set_handoff(self, handoff):
            pass

    a = Stub("a", float("nan"))
    pool = ReplicaPool([a], probe_interval_s=0)
    try:
        scaler = PoolScaler(
            pool, lambda: Stub("x", 0.9), max_replicas=3,
            up_headroom_floor=0.2, scale_up_wait_s=10.0, interval_s=0,
            sleep=lambda s: None,
        )
        for t in (0.0, 10.1, 60.0):
            assert scaler.evaluate(now=t) == "steady"
        assert len(pool.replicas) == 1
        # The same floor WITH a finite violation still scales.
        a._headroom = 0.05
        assert scaler.evaluate(now=100.0) == "steady"
        assert scaler.evaluate(now=110.1) == "up"
    finally:
        pool.close()


def test_engine_admission_nan_headroom_never_sheds():
    eng = make_engine()
    try:
        eng.admit_min_headroom = 0.99
        eng.hbm_headroom_ratio = lambda: float("nan")  # lying telemetry
        result = eng.generate_sync(
            "admit me", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, timeout=300,
        )
        assert len(result.token_ids) == 4
    finally:
        eng.close()


# ----------------------------------------------------------------------
# satellite: prefix-hit-aware admission ordering (off = byte-identical)
# ----------------------------------------------------------------------


class _Req:
    def __init__(self, name, slo_class="standard", hit=False):
        self.name = name
        self.slo_class = slo_class
        self.hit = hit


def test_queue_without_probe_is_byte_identical_fifo():
    plain = ClassPriorityQueue()
    probed = ClassPriorityQueue(prefix_probe=lambda req: False)
    reqs = [_Req(f"r{i}", hit=(i == 3)) for i in range(6)]
    for q in (plain, probed):
        for r in reqs:
            q.put_nowait(r)
    order_plain = [plain.get_nowait().name for _ in range(6)]
    order_probed = [probed.get_nowait().name for _ in range(6)]
    assert order_plain == order_probed == [f"r{i}" for i in range(6)]


def test_prefix_hit_jumps_within_its_class_lane():
    q = ClassPriorityQueue(prefix_probe=lambda req: req.hit)
    for i in range(5):
        q.put_nowait(_Req(f"r{i}", hit=(i == 3)))
    # The hit pops first; the misses keep their FIFO order after it.
    assert [q.get_nowait().name for _ in range(5)] == [
        "r3", "r0", "r1", "r2", "r4",
    ]


def test_prefix_probe_never_overrides_starvation_promotion():
    clock = FakeClock(0.0)
    q = ClassPriorityQueue(
        promote_after_s=5.0, clock=clock,
        prefix_probe=lambda req: req.hit,
    )
    q.put_nowait(_Req("old-batch", slo_class="batch"))
    clock.advance(6.0)
    q.put_nowait(_Req("hot-hit", slo_class="interactive", hit=True))
    # The over-age batch head outranks the interactive prefix hit —
    # the starvation bound is a hard contract, not a tie to break.
    assert q.get_nowait().name == "old-batch"
    assert q.get_nowait().name == "hot-hit"


def test_prefix_probe_exception_is_a_miss_not_a_wedge():
    def bad_probe(req):
        raise RuntimeError("trie corrupted")

    q = ClassPriorityQueue(prefix_probe=bad_probe)
    q.put_nowait(_Req("a"))
    q.put_nowait(_Req("b"))
    assert q.get_nowait().name == "a"
    assert q.get_nowait().name == "b"


def test_engine_knob_defaults_off_and_wires_probe_when_on():
    off = make_engine()
    try:
        assert off.queue_prefix_aware is False
        assert off._pending._prefix_probe is None
    finally:
        off.close()
    on = make_engine(
        queue_prefix_aware=True, auto_prefix=True, prefix_cache_blocks=8
    )
    try:
        assert on.queue_prefix_aware is True
        assert on._pending._prefix_probe is not None
    finally:
        on.close()
