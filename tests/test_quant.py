"""Int8 weight-only quantization: numerics, model forward, engine serving."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.registry import get_model
from gofr_tpu.models.transformer import init_transformer, transformer_forward
from gofr_tpu.ops.quant import Q8, dequantize, quantize_array, quantize_params
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer


def test_quantize_array_roundtrip_error():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    q = quantize_array(w)
    assert q.q.dtype == jnp.int8 and q.s.shape == (1, 32)
    err = np.abs(np.asarray(dequantize(q, jnp.float32) - w))
    # Per-channel absmax: error bounded by half a quantization step.
    bound = np.asarray(np.max(np.abs(np.asarray(w)), axis=0) / 127.0)
    assert (err <= bound[None, :] * 0.51 + 1e-6).all()


def test_quantize_stacked_per_layer_scales():
    w = jnp.stack([jnp.ones((8, 4)), 100.0 * jnp.ones((8, 4))])  # [L=2, in, out]
    q = quantize_array(w)
    assert q.s.shape == (2, 1, 4)
    np.testing.assert_allclose(np.asarray(dequantize(q, jnp.float32)), np.asarray(w))


def test_quantized_forward_close_to_dense():
    cfg = dataclasses.replace(get_model("llama-tiny").config, dtype=jnp.float32)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    ref = transformer_forward(params, tokens, cfg)
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["wq"], Q8)
    got = transformer_forward(qparams, tokens, cfg)
    # Logit agreement: quantization noise must not change the distribution
    # shape — check correlation and greedy-token agreement.
    a, b = np.asarray(ref).ravel(), np.asarray(got).ravel()
    corr = np.corrcoef(a, b)[0, 1]
    assert corr > 0.999, corr
    agree = (np.argmax(np.asarray(ref), -1) == np.argmax(np.asarray(got), -1)).mean()
    assert agree > 0.9, agree


def test_engine_int8_serving():
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        quant="int8",
    )
    assert eng.quant == "int8"
    assert isinstance(eng.params["layers"]["w_gate"], Q8)
    eng.start_sync()
    try:
        out = eng.generate_sync(
            "quantized", max_new_tokens=6, temperature=0.0, stop_on_eos=False
        )
        assert len(out.token_ids) == 6
        r2 = eng.generate_sync(
            "quantized", max_new_tokens=6, temperature=0.0, stop_on_eos=False
        )
        assert r2.token_ids == out.token_ids  # deterministic greedy
    finally:
        eng.stop_sync()


def test_engine_from_config_quant():
    from gofr_tpu.config import MockConfig

    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
        "TPU_QUANT": "int8",
    }))
    assert eng.quant == "int8"


def test_quant_rejections():
    with pytest.raises(ValueError, match="unsupported quant"):
        InferenceEngine(
            "llama-tiny", n_slots=2, max_len=64,
            tokenizer=ByteTokenizer(), quant="fp4",
        )
    with pytest.raises(ValueError, match="llm"):
        InferenceEngine("resnet-tiny", quant="int8")


def test_int4_groupwise_roundtrip():
    from gofr_tpu.ops.quant import dequantize, quantize_array4

    w = jax.random.normal(jax.random.PRNGKey(0), (256, 64), jnp.float32)
    q4 = quantize_array4(w, group=128)
    # Nibble-packed uint8: two 4-bit values per byte along the
    # contraction axis (plain-dtype storage; s4 trips backend bugs).
    assert q4.q.dtype.name == "uint8"
    assert q4.q.shape == (128, 64)
    assert q4.shape == (256, 64)  # logical
    assert q4.s.shape == (2, 1, 64)  # 256/128 groups
    recon = np.asarray(dequantize(q4, jnp.float32))
    # 4-bit group-wise: ~7% of group absmax worst case.
    err = np.abs(recon - np.asarray(w))
    assert err.max() <= np.abs(np.asarray(w)).max() / 7 + 1e-6


def test_int4_engine_serves_and_bytes_halve():
    from gofr_tpu.ops.quant import quantized_bytes
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    e8 = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        quant="int8",
    )
    e4 = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        quant="int4",
    )
    assert e4.quant == "int4"
    # int4 matmul weights store at half the int8 bytes (embeddings and
    # norms stay bf16 in both, so the full tree shrinks by less than 2x).
    assert quantized_bytes(e4.params) < quantized_bytes(e8.params)
    e4.start_sync()
    try:
        r1 = e4.generate_sync(
            "int4", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
        r2 = e4.generate_sync(
            "int4", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
    finally:
        e4.stop_sync()
    assert r1.token_ids == r2.token_ids and len(r1.token_ids) == 8


def test_int4_logits_close_to_bf16():
    from gofr_tpu.models.registry import get_model
    from gofr_tpu.models.transformer import transformer_forward
    from gofr_tpu.ops.quant import quantize_params

    spec = get_model("llama-tiny")
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    q4 = quantize_params(params, mode="int4")
    tokens = jnp.asarray([[1, 5, 9, 2, 7, 3]], jnp.int32)
    lb = np.asarray(transformer_forward(params, tokens, spec.config))
    l4 = np.asarray(transformer_forward(q4, tokens, spec.config))
    # Random-init tiny models have near-uniform logits (argmax gaps ~0),
    # so greedy agreement is meaningless here; logit correlation is the
    # right fidelity measure (trained models keep argmax via large gaps).
    corr = np.corrcoef(lb.ravel(), l4.ravel())[0, 1]
    assert corr >= 0.9


def test_int4_sharded_from_config():
    from gofr_tpu.config import MockConfig
    from gofr_tpu.serving.engine import InferenceEngine

    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
        "TPU_MESH_TP": "2", "TPU_QUANT": "int4",
    }))
    assert eng.quant == "int4"
    q4 = eng.params["layers"]["wq"]
    assert "tp" in str(q4.q.sharding.spec)
    eng.start_sync()
    try:
        r = eng.generate_sync(
            "int4 mesh", max_new_tokens=6, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        eng.stop_sync()
    assert len(r.token_ids) == 6
