"""Tenant attribution, SLO burn rates, and fairness-aware shedding
(serving/tenant_ledger.py + serving/slo.py; docs/advanced-guide/
observability.md "Tenant attribution & SLOs").

Deterministic throughout: ledger/SLO clocks are injectable (tests state
time instead of sleeping), greedy streams are byte-compared, and the
conservation invariants are exact under stated clocks."""

from __future__ import annotations

import pytest

from gofr_tpu.errors import ErrorTooManyRequests
from gofr_tpu.metrics.manager import Manager
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.slo import SLOEngine
from gofr_tpu.serving.tenant_ledger import TenantLedger
from gofr_tpu.serving.tokenizer import ByteTokenizer


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def tenant_metrics() -> Manager:
    m = Manager()
    for name in (
        "app_tpu_tenant_tokens_total",
        "app_tpu_tenant_kv_block_seconds_total",
        "app_tpu_tenant_requests_total",
        "app_tpu_tokens_generated",
        "app_tpu_requests_shed_total",
    ):
        m.new_counter(name)
    for name in ("app_tpu_slo_burn_rate", "app_tpu_slo_compliant"):
        m.new_gauge(name)
    return m


def counter_value(m: Manager, name: str, **labels: str) -> float:
    inst = [i for i in m.instruments() if i.name == name]
    if not inst:
        return 0.0
    want = set(labels.items())
    return sum(
        v for k, v in inst[0].collect().items() if want <= set(k)
    )


def make_engine(**kw):
    defaults = dict(
        n_slots=2, max_len=128, kv_block=16,
        tokenizer=ByteTokenizer(), tenant_ledger=True, seed=0,
    )
    defaults.update(kw)
    eng = InferenceEngine("llama-tiny", **defaults)
    eng.start_sync()
    return eng


# ----------------------------------------------------------------------
# TenantLedger units
# ----------------------------------------------------------------------


def test_ledger_kv_block_second_conservation_exact():
    """Σ per-tenant block·seconds == the pool-wide integral, EXACTLY,
    under a stated clock — the invariant is by-construction (same dt,
    same call), so any drift is a bug."""
    led = TenantLedger("m", clock=FakeClock())
    led.tick(0.0, [("a", 4), ("b", 2)])      # baseline (dt undefined)
    led.tick(1.0, [("a", 4), ("b", 2)])      # 1s: a+4, b+2
    led.tick(3.0, [("a", 1), ("c", 5)])      # 2s: a+2, c+10
    led.tick(3.0, [("a", 9)])                # dt=0: nothing accrues
    snap = led.snapshot()
    t = snap["tenants"]
    assert t["a"]["kv_block_seconds"] == 6.0
    assert t["b"]["kv_block_seconds"] == 2.0
    assert t["c"]["kv_block_seconds"] == 10.0
    assert snap["pool_kv_block_seconds"] == 18.0
    assert sum(
        s["kv_block_seconds"] for s in t.values()
    ) == snap["pool_kv_block_seconds"]
    # The dt=0 tick still refreshed the live held-block snapshot.
    assert t["a"]["held_blocks"] == 9


def test_ledger_label_clamp_overflow_folds_into_other():
    """Metric labels clamp to the first label_max distinct tenants;
    later tenants fold into tenant="_other" (bounded cardinality,
    monotonic series) while the /debug/tenants table stays unclamped."""
    m = tenant_metrics()
    led = TenantLedger("m", metrics=m, label_max=2, clock=FakeClock())
    led.tick(0.0, [])
    for i, tenant in enumerate(("a", "b", "c", "d")):
        led.tick(float(i + 1), [(tenant, 2)])
    inst = [
        i for i in m.instruments()
        if i.name == "app_tpu_tenant_kv_block_seconds_total"
    ][0]
    labels = {
        dict(k)["tenant"] for k in inst.collect()
    }
    assert labels == {"a", "b", "_other"}
    # The full table names everyone; the fold list names the clamped.
    snap = led.snapshot()
    assert set(snap["tenants"]) == {"a", "b", "c", "d"}
    assert snap["folded_tenants"] == ["c", "d"]
    assert snap["tenants"]["c"]["kv_block_seconds"] == 2.0


def test_ledger_table_bound_under_tenant_churn():
    """Tenant ids are request-controlled: a client minting a fresh id
    per request must not grow ledger memory without bound. Past
    table_max, new tenants account into the OVERFLOW row wholesale —
    attribution stays total, conservation still holds."""
    led = TenantLedger("m", label_max=2, table_max=3, clock=FakeClock())
    led.tick(0.0, [])
    for i in range(10):
        led.tick(float(i + 1), [(f"churn-{i}", 2)])
    snap = led.snapshot()
    assert len(snap["tenants"]) <= 4  # 3 rows + _other
    assert "_other" in snap["tenants"]
    assert sum(
        s["kv_block_seconds"] for s in snap["tenants"].values()
    ) == snap["pool_kv_block_seconds"] == 20.0

    class Req:
        prompt_ids = [1] * 10
        max_new_tokens = 10
        tenant = "churn-9"  # folded: no own row
        ledger_t0 = 0.0
        ledger_admitted = 0.0
        ledger_done = False

    # Folded tenants' queue accounting balances through OVERFLOW...
    led.note_enqueued(Req())
    assert led.snapshot()["tenants"]["_other"]["queued_requests"] == 1
    led.note_dequeued(Req())
    assert led.snapshot()["tenants"]["_other"]["queued_requests"] == 0
    # ...and fairness still bites on the overflow aggregate.
    led.note_enqueued(Req())
    assert led.over_fair_share("churn-99", 20, 0.5, 60, 100)


def test_ledger_fair_share_math_tokens_and_seats():
    led = TenantLedger("m", clock=FakeClock())

    class Req:
        prompt_ids = [1] * 10
        max_new_tokens = 10
        tenant = "a"
        ledger_t0 = 0.0
        ledger_admitted = 0.0
        ledger_done = False

    led.note_enqueued(Req())  # a holds 20 queued tokens / 1 seat
    # Token-denominated (budget_tokens set): 20 + 20 > 0.5 × 60 → over.
    assert led.over_fair_share("a", 20, 0.5, 60, 100)
    assert not led.over_fair_share("a", 20, 0.8, 60, 100)
    # Seat-denominated (no token budget): 1 + 1 > 0.5 × 2 → over.
    assert led.over_fair_share("a", 20, 0.5, 0, 2)
    assert not led.over_fair_share("a", 20, 0.5, 0, 100)
    # Another tenant holds nothing; untenanted never trips.
    assert not led.over_fair_share("b", 20, 0.5, 60, 100)
    assert not led.over_fair_share("", 10 ** 6, 0.01, 60, 100)


# ----------------------------------------------------------------------
# SLOEngine units
# ----------------------------------------------------------------------


def test_burn_rate_window_math_and_recovery():
    clock = FakeClock(10_000.0)
    m = tenant_metrics()
    slo = SLOEngine(
        "m", ttft_ms=100.0, availability=0.99, metrics=m, clock=clock,
    )
    # 8 good + 2 bad TTFTs → bad fraction 0.2, budget 0.01 → burn 20.
    for i in range(10):
        slo.observe("ok", {"ttft_s": 0.05 if i < 8 else 0.5})
        clock.advance(1.0)
    assert slo.burn_rate("ttft", "5m") == pytest.approx(20.0)
    assert slo.burn_rate("ttft", "1h") == pytest.approx(20.0)
    # Availability saw 10 ok → burning nothing.
    assert slo.burn_rate("availability", "5m") == 0.0
    assert not slo.compliant()
    gauge = [
        i for i in m.instruments() if i.name == "app_tpu_slo_compliant"
    ][0]
    assert list(gauge.collect().values()) == [0.0]
    # Sheds charge availability (the server failed the client) but not
    # the latency SLOs (a shed has no TTFT); cancels count nowhere.
    slo.observe("shed", {})
    slo.observe("cancelled", {"ttft_s": 9.9, "e2e_s": 9.9})
    assert slo.burn_rate("availability", "5m") == pytest.approx(
        (1 / 11) / 0.01
    )
    # Recovery: 6 minutes later the 5m window has aged out, the 1h one
    # still remembers.
    clock.advance(360.0)
    assert slo.burn_rate("ttft", "5m") == 0.0
    assert slo.burn_rate("ttft", "1h") > 0.0
    clock.advance(3600.0)
    assert slo.burn_rate("ttft", "1h") == 0.0
    assert slo.compliant()


def test_slo_snapshot_shape():
    slo = SLOEngine("m", e2e_ms=200.0, clock=FakeClock(5.0))
    slo.observe("ok", {"e2e_s": 0.1})
    snap = slo.snapshot()
    assert snap["enabled"] and snap["compliant"]
    w = snap["slos"]["e2e"]["windows"]
    assert w["5m"]["total"] == 1 and w["5m"]["good"] == 1
    assert set(w) == {"5m", "1h"}
    assert snap["slos"]["e2e"]["target"] == 0.99  # latency default


# ----------------------------------------------------------------------
# engine integration: conservation at tp=1 and tp=2
# ----------------------------------------------------------------------


def _run_mixed_tenants(eng, m):
    handles = []
    for i, tenant in enumerate(
        ("alice", "bob", "alice", "", "carol", "bob")
    ):
        handles.append(eng.submit_generate(
            f"conserve {i:02d} {'x' * (4 * i)}", max_new_tokens=4 + i,
            temperature=0.0, stop_on_eos=False, tenant=tenant,
        ))
    results = [h.future.result(timeout=300) for h in handles]
    rep = eng.tenant_report()
    t = rep["tenants"]
    # KV conservation: Σ tenants == the pool-wide integral from the
    # same ticks, compared on the UNROUNDED accumulators (the snapshot
    # rounds for JSON; float-add order differs between the two sums,
    # hence approx — under the unit test's integer clock it is exact).
    led = eng._tenant_ledger
    assert sum(
        s.kv_block_seconds for s in led._stats.values()
    ) == pytest.approx(led.pool_block_seconds, rel=1e-9)
    assert rep["pool_kv_block_seconds"] > 0.0
    # Token conservation: per-tenant decode totals sum to the engine's
    # aggregate generated-token counter; prefill totals to the known
    # prompt lengths.
    assert sum(s["decode_tokens"] for s in t.values()) == sum(
        len(r.token_ids) for r in results
    ) == counter_value(m, "app_tpu_tokens_generated")
    assert sum(s["prefill_tokens"] for s in t.values()) == sum(
        len(h.prompt_ids) for h in handles
    )
    # Attribution named the right tenants.
    assert t["alice"]["requests"]["ok"] == 2
    assert t["_untenanted"]["requests"]["ok"] == 1
    return results


def test_conservation_tp1():
    m = tenant_metrics()
    eng = make_engine(metrics=m)
    try:
        _run_mixed_tenants(eng, m)
    finally:
        eng.close()


def test_conservation_tp2():
    """The attribution spine is host bookkeeping — device-count
    agnostic, so the same invariants hold on a GSPMD-sharded engine
    (conftest's 8 virtual devices)."""
    import jax

    m = tenant_metrics()
    eng = make_engine(metrics=m, tp=2, devices=jax.devices()[:2])
    try:
        _run_mixed_tenants(eng, m)
    finally:
        eng.close()


# ----------------------------------------------------------------------
# fairness-aware shedding: THE acceptance path
# ----------------------------------------------------------------------

WB_PROMPTS = [f"well behaved {i:02d}" for i in range(4)]


def _wb_streams(eng):
    handles = [
        eng.submit_generate(
            p, max_new_tokens=6, temperature=0.0, stop_on_eos=False,
            tenant=f"wb-{i % 2}",
        )
        for i, p in enumerate(WB_PROMPTS)
    ]
    return [h.future.result(timeout=300).token_ids for h in handles]


def test_fairness_shed_acceptance_path():
    """A hog saturating the queue is shed reason=tenant_fair_share —
    the hog only; well-behaved tenants' greedy streams stay
    byte-identical to a no-hog run; the availability burn rate rises
    then recovers; /debug/tenants names the hog."""
    # Reference: the same well-behaved traffic with no hog at all.
    ref_eng = make_engine()
    try:
        reference = _wb_streams(ref_eng)
    finally:
        ref_eng.close()

    m = tenant_metrics()
    clock = FakeClock(50_000.0)
    eng = make_engine(
        metrics=m,
        queue_max_tokens=512,
        tenant_fair_share=0.3,
        slo_availability=0.999,
    )
    eng._slo._clock = clock  # stated time for the burn windows
    try:
        # The hog floods: its queued share caps at 0.3 × 512 tokens —
        # about one 80-token request at a time — so past that every hog
        # submit sheds with the fairness reason while the queue keeps
        # room for everyone else.
        hog_handles, hog_sheds = [], 0
        for i in range(24):
            try:
                hog_handles.append(eng.submit_generate(
                    "H" * 64 + f" {i:02d}", max_new_tokens=16,
                    temperature=0.0, stop_on_eos=False, tenant="hog",
                ))
            except ErrorTooManyRequests as exc:
                hog_sheds += 1
                assert "tenant_fair_share" in str(exc)
        # Degraded, not banned: the hog keeps its share of service and
        # only the burst beyond it is shed.
        assert hog_sheds > 0 and hog_handles
        assert counter_value(
            m, "app_tpu_requests_shed_total", reason="tenant_fair_share"
        ) == hog_sheds
        # No other shed reason fired: the fairness shed kept the global
        # budgets un-exhausted, so only the hog paid.
        assert counter_value(
            m, "app_tpu_requests_shed_total"
        ) == hog_sheds
        # Well-behaved tenants ride through the hog's burst untouched.
        streams = _wb_streams(eng)
        assert streams == reference
        for h in hog_handles:
            h.future.result(timeout=300)
        # Burn rose: the hog's sheds are availability failures.
        assert eng._slo.burn_rate("availability", "5m") > 1.0
        rep = eng.tenant_report()
        assert rep["tenants"]["hog"]["requests"]["shed"] == hog_sheds
        # The attribution table /debug/tenants serves names the hog —
        # by shed count AND occupancy share.
        top = eng.capacity_report()["tenants"]
        assert any(
            e["tenant"] == "hog" and e["shed"] == hog_sheds
            for e in top
        )
        assert rep["tenants"]["hog"]["kv_block_seconds"] > 0
        # ... and recovered: 6 minutes of clean traffic later the 5m
        # window has aged the sheds out (the 1h window still remembers
        # — sustained-burn alerts are supposed to outlive the page).
        clock.advance(360.0)
        _wb_streams(eng)
        assert eng._slo.burn_rate("availability", "5m") == 0.0
        assert eng._slo.burn_rate("availability", "1h") > 0.0
        # An hour later the sustained window is clean too.
        clock.advance(3700.0)
        _wb_streams(eng)
        assert eng.slo_report()["compliant"] is True
    finally:
        eng.close()


def test_fairness_off_is_default_and_ledger_off_means_no_hooks():
    """TPU_TENANT_FAIR_SHARE unset → no fairness shed path at all;
    TPU_TENANT_LEDGER=0 → the whole layer is one is-not-None check:
    no ledger object, no request stamps, tenant_report disabled."""
    eng = make_engine(tenant_ledger=False)
    try:
        assert eng._tenant_ledger is None
        assert eng.tenant_fair_share == 0.0
        h = eng.submit_generate(
            "no ledger", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, tenant="alice",
        )
        h.future.result(timeout=300)
        # The request was never stamped: zero attribution work done.
        assert h.ledger_t0 == 0.0 and not h.ledger_done
        assert eng.tenant_report() == {"enabled": False}
        assert "tenants" not in eng.flight_records()
    finally:
        eng.close()


# ----------------------------------------------------------------------
# advertisement: health, probes, pool stamps
# ----------------------------------------------------------------------


def test_health_probe_and_pool_advertisement():
    from gofr_tpu.service.replica_pool import EngineReplica, ReplicaPool

    m = tenant_metrics()
    eng = make_engine(metrics=m, slo_ttft_ms=60_000)
    try:
        eng.generate_sync(
            "advertise", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, tenant="alice", timeout=300,
        )
        health = eng.health_check()
        assert health["details"]["slo"]["compliant"] is True
        assert "ttft" in health["details"]["slo"]["burn_rate_5m"]
        assert health["details"]["tenant_ledger"]["tenants"] >= 1
        replica = EngineReplica("r0", eng)
        desc = replica.describe()
        assert desc["slo_compliant"] is True
        pool = ReplicaPool([replica])
        flights = pool.flight_records()["replicas"]["r0"]
        assert flights["slo_compliant"] is True
        assert flights["tenants"][0]["tenant"] in ("alice", "_untenanted")
        caps = pool.capacity_report()["replicas"]["r0"]
        assert caps["slo_compliant"] is True
        tenants = pool.tenant_report()["replicas"]["r0"]
        assert "alice" in tenants["tenants"]
        slo_rep = pool.slo_report()["replicas"]["r0"]
        assert slo_rep["enabled"] and slo_rep["compliant"]
    finally:
        eng.close()


# ----------------------------------------------------------------------
# compile-cache persistence (TPU_COMPILE_CACHE_DIR)
# ----------------------------------------------------------------------


def test_compile_cache_dir_recorded_and_no_steady_state_regression(
    tmp_path,
):
    """A second engine boot against a populated cache dir serves with
    zero steady-state recompiles, and the cache's provenance rides
    health and /debug/capacity."""
    cache_dir = str(tmp_path / "xla-cache")

    def boot():
        eng = make_engine(compile_cache_dir=cache_dir)
        eng.generate_sync(
            "cache me", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, timeout=300,
        )
        return eng

    eng1 = boot()
    cache1 = eng1.compile_stats()["compile_cache"]
    assert cache1["dir"] == cache_dir
    health = eng1.health_check()
    assert (
        health["details"]["compiles"]["compile_cache"]["dir"] == cache_dir
    )
    eng1.close()

    eng2 = boot()
    try:
        # Warm-up fence armed after the boot request: any further
        # compile is a regression — a populated cache dir must never
        # ADD steady-state recompiles.
        eng2.mark_steady_state()
        eng2.generate_sync(
            "cache me again", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, timeout=300,
        )
        stats = eng2.compile_stats()
        assert stats["steady_state_recompiles"] == 0
        assert stats["compile_cache"]["dir"] == cache_dir
        assert eng2.capacity_report()["compiles"]["compile_cache"][
            "dir"
        ] == cache_dir
    finally:
        eng2.close()
