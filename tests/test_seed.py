"""Per-request sampling seeds: counter-based keys
(fold_in(fold_in(base, seed), n_sampled)) make a seeded stream a pure
function of (engine seed, request seed, prompt, params) — independent of
batch composition, window size, and pipelined/mega scheduling."""

from __future__ import annotations

import pytest

from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer

PROMPT = "the quick brown fox"


def _engine(**kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("window_k", 4)
    kw.setdefault("tokenizer", ByteTokenizer())
    return InferenceEngine("llama-tiny", **kw)


def _sample(eng, **kw):
    return eng.generate_sync(
        PROMPT, max_new_tokens=16, temperature=0.9, stop_on_eos=False,
        timeout=120, **kw
    ).token_ids


@pytest.fixture(scope="module")
def eng():
    e = _engine()
    e.start_sync()
    yield e
    e.stop_sync()


def test_same_seed_reproduces(eng):
    assert _sample(eng, seed=42) == _sample(eng, seed=42)


def test_different_seeds_differ(eng):
    assert _sample(eng, seed=1) != _sample(eng, seed=2)


def test_unseeded_requests_differ(eng):
    # OpenAI semantics: no seed → independent draws per request.
    assert _sample(eng) != _sample(eng)


def test_seeded_stream_scheduling_invariant(eng):
    # The SAME seeded stream must come out of a different window size, a
    # mega-window engine, and alongside concurrent traffic — the key
    # depends only on (seed, n_sampled), never on how steps were batched.
    want = _sample(eng, seed=7)
    for kw in ({"window_k": 8}, {"mega_windows": 4}, {"window_k": 2}):
        other = _engine(**kw)
        other.start_sync()
        try:
            assert _sample(other, seed=7) == want, kw
        finally:
            other.stop_sync()
    # Concurrent batch-mate on the same engine.
    a = eng.submit_generate(
        PROMPT, max_new_tokens=16, temperature=0.9, stop_on_eos=False,
        seed=7,
    )
    b = eng.submit_generate(
        "completely different prompt", max_new_tokens=16, temperature=0.7,
        stop_on_eos=False,
    )
    assert a.future.result(timeout=120).token_ids == want
    b.future.result(timeout=120)


def test_seed_with_spec_engine_reproduces():
    e = _engine(spec_tokens=2)
    e.start_sync()
    try:
        assert _sample(e, seed=5) == _sample(e, seed=5)
    finally:
        e.stop_sync()


def test_greedy_unaffected_by_seed(eng):
    g = lambda **kw: eng.generate_sync(  # noqa: E731
        PROMPT, max_new_tokens=16, temperature=0.0, stop_on_eos=False,
        timeout=120, **kw
    ).token_ids
    assert g(seed=1) == g(seed=99)
