"""Request-lifecycle observability suite (ISSUE 6 acceptance gate).

Deterministic throughout: injectable clocks (no sleeps-as-
synchronization), an in-memory span collector instead of a wire
exporter, faults driven through ``gofr_tpu/faults``, and the prober/
supervisor seams the chaos suites already use.

Covered:

* timeline phase math and flight-recorder entries (injected clock);
* flight-recorder ring eviction with slow/errored requests PINNED so a
  burst cannot evict them;
* phase histograms record EXACTLY once per request per phase, from
  host-side values only;
* one trace per request: ``tpu.request`` is a child of the caller's
  ``traceparent`` and every phase span (queue-wait, admission, prefill
  chunks, emit-flush, decode) shares its trace id;
* THE acceptance path: a request served through a ``ReplicaPool`` whose
  replica dies mid-stream produces ONE trace whose spans — phases on
  replica A, the replay and failover annotations, phases on replica B —
  all share the request's trace id, and ``/debug/flight`` (the pool's
  ``flight_records``) shows the same timeline with the failover
  annotation;
* ``traceparent`` round-trips through ``HTTPReplica`` so cross-replica
  traces stitch;
* shed requests land PINNED in the recorder with the shed outcome;
* the layer costs nothing when off: ``TPU_FLIGHT_RECORDER=0`` with no
  metrics and no active exporter mints no timeline at all.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.config import MockConfig
from gofr_tpu.container import Container
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.observability import (
    FlightRecorder,
    RequestObservability,
    parse_traceparent,
)
from gofr_tpu.serving.supervisor import EngineSupervisor
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.replica_pool import (
    EngineReplica,
    HTTPReplica,
    ReplicaPool,
)
from gofr_tpu.tracing import Tracer, get_tracer, set_tracer

TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


class _CaptureExporter:
    """In-memory span sink; ``is_noop`` absent → the tracer is ACTIVE."""

    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()

    def export(self, span, service_name):
        with self._lock:
            self.spans.append(span)

    def by_name(self, name):
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def clear(self):
        with self._lock:
            self.spans.clear()


@pytest.fixture()
def capture():
    """Install a capturing tracer for the test, restore after."""
    old = get_tracer()
    cap = _CaptureExporter()
    set_tracer(Tracer(service_name="obs-test", exporter=cap))
    yield cap
    set_tracer(old)


@pytest.fixture(scope="module")
def metrics():
    # Container registration is the real instrument set (histograms
    # with buckets, gauges) — the one production records into.
    return Container.create(MockConfig({"APP_NAME": "obs-test"})).metrics


@pytest.fixture(scope="module")
def engine(metrics):
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        metrics=metrics,
    )
    eng.start_sync()
    yield eng
    eng.stop_sync()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _hist_count(metrics, name, model="llama-tiny"):
    inst = {i.name: i for i in metrics.instruments()}[name]
    for labels, (_counts, (_total, n)) in inst.collect().items():
        if ("model", model) in labels:
            return n
    return 0


def _gauge(metrics, name):
    inst = {i.name: i for i in metrics.instruments()}[name]
    values = inst.collect()
    return next(iter(values.values())) if values else None


PHASES = (
    "app_tpu_queue_wait_seconds",
    "app_tpu_prefill_seconds",
    "app_tpu_ttft_seconds",
    "app_tpu_inter_token_seconds",
    "app_tpu_e2e_seconds",
)


# ----------------------------------------------------------------------
# timeline + recorder units (injected clock, no engine)
# ----------------------------------------------------------------------


def test_timeline_phase_math_with_injected_clock():
    t = [100.0]
    hub = RequestObservability(
        "m", recorder=FlightRecorder(), clock=lambda: t[0],
        wall_ns=lambda: 1_000_000_000,
    )
    tl = hub.begin(prompt_tokens=7, traceparent=TRACEPARENT)
    assert tl is not None
    assert tl.trace_id == "ab" * 16 and tl.parent_span_id == "cd" * 8
    t[0] = 100.5
    tl.mark_admitted(t[0])
    t[0] = 101.0
    tl.note_chunk(100.5, 101.0, 7)
    tl.mark_prefill_done(t[0])
    t[0] = 101.25
    tl.mark_first_token(t[0])
    t[0] = 103.25
    tl.finish("ok", "stop", output_tokens=5)
    phases = tl.phases()
    assert phases["queue_wait_s"] == pytest.approx(0.5)
    assert phases["prefill_s"] == pytest.approx(0.5)
    assert phases["ttft_s"] == pytest.approx(1.25)
    assert phases["decode_s"] == pytest.approx(2.0)
    assert phases["inter_token_s"] == pytest.approx(0.5)  # 2.0 / (5-1)
    assert phases["e2e_s"] == pytest.approx(3.25)
    snap = hub.recorder.snapshot()
    assert len(snap["records"]) == 1 and not snap["pinned"]
    entry = snap["records"][0]
    assert entry["outcome"] == "ok" and entry["prompt_tokens"] == 7
    assert entry["prefill_chunks"] == 1
    # finish() is latched: a racing second terminal path is a no-op.
    tl.finish("error", "late")
    assert tl.outcome == "ok"
    assert len(hub.recorder.snapshot()["records"]) == 1


def test_flight_recorder_evicts_ring_but_pins_survive_burst():
    t = [0.0]
    hub = RequestObservability(
        "m", recorder=FlightRecorder(capacity=4, pin_capacity=2, slow_s=5.0),
        clock=lambda: t[0], wall_ns=lambda: 0,
    )

    def run_one(outcome, e2e):
        tl = hub.begin(prompt_tokens=1)
        start = t[0]
        t[0] += e2e
        tl.finish(outcome, "x", output_tokens=1)
        return start

    run_one("error", 0.1)   # pinned (errored)
    run_one("ok", 9.0)      # pinned (slow: e2e > slow_s)
    for _ in range(10):     # healthy burst far beyond the ring
        run_one("ok", 0.1)
    snap = hub.recorder.snapshot()
    assert len(snap["records"]) == 4  # ring capacity: burst evicted
    assert len(snap["pinned"]) == 2   # the interesting ones survived
    assert {e["outcome"] for e in snap["pinned"]} == {"error", "ok"}
    assert snap["pinned"][1]["phases"]["e2e_s"] == pytest.approx(9.0)


def test_layer_off_mints_no_timeline():
    hub = RequestObservability("m", metrics=None, recorder=None)
    assert hub.begin(prompt_tokens=1) is None  # noop tracer, nothing on


# ----------------------------------------------------------------------
# engine integration: histograms, spans, recorder
# ----------------------------------------------------------------------


def test_phase_histograms_record_exactly_once_per_request(metrics, engine):
    before = {name: _hist_count(metrics, name) for name in PHASES}
    for _ in range(2):
        r = engine.generate_sync(
            "histogram once per phase", max_new_tokens=8,
            temperature=0.0, stop_on_eos=False,
        )
        assert len(r.token_ids) == 8
    after = {name: _hist_count(metrics, name) for name in PHASES}
    for name in PHASES:
        assert after[name] - before[name] == 2, name
    # Per-window utilization gauges rode along (host values only).
    assert _gauge(metrics, "app_tpu_batch_occupancy") is not None
    assert _gauge(metrics, "app_tpu_tokens_per_step") is not None
    assert _gauge(metrics, "app_tpu_decode_step_seconds") is not None


def test_one_trace_per_request_with_phase_parentage(capture, engine):
    r = engine.generate_sync(
        "trace me end to end", max_new_tokens=6, temperature=0.0,
        stop_on_eos=False, traceparent=TRACEPARENT,
    )
    assert len(r.token_ids) == 6
    roots = capture.by_name("tpu.request")
    assert len(roots) == 1
    root = roots[0]
    # The engine's request span is a CHILD of the caller's traceparent.
    assert root.trace_id == "ab" * 16
    assert root.parent_id == "cd" * 8
    assert root.attributes["tpu.outcome"] == "ok"
    for name in (
        "tpu.queue_wait", "tpu.admission", "tpu.prefill.chunk",
        "tpu.emit_flush", "tpu.decode",
    ):
        spans = capture.by_name(name)
        assert spans, f"missing {name} span"
        assert all(s.trace_id == root.trace_id for s in spans), name
        assert all(s.parent_id == root.span_id for s in spans), name
    decode = capture.by_name("tpu.decode")[0]
    assert decode.attributes["tokens"] == 6
    # Spans carry real wall-clock extents (start <= end, all inside the
    # request span).
    assert root.start_ns <= decode.start_ns <= decode.end_ns <= root.end_ns


def test_trace_adopted_from_current_span_without_explicit_header(
    capture, engine
):
    # The HTTP middleware / gRPC interceptor set a context-var span; an
    # in-task submit with NO explicit traceparent still joins its trace.
    span = get_tracer().start_span("GET /v1/completions")
    try:
        engine.generate_sync(
            "adopt ambient span", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        span.end()
    root = capture.by_name("tpu.request")[0]
    assert root.trace_id == span.trace_id
    assert root.parent_id == span.span_id


def test_shed_request_is_pinned_with_outcome(engine):
    from gofr_tpu.errors import ErrorDeadlineExceeded

    with pytest.raises(ErrorDeadlineExceeded):
        engine.submit_generate(
            "shed me", max_new_tokens=4, temperature=0.0,
            deadline_s=-1.0,
        )
    pinned = engine.flight_records()["pinned"]
    assert pinned, "shed request must be pinned"
    entry = pinned[-1]
    assert entry["outcome"] == "shed"
    assert any(a["name"] == "tpu.shed" for a in entry["annotations"])


def test_flight_recorder_off_disables_layer(metrics):
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        flight_recorder=False,
    )
    eng.start_sync()
    try:
        req = eng.submit_generate(
            "no timeline", max_new_tokens=2, temperature=0.0,
            stop_on_eos=False,
        )
        assert req.timeline is None  # no metrics, noop tracer, ring off
        req.future.result(timeout=120)
        assert eng.flight_records() == {"enabled": False}
    finally:
        eng.stop_sync()


# ----------------------------------------------------------------------
# traceparent round-trip through HTTPReplica
# ----------------------------------------------------------------------


class _FakeResp:
    status_code = 200
    body = b""

    def json(self):
        return {
            "choices": [{"text": "ok", "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 1},
        }


class _CaptureService:
    def __init__(self):
        self.headers = None

    def post(self, path, json=None, headers=None):
        self.headers = dict(headers or {})
        return _FakeResp()


def test_traceparent_round_trips_through_http_replica():
    service = _CaptureService()
    replica = HTTPReplica("remote", service)
    req = replica.submit(
        "stitch me", max_new_tokens=4, traceparent=TRACEPARENT
    )
    result = req.future.result(timeout=30)
    assert result.text == "ok"
    # Propagated downstream verbatim...
    assert service.headers.get("traceparent") == TRACEPARENT
    # ...and the receiving server's middleware would adopt exactly the
    # caller's trace id (the round trip: one trace across replicas).
    trace_id, span_id = parse_traceparent(service.headers["traceparent"])
    assert trace_id == "ab" * 16 and span_id == "cd" * 8


# ----------------------------------------------------------------------
# THE acceptance path: replay + failover keep one trace
# ----------------------------------------------------------------------


def _make_supervised(metrics, **eng_kw):
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        metrics=metrics, **eng_kw,
    )
    sup = EngineSupervisor(
        eng, max_restarts=1, backoff_s=0.25, backoff_reset_s=60.0,
        rng=random.Random(99), sleep=lambda s: None, metrics=metrics,
    ).start()
    eng.start_sync()
    return eng, sup


@pytest.fixture(scope="module")
def engine_pair(metrics):
    a = _make_supervised(metrics)
    b = _make_supervised(metrics)
    yield a, b
    faults.reset()
    for eng, sup in (a, b):
        sup.stop()
        eng.stop_sync()


def _drain(req, timeout=180.0):
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def test_failover_mid_stream_keeps_one_trace_and_flight_timeline(
    capture, metrics, engine_pair
):
    """A request served through a ReplicaPool whose replica dies
    mid-stream produces ONE trace — queue/admission/prefill on A,
    decode on A, the replay + failover annotations, decode on B — all
    under the request's trace id, and the pool's flight view shows the
    same timeline with the failover annotation."""
    (eng_a, sup_a), (eng_b, sup_b) = engine_pair
    pool = ReplicaPool(
        [EngineReplica("a", eng_a), EngineReplica("b", eng_b)],
        probe_interval_s=0, probe_timeout_s=60.0,
        rng=random.Random(7), metrics=metrics,
    )
    params = dict(max_new_tokens=24, temperature=0.0, stop_on_eos=False)
    try:
        ref = eng_b.generate_sync("observed failover stream", **params)
        capture.clear()

        # A's device dies from its 4th dispatch on — persistent and
        # targeted, so crash 1 lands mid-stream, the recovery replay's
        # prefill is crash 2, max_restarts=1 exhausts, A goes DOWN and
        # hands the live request to B.
        hits = {"n": 0}

        def crash_a(engine=None, **kw):
            if engine is eng_a:
                hits["n"] += 1
                if hits["n"] >= 4:
                    raise RuntimeError("injected: replica A device loss")

        faults.arm("scheduler.device_step", action=crash_a)
        req = pool.submit_generate(
            "observed failover stream", traceparent=TRACEPARENT, **params
        )
        toks = _drain(req)
        result = req.future.result(timeout=180)
        assert toks == ref.token_ids
        assert result.token_ids == ref.token_ids

        # ONE trace: every span shares the request's trace id.
        root = capture.by_name("tpu.request")[0]
        assert root.trace_id == "ab" * 16
        span_names = {s.name for s in capture.spans}
        for needed in (
            "tpu.queue_wait", "tpu.admission", "tpu.prefill.chunk",
            "tpu.decode", "tpu.replay", "tpu.failover",
        ):
            assert needed in span_names, needed
        # tpu.compile spans are the one deliberate exception: a warm-up
        # compile belongs to the ENGINE's boot trace (or its own), not
        # to whichever request happened to trigger it — the request's
        # trace must still be complete without them.
        assert all(
            s.trace_id == root.trace_id
            for s in capture.spans
            if s.name.startswith("tpu.") and s.name != "tpu.compile"
        )
        failover_span = capture.by_name("tpu.failover")[0]
        assert failover_span.attributes["source"] == "a"
        assert failover_span.attributes["target"] == "b"

        # /debug/flight view: the SAME timeline, once, in the origin
        # replica's recorder, carrying the failover annotation.
        flights = pool.flight_records()["replicas"]
        entries = [
            e
            for snap in flights.values()
            for e in snap.get("records", []) + snap.get("pinned", [])
            if e["trace_id"] == root.trace_id
            and any(
                a["name"] == "tpu.failover" for a in e["annotations"]
            )
        ]
        assert len(entries) == 1
        entry = entries[0]
        assert entry["outcome"] == "ok"
        assert entry["replays"] >= 1
        names = [a["name"] for a in entry["annotations"]]
        assert "tpu.replay" in names and "tpu.failover" in names
        assert entry["output_tokens"] == len(ref.token_ids)
    finally:
        faults.reset()
        pool.stop_prober()
        for replica in pool.replicas:
            replica.engine.set_replica_handoff(None)
        # The wounded replica must be healthy again for later tests.
        assert eng_b.state == "SERVING"
        if eng_a.state != "SERVING":
            sup_a.revive()
        assert eng_a.state == "SERVING"
