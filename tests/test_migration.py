"""Migration runner tests (reference ``migration/migration_test.go`` behaviors)."""

import io

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.container import Container
from gofr_tpu.datasource.redis import MiniRedis
from gofr_tpu.logging import Level, Logger
from gofr_tpu.migration import Migrate, run


def make_container(with_redis=False, mini=None):
    cfg = {"DB_DIALECT": "sqlite", "DB_NAME": ":memory:"}
    if with_redis:
        cfg.update({"REDIS_HOST": "127.0.0.1", "REDIS_PORT": str(mini.port)})
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    c = Container.create(MockConfig(cfg), logger=logger)
    return c, out


def test_migrations_run_in_order_and_track():
    c, _ = make_container()
    order = []

    migrations = {
        2: Migrate(up=lambda ds: order.append(2)),
        1: Migrate(
            up=lambda ds: (
                order.append(1),
                ds.sql.exec("CREATE TABLE t1 (id INTEGER)"),
            )
        ),
    }
    run(migrations, c)
    assert order == [1, 2]
    rows = c.sql.query("SELECT version FROM gofr_migrations ORDER BY version")
    assert [r["version"] for r in rows] == [1, 2]


def test_migrations_idempotent_on_rerun():
    c, _ = make_container()
    count = {"n": 0}
    migrations = {1: Migrate(up=lambda ds: count.__setitem__("n", count["n"] + 1))}
    run(migrations, c)
    run(migrations, c)
    assert count["n"] == 1


def test_failed_migration_rolls_back_and_raises():
    c, _ = make_container()

    def bad(ds):
        ds.sql.exec("CREATE TABLE will_rollback (id INTEGER)")
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        run({1: Migrate(up=bad)}, c)
    # Not recorded as applied; rerun executes it again.
    assert c.sql.query("SELECT * FROM gofr_migrations") == []


def test_invalid_version_rejected():
    c, _ = make_container()
    with pytest.raises(ValueError):
        run({0: Migrate(up=lambda ds: None)}, c)
    with pytest.raises(ValueError):
        run({-5: Migrate(up=lambda ds: None)}, c)


def test_redis_tracking():
    mini = MiniRedis().start()
    try:
        c, _ = make_container(with_redis=True, mini=mini)
        run({1: Migrate(up=lambda ds: ds.redis.set("migrated", "yes"))}, c)
        assert c.redis.get("migrated") == "yes"
        assert "1" in c.redis.hgetall("gofr_migrations")
        # Re-run skips.
        run({1: Migrate(up=lambda ds: ds.redis.set("migrated", "twice"))}, c)
        assert c.redis.get("migrated") == "yes"
    finally:
        mini.stop()


def test_no_datasources_warns_and_skips():
    out = io.StringIO()
    logger = Logger(level=Level.DEBUG, out=out, err=out, is_terminal=False)
    c = Container.create(MockConfig({}), logger=logger)
    run({1: Migrate(up=lambda ds: None)}, c)
    assert "no datasources" in out.getvalue()
