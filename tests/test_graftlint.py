"""graftlint rule-by-rule suite: one positive and one negative fixture
per rule (GL001–GL015), suppression syntax, baseline round-trip/drift,
CLI exit codes, and the gate that keeps the committed baseline in sync
with the tree."""

import os
import subprocess
import sys
import textwrap

from gofr_tpu.analysis.cli import main
from gofr_tpu.analysis.core import Baseline, LintConfig, run_paths


def _lint(tmp_path, rel, source, select=None):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    config = LintConfig()
    if select:
        config.select = set(select)
    findings = run_paths([str(tmp_path)], config=config)
    return [f.rule_id for f in findings], findings


# ----------------------------------------------------------------------
# GL001 — host-device sync
# ----------------------------------------------------------------------


def test_gl001_flags_item_and_conversions_on_hot_path(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/hot.py",
        """
        import numpy as np

        def emit(tokens_dev, logps_dev):
            a = tokens_dev.item()
            b = float(logps_dev)
            c = np.asarray(tokens_dev)
            return a, b, c
        """,
        select=["GL001"],
    )
    assert ids == ["GL001", "GL001", "GL001"]
    assert "device" in findings[0].message


def test_gl001_ignores_cold_paths_and_host_values(tmp_path):
    ids, _ = _lint(
        tmp_path, "datasource/cold.py",
        """
        def emit(tokens_dev):
            return float(tokens_dev)
        """,
        select=["GL001"],
    )
    assert ids == []  # datasource/ is not a hot-path dir
    ids, _ = _lint(
        tmp_path, "serving/host.py",
        """
        def emit(count):
            return float(count)  # plain host value, no device naming
        """,
        select=["GL001"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL002 — tracer branch in jit
# ----------------------------------------------------------------------


def test_gl002_flags_python_branch_on_tracer(tmp_path):
    ids, findings = _lint(
        tmp_path, "mod.py",
        """
        import jax

        @jax.jit
        def relu_bad(x):
            if x > 0:
                return x
            return 0.0
        """,
        select=["GL002"],
    )
    assert ids == ["GL002"]
    assert "relu_bad" in findings[0].message


def test_gl002_allows_shape_static_and_identity_branches(tmp_path):
    ids, _ = _lint(
        tmp_path, "mod.py",
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("k",))
        def ok(x, k, mask=None):
            if x.shape[0] > 2:      # shapes are static under trace
                x = x + 1
            if mask is not None:    # identity checks are host-level
                x = x * mask
            if k > 1:               # declared static
                x = x * k
            return x
        """,
        select=["GL002"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL003 — recompilation hazards
# ----------------------------------------------------------------------


def test_gl003_flags_mutable_static_arg_and_shape_keys(tmp_path):
    ids, _ = _lint(
        tmp_path, "mod.py",
        """
        import jax

        def run(x, opts):
            return x

        jitted = jax.jit(run, static_argnums=(1,))
        compiled = {}

        def call(x):
            compiled[f"{x.shape}"] = 1
            return jitted(x, [1, 2])
        """,
        select=["GL003"],
    )
    assert ids == ["GL003", "GL003"]


def test_gl003_allows_hashable_static_args(tmp_path):
    ids, _ = _lint(
        tmp_path, "mod.py",
        """
        import jax

        def run(x, opts):
            return x

        jitted = jax.jit(run, static_argnums=(1,))

        def call(x):
            return jitted(x, (1, 2))
        """,
        select=["GL003"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL004 — blocking calls
# ----------------------------------------------------------------------


def test_gl004_flags_sleep_in_async_and_hot_path(tmp_path):
    ids, _ = _lint(
        tmp_path, "handlers.py",
        """
        import time

        async def handler(ctx):
            time.sleep(0.1)
        """,
        select=["GL004"],
    )
    assert ids == ["GL004"]
    _, findings = _lint(
        tmp_path, "serving/engine.py",
        """
        import time

        def drain(self):
            time.sleep(0.05)
        """,
        select=["GL004"],
    )
    hot = [f for f in findings if f.path.endswith("serving/engine.py")]
    assert [f.rule_id for f in hot] == ["GL004"]
    assert "hot path" in hot[0].message


def test_gl004_allows_async_sleep_and_cold_path_sleep(tmp_path):
    ids, _ = _lint(
        tmp_path, "handlers.py",
        """
        import asyncio
        import time

        async def handler(ctx):
            await asyncio.sleep(0.1)

        def retry_backoff():
            time.sleep(1.0)  # not async, not a hot-path file
        """,
        select=["GL004"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL005 — lock discipline
# ----------------------------------------------------------------------


def test_gl005_flags_unlocked_write_to_guarded_attr(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/engine.py",
        """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._draining = False

            def stop(self):
                with self._lock:
                    self._draining = True

            def restart(self):
                self._draining = False  # raced against stop()
        """,
        select=["GL005"],
    )
    assert ids == ["GL005"]
    assert "_draining" in findings[0].message


def test_gl005_sees_across_mixin_classes_and_sibling_files(tmp_path):
    # The serving core is ONE runtime object composed from mixins across
    # files: a lock taken in engine.py must guard the same attribute
    # written from scheduler.py (and from another class in the same file).
    (tmp_path / "serving").mkdir(parents=True)
    (tmp_path / "serving" / "engine.py").write_text(textwrap.dedent(
        """
        import threading

        class Engine:
            def stop(self):
                with self._submit_lock:
                    self._running = False

        class OtherMixin:
            def boot(self):
                self._running = True  # same object, no lock
        """
    ))
    (tmp_path / "serving" / "scheduler.py").write_text(textwrap.dedent(
        """
        class SchedulerMixin:
            def loop(self):
                self._running = False  # lock lives in engine.py
        """
    ))
    config = LintConfig()
    config.select = {"GL005"}
    findings = run_paths([str(tmp_path)], config=config)
    flagged = sorted(f.path.rsplit("/", 1)[-1] for f in findings)
    assert flagged == ["engine.py", "scheduler.py"]


def test_gl005_allows_consistent_locking(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/engine.py",
        """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._draining = False

            def stop(self):
                with self._lock:
                    self._draining = True

            def restart(self):
                with self._lock:
                    self._draining = False
        """,
        select=["GL005"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL006 — swallowed exceptions
# ----------------------------------------------------------------------


def test_gl006_flags_broad_silent_except(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/routes.py",
        """
        def handle(req):
            try:
                return req.run()
            except Exception:
                pass
        """,
        select=["GL006"],
    )
    assert ids == ["GL006"]


def test_gl006_allows_narrow_or_handled_excepts(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/routes.py",
        """
        def handle(req, log):
            try:
                return req.run()
            except ValueError:
                pass                      # narrow: fine
            except Exception as exc:
                log.errorf("failed: %s", exc)   # handled: fine
                return None

        def fallback(req):
            try:
                return req.fast_path()
            except Exception:
                return req.slow_path()    # fallback work: fine
        """,
        select=["GL006"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL007 — donated-buffer reuse after donate_argnums
# ----------------------------------------------------------------------


def test_gl007_flags_read_after_donation(tmp_path):
    ids, findings = _lint(
        tmp_path, "mod.py",
        """
        import jax

        step = jax.jit(run, donate_argnums=(0,))

        def bad(cache, tokens):
            out = step(cache, tokens)
            return out, cache.lengths  # donated buffer read back
        """,
        select=["GL007"],
    )
    assert ids == ["GL007"]
    assert "donate" in findings[0].message


def test_gl007_flags_immediately_invoked_jit_donation(tmp_path):
    ids, _ = _lint(
        tmp_path, "mod.py",
        """
        import jax

        def bad(params, quantize):
            quantized = jax.jit(quantize, donate_argnums=(0,))(params)
            total = sum(params.values())  # params' buffers are gone
            return quantized, total
        """,
        select=["GL007"],
    )
    assert ids == ["GL007"]


def test_gl007_allows_rebinding_and_reassignment(tmp_path):
    ids, _ = _lint(
        tmp_path, "mod.py",
        """
        import jax

        step = jax.jit(run, donate_argnums=(0,))

        def good_rebind(cache, tokens):
            cache = step(cache, tokens)   # idiomatic: result rebinds
            return cache.lengths

        def good_attr(self, tokens):
            self.cache = step(self.cache, tokens)
            return self.cache

        def good_reassign(cache, tokens, fresh):
            out = step(cache, tokens)
            cache = fresh()               # new binding clears the taint
            return out, cache

        def good_no_donation(cache, tokens, plain):
            out = plain(cache, tokens)    # not a donating wrapper
            return out, cache
        """,
        select=["GL007"],
    )
    assert ids == []


def test_gl007_scopes_do_not_leak(tmp_path):
    # A donation inside one function must not taint another function's
    # use of the same variable name; args evaluated as part of the
    # donating call itself are pre-donation reads.
    ids, _ = _lint(
        tmp_path, "mod.py",
        """
        import jax

        step = jax.jit(run, donate_argnums=(0,))

        def donates(cache):
            return step(cache, cache.lengths)  # arg reads: pre-donation

        def unrelated(cache):
            return cache.lengths
        """,
        select=["GL007"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL008 — jnp.asarray / jnp.array inside lax.scan bodies
# ----------------------------------------------------------------------


def test_gl008_flags_asarray_in_scan_bodies(tmp_path):
    ids, findings = _lint(
        tmp_path, "models/layers.py",
        """
        import jax
        import jax.numpy as jnp

        def forward(x, params, table):
            def body(carry, layer):
                bias = jnp.asarray(table)       # baked per body trace
                return carry + layer + bias, None

            x, _ = jax.lax.scan(body, x, params)
            y, _ = jax.lax.scan(
                lambda c, l: (c + jnp.array([1.0]), None), x, params
            )
            return x + y
        """,
        select=["GL008"],
    )
    assert ids == ["GL008", "GL008"]
    assert "lax.scan" in findings[0].message
    assert "hoist" in findings[0].message


def test_gl008_ignores_conversions_outside_bodies(tmp_path):
    ids, _ = _lint(
        tmp_path, "models/layers.py",
        """
        import jax
        import jax.numpy as jnp

        def forward(x, params, table):
            bias = jnp.asarray(table)           # hoisted: fine
            def body(carry, layer):
                return carry + layer + bias, None

            x, _ = jax.lax.scan(body, x, params)
            return x

        def unrelated(table):
            # Not a scan body at all.
            return jnp.array(table)

        def factory_scan(x, params, make_body):
            # Factory-built bodies are statically out of reach — the
            # rule must stay quiet rather than guess.
            x, _ = jax.lax.scan(make_body(1), x, params)
            return x
        """,
        select=["GL008"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL009 — per-request jit-cache growth
# ----------------------------------------------------------------------


def test_gl009_flags_shape_keyed_lru_cache_and_dict_cached_jit(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/progs.py",
        """
        import functools
        from functools import lru_cache

        import jax

        class Engine:
            @lru_cache(maxsize=128)
            def _program(self, seq_len):
                # Method + per-request key: one executable per observed
                # prompt length, and the cache pins self forever.
                return jax.jit(lambda x: x * seq_len)

            def warm(self, prompt_len):
                self._cache[prompt_len] = jax.jit(lambda x: x)
                self._cache.setdefault(prompt_len, jax.jit(lambda x: x))

        @functools.cache
        def build_step(n_tokens):
            # Unbounded decorator around a jit builder.
            return jax.jit(lambda x: x[:n_tokens])
        """,
        select=["GL009"],
    )
    assert ids == ["GL009", "GL009", "GL009", "GL009"]
    assert "padding bucket" in findings[0].message


def test_gl009_ignores_bounded_bucketed_caches(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/progs.py",
        """
        from functools import lru_cache

        import jax

        @lru_cache(maxsize=8)
        def program_for_bucket(bucket):
            # Module-level, bounded, keyed on a CLOSED bucket set — the
            # fix the rule recommends.
            return jax.jit(lambda x: x + bucket)

        @lru_cache
        def expensive_lookup(seq_len):
            # Shape-ish key but no jit built: not a compile cache.
            return seq_len * 2

        PROGS = {}

        def warm(bucket):
            PROGS[bucket] = jax.jit(lambda x: x)  # bucket id key: fine
        """,
        select=["GL009"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL010 — repeated host pull of the same device value in a loop
# ----------------------------------------------------------------------


def test_gl010_flags_repeated_pull_of_same_value_in_loop(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/emit.py",
        """
        import jax
        import numpy as np

        def emit(rows, first_dev, lp_dev):
            out = []
            for row in rows:
                tok = int(np.asarray(first_dev)[row])
                lp = float(np.asarray(first_dev)[row])
                out.append((tok, lp))
            return out

        def fetch(rows, planes_dev):
            while rows:
                row = rows.pop()
                a = jax.device_get(planes_dev)[row]
                b = jax.device_get(planes_dev)[row + 1]
        """,
        select=["GL010"],
    )
    assert ids == ["GL010", "GL010"]
    assert "hoist one host copy" in findings[0].message


def test_gl010_ignores_hoisted_rebound_and_closure_pulls(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/emit.py",
        """
        import numpy as np

        def emit(rows, first_dev):
            first = np.asarray(first_dev)  # hoisted: the fix
            return [int(first[row]) for row in rows]

        def drain(inflight):
            while inflight:
                emitted = inflight.popleft()[0]
                a = np.asarray(emitted)  # rebound per iteration
                b = np.asarray(emitted)  # same iteration's value: fine
                del a, b

        def lazy(rows, x_dev):
            for row in rows:
                # Closure bodies are not per-iteration work of THIS loop.
                pull = lambda: np.asarray(x_dev) + np.asarray(x_dev)
            return pull

        def upload(rows, table):
            import jax.numpy as jnp
            for row in rows:
                a = jnp.asarray(table)  # host->device: GL008's business
                b = jnp.asarray(table)

        class Drainer:
            def drain(self):
                while self.queue:
                    a = np.asarray(self.emitted)
                    self.emitted = self.fetch()  # attribute rebound:
                    b = np.asarray(self.emitted)  # a different array
        """,
        select=["GL010"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL011 — per-row clock reads in scheduler emit/decode loops
# ----------------------------------------------------------------------


def test_gl011_flags_clock_in_per_row_loop_on_hot_path(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import time

        def process_window(snapshot):
            for seq in snapshot:
                now = time.time()  # per-row stamp: k*S syscalls/window
                seq.ttft = now - seq.enqueued_at

        def flush(entries):
            for entry in entries:
                entry.first_at = time.monotonic()
        """,
        select=["GL011"],
    )
    assert ids == ["GL011", "GL011"]
    assert "once per window" in findings[0].message


def test_gl011_ignores_hoisted_while_polls_cold_paths_and_closures(tmp_path):
    # Hoisted stamps, while-loop deadline polls, and nested closures are
    # all fine on the hot path.
    ids, _ = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import time

        def process_window(snapshot):
            now = time.time()  # hoisted: the fix
            for seq in snapshot:
                seq.ttft = now - seq.enqueued_at

        def drain(deadline_s):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:  # poll: condition IS time
                pass

        def fetch(emitted, entries):
            for entry in entries:
                while not emitted.is_ready():  # readiness poll in a for
                    t = time.monotonic()
                entry.mark = 1

        def lazy(rows):
            for row in rows:
                stamp = lambda: time.time()  # not run by this loop
            return stamp
        """,
        select=["GL011"],
    )
    assert ids == []
    # Same per-row stamping OFF the hot path: not this rule's business.
    ids, _ = _lint(
        tmp_path, "datasource/poll.py",
        """
        import time

        def poll(rows):
            for row in rows:
                row.at = time.time()
        """,
        select=["GL011"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL012 — blocking network I/O without an explicit timeout
# ----------------------------------------------------------------------


def test_gl012_flags_timeoutless_clients_in_serving_and_service(tmp_path):
    ids, findings = _lint(
        tmp_path, "service/wire.py",
        """
        import httpx
        import requests
        import socket
        import urllib.request

        def build():
            return httpx.Client()  # inherits someone else's default

        def fetch(url):
            return requests.get(url)  # requests default: NO timeout

        def open_raw(url):
            return urllib.request.urlopen(url)

        def connect(addr):
            return socket.create_connection(addr)
        """,
        select=["GL012"],
    )
    assert ids == ["GL012", "GL012", "GL012", "GL012"]
    assert "timeout" in findings[0].message


def test_gl012_accepts_budgeted_calls_and_other_tiers(tmp_path):
    # Explicit budgets (kwarg or positional) are the fix; client METHOD
    # calls inherit their constructor's budget; other tiers are out of
    # scope for this rule.
    ids, _ = _lint(
        tmp_path, "serving/wire.py",
        """
        import httpx
        import requests
        import socket
        import urllib.request

        def build(read_s, connect_s):
            return httpx.Client(
                timeout=httpx.Timeout(read_s, connect=connect_s)
            )

        def fetch(client, url):
            return client.get(url)  # budget set at construction

        def fetch2(url):
            return requests.get(url, timeout=10)

        def open_raw(url):
            return urllib.request.urlopen(url, None, 10)

        def connect(addr):
            return socket.create_connection(addr, 5)
        """,
        select=["GL012"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "datasource/wire.py",
        """
        import requests

        def fetch(url):
            return requests.get(url)
        """,
        select=["GL012"],
    )
    assert ids == []  # datasource clients carry their own conventions


# ----------------------------------------------------------------------
# GL013 — retry loops without backoff
# ----------------------------------------------------------------------


def test_gl013_flags_backoffless_retry_loops(tmp_path):
    ids, findings = _lint(
        tmp_path, "service/retry.py",
        """
        def fetch(svc, url, max_retries):
            for attempt in range(max_retries + 1):
                try:
                    return svc.get(url)
                except ConnectionError:
                    continue  # immediate re-attempt: herd amplifier

        def push(svc, body, budget):
            retries_left = budget
            while retries_left > 0:
                try:
                    return svc.post("v1/x", json=body)
                except ConnectionError:
                    retries_left -= 1
        """,
        select=["GL013"],
    )
    assert ids == ["GL013", "GL013"]
    assert "backoff" in findings[0].message


def test_gl013_accepts_backoff_and_plain_loops(tmp_path):
    # Jittered sleeps, RetryConfig, re-raising handlers, and loops that
    # are not retry loops at all are the negative space.
    ids, _ = _lint(
        tmp_path, "serving/retry_ok.py",
        """
        import time

        def fetch(svc, url, cfg):
            for attempt in range(cfg.max_retries + 1):
                try:
                    return svc.get(url)
                except ConnectionError:
                    time.sleep(cfg.delay_s(attempt))

        def ship(self, req, payload):
            for attempt in range(self.transfer_retries + 1):
                try:
                    return self._import(req, payload)
                except ConnectionError:
                    pass
                self._sleep(self._transfer_delay(attempt))

        def strict(svc, url, max_retries):
            for attempt in range(max_retries):
                try:
                    return svc.get(url)
                except ConnectionError:
                    raise  # not a retry: failures propagate

        def walk(replicas):
            for replica in replicas:  # adoption walk, not a retry loop
                try:
                    if replica.adopt():
                        return True
                except ValueError:
                    continue
            return False
        """,
        select=["GL013"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "datasource/retry.py",
        """
        def fetch(svc, url, max_retries):
            for attempt in range(max_retries):
                try:
                    return svc.get(url)
                except ConnectionError:
                    continue
        """,
        select=["GL013"],
    )
    assert ids == []  # out of the serving/service scope


# ----------------------------------------------------------------------
# GL014 — cross-mesh host pulls / sharding-annotation drift
# ----------------------------------------------------------------------


def test_gl014_flags_cache_pulls_and_bare_device_put(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import jax
        import numpy as np

        def _flush(self):
            planes = jax.device_get(self.cache.k)  # all-gathers the pool
            rows = np.asarray(self.cache.lengths)
            return planes, rows

        def _upload(self, table):
            return jax.device_put(table)  # no placement: drift
        """,
        select=["GL014"],
    )
    assert ids == ["GL014", "GL014", "GL014"]
    assert "export seam" in findings[0].message
    assert "NamedSharding" in findings[2].message


def test_gl014_accepts_export_seam_placed_puts_and_cold_files(tmp_path):
    # The export seam, device-side jnp.asarray, placed device_puts, and
    # non-cache pulls are the negative space.
    ids, _ = _lint(
        tmp_path, "serving/engine.py",
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        def export_blocks_for(self, ids):
            # the deliberate host bounce: export-named seam
            return np.asarray(jax.device_get(self.cache.k[:, ids]))

        def _up(self, x, rep):
            return jax.device_put(x, rep)  # placed: fine

        def _emit(self, tokens_dev):
            return np.asarray(tokens_dev)  # not a cache plane (GL001's job)

        def _trace(self, cache):
            return jnp.asarray(cache.lengths)  # stays on device
        """,
        select=["GL014"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "serving/hf_loader.py",
        """
        import jax

        def to_device(x):
            return jax.device_put(x)  # boot path, out of scope
        """,
        select=["GL014"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL015 — jax.jit created inside a per-request function body
# ----------------------------------------------------------------------


def test_gl015_flags_jit_built_in_request_path(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/pipeline.py",
        """
        import jax
        from functools import partial

        def handle_generate(self, tokens):
            step = jax.jit(lambda t: t + 1)  # fresh program per request
            return step(tokens)

        def _decode_once(self, params, x):
            fn = partial(jax.jit, donate_argnums=(0,))(self._fwd)
            return fn(params, x)
        """,
        select=["GL015"],
    )
    assert ids == ["GL015", "GL015"]
    assert "per-request" in findings[0].message


def test_gl015_exempts_module_scope_builders_and_boot(tmp_path):
    # Module scope, _build_*/*_program builders (exemption inherited by
    # their nested defs), __init__/_init* boot paths, and the loader
    # files are the negative space; calling an already-built program in
    # a request path is of course fine.
    ids, _ = _lint(
        tmp_path, "serving/steps.py",
        """
        import jax
        from functools import partial

        shared_step = jax.jit(lambda t: t + 1)  # module scope

        class EngineBits:
            def __init__(self):
                self._cache_init = jax.jit(self._make_cache)

            def _init_serving_state(self):
                self._pool = jax.jit(self._make_pool)()

            def _build_steps(self):
                @partial(jax.jit, donate_argnums=(1,))
                def decode(params, cache):
                    return params, cache

                self._decode = decode

            def sampling_program(self):
                return jax.jit(self._sample)

            def handle(self, tokens):
                return self._decode(tokens)  # CALLING a program: fine
        """,
        select=["GL015"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "serving/hf_loader.py",
        """
        import jax

        def load_leaf(x):
            return jax.jit(lambda v: v)(x)  # loader module, out of scope
        """,
        select=["GL015"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "ops/kernels.py",
        """
        import jax

        def helper(x):
            return jax.jit(lambda v: v)(x)  # outside serving/
        """,
        select=["GL015"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL016 — request-controlled strings as metric label values
# ----------------------------------------------------------------------


def test_gl016_flags_request_controlled_label_values(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/handlers.py",
        """
        def account(self, req, ctx):
            self._metrics.increment_counter(
                "app_requests_total", "tenant", req.tenant
            )
            self._metrics.add_counter(
                "app_tokens_total", 5, "model", self.model_name,
                "tenant", tenant_id,
            )
            self._metrics.set_gauge(
                "app_queue", 1.0, "who", ctx.headers["x-tenant-id"]
            )
            REQUESTS.labels(tenant=req.tenant).inc()
        """,
        select=["GL016"],
    )
    assert ids == ["GL016", "GL016", "GL016", "GL016"]
    assert "cardinality" in findings[0].message


def test_gl016_accepts_clamped_and_engine_owned_labels(tmp_path):
    # A clamp-helper call (label_for/*_label) bounds the value by
    # construction; engine-owned values (model names, reason literals)
    # never taint; key POSITIONS named "tenant" are fine — only the
    # VALUE matters; and metric calls outside serving//service/ are out
    # of scope.
    ids, _ = _lint(
        tmp_path, "serving/handlers.py",
        """
        def account(self, req, ledger):
            self._metrics.increment_counter(
                "app_requests_total",
                "tenant", ledger.label_for(req.tenant),
            )
            self._metrics.add_counter(
                "app_tokens_total", 5,
                "tenant", clamp_label(req.tenant),
            )
            self._metrics.increment_counter(
                "app_requests_shed_total",
                "model", self.model_name, "reason", "tenant_quota",
            )
        """,
        select=["GL016"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "metrics/export.py",
        """
        def account(m, req):
            m.increment_counter("app_requests_total", "tenant", req.tenant)
        """,
        select=["GL016"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL017 — control-loop threshold comparisons without hysteresis
# ----------------------------------------------------------------------


def test_gl017_flags_threshold_state_flip_without_hysteresis(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/controller.py",
        """
        class Controller:
            def tick(self):
                if self.burn_rate > self.enter_threshold:
                    self.level = 1  # flips on one noisy tick
                if self.pool_headroom() < self.headroom_floor:
                    self.degraded = True
        """,
        select=["GL017"],
    )
    assert ids == ["GL017", "GL017"]
    assert "sustain" in findings[0].message


def test_gl017_accepts_sustain_windows_and_shed_decisions(tmp_path):
    # A sustain anchor (the *_since idiom) or any hysteresis/budget
    # guard evidence in the function exempts it; shedding/raising in
    # the branch is a per-request decision, not controller state; and
    # files outside serving//service/ are out of scope.
    ids, _ = _lint(
        tmp_path, "serving/controller.py",
        """
        class Controller:
            def tick(self, now):
                if self.burn_rate > self.enter_threshold:
                    if self._over_since is None:
                        self._over_since = now
                    elif now - self._over_since >= self.sustain_s:
                        self.level += 1
        """,
        select=["GL017"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "serving/admission.py",
        """
        class Admission:
            def check(self, req):
                if self.pool_headroom() < self.admit_floor:
                    self._shed("hbm_headroom")
                    raise TooManyRequests("retry elsewhere")
        """,
        select=["GL017"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "ops/controller.py",
        """
        class Controller:
            def tick(self):
                if self.burn_rate > self.enter_threshold:
                    self.level = 1  # outside serving//service/
        """,
        select=["GL017"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL018 — host pull inside the device transfer leg
# ----------------------------------------------------------------------


def test_gl018_flags_host_pulls_in_device_leg_functions(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import jax
        import numpy as np

        def _export_payload_device_leg(self, block_ids):
            planes = [
                np.asarray(self.cache.k[:, b]) for b in block_ids
            ]  # the bounce the leg exists to remove
            return planes

        def paged_move_block(cache, dst, k_blk):
            host = jax.device_get(k_blk)  # never on the device leg
            return cache
        """,
        select=["GL018"],
    )
    assert ids == ["GL018", "GL018"]
    assert "device" in findings[0].message


def test_gl018_accepts_device_resident_legs_and_export_seam(tmp_path):
    # Jitted extraction, explicit sharding-aware device_put, non-plane
    # host reads, and the documented export* host bounce are the
    # negative space; device-leg-ness inherits into nested helpers.
    ids, _ = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import jax
        import numpy as np

        def _write_block_device_leg(self, bid, payload, j):
            k_blk = jax.device_put(
                payload.k_blocks[j], self._block_sharding
            )  # shard-to-shard, stays on device
            return self._paged_move_block(
                self.cache, self._up(np.int32(bid)), k_blk
            )

        def export_blocks(cache, ids):
            # the deliberate host bounce: export-named seam (GL014)
            return np.asarray(jax.device_get(cache.k[:, ids]))

        def _transfer_stats_device_leg(self):
            return np.asarray(self._timings)  # host data, not a plane
        """,
        select=["GL018"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import numpy as np

        def _import_device_leg(self, payload):
            def helper(j):
                return np.asarray(payload.k_blocks[j])  # inherited leg
            return [helper(j) for j in range(payload.n_blocks)]
        """,
        select=["GL018"],
    )
    assert ids == ["GL018"]


# ----------------------------------------------------------------------
# GL019 — device sync outside the designated device-window seam
# ----------------------------------------------------------------------


def test_gl019_flags_syncs_in_loop_phase_functions(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import jax

        def _reap_lifecycle(self):
            jax.block_until_ready(self.cache.lengths)  # hidden wait

        def _ledger_tick(self):
            n = self._nsteps_dev.item()  # device pull in a host phase
            return n

        def _dispatch_prefill_chunk(self):
            lp = float(self._logps_dev)  # sync outside the seam
            return lp
        """,
        select=["GL019"],
    )
    assert ids == ["GL019", "GL019", "GL019"]
    assert "device-window seam" in findings[0].message


def test_gl019_accepts_seam_waits_and_host_reads(tmp_path):
    # The designated seam (incl. nested helpers), float()/.item() of
    # already-pulled host arrays (call results), and non-device values
    # are the negative space; inline disables document deliberate
    # barriers (the lockstep idiom).
    ids, _ = _lint(
        tmp_path, "serving/scheduler.py",
        """
        import jax
        import numpy as np

        def _process_window(self, emitted):
            jax.block_until_ready(emitted)  # THE device-wait seam

            def helper(arr):
                return float(arr_dev)  # seam-ness inherits
            return helper(emitted)

        def _dispatch_window(self):
            self._jax.block_until_ready(self._tokens_dev)  # lockstep seam

        def _flush_prefill_emits(self, pull, lp_dev, row):
            lp = float(pull(lp_dev)[row])  # pulled host copy, not a sync
            return lp

        def _retire(self, req):
            return float(req.ttft_s)  # host value, not a device plane

        def _dispatch_prefill_chunk(self):
            if self._lockstep:
                self._jax.block_until_ready(self.cache.lengths)  # graftlint: disable=GL019 — deliberate lockstep barrier
        """,
        select=["GL019"],
    )
    assert ids == []
    # Out-of-scope file: the rule is scheduler-loop specific.
    ids, _ = _lint(
        tmp_path, "serving/engine.py",
        """
        import jax

        def warm_up(self):
            jax.block_until_ready(self._tokens_dev)
        """,
        select=["GL019"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL023 — ack before the result publish / terminal seam
# ----------------------------------------------------------------------


def test_gl023_flags_ack_before_result_seam(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/consumer.py",
        """
        def handle(self, msg):
            self._sub.ack(msg.id)  # broker forgets the message here
            reply = self._run(msg)
            self.broker.publish("tpu.replies", reply)

        def park(self, msg, exc):
            self._sub.ack(msg.id)  # crash here and the DLQ entry is lost
            self._dead_letter(msg, exc)

        def resolve(self, msg, result):
            self.sub.ack(msg.id)
            msg.future.set_result(result)
        """,
        select=["GL023"],
    )
    assert ids == ["GL023", "GL023", "GL023"]
    assert "at-least-once" in findings[0].message


def test_gl023_accepts_publish_then_ack_and_ack_only(tmp_path):
    # Publish-first-ack-last is the contract; an ack with no later seam
    # (the dedup replay path, where the reply already went out) is the
    # negative space; nested defs are separate bodies; out-of-scope
    # files are untouched; deliberate at-most-once carries a disable.
    ids, _ = _lint(
        tmp_path, "pubsub/consumer.py",
        """
        def handle(self, msg):
            reply = self._run(msg)
            self.broker.publish("tpu.replies", reply)
            self._sub.ack(msg.id)  # reply is durable; safe to forget

        def replay(self, msg):
            if msg.id in self._ledger:
                self._sub.ack(msg.id)  # reply already published

        def outer(self, msg):
            self._sub.ack(msg.id)
            def emit(r):
                self.broker.publish("tpu.replies", r)
            return emit

        def at_most_once(self, msg):
            self._sub.ack(msg.id)  # graftlint: disable=GL023 — metrics tick, loss-tolerant by contract
            self.broker.publish("tpu.metrics", msg.value)
        """,
        select=["GL023"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "datasource/consumer.py",
        """
        def handle(self, msg):
            self._sub.ack(msg.id)
            self.broker.publish("tpu.replies", msg.value)
        """,
        select=["GL023"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL024 — transfer-handle acquisition without a budget
# ----------------------------------------------------------------------


def test_gl024_flags_budgetless_handle_acquisition(tmp_path):
    ids, findings = _lint(
        tmp_path, "service/puller.py",
        """
        def pull(self, handle):
            return dma_fetch(handle)  # blocks on the exporter forever

        def ask(self, source, ids):
            return source.fetch_prefilled(ids)

        def export(self, engine, ids):
            return engine.export_cached(ids)
        """,
        select=["GL024"],
    )
    assert ids == ["GL024", "GL024", "GL024"]
    assert "deadline" in findings[0].message


def test_gl024_accepts_budgeted_and_out_of_scope(tmp_path):
    # A deadline=/timeout_s= kwarg (or a **kwargs splat that may carry
    # one) states the budget; files outside serving//service/ are not
    # transfer-plane code; deliberate unbounded waits carry a disable.
    ids, _ = _lint(
        tmp_path, "service/puller.py",
        """
        def pull(self, handle, deadline):
            return dma_fetch(handle, deadline=deadline)

        def ask(self, source, ids, budget):
            return source.fetch_prefilled(
                ids, deadline=budget, timeout_s=2.0
            )

        def export(self, engine, ids, **kw):
            return engine.export_cached(ids, **kw)

        def forever(self, handle):
            return dma_fetch(handle)  # graftlint: disable=GL024 — test harness, budget owned by the pytest timeout
        """,
        select=["GL024"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "datasource/puller.py",
        """
        def pull(self, handle):
            return dma_fetch(handle)
        """,
        select=["GL024"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL025 — a second decode-logits path in the serving plane
# ----------------------------------------------------------------------


def test_gl025_flags_batched_verify_forward_in_serving(tmp_path):
    # The once-shipped bug class: serving calls a batched verify
    # forward whose contraction shape accumulates bf16 in a different
    # order than the decode step, so near-tie argmaxes flip.
    ids, findings = _lint(
        tmp_path, "serving/programs.py",
        """
        def body(carry, _):
            logits, cache = transformer_verify_step(
                params, inputs, cache, active, cfg
            )
            return carry, logits

        def other(carry, _):
            return carry, models.custom_verify_step(params, inputs)
        """,
        select=["GL025"],
    )
    assert ids == ["GL025", "GL025"]
    assert "contraction shape" in findings[0].message
    assert "transformer_decode_step" in findings[0].message


def test_gl025_accepts_decode_step_and_out_of_scope(tmp_path):
    # Reusing the decode-step builder is the fix, not a finding; the
    # models layer (parity tests, builders) legitimately calls the
    # batched verify; deliberate tolerance-checked uses carry a disable.
    ids, _ = _lint(
        tmp_path, "serving/programs.py",
        """
        def pos_body(pcarry, tok_j):
            cache_i, n_i = pcarry
            logits, cache_i = transformer_decode_step(
                params, tok_j, cache_i, active, cfg
            )
            return (cache_i, n_i), logits

        def parity(params, inputs, cache):
            return transformer_verify_step(params, inputs, cache)  # graftlint: disable=GL025 — tolerance-checked models-layer parity harness
        """,
        select=["GL025"],
    )
    assert ids == []
    ids, _ = _lint(
        tmp_path, "models/transformer.py",
        """
        def build(params, inputs, cache):
            return transformer_verify_step(params, inputs, cache)
        """,
        select=["GL025"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------


def test_inline_suppression_silences_one_rule(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/routes.py",
        """
        def handle(req):
            try:
                return req.run()
            except Exception:  # graftlint: disable=GL006 — probe endpoint
                pass
        """,
        select=["GL006"],
    )
    assert ids == []


def test_disable_next_line_and_unrelated_rule_still_fires(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/hot.py",
        """
        def emit(tokens_dev):
            # graftlint: disable-next-line=GL001
            a = float(tokens_dev)
            b = float(tokens_dev)  # graftlint: disable=GL004 (wrong rule)
            return a, b
        """,
        select=["GL001"],
    )
    assert ids == ["GL001"]  # only the wrongly-suppressed line fires


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------

_BASELINE_SRC = """
def handle(req):
    try:
        return req.run()
    except Exception:
        pass
"""


def test_baseline_roundtrip_and_line_shift_stability(tmp_path):
    _, findings = _lint(tmp_path, "serving/routes.py", _BASELINE_SRC)
    baseline = Baseline.from_findings(findings)
    new, stale = baseline.apply(findings)
    assert new == [] and stale == []
    # Insert lines above: fingerprints key on content, not line numbers.
    shifted = "# a comment\n# another\n" + textwrap.dedent(_BASELINE_SRC)
    (tmp_path / "serving/routes.py").write_text(shifted)
    _, findings2 = _lint(tmp_path, "serving/routes.py", shifted)
    new, stale = baseline.apply(findings2)
    assert new == [] and stale == []


def test_baseline_drift_detection(tmp_path):
    _, findings = _lint(tmp_path, "serving/routes.py", _BASELINE_SRC)
    baseline = Baseline.from_findings(findings)
    # The debt is paid off: the baseline entry must be reported stale.
    new, stale = baseline.apply([])
    assert new == [] and len(stale) == 1


def test_cli_exit_codes_and_check_baseline(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    target = tmp_path / "serving" / "routes.py"
    target.parent.mkdir(parents=True)
    target.write_text(textwrap.dedent(_BASELINE_SRC))
    # New findings, no baseline yet -> 1.
    assert main([str(tmp_path)]) == 1
    assert "GL006" in capsys.readouterr().out
    # Accept as baseline -> 0, then a clean re-run -> 0.
    assert main([str(tmp_path), "--write-baseline"]) == 0
    assert main([str(tmp_path)]) == 0
    assert main([str(tmp_path), "--check-baseline"]) == 0
    # Pay off the debt: plain run stays 0, --check-baseline demands a
    # baseline refresh (exit 1) so stale entries can't mask regressions.
    target.write_text("def handle(req):\n    return req.run()\n")
    assert main([str(tmp_path)]) == 0
    assert main([str(tmp_path), "--check-baseline"]) == 1
    assert "no longer occur" in capsys.readouterr().err
    assert main([str(tmp_path), "--write-baseline"]) == 0
    assert main([str(tmp_path), "--check-baseline"]) == 0


def test_pyproject_fallback_parses_multiline_lists(tmp_path):
    # The 3.10 fallback parser must handle values spanning lines — the
    # repo's own hot-path-files list does.
    from gofr_tpu.analysis.core import load_pyproject_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_pyproject_config(os.path.join(repo, "pyproject.toml"))
    assert cfg.get("hot-path-files") == [
        "serving/batcher.py", "serving/scheduler.py", "serving/engine.py",
    ]
    assert cfg.get("request-path-dirs") == ["serving", "ops", "grpc"]


def test_pyproject_fallback_recovers_from_non_literal_values(tmp_path):
    # TOML booleans parse, and a value the fallback cannot parse must not
    # wedge the scan and swallow every following key.
    from gofr_tpu.analysis.core import load_pyproject_config

    pp = tmp_path / "pyproject.toml"
    pp.write_text(textwrap.dedent(
        """
        [tool.graftlint]
        flag = true
        weird = 1979-05-27T07:32:00Z
        exclude = [
            "a.py",
            "b.py",
        ]
        """
    ))
    cfg = load_pyproject_config(str(pp))
    assert cfg.get("exclude") == ["a.py", "b.py"]
    # tomllib parses `flag` natively; the 3.10 fallback maps true->True.
    assert cfg.get("flag") is True


def test_baseline_is_cwd_independent(tmp_path, monkeypatch):
    proj = tmp_path / "proj"
    (proj / "serving").mkdir(parents=True)
    (proj / "pyproject.toml").write_text("")  # marks the repo root
    (proj / "serving" / "routes.py").write_text(textwrap.dedent(_BASELINE_SRC))
    monkeypatch.chdir(proj)
    assert main([str(proj), "--write-baseline"]) == 0
    # Same tree, analyzed from a different CWD: fingerprints must match.
    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir()
    monkeypatch.chdir(elsewhere)
    assert main([str(proj), "--check-baseline"]) == 0


def test_scoped_select_does_not_rot_the_baseline(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    target = tmp_path / "serving" / "routes.py"
    target.parent.mkdir(parents=True)
    target.write_text(textwrap.dedent(_BASELINE_SRC))  # one GL006 finding
    assert main([str(tmp_path), "--write-baseline"]) == 0
    # A GL001-only run produces no GL006 findings; that absence is NOT
    # paid-off debt, and a scoped rewrite must keep the GL006 entry.
    assert main([str(tmp_path), "--select", "GL001", "--check-baseline"]) == 0
    assert main([str(tmp_path), "--select", "GL001", "--write-baseline"]) == 0
    assert main([str(tmp_path), "--check-baseline"]) == 0


def test_cli_list_rules_and_missing_path(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in (
        "GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
        "GL008", "GL009", "GL010", "GL011", "GL012", "GL013", "GL014",
    ):
        assert rule_id in out
    assert main(["/nonexistent/path"]) == 2


def test_module_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "gofr_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    assert "GL001" in proc.stdout


# ----------------------------------------------------------------------
# the repo gate: committed baseline stays in sync with the tree
# ----------------------------------------------------------------------


def test_repo_clean_against_committed_baseline(monkeypatch, capsys):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.chdir(repo)
    rc = main(["gofr_tpu", "--check-baseline"])
    captured = capsys.readouterr()
    assert rc == 0, (
        "graftlint gate failed — new findings or baseline drift:\n"
        + captured.out + captured.err
    )


# ----------------------------------------------------------------------
# the project index (GL020–GL022's shared substrate)
# ----------------------------------------------------------------------


def _index(tmp_path, files):
    """Build a ProjectIndex from {relpath: source} the way run_paths
    does — via core._load_file, so suppressions/paths match production."""
    from gofr_tpu.analysis.core import _load_file
    from gofr_tpu.analysis.project import ProjectIndex

    loaded = []
    for rel, source in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(source))
        got = _load_file(str(p), root=str(tmp_path))
        assert isinstance(got, tuple), f"parse failed for {rel}: {got}"
        loaded.append(got)
    return ProjectIndex.build(loaded)


def test_project_index_groups_mixins_into_one_runtime_object(tmp_path):
    index = _index(tmp_path, {
        "serving/engine.py": """
            class SchedulerMixin:
                def loop(self):
                    pass

            class Engine(SchedulerMixin):
                def submit(self):
                    self.loop()
        """,
    })
    # One composition group; self.loop() resolves into it.
    (leader,) = [g for g, members in index.groups.items()
                 if {"Engine", "SchedulerMixin"} <= members]
    submit = index.functions["serving/engine.py::Engine.submit"]
    assert submit.group == leader
    callees = [c.callee for c in submit.calls]
    assert "serving/engine.py::SchedulerMixin.loop" in callees


def test_project_index_call_edges_and_import_shadowing(tmp_path):
    index = _index(tmp_path, {
        "serving/a.py": """
            import os

            def helper():
                pass

            class Widget:
                def exists(self):
                    pass

                def run(self):
                    helper()            # module-level function
                    os.path.exists("x")  # library call — NOT Widget.exists
        """,
    })
    run = index.functions["serving/a.py::Widget.run"]
    resolved = {c.name: c.callee for c in run.calls}
    assert resolved["helper"] == "serving/a.py::helper"
    # `os` is an imported name: the unique-method fallback must not
    # resolve os.path.exists to Widget.exists.
    assert resolved.get("os.path.exists") is None


def test_project_index_lock_regions_subtract_release_windows(tmp_path):
    from gofr_tpu.analysis.project import lock_regions

    index = _index(tmp_path, {
        "serving/b.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def flip(self):
                    with self._lock:
                        a = 1
                        self._lock.release()
                        b = 2   # NOT held here
                        self._lock.acquire()
                        c = 3
        """,
    })
    ctx = index.files["serving/b.py"]
    tree = __import__("ast").parse(ctx.source)
    fn = tree.body[1].body[1]  # Box.flip
    (region,) = lock_regions(fn)
    held = {line: region.holds_at(line) for line in range(10, 15)}
    assert held[10] and held[14]         # a = 1, c = 3
    assert not held[12]                  # b = 2 — inside the window


def test_project_index_thread_roots_and_reachability(tmp_path):
    index = _index(tmp_path, {
        "serving/c.py": """
            import threading

            class Prober:
                def start(self):
                    threading.Thread(target=self._probe).start()
                    t = threading.Thread(None, self._watch)
                    t.start()

                def _probe(self):
                    self._tick()

                def _watch(self):
                    pass

                def _tick(self):
                    pass
        """,
    })
    assert "serving/c.py::Prober._probe" in index.thread_roots
    assert "serving/c.py::Prober._watch" in index.thread_roots
    # _tick runs on the probe thread (and on no caller thread: only
    # start() is public, and it never calls _tick directly).
    roots = index.roots_of("serving/c.py::Prober._tick")
    assert roots == frozenset({"_probe"})  # probe thread only, no caller


def test_project_index_entry_locks_meet_over_call_sites(tmp_path):
    index = _index(tmp_path, {
        "serving/d.py": """
            import threading

            class Ledger:
                def __init__(self):
                    self._lock = threading.Lock()

                def tick(self):
                    with self._lock:
                        self._step()

                def flush(self):
                    with self._lock:
                        self._step()

                def _step(self):
                    pass

                def _orphan(self):
                    pass
        """,
    })
    # Every call site holds _lock -> the helper inherits it on entry.
    entry = index.entry_locks("serving/d.py::Ledger._step")
    assert any(k.endswith("._lock") for k in entry)
    # A never-called private helper gets no guarantee.
    assert index.entry_locks("serving/d.py::Ledger._orphan") == frozenset()


# ----------------------------------------------------------------------
# GL020 — unguarded shared state
# ----------------------------------------------------------------------


def test_gl020_flags_lock_free_write_with_inferred_guard(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/pool.py",
        """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def add(self):
                with self._lock:
                    self._count += 1

            def remove(self):
                with self._lock:
                    self._count -= 1

            def reset(self):
                self._count = 0  # lock-free, raced by the drain thread

            def start(self):
                threading.Thread(target=self._drain).start()

            def _drain(self):
                self.remove()
        """,
        select=["GL020"],
    )
    assert ids == ["GL020"]
    assert "_count" in findings[0].message
    assert "inferred" in findings[0].message


def test_gl020_declared_guard_flags_reads_too(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/gauge.py",
        """
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0  # graftlint: guarded-by=_lock

            def bump(self):
                with self._lock:
                    self._value += 1

            def peek(self):
                return self._value  # declared guard: reads count

            def start(self):
                threading.Thread(target=self.bump).start()
        """,
        select=["GL020"],
    )
    assert ids == ["GL020"]
    assert "read" in findings[0].message
    assert "declared" in findings[0].message


def test_gl020_quiet_on_consistent_locking_and_single_thread(tmp_path):
    # Consistent locking: clean.
    ids, _ = _lint(
        tmp_path, "serving/ok.py",
        """
        import threading

        class Ok:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def b(self):
                with self._lock:
                    self._n -= 1

            def start(self):
                threading.Thread(target=self.a).start()
        """,
        select=["GL020"],
    )
    assert ids == []
    # No second thread root: a lock-free write is single-threaded
    # discipline, not a race — stay quiet.
    ids, _ = _lint(
        tmp_path, "serving/solo.py",
        """
        import threading

        class Solo:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def b(self):
                with self._lock:
                    self._n -= 1

            def reset(self):
                self._n = 0
        """,
        select=["GL020"],
    )
    assert ids == []


def test_gl020_helper_called_under_lock_is_not_flagged(tmp_path):
    # The `# Callers hold self._lock` idiom: every call site of _step
    # holds the lock, so its write is covered by entry_locks.
    ids, _ = _lint(
        tmp_path, "serving/brown.py",
        """
        import threading

        class Brownout:
            def __init__(self):
                self._lock = threading.Lock()
                self._factor = 1.0

            def tighten(self):
                with self._lock:
                    self._step(-0.1)

            def relax(self):
                with self._lock:
                    self._step(0.1)

            def _step(self, delta):
                self._factor += delta

            def start(self):
                threading.Thread(target=self.tighten).start()
        """,
        select=["GL020"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL021 — lock-order inversion
# ----------------------------------------------------------------------


def test_gl021_flags_pool_engine_inversion(tmp_path):
    # The pre-PR-4 shape: the submit path holds the engine's submit
    # lock while reserving in the pool (engine -> pool), while the
    # scaler's drain path holds the pool lock while cancelling in the
    # engine (pool -> engine). Two threads, opposite order: deadlock
    # under the wrong interleaving.
    ids, findings = _lint(
        tmp_path, "serving/pair.py",
        """
        import threading

        class Engine:
            def __init__(self, pool):
                self._submit_lock = threading.Lock()
                self._pool = pool

            def submit(self):
                with self._submit_lock:
                    self._pool.reserve()

            def cancel_all(self):
                with self._submit_lock:
                    pass

        class Pool:
            def __init__(self, engine):
                self._lock = threading.Lock()
                self._engine = engine

            def reserve(self):
                with self._lock:
                    pass

            def scale_down(self):
                with self._lock:
                    self._engine.cancel_all()
        """,
        select=["GL021"],
    )
    assert ids and set(ids) == {"GL021"}
    joined = " ".join(f.message for f in findings)
    assert "_submit_lock" in joined and "_lock" in joined


def test_gl021_quiet_on_consistent_order_and_rlock_reentry(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/ordered.py",
        """
        import threading

        class Ordered:
            def __init__(self):
                self._outer = threading.Lock()
                self._inner = threading.Lock()
                self._re = threading.RLock()

            def a(self):
                with self._outer:
                    with self._inner:
                        pass

            def b(self):
                with self._outer:
                    self._help()

            def _help(self):
                with self._inner:
                    pass

            def reenter(self):
                with self._re:
                    self._again()

            def _again(self):
                with self._re:
                    pass
        """,
        select=["GL021"],
    )
    assert ids == []


def test_gl021_flags_blocking_self_reacquisition_of_plain_lock(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/selfhang.py",
        """
        import threading

        class SelfHang:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._inner()

            def _inner(self):
                with self._lock:
                    pass
        """,
        select=["GL021"],
    )
    assert ids == ["GL021"]
    assert "deadlock" in findings[0].message.lower()


# ----------------------------------------------------------------------
# GL022 — blocking call under a lock
# ----------------------------------------------------------------------


def test_gl022_flags_direct_and_transitive_blocking_under_lock(tmp_path):
    ids, findings = _lint(
        tmp_path, "serving/blocky.py",
        """
        import threading
        import time
        import urllib.request

        class Blocky:
            def __init__(self):
                self._lock = threading.Lock()

            def direct(self):
                with self._lock:
                    time.sleep(0.5)

            def transitive(self):
                with self._lock:
                    self._fetch()

            def _fetch(self):
                urllib.request.urlopen("http://upstream")
        """,
        select=["GL022"],
    )
    assert ids == ["GL022", "GL022"]
    assert "time.sleep" in findings[0].message
    assert "_fetch" in findings[1].message or "urlopen" in findings[1].message


def test_gl022_quiet_on_conditions_nonblocking_and_release_windows(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/fine.py",
        """
        import queue
        import threading
        import time

        class Fine:
            def __init__(self):
                self._cond = threading.Condition()
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def waiter(self):
                # Conditions exist to sleep while held: exempt.
                with self._cond:
                    self._cond.wait(timeout=1.0)

            def poll(self):
                with self._lock:
                    item = self._q.get(block=False)
                return item

            def around(self):
                with self._lock:
                    self._lock.release()
                    time.sleep(0.1)  # lock NOT held here
                    self._lock.acquire()
        """,
        select=["GL022"],
    )
    assert ids == []


def test_gl022_counters_named_queued_are_not_queues(tmp_path):
    ids, _ = _lint(
        tmp_path, "serving/counter.py",
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._tenant_queued = {}

            def depth(self, tenant):
                with self._lock:
                    return self._tenant_queued.get(tenant, 0)
        """,
        select=["GL022"],
    )
    assert ids == []


# ----------------------------------------------------------------------
# GL005 regression — writes in release-around windows
# ----------------------------------------------------------------------


def test_gl005_flags_write_inside_release_window(tmp_path):
    # PR 4's release-around shape: the lexical with-block no longer
    # means "held" once the body releases — a write between release()
    # and re-acquire() is a lock-free write (the old span-based check
    # missed these).
    ids, findings = _lint(
        tmp_path, "serving/engine.py",  # GL005 scopes to hot-path files
        """
        import threading

        class Window:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = "idle"

            def run(self):
                with self._lock:
                    self._state = "running"

            def handoff(self):
                with self._lock:
                    self._lock.release()
                    self._state = "detached"  # lock NOT held
                    self._lock.acquire()
        """,
        select=["GL005"],
    )
    assert ids == ["GL005"]
    assert findings[0].line == 16
    assert "_state" in findings[0].message


# ----------------------------------------------------------------------
# SARIF output
# ----------------------------------------------------------------------


def test_cli_sarif_format_and_exit_semantics(tmp_path, capsys, monkeypatch):
    import json as jsonlib

    bad = tmp_path / "serving" / "hot.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent(
        """
        def emit(tokens_dev):
            return tokens_dev.item()
        """
    ))
    (tmp_path / "pyproject.toml").write_text("")
    monkeypatch.chdir(tmp_path)
    rc = main(["serving", "--format=sarif", "--no-baseline", "--select=GL001"])
    out = capsys.readouterr().out
    assert rc == 1  # findings still fail the run — format is reporting only
    log = jsonlib.loads(out)
    assert log["version"] == "2.1.0"
    (run,) = log["runs"]
    assert run["tool"]["driver"]["name"] == "graftlint"
    (result,) = run["results"]
    assert result["ruleId"] == "GL001"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "serving/hot.py"
    assert loc["region"]["startLine"] == 3
    # Clean tree -> SARIF with zero results, exit 0.
    good = tmp_path / "serving" / "cold.py"
    good.write_text("x = 1\n")
    rc = main(
        ["serving/cold.py", "--format=sarif", "--no-baseline", "--select=GL001"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert jsonlib.loads(out)["runs"][0]["results"] == []
