"""Serving engine tests: continuous batching, dynamic batcher, ctx.infer,
and the gRPC inference service — all on the CPU backend with tiny models
(the stub-backend strategy SURVEY §4 prescribes)."""

from __future__ import annotations

import asyncio
import threading
import time

import numpy as np
import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.serving.batcher import DynamicBatcher, pad_bucket
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def llm_engine():
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, tokenizer=ByteTokenizer()
    )
    eng.start_sync()
    yield eng
    eng.stop_sync()


def test_pad_bucket():
    assert pad_bucket(3, (16, 32, 64)) == 16
    assert pad_bucket(17, (16, 32, 64)) == 32
    assert pad_bucket(999, (16, 32, 64)) == 64


def test_dynamic_batcher_flush_by_size_and_deadline():
    batches = []

    def execute(payloads):
        batches.append(len(payloads))
        return [p * 2 for p in payloads]

    b = DynamicBatcher(execute, max_batch=4, max_wait_s=0.02)
    b.start()
    futures = [b.submit(i) for i in range(4)]
    assert [f.result(timeout=5) for f in futures] == [0, 2, 4, 6]
    assert batches[0] == 4  # size-triggered flush

    f = b.submit(10)
    assert f.result(timeout=5) == 20  # deadline-triggered flush of 1
    assert batches[-1] == 1
    b.stop()


def test_dynamic_batcher_execute_error_fails_futures():
    def execute(payloads):
        raise RuntimeError("device on fire")

    b = DynamicBatcher(execute, max_batch=2, max_wait_s=0.01)
    b.start()
    f = b.submit(1)
    with pytest.raises(RuntimeError, match="device on fire"):
        f.result(timeout=5)
    b.stop()


def test_generate_deterministic_greedy(llm_engine):
    r1 = llm_engine.generate_sync("hello", max_new_tokens=8, temperature=0.0,
                                  stop_on_eos=False)
    r2 = llm_engine.generate_sync("hello", max_new_tokens=8, temperature=0.0,
                                  stop_on_eos=False)
    assert r1.token_ids == r2.token_ids
    assert len(r1.token_ids) == 8
    assert r1.ttft_s > 0


def test_concurrent_requests_share_slots(llm_engine):
    reqs = [
        llm_engine.submit_generate(f"prompt {i}", max_new_tokens=6,
                                   temperature=0.5, stop_on_eos=False)
        for i in range(8)  # 2x the slot count → queueing works
    ]
    results = [r.future.result(timeout=120) for r in reqs]
    assert all(len(r.token_ids) == 6 for r in results)


def test_generation_independent_of_batch_composition(llm_engine):
    """A request's tokens must not change with co-scheduled traffic."""
    solo = llm_engine.generate_sync("isolation", max_new_tokens=6,
                                    temperature=0.0, stop_on_eos=False)
    reqs = [
        llm_engine.submit_generate("isolation", max_new_tokens=6,
                                   temperature=0.0, stop_on_eos=False)
        for _ in range(4)
    ]
    noise = [
        llm_engine.submit_generate(f"noise {i}", max_new_tokens=6,
                                   temperature=0.9, stop_on_eos=False)
        for i in range(4)
    ]
    for r in reqs:
        assert r.future.result(timeout=120).token_ids == solo.token_ids
    for r in noise:
        r.future.result(timeout=120)


def test_streaming(llm_engine):
    async def run():
        toks = []
        async for tok in llm_engine.generate_stream(
            "stream me", max_new_tokens=5, temperature=0.0, stop_on_eos=False
        ):
            toks.append(tok)
        return toks

    toks = asyncio.run(run())
    assert len(toks) == 5


def test_greedy_matches_cache_free_rollout(llm_engine):
    """Engine output == argmax rollout of the plain forward (no KV cache).

    Catches emission bugs no engine-vs-engine comparison can: a duplicated
    first token (early prefill emission + window re-emission), dropped or
    reordered window tokens, off-by-one cache lengths.
    """
    import jax.numpy as jnp

    from gofr_tpu.models.transformer import transformer_forward

    prompt = "oracle"
    n_new = 7
    r = llm_engine.generate_sync(
        prompt, max_new_tokens=n_new, temperature=0.0, stop_on_eos=False
    )
    seq = list(llm_engine.tokenizer.encode(prompt))
    for _ in range(n_new):
        logits = transformer_forward(
            llm_engine.params, jnp.asarray([seq]), llm_engine.cfg
        )
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert r.token_ids == seq[-n_new:]


@pytest.fixture(scope="module")
def f32_plain_engine():
    # f32 predates the exact-verify redesign, which made spec-on vs
    # spec-off bit-identical at bf16 too (the verify path now IS the
    # decode-step program — see tests/test_spec_decoding.py for the
    # bf16 identity suite); kept at f32 for variety across dtypes.
    eng = InferenceEngine(
        "llama-tiny-f32", n_slots=4, max_len=256, tokenizer=ByteTokenizer()
    )
    eng.start_sync()
    yield eng
    eng.stop_sync()


def test_speculative_decoding_lossless_greedy(f32_plain_engine):
    """Greedy generation with n-gram speculation must produce EXACTLY the
    tokens of plain greedy decode (acceptance is by exact match), for
    several concurrent requests; sampled-temperature requests still
    complete in the same batch (they take no drafts)."""
    spec = InferenceEngine(
        "llama-tiny-f32", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        spec_tokens=3,
    )
    spec.start_sync()
    try:
        prompts = ["hello world", "abab abab abab", "the cat sat on"]
        want = [
            f32_plain_engine.generate_sync(
                p, max_new_tokens=12, temperature=0.0, stop_on_eos=False
            ).token_ids
            for p in prompts
        ]
        reqs = [
            spec.submit_generate(
                p, max_new_tokens=12, temperature=0.0, stop_on_eos=False
            )
            for p in prompts
        ]
        noise = spec.submit_generate(
            "noise", max_new_tokens=8, temperature=0.9, stop_on_eos=False
        )
        got = [r.future.result(timeout=120).token_ids for r in reqs]
        assert got == want
        assert len(noise.future.result(timeout=120).token_ids) == 8
    finally:
        spec.stop_sync()


def test_speculative_decoding_lossless_int8_kv():
    """Spec-on == spec-off under an int8 KV cache too: the verify path
    fake-quantizes in-chunk K/V so it attends exactly what commit writes
    (f32 weights so argmax ties can't flip between execution shapes)."""
    results = []
    for spec_tokens in (0, 3):
        eng = InferenceEngine(
            "llama-tiny-f32", n_slots=2, max_len=256,
            tokenizer=ByteTokenizer(), kv_quant="int8",
            spec_tokens=spec_tokens,
        )
        eng.start_sync()
        try:
            results.append(
                eng.generate_sync(
                    "quantized spec", max_new_tokens=14, temperature=0.0,
                    stop_on_eos=False,
                ).token_ids
            )
        finally:
            eng.stop_sync()
    assert results[0] == results[1]


def test_spec_streaming_order(f32_plain_engine):
    """Streaming through the spec engine yields the same token order as
    the non-spec engine's result."""
    spec = InferenceEngine(
        "llama-tiny-f32", n_slots=2, max_len=256, tokenizer=ByteTokenizer(),
        spec_tokens=2,
    )
    spec.start_sync()
    try:
        want = f32_plain_engine.generate_sync(
            "stream spec", max_new_tokens=9, temperature=0.0,
            stop_on_eos=False,
        ).token_ids

        async def run():
            toks = []
            async for tok in spec.generate_stream(
                "stream spec", max_new_tokens=9, temperature=0.0,
                stop_on_eos=False,
            ):
                toks.append(tok)
            return toks

        assert asyncio.run(run()) == want
    finally:
        spec.stop_sync()


def test_paged_cache_matches_slot_cache(llm_engine):
    """TPU_KV_BLOCK engine produces the same greedy tokens as the slot
    cache, across concurrent requests and block boundaries (max_len 128,
    block 32 → prompts + generations span multiple blocks)."""
    paged = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, tokenizer=ByteTokenizer(),
        kv_block=32,
    )
    paged.start_sync()
    try:
        prompts = ["hello world", "paged attention", "x" * 40]
        want = [
            llm_engine.generate_sync(
                p, max_new_tokens=10, temperature=0.0, stop_on_eos=False
            ).token_ids
            for p in prompts
        ]
        reqs = [
            paged.submit_generate(
                p, max_new_tokens=10, temperature=0.0, stop_on_eos=False
            )
            for p in prompts
        ]
        got = [r.future.result(timeout=120).token_ids for r in reqs]
        assert got == want
        h = paged.health_check()
        assert h["details"]["kv_blocks"]["block"] == 32
    finally:
        paged.stop_sync()


def test_paged_pool_exhaustion_holds_requests_back():
    """A pool smaller than slots×max_len admits what fits and holds the
    rest back until retirements free blocks — all requests complete."""
    paged = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, tokenizer=ByteTokenizer(),
        kv_block=32, kv_pool_blocks=9,  # parking + 8 = two slots' worth
    )
    paged.start_sync()
    try:
        reqs = [
            paged.submit_generate(
                f"request {i}", max_new_tokens=6, temperature=0.0,
                stop_on_eos=False,
            )
            for i in range(6)
        ]
        results = [r.future.result(timeout=180) for r in reqs]
        assert all(len(r.token_ids) == 6 for r in results)
        assert len(paged._free_blocks) == 8  # everything returned
    finally:
        paged.stop_sync()


def test_paged_prefill_padding_does_not_corrupt_prompt():
    """A prefill chunk whose padding columns extend past max_len must park
    them in block 0 — remapping them into the last real block would
    scatter garbage over the prompt's tail K/V (regression)."""
    mk = lambda **kw: InferenceEngine(  # noqa: E731
        "llama-tiny", n_slots=2, max_len=96, prefill_chunk=64,
        tokenizer=ByteTokenizer(), **kw,
    )
    plain, paged = mk(), mk(kv_block=32)
    plain.start_sync()
    paged.start_sync()
    try:
        prompt = "abcdefgh" * 8  # 64 chars → chunk 2 pads past max_len
        want = plain.generate_sync(
            prompt, max_new_tokens=6, temperature=0.0, stop_on_eos=False
        ).token_ids
        got = paged.generate_sync(
            prompt, max_new_tokens=6, temperature=0.0, stop_on_eos=False
        ).token_ids
        assert got == want
    finally:
        plain.stop_sync()
        paged.stop_sync()


def test_paged_oversized_prompt_fails_without_deadlock():
    """A prompt needing more blocks than the whole pool fails its own
    future immediately — and does NOT wedge admission for requests
    behind it."""
    paged = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        kv_block=32, kv_pool_blocks=4,  # 3 usable blocks = 96 tokens
    )
    paged.start_sync()
    try:
        big = paged.submit_generate(
            "x" * 100, max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        small = paged.submit_generate(
            "ok", max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        with pytest.raises(RuntimeError, match="KV blocks"):
            big.future.result(timeout=60)
        assert len(small.future.result(timeout=60).token_ids) == 4
    finally:
        paged.stop_sync()


def test_paged_with_int8_kv_and_spec():
    """Paged × int8 KV × speculation compose: same tokens as the plain
    slot-cache engine (f32 oracle model)."""
    plain = InferenceEngine(
        "llama-tiny-f32", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        kv_quant="int8",
    )
    paged = InferenceEngine(
        "llama-tiny-f32", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        kv_quant="int8", kv_block=32, spec_tokens=2,
    )
    for eng in (plain, paged):
        eng.start_sync()
    try:
        want = plain.generate_sync(
            "compose everything", max_new_tokens=9, temperature=0.0,
            stop_on_eos=False,
        ).token_ids
        got = paged.generate_sync(
            "compose everything", max_new_tokens=9, temperature=0.0,
            stop_on_eos=False,
        ).token_ids
        assert got == want
    finally:
        plain.stop_sync()
        paged.stop_sync()


def test_top_p_sampling():
    """Nucleus sampling: top_p→0 collapses to greedy (the nucleus keeps
    only the argmax token) even at temperature 1; a top_p request
    against an engine compiled without it gets the 400-class error."""
    from gofr_tpu.errors import ErrorInvalidParam

    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        enable_top_p=True,
    )
    eng.start_sync()
    try:
        greedy = eng.generate_sync(
            "nucleus", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        ).token_ids
        collapsed = eng.generate_sync(
            "nucleus", max_new_tokens=8, temperature=1.0, top_p=1e-9,
            stop_on_eos=False,
        ).token_ids
        assert collapsed == greedy
        with pytest.raises(ErrorInvalidParam):
            eng.submit_generate("x", top_p=1.5)
    finally:
        eng.stop_sync()


def test_top_p_rejected_when_not_compiled(llm_engine):
    from gofr_tpu.errors import ErrorInvalidParam

    with pytest.raises(ErrorInvalidParam, match="TPU_TOP_P"):
        llm_engine.submit_generate("x", top_p=0.9)


def test_llm_health(llm_engine):
    h = llm_engine.health_check()
    assert h["status"] == "UP"
    assert h["details"]["kv_slots"]["total"] == 4


def test_encoder_family():
    eng = InferenceEngine("bert-tiny", tokenizer=ByteTokenizer())
    eng.start_sync()
    try:
        a = eng.embed_sync("the cat sat")
        b = eng.embed_sync("the cat sat")
        np.testing.assert_allclose(a, b, rtol=1e-5)
        assert a.shape == (128,)
    finally:
        eng.stop_sync()


def test_vision_family():
    eng = InferenceEngine("resnet-tiny")
    eng.start_sync()
    try:
        out = eng.classify_sync(np.random.RandomState(0).randn(64, 64, 3))
        assert out.shape == (10,)
    finally:
        eng.stop_sync()


def test_engine_from_config_and_container():
    from gofr_tpu.container import Container

    cfg = MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
    })
    c = Container.create(cfg)
    assert c.tpu is not None
    assert c.tpu.n_slots == 2
    c.tpu.start_sync()
    try:
        out = c.tpu.infer_sync("hi", max_new_tokens=3, stop_on_eos=False)
        assert out["tokens"] == 3
        health = c.health()
        assert "tpu" in health["details"]
    finally:
        c.tpu.stop_sync()


@pytest.mark.parametrize("quant,kv_block", [("", 0), ("int8", 0), ("", 32)])
def test_sharded_serving_matches_single_device(quant, kv_block):
    """TPU_MESH_TP=2: Megatron-sharded params + KV heads over a 2-device
    mesh must produce identical greedy generations — in bf16, with
    weight-only int8 (the quant × mesh composition, VERDICT r2 next #2),
    and with the paged block pool (its KV axis shards like the slot
    cache; the table replicates)."""
    # Init bf16 then quantize — the same init path the mesh branch takes
    # (the quant="int8" ctor arg would take the leaf-wise init, whose
    # different key-split order gives different random weights).
    single = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        kv_block=kv_block,
    )
    if quant:
        single.apply_quantization(quant)
    single.start_sync()
    try:
        ref = single.generate_sync(
            "shard me", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
    finally:
        single.stop_sync()

    cfg = MockConfig({
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "64", "TPU_MESH_TP": "2", "TPU_QUANT": quant,
        "TPU_KV_BLOCK": str(kv_block),
    })
    sharded = InferenceEngine.from_config(cfg)
    if quant:
        assert sharded.quant == "int8"
        q8 = sharded.params["layers"]["wq"]
        assert "tp" in str(q8.q.sharding.spec)
        # Scale shards with the output-channel axis, NOT the contraction
        # axis (extent 1 there).
        assert "tp" in str(q8.s.sharding.spec)
    else:
        assert "tp" in str(sharded.params["layers"]["wq"].sharding.spec)
    sharded.start_sync()
    try:
        got = sharded.generate_sync(
            "shard me", max_new_tokens=8, temperature=0.0, stop_on_eos=False
        )
    finally:
        sharded.stop_sync()
    assert got.token_ids == ref.token_ids


def test_context_parallel_serving_matches_single_device():
    """TPU_MESH_CP=2 (± tp): the KV cache's LENGTH axis shards over cp
    chips — the long-context serving axis (max_len past one chip's cache
    HBM) — and greedy generations must match single-device exactly
    (GSPMD turns the sharded softmax reductions into collectives)."""
    single = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
    )
    single.start_sync()
    try:
        ref = single.generate_sync(
            "long context", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
    finally:
        single.stop_sync()

    for axes in ({"TPU_MESH_CP": "2"},
                 {"TPU_MESH_TP": "2", "TPU_MESH_CP": "2"}):
        cfg = MockConfig({
            "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2",
            "TPU_MAX_LEN": "64", **axes,
        })
        sharded = InferenceEngine.from_config(cfg)
        assert "cp" in str(sharded.cache.k.sharding.spec)
        sharded.start_sync()
        try:
            got = sharded.generate_sync(
                "long context", max_new_tokens=8, temperature=0.0,
                stop_on_eos=False,
            )
        finally:
            sharded.stop_sync()
        assert got.token_ids == ref.token_ids, axes


def test_ctx_infer_through_http_app(free_port):
    """ctx.infer end to end through the HTTP surface."""
    import http.client
    import json as jsonlib

    from gofr_tpu import App

    app = App(config=MockConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny", "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "64",
    }))

    @app.post("/generate")
    async def generate(ctx):
        body = ctx.request.json()
        return await ctx.infer(
            body.get("prompt", ""), max_new_tokens=4, stop_on_eos=False
        )

    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=30)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=60)
        conn.request(
            "POST", "/generate", body=jsonlib.dumps({"prompt": "hey"}),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        data = jsonlib.loads(resp.read())
        assert resp.status == 201
        assert data["data"]["tokens"] == 4
        assert "ttft_ms" in data["data"]
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)


def test_grpc_inference_service():
    """gRPC unary + streaming against a real server."""
    from gofr_tpu.grpc import GRPCServer, InferenceClient, add_inference_service
    from gofr_tpu.grpc.inference import InferenceServicer
    from gofr_tpu.logging import Logger, Level
    import io

    eng = InferenceEngine("llama-tiny", n_slots=2, max_len=64,
                          tokenizer=ByteTokenizer())
    eng.start_sync()
    logger = Logger(level=Level.DEBUG, out=io.StringIO(), err=io.StringIO(),
                    is_terminal=False)

    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    server = GRPCServer(0, logger)
    server.register(add_inference_service, InferenceServicer(eng))
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=30)
    try:
        client = InferenceClient(f"127.0.0.1:{server.port}")
        out = client.generate("hello grpc", max_new_tokens=4, stop_on_eos=False)
        assert out["tokens"] == 4
        assert out["ttft_ms"] > 0

        chunks = list(client.generate_stream("stream", max_new_tokens=3))
        assert chunks[-1]["done"] is True
        assert chunks[-1]["tokens"] == 3

        health = client.health()
        assert health["status"] == "UP"
        client.close()
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(0), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        eng.stop_sync()


def test_scheduler_death_fails_futures_fast():
    """A crash in the scheduler loop (e.g. a kernel that fails to compile on
    real hardware) must fail pending futures and later submissions — not
    strand callers until their timeout."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer()
    )
    eng._dispatch_prefill_chunk = (
        lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    eng.start_sync()
    try:
        # Depending on who wins the race, the submit fails fast (scheduler
        # already dead) or returns a future the drain fails — never a hang.
        with pytest.raises(RuntimeError, match="boom|engine stopped|scheduler died"):
            req = eng.submit_generate("hi", max_new_tokens=4, stop_on_eos=False)
            req.future.result(timeout=10)
        # Scheduler is dead now; new submissions fail immediately.
        deadline = time.time() + 5
        while eng._fatal is None and time.time() < deadline:
            time.sleep(0.01)
        with pytest.raises(RuntimeError, match="scheduler died"):
            eng.submit_generate("again")
    finally:
        eng.stop_sync()


def test_cancelled_request_frees_slot():
    """A caller cancelling its future mid-generation must not leak the slot
    (pipelined windows skip done futures — the slot still has to free)."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer()
    )
    eng.start_sync()
    try:
        req = eng.submit_generate("x" * 20, max_new_tokens=64, stop_on_eos=False)
        deadline = time.time() + 10
        while not any(s is not None for s in eng._slots) and time.time() < deadline:
            time.sleep(0.01)
        req.future.cancel()
        deadline = time.time() + 10
        while any(s is not None for s in eng._slots) and time.time() < deadline:
            time.sleep(0.05)
        assert all(s is None for s in eng._slots), "cancelled slot leaked"
    finally:
        eng.stop_sync()


def test_max_len_too_small_for_pipeline_rejected():
    with pytest.raises(ValueError, match="max_len"):
        InferenceEngine(
            "llama-tiny", n_slots=2, max_len=16, tokenizer=ByteTokenizer(),
            window_k=8, pipeline_depth=2,
        )


def test_chunked_prefill_matches_single_chunk():
    """A prompt spanning several prefill chunks must generate exactly the
    tokens a single-chunk prefill produces (VERDICT r1 #3: chunked
    admission changes scheduling, never results)."""
    prompt = "chunk boundary crossing prompt " * 3  # ~93 tokens (bytes)
    big = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=256, prefill_chunk=128,
        tokenizer=ByteTokenizer(),
    )
    big.start_sync()
    want = big.generate_sync(
        prompt, max_new_tokens=8, temperature=0.0, stop_on_eos=False
    ).token_ids
    big.stop_sync()

    small = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=256, prefill_chunk=16,
        tokenizer=ByteTokenizer(),
    )
    small.start_sync()
    got = small.generate_sync(
        prompt, max_new_tokens=8, temperature=0.0, stop_on_eos=False
    ).token_ids
    # Interleave decode traffic with a second multi-chunk prompt to cover
    # prefill-between-windows for occupied slots.
    noise = small.generate_sync(
        prompt[::-1], max_new_tokens=8, temperature=0.0, stop_on_eos=False
    )
    small.stop_sync()
    assert got == want
    assert len(noise.token_ids) == 8


def test_overlong_prompt_rejected_and_truncation_optin():
    from gofr_tpu.errors import ErrorPromptTooLong

    eng = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, window_k=4, pipeline_depth=1,
        tokenizer=ByteTokenizer(),
    )
    eng.start_sync()
    long_prompt = "x" * 500
    with pytest.raises(ErrorPromptTooLong) as exc:
        eng.submit_generate(long_prompt, max_new_tokens=4)
    assert exc.value.status_code == 413
    eng.stop_sync()

    tr = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=64, window_k=4, pipeline_depth=1,
        truncate_prompts=True, tokenizer=ByteTokenizer(),
    )
    tr.start_sync()
    res = tr.generate_sync(
        long_prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False
    )
    assert res.truncated is True
    short = tr.generate_sync(
        "ok", max_new_tokens=4, temperature=0.0, stop_on_eos=False
    )
    assert short.truncated is False
    tr.stop_sync()


def test_typed_protobuf_grpc_service():
    """A STOCK grpc client with the protoc-generated message stubs
    round-trips Generate/GenerateStream/Health — the typed contract of
    proto/inference.proto (VERDICT r1 missing #1)."""
    import io

    import grpc as grpc_lib

    from gofr_tpu.grpc import (
        GRPCServer,
        TypedInferenceServicer,
        add_typed_inference_service,
    )
    from gofr_tpu.grpc import inference_pb2 as pb
    from gofr_tpu.grpc.inference_pb2_grpc import InferenceStub
    from gofr_tpu.logging import Level, Logger

    eng = InferenceEngine("llama-tiny", n_slots=2, max_len=64,
                          tokenizer=ByteTokenizer())
    eng.start_sync()
    logger = Logger(level=Level.DEBUG, out=io.StringIO(), err=io.StringIO(),
                    is_terminal=False)

    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    server = GRPCServer(0, logger)
    server.register(add_typed_inference_service, TypedInferenceServicer(eng))
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=30)
    try:
        channel = grpc_lib.insecure_channel(f"127.0.0.1:{server.port}")
        stub = InferenceStub(channel)

        reply = stub.Generate(pb.GenerateRequest(
            prompt="hello proto", max_new_tokens=4
        ), timeout=60)
        assert isinstance(reply, pb.GenerateReply)
        assert reply.tokens == 4
        assert reply.ttft_ms > 0
        assert reply.truncated is False
        assert reply.finish_reason == "length"  # budget, no eos
        assert len(reply.token_logprobs) == 4
        assert all(lp <= 0 for lp in reply.token_logprobs)

        # top_p on an engine compiled without it → INVALID_ARGUMENT.
        with pytest.raises(grpc_lib.RpcError) as exc_info:
            stub.Generate(pb.GenerateRequest(
                prompt="x", max_new_tokens=2, top_p=0.9
            ), timeout=60)
        assert exc_info.value.code() == grpc_lib.StatusCode.INVALID_ARGUMENT

        chunks = list(stub.GenerateStream(pb.GenerateRequest(
            prompt="stream", max_new_tokens=3
        ), timeout=60))
        assert chunks[-1].done is True
        assert chunks[-1].tokens == 3
        assert chunks[-1].finish_reason == "length"
        assert all(not c.done for c in chunks[:-1])

        # Stop sequences: unary and streaming must deliver the SAME
        # trimmed text (the stream holds text back until a match is
        # ruled out). Derive a stop string this model will actually
        # emit: the 3rd+4th greedy characters.
        probe = stub.Generate(pb.GenerateRequest(
            prompt="trim me", max_new_tokens=8, stop_on_eos=False
        ), timeout=60)
        stop_s = probe.text[2:4]
        if stop_s:
            unary = stub.Generate(pb.GenerateRequest(
                prompt="trim me", max_new_tokens=8, stop_on_eos=False,
                stop=[stop_s],
            ), timeout=60)
            assert unary.finish_reason == "stop"
            schunks = list(stub.GenerateStream(pb.GenerateRequest(
                prompt="trim me", max_new_tokens=8, stop_on_eos=False,
                stop=[stop_s],
            ), timeout=60))
            streamed = "".join(c.text for c in schunks if not c.done)
            assert streamed == unary.text
            assert schunks[-1].finish_reason == "stop"

        health = stub.Health(pb.HealthRequest(), timeout=30)
        assert health.status == "UP"
        import json as jsonlib

        assert jsonlib.loads(health.details_json)["kv_slots"]["total"] == 2

        # Pre-tokenized prompt path.
        reply2 = stub.Generate(pb.GenerateRequest(
            prompt_ids=[5, 6, 7], max_new_tokens=3
        ), timeout=60)
        assert reply2.tokens == 3
        channel.close()
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(0), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        eng.stop_sync()


def test_typed_grpc_embed_and_classify():
    import io

    import grpc as grpc_lib

    from gofr_tpu.grpc import (
        GRPCServer,
        TypedInferenceServicer,
        add_typed_inference_service,
    )
    from gofr_tpu.grpc import inference_pb2 as pb
    from gofr_tpu.grpc.inference_pb2_grpc import InferenceStub
    from gofr_tpu.logging import Level, Logger

    logger = Logger(level=Level.INFO, out=io.StringIO(), err=io.StringIO(),
                    is_terminal=False)
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()

    bert = InferenceEngine("bert-tiny", tokenizer=ByteTokenizer())
    bert.start_sync()
    server = GRPCServer(0, logger)
    server.register(add_typed_inference_service, TypedInferenceServicer(bert))
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=30)
    try:
        stub = InferenceStub(grpc_lib.insecure_channel(f"127.0.0.1:{server.port}"))
        emb = stub.Embed(pb.EmbedRequest(text="vector me"), timeout=60)
        assert len(emb.embedding) == 128
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(0), loop).result(timeout=30)
        bert.stop_sync()

    vision = InferenceEngine("resnet-tiny")
    vision.start_sync()
    server2 = GRPCServer(0, logger)
    server2.register(add_typed_inference_service, TypedInferenceServicer(vision))
    asyncio.run_coroutine_threadsafe(server2.start(), loop).result(timeout=30)
    try:
        stub = InferenceStub(grpc_lib.insecure_channel(f"127.0.0.1:{server2.port}"))
        img = np.random.RandomState(0).randn(32, 32, 3).astype(np.float32)
        out = stub.Classify(pb.ClassifyRequest(
            image=img.ravel().tolist(), shape=[32, 32, 3]
        ), timeout=60)
        assert len(out.logits) == 10
        assert 0 <= out.label < 10
    finally:
        asyncio.run_coroutine_threadsafe(server2.stop(0), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        vision.stop_sync()


def test_moe_model_serves_with_spec_and_paged():
    """The MoE FFN path (top-k routed experts) through the FULL serving
    stack — continuous batching, speculation, paged cache — not just the
    forward: decode/verify share _ffn_moe with training."""
    plain = InferenceEngine(
        "moe-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
    )
    fancy = InferenceEngine(
        "moe-tiny", n_slots=2, max_len=128, tokenizer=ByteTokenizer(),
        spec_tokens=2, kv_block=32,
    )
    plain.start_sync()
    fancy.start_sync()
    try:
        want = plain.generate_sync(
            "mixture of experts", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        ).token_ids
        got = fancy.generate_sync(
            "mixture of experts", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        ).token_ids
        assert len(want) == 8
        # bf16 MoE: routing ties can flip between the [S,1] decode and
        # [S,c] verify shapes, so exact equality is only guaranteed for
        # the prefix before any divergence — require a common first
        # token and full lengths instead of exact match.
        assert got[0] == want[0]
        assert len(got) == 8
    finally:
        plain.stop_sync()
        fancy.stop_sync()


def test_grpc_stream_cancel_frees_slot():
    """Cancelling a streaming RPC client-side must cancel the engine
    request so its KV slot frees (same contract as the SSE surface)."""
    import io

    import grpc as grpc_lib

    from gofr_tpu.grpc import (
        GRPCServer,
        TypedInferenceServicer,
        add_typed_inference_service,
    )
    from gofr_tpu.grpc import inference_pb2 as pb
    from gofr_tpu.grpc.inference_pb2_grpc import InferenceStub
    from gofr_tpu.logging import Level, Logger

    eng = InferenceEngine("llama-tiny", n_slots=1, max_len=128,
                          tokenizer=ByteTokenizer())
    eng.start_sync()
    logger = Logger(level=Level.DEBUG, out=io.StringIO(), err=io.StringIO(),
                    is_terminal=False)
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    server = GRPCServer(0, logger)
    server.register(add_typed_inference_service, TypedInferenceServicer(eng))
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=30)
    channel = grpc_lib.insecure_channel(f"127.0.0.1:{server.port}")
    try:
        stub = InferenceStub(channel)
        call = stub.GenerateStream(pb.GenerateRequest(
            prompt="cancel me", max_new_tokens=90, stop_on_eos=False
        ))
        next(iter(call))  # first chunk arrived → generation is live
        seqs = [s for s in eng._slots if s is not None]
        assert seqs, "stream started but no active slot"
        victim = seqs[0].request
        call.cancel()
        # The engine request must be CANCELLED, not run out its budget —
        # if the RPC cancel were a no-op, the future would complete with
        # a result and cancelled() would be False.
        deadline = time.time() + 30
        while not victim.future.done() and time.time() < deadline:
            time.sleep(0.05)
        assert victim.future.cancelled()
        assert len(victim.token_ids) < 90
        # The slot frees promptly; a follow-up request completes.
        r = stub.Generate(pb.GenerateRequest(
            prompt="after cancel", max_new_tokens=4, stop_on_eos=False,
        ), timeout=120)
        assert r.tokens == 4
    finally:
        channel.close()
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)
        eng.stop_sync()


def test_graceful_drain_completes_inflight_and_rejects_new():
    """stop_sync(drain_s=...) lets live generations finish (no 'engine
    stopped' failures on a rolling restart) while new submissions get
    the 503-class error."""
    from gofr_tpu.errors import ErrorServiceUnavailable

    eng = InferenceEngine(
        "llama-tiny", n_slots=1, max_len=128, tokenizer=ByteTokenizer(),
    )
    eng.start_sync()
    req = eng.submit_generate(
        "drain me", max_new_tokens=40, temperature=0.0, stop_on_eos=False
    )
    stopper = threading.Thread(target=lambda: eng.stop_sync(drain_s=60))
    stopper.start()
    # Submissions during the drain are rejected with 503.
    deadline = time.time() + 10
    saw_reject = False
    while time.time() < deadline and not saw_reject:
        try:
            eng.submit_generate("late", max_new_tokens=2)
        except ErrorServiceUnavailable:
            saw_reject = True
        except Exception:
            break
        time.sleep(0.02)
    stopper.join(timeout=120)
    assert saw_reject
    # The in-flight request COMPLETED (drain, not the hard-stop failure).
    result = req.future.result(timeout=5)
    assert len(result.token_ids) == 40
