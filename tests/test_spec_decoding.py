"""Speculative decoding exactness suite (ISSUE 20 acceptance gate).

The exact-verify redesign makes the spec window run the LITERAL
decode-step program once per candidate position (same shapes, same
bf16 reduction order), so spec-on streams are byte-identical to
spec-off BY CONSTRUCTION — at bf16, where the old batched ``[S, G+1]``
verify forward flipped near-tie argmaxes on 4/8 bench prompts. This
suite pins that contract everywhere the stream contract already
reaches:

* bf16 byte-identity (tokens AND logprob floats) at ``spec=2`` vs
  ``spec=0`` on the exact BENCH_SPEC_WORKLOAD prompt set — the four
  previously-flipping prompts included;
* seeded-sampled streams identical too, and sampled slots now ACCEPT
  drafts (the counter-keyed draw is reproduced inside the verify scan,
  so acceptance is no longer pinned to zero off the greedy path);
* ``logit_bias`` composes with speculation (the per-request bias plane
  rides the same shared sampling closure);
* byte-identity across prefix-cache warm hits, disaggregated-tier
  KV-block transfers, mid-stream supervisor replay, and tp=2;
* acceptance-counter math: tokens-per-step lives in [1, G+1], and the
  n-gram-friendly repeated-text shape accepts well above 1;
* zero steady-state recompiles with spec on (the exit-6 fence's
  invariant, asserted engine-side);
* the ``TPU_SPEC_TOKENS=auto`` default seam: ON only where the bench
  gate holds (TPU backend, no conflicting feature), OFF with a boot
  note otherwise, and both precedence directions of the
  penalties/top_logprobs interaction (implicit default yields,
  explicit contradiction still raises).

Determinism: engines share the default seed; faults fire on exact hit
counts through ``gofr_tpu/faults``; supervisor backoff sleeps are
recorded, not slept.
"""

from __future__ import annotations

import random
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.config import MockConfig
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.serving.engine import (
    SPEC_AUTO_TOKENS,
    InferenceEngine,
    resolve_spec_tokens,
)
from gofr_tpu.serving.supervisor import EngineSupervisor
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.service.replica_pool import EngineReplica, ReplicaPool

#: The BENCH_SPEC_WORKLOAD prompt set verbatim: repeated text with a
#: per-request rotation — the n-gram draft's best case, and the set on
#: which the old batched verify diverged on 4 of 8.
BENCH_PROMPTS = [
    ("abcdefgh"[i % 4:] + "abcdefgh" * 12)[:64] for i in range(8)
]

#: 96 tokens = exactly 3 full 32-token KV blocks, so prefix hits and
#: tier transfers engage their block-aligned paths (tier-suite idiom).
BLOCK_PROMPT = list(range(2, 200, 3)) + [7] * 30
assert len(BLOCK_PROMPT) == 96

G = 2

#: Shared serving geometry so both engines compile the same programs.
ENG_KW = dict(n_slots=4, max_len=256, window_k=4)

GREEDY = dict(max_new_tokens=24, temperature=0.0, stop_on_eos=False)
SAMPLED = dict(max_new_tokens=24, temperature=0.8, seed=42,
               stop_on_eos=False)


def _spec_metrics():
    # Only the acceptance histogram is registered (the bench serve()
    # idiom) — the metrics manager tolerates records against
    # unregistered instruments.
    m = new_metrics_manager()
    m.new_histogram("app_tpu_spec_tokens_per_step")
    return m


def _acceptance(metrics):
    """(sum, count) of the acceptance histogram — tokens emitted per
    live spec step, aggregated over every record so far."""
    for inst in metrics.instruments():
        if inst.name == "app_tpu_spec_tokens_per_step":
            agg_sum = agg_n = 0.0
            for _, (_, (s_, n_)) in inst.collect().items():
                agg_sum += s_
                agg_n += n_
            return agg_sum, agg_n
    return 0.0, 0.0


def _make_engine(spec_tokens, metrics=None, **kw):
    eng = InferenceEngine(
        "llama-tiny", tokenizer=ByteTokenizer(),
        spec_tokens=spec_tokens, metrics=metrics, **{**ENG_KW, **kw},
    )
    eng.start_sync()
    return eng


@pytest.fixture(scope="module")
def spec_metrics():
    return _spec_metrics()


@pytest.fixture(scope="module")
def engines(spec_metrics):
    """The shared pair: a spec=0 reference and a spec=2 engine, both
    bf16 llama-tiny with prefix pools. Module-scoped — construction
    and first-dispatch compiles dominate this suite's wall clock."""
    ref = _make_engine(0, prefix_slots=2)
    spec = _make_engine(G, prefix_slots=2, metrics=spec_metrics)
    yield ref, spec
    faults.reset()
    for eng in (ref, spec):
        eng.close()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _drain_stream(req, timeout=120.0):
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


# ----------------------------------------------------------------------
# bf16 byte-identity: the exact-verify contract, on the bench prompts
# ----------------------------------------------------------------------


def test_bf16_greedy_byte_identical_on_bench_prompts(engines):
    """spec=2 == spec=0 at bf16 on all 8 BENCH_SPEC_WORKLOAD prompts —
    tokens AND per-token logprob floats, which pins the verify LOGITS,
    not just the argmax (log_softmax is injective in the chosen row)."""
    ref, spec = engines
    want = [ref.generate_sync(p, **GREEDY) for p in BENCH_PROMPTS]
    reqs = [spec.submit_generate(p, **GREEDY) for p in BENCH_PROMPTS]
    got = [r.future.result(timeout=120) for r in reqs]
    for w, g in zip(want, got):
        assert g.token_ids == w.token_ids
        assert g.token_logprobs == w.token_logprobs  # exact floats
        assert g.finish_reason == w.finish_reason


def test_bf16_seeded_sampled_byte_identical_and_accepting(
    engines, spec_metrics
):
    """Satellite regression: sampled slots are no longer draft-free.
    The verify scan reproduces the counter-keyed categorical draw at
    every candidate position, so (a) seeded-sampled streams stay
    byte-identical at spec=G vs spec=0, and (b) acceptance can exceed
    the old hard floor of exactly 1.0 token per step (acc pinned 0)."""
    ref, spec = engines
    sum0, n0 = _acceptance(spec_metrics)
    # Repeated text at a LOW temperature: the seeded draw mostly
    # follows the mode, so n-gram drafts land often enough that a
    # single pinned-zero acceptance path would show mean == 1.0.
    near_greedy = dict(max_new_tokens=32, temperature=0.2, seed=7,
                       stop_on_eos=False)
    for params in (SAMPLED, near_greedy):
        for prompt in BENCH_PROMPTS[:4]:
            want = ref.generate_sync(prompt, **params)
            got = spec.generate_sync(prompt, **params)
            assert got.token_ids == want.token_ids
            assert got.token_logprobs == want.token_logprobs
    sum1, n1 = _acceptance(spec_metrics)
    assert n1 > n0
    mean = (sum1 - sum0) / (n1 - n0)
    assert mean > 1.0  # sampled slots accepted at least some drafts


def test_logit_bias_composes_with_speculation(engines):
    """The per-request bias plane rides the shared sampling closure
    inside the verify scan, so logit_bias no longer disables (or
    refuses) speculation — and the biased stream is byte-identical."""
    ref, spec = engines
    banned = ref.tokenizer.encode("a")[0]
    params = dict(max_new_tokens=16, temperature=0.0, stop_on_eos=False,
                  logit_bias={int(banned): -100.0})
    want = ref.generate_sync(BENCH_PROMPTS[0], **params)
    got = spec.generate_sync(BENCH_PROMPTS[0], **params)
    assert got.token_ids == want.token_ids
    assert banned not in got.token_ids  # the bias actually bit


# ----------------------------------------------------------------------
# identity across the stream contract's existing features
# ----------------------------------------------------------------------


def test_prefix_cache_warm_hit_byte_identical(engines):
    """A pooled-prefix warm hit changes the prefill path (admission
    copy instead of chunked prefill) but not one emitted byte — with
    speculation drafting over the copied history from token one."""
    ref, spec = engines
    system = "You are a terse assistant. Answer in one word. "
    ref.register_prefix_sync(system)
    spec.register_prefix_sync(system)
    prompt = system + "go go go go"
    want_cold = ref.generate_sync(prompt, **GREEDY)
    got_cold = spec.generate_sync(prompt, **GREEDY)
    # Second pass re-hits the pool on both engines (warm path).
    want_warm = ref.generate_sync(prompt, **GREEDY)
    got_warm = spec.generate_sync(prompt, **GREEDY)
    assert got_cold.token_ids == want_cold.token_ids
    assert got_warm.token_ids == want_warm.token_ids == want_cold.token_ids


def test_tier_transfer_byte_identical_with_spec():
    """Prefill-on-A → KV-block ship → decode-on-B with spec=2 on both
    replicas: greedy and seeded-sampled streams match a fused spec=0
    single-engine reference byte for byte."""
    paged = dict(
        n_slots=4, max_len=256, window_k=4, pipeline_depth=1,
        prefill_chunk=32, kv_block=32, auto_prefix=True,
    )
    ref = _make_engine(0, **paged)
    pf = _make_engine(G, **paged)
    dc = _make_engine(G, **paged)
    pool = ReplicaPool(
        [
            EngineReplica("pf", pf, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        probe_interval_s=0, probe_timeout_s=60.0, hedge_delay_s=300.0,
        transfer_retries=2, transfer_backoff_s=0.01,
        sleep=lambda s: None, rng=random.Random(7),
    )
    try:
        for params in (
            dict(max_new_tokens=12, temperature=0.0),
            dict(max_new_tokens=10, temperature=0.8, seed=42),
        ):
            want = ref.generate_sync(BLOCK_PROMPT, timeout=120, **params)
            req = pool.submit_generate(BLOCK_PROMPT, **params)
            toks = _drain_stream(req)
            assert toks == want.token_ids
            assert req.future.result(timeout=5).token_ids == want.token_ids
    finally:
        pool.stop_prober()
        for eng in (pf, dc, ref):
            eng.close()


def test_supervisor_replay_byte_identical_with_spec(engines):
    """A device crash mid-generation on the spec engine: the supervisor
    warm-restarts, the request replays, and what the client streamed —
    pre-crash tokens plus the continuation — is exactly the spec=0
    fault-free sequence. Speculation state (history plane, acceptance
    counters) rebuilds from the replay without changing a byte."""
    ref, _ = engines
    want = ref.generate_sync("the quick brown fox", max_new_tokens=32,
                             temperature=0.0, stop_on_eos=False)
    eng = _make_engine(G)
    sleeps = []
    sup = EngineSupervisor(
        eng, max_restarts=3, backoff_s=0.25, backoff_reset_s=60.0,
        join_timeout_s=5.0, rng=random.Random(1234),
        sleep=lambda s: sleeps.append((eng.state, s)),
    ).start()
    try:
        # Warm the compile caches fault-free first.
        warm = eng.generate_sync("the quick brown fox", max_new_tokens=32,
                                 temperature=0.0, stop_on_eos=False)
        assert warm.token_ids == want.token_ids
        # Crash at the 4th device dispatch — past the prefill chunk and
        # the first spec windows, so tokens are already on the stream.
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("injected device loss"),
            after=3, times=1,
        )
        req = eng.submit_generate("the quick brown fox", max_new_tokens=32,
                                  temperature=0.0, stop_on_eos=False)
        pre = [req.stream.get(timeout=120) for _ in range(3)]
        assert all(t is not None for t in pre)
        rest = _drain_stream(req)
        result = req.future.result(timeout=120)
        assert pre + rest == want.token_ids
        assert result.token_ids == want.token_ids
        assert req.replays == 1
        assert [s for s, _ in sleeps] == ["RESTARTING"]
    finally:
        faults.reset()
        sup.stop()
        eng.close()


def test_tp2_spec_byte_identical(engines):
    """tp=2 with spec=2 == the unsharded spec=0 reference: the verify
    scan runs the same GSPMD-sharded decode-step program, so sharding
    and speculation compose without touching the stream."""
    import jax

    devs = jax.devices()
    assert len(devs) >= 2, "suite needs the conftest's virtual devices"
    ref, _ = engines
    tp2 = _make_engine(G, devices=devs[:2], tp=2)
    try:
        for params in (GREEDY, SAMPLED):
            want = ref.generate_sync("shard me please", **params)
            got = tp2.generate_sync("shard me please", timeout=240, **params)
            assert got.token_ids == want.token_ids
    finally:
        tp2.close()


# ----------------------------------------------------------------------
# acceptance-counter math + the recompile fence
# ----------------------------------------------------------------------


def test_acceptance_counter_math(engines, spec_metrics):
    """Tokens-per-live-step ∈ [1, G+1] always (one bonus token even at
    zero accepted drafts; at most G drafts + the bonus), and the
    n-gram-friendly repeated-text shape accepts well above the floor."""
    _, spec = engines
    sum0, n0 = _acceptance(spec_metrics)
    results = [
        spec.generate_sync(p, max_new_tokens=32, temperature=0.0,
                           stop_on_eos=False)
        for p in BENCH_PROMPTS[:4]
    ]
    assert all(len(r.token_ids) == 32 for r in results)
    sum1, n1 = _acceptance(spec_metrics)
    assert n1 > n0
    mean = (sum1 - sum0) / (n1 - n0)
    assert 1.0 <= mean <= G + 1
    # "abcabc…" is the prompt-lookup best case — if drafting or the
    # verify scan silently stopped accepting, this drops to ~1.0.
    assert mean > 1.2


def test_zero_steady_state_recompiles_with_spec():
    """The warm-up fence with spec on: after greedy, seeded-sampled,
    and logit_bias variants have each compiled once, further traffic
    of any of those shapes recompiles NOTHING (bench exit-6 fence)."""
    eng = _make_engine(G)
    try:
        variants = (
            dict(max_new_tokens=8, temperature=0.0, stop_on_eos=False),
            dict(max_new_tokens=8, temperature=0.8, seed=3,
                 stop_on_eos=False),
            dict(max_new_tokens=8, temperature=0.0, stop_on_eos=False,
                 logit_bias={5: -100.0}),
        )
        for params in variants:
            eng.generate_sync(BENCH_PROMPTS[0], **params)
        eng.mark_steady_state()
        for params in variants:
            eng.generate_sync(BENCH_PROMPTS[1], **params)
        stats = eng.compile_stats()
        assert stats["steady_state_recompiles"] == 0, stats
    finally:
        eng.close()


# ----------------------------------------------------------------------
# the TPU_SPEC_TOKENS=auto default seam
# ----------------------------------------------------------------------


def test_resolve_spec_tokens_auto_seam():
    # ON exactly where the bench gate holds: TPU backend, no
    # conflicting feature.
    n, note = resolve_spec_tokens("auto", "tpu", False, 0)
    assert n == SPEC_AUTO_TOKENS and "ON by default" in note
    # OFF on compute-bound backends — the exact verify pays one decode
    # forward per candidate, so the A/B measures tok/s DOWN there.
    n, note = resolve_spec_tokens("auto", "cpu", False, 0)
    assert n == 0 and "backend='cpu'" in note
    # Explicitly-enabled features win over the implicit default.
    n, note = resolve_spec_tokens("auto", "tpu", True, 0)
    assert n == 0 and "TPU_PENALTIES" in note
    n, note = resolve_spec_tokens("auto", "tpu", False, 3)
    assert n == 0 and "TPU_TOP_LOGPROBS" in note
    # Explicit integers pass through untouched (backend-independent);
    # the constructor owns explicit-conflict errors.
    assert resolve_spec_tokens("3", "cpu", True, 5) == (3, None)
    assert resolve_spec_tokens("0", "tpu", False, 0) == (0, None)
    assert resolve_spec_tokens("-2", "tpu", False, 0) == (0, None)
    with pytest.raises(ValueError, match="integer or 'auto'"):
        resolve_spec_tokens("bogus", "tpu", False, 0)


class _RecordingLogger:
    def __init__(self):
        self.lines = []

    def infof(self, fmt, *args):
        self.lines.append(fmt % args if args else fmt)

    warnf = errorf = debugf = infof


def _cfg(**extra):
    return MockConfig({
        "TPU_KV_SLOTS": "2", "TPU_MAX_LEN": "128", **extra,
    })


def test_from_config_auto_resolves_per_backend_and_logs():
    # On the CPU test backend, auto resolves OFF with an attributable
    # boot note; nothing raises, nothing needs TPU_SPEC_TOKENS set.
    logger = _RecordingLogger()
    eng = InferenceEngine.from_config(_cfg(), logger=logger)
    try:
        assert eng.spec_tokens == 0
        assert any("speculative decoding" in ln for ln in logger.lines)
    finally:
        eng.close()
    # An explicit integer overrides the backend heuristic.
    eng = InferenceEngine.from_config(_cfg(TPU_SPEC_TOKENS="2"))
    try:
        assert eng.spec_tokens == 2
    finally:
        eng.close()


def test_spec_feature_precedence_both_directions():
    # Direction 1: the IMPLICIT default yields — a deployment that
    # enabled penalties (or top_logprobs) before spec defaulted on
    # keeps booting, with spec auto-disabled and a note logged.
    for extra in ({"TPU_PENALTIES": "true"}, {"TPU_TOP_LOGPROBS": "3"}):
        logger = _RecordingLogger()
        eng = InferenceEngine.from_config(_cfg(**extra), logger=logger)
        try:
            assert eng.spec_tokens == 0
            assert any("default-on skipped" in ln for ln in logger.lines)
        finally:
            eng.close()
    # Direction 2: an EXPLICIT contradiction the user typed still
    # raises — both through from_config and the constructor.
    with pytest.raises(ValueError, match="mutually exclusive"):
        InferenceEngine.from_config(
            _cfg(TPU_PENALTIES="true", TPU_SPEC_TOKENS="2")
        )
    with pytest.raises(ValueError, match="mutually"):
        InferenceEngine(
            "llama-tiny", n_slots=2, max_len=128,
            tokenizer=ByteTokenizer(), top_logprobs=2, spec_tokens=2,
        )
