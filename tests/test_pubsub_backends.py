"""MQTT (real wire protocol vs in-proc broker), Kafka/Google (fake drivers),
and the PUBSUB_BACKEND switch."""

from __future__ import annotations

import threading
import time

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.datasource.pubsub import (
    GooglePubSubClient,
    KafkaClient,
    MQTTClient,
    PubSubBackendUnavailable,
    new_pubsub_from_config,
)
from gofr_tpu.datasource.pubsub.mqtt import topic_matches
from gofr_tpu.testutil.mqtt_broker import InProcMQTTBroker


# ---------------------------------------------------------------------------
# MQTT: real client ↔ real (in-process) broker over TCP
# ---------------------------------------------------------------------------


@pytest.fixture
def broker():
    with InProcMQTTBroker() as b:
        yield b


def _client(broker, **kw):
    return MQTTClient(host=broker.host, port=broker.port, **kw)


def test_mqtt_publish_subscribe_qos1(broker):
    sub = _client(broker, client_id="sub")
    pub = _client(broker, client_id="pub")
    try:
        assert sub.subscribe("orders", timeout=0.05) is None  # subscribes lazily
        pub.publish("orders", b'{"id": 1}')
        msg = sub.subscribe("orders", timeout=2.0)
        assert msg is not None
        assert msg.value == b'{"id": 1}'
        assert msg.param("topic") == "orders"
        assert msg.metadata["qos"] == "1"
        msg.commit()  # sends PUBACK; must not raise
    finally:
        sub.close()
        pub.close()


def test_mqtt_qos0_roundtrip(broker):
    sub = _client(broker, client_id="sub0", qos=0)
    pub = _client(broker, client_id="pub0", qos=0)
    try:
        assert sub.subscribe("t0", timeout=0.05) is None
        pub.publish("t0", b"x")
        msg = sub.subscribe("t0", timeout=2.0)
        assert msg is not None and msg.value == b"x"
    finally:
        sub.close()
        pub.close()


def test_mqtt_subscribe_with_function_and_unsubscribe(broker):
    sub = _client(broker, client_id="cb")
    pub = _client(broker, client_id="pub")
    got = []
    done = threading.Event()
    try:
        sub.subscribe_with_function("alerts", lambda m: (got.append(m), done.set()))
        pub.publish("alerts", b"fire")
        assert done.wait(2.0)
        assert got[0].value == b"fire"

        sub.unsubscribe("alerts")
        pub.publish("alerts", b"after-unsub")
        time.sleep(0.2)
        assert len(got) == 1
    finally:
        sub.close()
        pub.close()


def test_mqtt_wildcards(broker):
    sub = _client(broker, client_id="wild")
    pub = _client(broker, client_id="pub")
    try:
        assert sub.subscribe("sensors/+/temp", timeout=0.05) is None
        pub.publish("sensors/a1/temp", b"21")
        msg = sub.subscribe("sensors/+/temp", timeout=2.0)
        assert msg is not None and msg.topic == "sensors/a1/temp"
    finally:
        sub.close()
        pub.close()


def test_mqtt_overlapping_subscriptions_all_delivered(broker):
    sub = _client(broker, client_id="multi")
    pub = _client(broker, client_id="pub")
    got_cb = []
    done = threading.Event()
    try:
        sub.subscribe_with_function("#", lambda m: (got_cb.append(m), done.set()))
        assert sub.subscribe("orders", timeout=0.05) is None  # queue sub too
        pub.publish("orders", b"both")
        assert done.wait(2.0)
        msg = sub.subscribe("orders", timeout=2.0)
        assert msg is not None and msg.value == b"both"  # queue got it too
        assert got_cb[0].value == b"both"
    finally:
        sub.close()
        pub.close()


def test_mqtt_callback_may_publish(broker):
    """Handlers run off the reader thread, so QoS-1 publish from a callback
    must not deadlock on its PUBACK."""
    sub = _client(broker, client_id="replier")
    pub = _client(broker, client_id="req")
    done = threading.Event()

    def handler(m):
        sub.publish("replies", b"pong")  # QoS-1: waits for PUBACK
        done.set()

    try:
        sub.subscribe_with_function("requests", handler)
        assert pub.subscribe("replies", timeout=0.05) is None
        pub.publish("requests", b"ping")
        assert done.wait(5.0), "callback publish deadlocked"
        reply = pub.subscribe("replies", timeout=2.0)
        assert reply is not None and reply.value == b"pong"
    finally:
        sub.close()
        pub.close()


def test_mqtt_ping_and_health(broker):
    c = _client(broker, client_id="hc")
    try:
        assert c.ping()
        assert c.health_check()["status"] == "UP"
    finally:
        c.close()


def test_topic_matches():
    assert topic_matches("a/b", "a/b")
    assert topic_matches("a/+", "a/b")
    assert not topic_matches("a/+", "a/b/c")
    assert topic_matches("a/#", "a/b/c")
    assert topic_matches("#", "anything/at/all")
    assert not topic_matches("a/b", "a")


def test_mqtt_via_backend_switch(broker):
    cfg = MockConfig({
        "PUBSUB_BACKEND": "MQTT",
        "MQTT_HOST": broker.host,
        "MQTT_PORT": str(broker.port),
    })
    client = new_pubsub_from_config(cfg)
    assert isinstance(client, MQTTClient)
    client.close()


# ---------------------------------------------------------------------------
# Kafka: client logic over fake Reader/Writer/Admin (reference test pattern)
# ---------------------------------------------------------------------------


class _FakeKafka:
    def __init__(self):
        self.topics: dict[str, list[bytes]] = {}
        self.commits: list[str] = []

    def writer(self):
        fake = self

        class W:
            def write(self, topic, value):
                fake.topics.setdefault(topic, []).append(value)

            def close(self):
                pass

        return W()

    def reader_factory(self, topic):
        fake = self

        class R:
            def read(self, timeout):
                q = fake.topics.get(topic) or []
                if not q:
                    return None
                value = q.pop(0)
                return value, lambda: fake.commits.append(topic)

            def close(self):
                pass

        return R()

    def admin(self):
        fake = self

        class A:
            def create_topic(self, name):
                fake.topics.setdefault(name, [])

            def delete_topic(self, name):
                fake.topics.pop(name, None)

            def ping(self):
                return True

        return A()


def test_kafka_client_roundtrip_and_commit():
    fake = _FakeKafka()
    client = KafkaClient(
        fake.writer(), fake.reader_factory, fake.admin(), brokers="fake:9092"
    )
    client.create_topic("orders")
    client.publish("orders", b"o1")
    msg = client.subscribe("orders")
    assert msg is not None and msg.value == b"o1"
    assert fake.commits == []  # commit only after handler success
    msg.commit()
    assert fake.commits == ["orders"]
    assert client.subscribe("orders", timeout=0.01) is None
    assert client.health_check()["status"] == "UP"
    client.delete_topic("orders")
    assert "orders" not in fake.topics
    client.close()


def test_kafka_without_driver_raises_clear_error():
    cfg = MockConfig({"PUBSUB_BACKEND": "KAFKA"})
    from gofr_tpu.datasource.pubsub.kafka import new_kafka_from_config

    with pytest.raises(PubSubBackendUnavailable, match="kafka-python"):
        new_kafka_from_config(cfg)
    # The container-level switch degrades to None instead of crashing boot.
    assert new_pubsub_from_config(cfg) is None


# ---------------------------------------------------------------------------
# Google Pub/Sub: client logic over a fake driver
# ---------------------------------------------------------------------------


class _FakeGoogleDriver:
    def __init__(self):
        self.topics: set[str] = set()
        self.subs: dict[str, str] = {}  # sub → topic
        self.pending: dict[str, list[bytes]] = {}
        self.acked: list[object] = []

    def ensure_topic(self, topic):
        self.topics.add(topic)

    def ensure_subscription(self, topic, subscription):
        self.subs[subscription] = topic

    def publish(self, topic, value):
        for sub, t in self.subs.items():
            if t == topic:
                self.pending.setdefault(sub, []).append(value)
        self.pending.setdefault(f"__topic__{topic}", []).append(value)

    def pull_one(self, subscription, timeout):
        q = self.pending.get(subscription) or []
        if not q:
            return None
        value = q.pop(0)
        return value, ("handle", value)

    def ack(self, subscription, ack_handle):
        self.acked.append(ack_handle)

    def delete_topic(self, topic):
        self.topics.discard(topic)

    def ping(self):
        return True

    def close(self):
        pass


def test_google_client_auto_create_and_ack():
    drv = _FakeGoogleDriver()
    client = GooglePubSubClient(drv, subscription_name="svc", project="p1")
    # Subscribe first: topic + subscription auto-created (reference
    # google.go:115-166), named ${SUB}-${topic}.
    assert client.subscribe("events", timeout=0.01) is None
    assert "events" in drv.topics
    assert drv.subs == {"svc-events": "events"}

    client.publish("events", b"e1")
    msg = client.subscribe("events")
    assert msg is not None and msg.value == b"e1"
    assert drv.acked == []
    msg.commit()
    assert drv.acked == [("handle", b"e1")]
    assert client.health_check()["status"] == "UP"


def test_google_without_driver_raises_clear_error():
    from gofr_tpu.datasource.pubsub.google import new_google_from_config

    with pytest.raises(PubSubBackendUnavailable, match="google-cloud-pubsub"):
        new_google_from_config(MockConfig({}))


def test_mqtt_reconnect_replays_subscriptions():
    """A dropped broker connection must self-heal: the client re-dials
    with backoff, replays its SUBSCRIBEs, and deliveries resume —
    pinned by killing the broker and restarting one on the SAME port
    (mqtt.py:_reconnect, the path nothing exercised)."""
    import random
    import time as _time

    # A port BELOW the ephemeral range: the client's reconnect loop
    # dials the freed port continuously, and against an ephemeral port
    # the kernel can self-connect (source==dest), holding the port and
    # blocking the broker's rebind forever.
    b1 = None
    for _ in range(20):
        try:
            b1 = InProcMQTTBroker(port=random.randint(20000, 28000))
            break
        except OSError:
            continue
    assert b1 is not None, "no free low port found"
    port = b1.port
    sub = MQTTClient(host=b1.host, port=port, client_id="rc-sub")
    pub = None
    try:
        assert sub.subscribe("orders", timeout=0.05) is None  # lazy sub
        b1.close()  # drop every connection
        # Rebind the same port (the old listener can linger briefly).
        b2 = None
        deadline = _time.time() + 10
        while b2 is None:
            try:
                b2 = InProcMQTTBroker(port=port)
            except OSError:
                if _time.time() > deadline:
                    raise
                _time.sleep(0.2)
        try:
            # Reconnect + SUBSCRIBE replay happen with backoff; publish
            # retries until the subscription is live again.
            pub = MQTTClient(host=b2.host, port=port, client_id="rc-pub")
            msg = None
            deadline = _time.time() + 20
            while msg is None and _time.time() < deadline:
                pub.publish("orders", b"after-reconnect")
                msg = sub.subscribe("orders", timeout=1.0)
            assert msg is not None, "no delivery after broker restart"
            assert msg.value == b"after-reconnect"
        finally:
            b2.close()
    finally:
        sub.close()
        if pub is not None:
            pub.close()
