"""Frequency/presence penalties (TPU_PENALTIES): OpenAI-parity sampling
controls, compiled into the sampler as a per-slot generated-token count
plane. Greedy requests honor them too (penalties apply before argmax)."""

from __future__ import annotations

import pytest

from gofr_tpu.errors import ErrorInvalidParam
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer

PROMPT = "the quick brown fox"


def _engine(**kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 128)
    kw.setdefault("window_k", 4)
    kw.setdefault("tokenizer", ByteTokenizer())
    return InferenceEngine("llama-tiny", **kw)


def _greedy(eng, n=24, **kw):
    return eng.generate_sync(
        PROMPT, max_new_tokens=n, temperature=0.0, stop_on_eos=False,
        timeout=120, **kw
    ).token_ids


@pytest.fixture(scope="module")
def base_tokens():
    eng = _engine()
    eng.start_sync()
    try:
        yield _greedy(eng)
    finally:
        eng.stop_sync()


def _max_run_frequency(tokens):
    from collections import Counter

    return max(Counter(tokens).values())


def test_zero_penalties_identical_to_base(base_tokens):
    # The penalties COMPILE path with zero coefficients must not perturb
    # the stream: penalized logits == raw logits when both are 0.
    eng = _engine(enable_penalties=True)
    eng.start_sync()
    try:
        assert _greedy(eng) == base_tokens
    finally:
        eng.stop_sync()


def test_frequency_penalty_breaks_repetition(base_tokens):
    # Random-weight greedy decode loops hard; a strong frequency penalty
    # must reduce the most-repeated token's count and change the stream.
    eng = _engine(enable_penalties=True)
    eng.start_sync()
    try:
        toks = _greedy(eng, frequency_penalty=1.5)
        assert toks != base_tokens
        assert _max_run_frequency(toks) < _max_run_frequency(base_tokens)
        # And independence: a concurrent zero-penalty request on the SAME
        # engine still matches the base stream (per-slot counts/coeffs).
        pen = eng.submit_generate(
            PROMPT, max_new_tokens=24, temperature=0.0, stop_on_eos=False,
            frequency_penalty=1.5,
        )
        plain = eng.submit_generate(
            PROMPT, max_new_tokens=24, temperature=0.0, stop_on_eos=False,
        )
        assert plain.future.result(timeout=120).token_ids == base_tokens
        assert pen.future.result(timeout=120).token_ids == toks
    finally:
        eng.stop_sync()


def test_presence_penalty_deviates_and_mild_frequency_differs(base_tokens):
    # Presence penalizes each seen token ONCE (not per occurrence). At a
    # strong coefficient both penalties suppress any repeat, so the
    # distinguishing case is a MILD coefficient: frequency accumulates
    # per occurrence and eventually overtakes the one-shot presence hit.
    eng = _engine(enable_penalties=True)
    eng.start_sync()
    try:
        base48 = _greedy(eng, n=48)
        p = _greedy(eng, n=48, presence_penalty=0.3)
        f = _greedy(eng, n=48, frequency_penalty=0.3)
        assert p != base48 and f != base48
        assert _max_run_frequency(f) <= _max_run_frequency(p)
    finally:
        eng.stop_sync()


def test_mega_windows_compose(base_tokens):
    eng = _engine(enable_penalties=True, mega_windows=4)
    ref = _engine(enable_penalties=True)
    for e in (eng, ref):
        e.start_sync()
    try:
        assert _greedy(eng, frequency_penalty=1.5) == _greedy(
            ref, frequency_penalty=1.5
        )
        assert _greedy(eng) == base_tokens
    finally:
        eng.stop_sync()
        ref.stop_sync()


def test_penalties_require_flag_and_range():
    eng = _engine()  # feature compiled OUT
    eng.start_sync()
    try:
        with pytest.raises(ErrorInvalidParam, match="TPU_PENALTIES"):
            eng.submit_generate(PROMPT, frequency_penalty=0.5)
    finally:
        eng.stop_sync()
    eng = _engine(enable_penalties=True)
    eng.start_sync()
    try:
        with pytest.raises(ErrorInvalidParam, match=r"\[-2, 2\]"):
            eng.submit_generate(PROMPT, presence_penalty=3.0)
    finally:
        eng.stop_sync()


def test_penalties_reject_speculation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        _engine(enable_penalties=True, spec_tokens=2)


class TestLogitBias:
    """OpenAI logit_bias: sparse per-request (token, bias) planes applied
    to raw logits before penalties, argmax, and sampling."""

    def test_minus_100_bans_and_plus_forces(self, base_tokens):
        eng = _engine()
        eng.start_sync()
        try:
            # Ban the greedy stream's first token: the stream must change
            # and never contain it.
            banned = int(base_tokens[0])
            toks = _greedy(eng, logit_bias={banned: -100})
            assert banned not in toks
            # +100 on one token forces it everywhere (greedy).
            forced = 7
            toks = _greedy(eng, n=8, logit_bias={forced: 100})
            assert toks == [forced] * 8
            # No bias → base stream intact on the same engine.
            assert _greedy(eng) == base_tokens
        finally:
            eng.stop_sync()

    def test_bias_validation(self):
        from gofr_tpu.errors import ErrorInvalidParam

        eng = _engine()
        eng.start_sync()
        try:
            with pytest.raises(ErrorInvalidParam, match="at most"):
                eng.submit_generate(
                    PROMPT, logit_bias={i: 1.0 for i in range(301)}
                )
            with pytest.raises(ErrorInvalidParam, match="integral"):
                eng.submit_generate(PROMPT, logit_bias={7.9: -100.0})
            with pytest.raises(ErrorInvalidParam, match="token ids"):
                eng.submit_generate(PROMPT, logit_bias={10_000_000: 1.0})
            with pytest.raises(ErrorInvalidParam, match="object"):
                eng.submit_generate(PROMPT, logit_bias=[5])
        finally:
            eng.stop_sync()

    def test_bias_with_mega_and_penalties(self, base_tokens):
        eng = _engine(enable_penalties=True, mega_windows=4)
        eng.start_sync()
        try:
            banned = int(base_tokens[0])
            toks = eng.generate_sync(
                PROMPT, max_new_tokens=16, temperature=0.0,
                stop_on_eos=False, logit_bias={banned: -100},
                frequency_penalty=0.5, timeout=120,
            ).token_ids
            assert banned not in toks
        finally:
            eng.stop_sync()


class TestTopLogprobs:
    """OpenAI top_logprobs alternatives (TPU_TOP_LOGPROBS compile gate)."""

    def test_alternatives_align_and_contain_chosen(self):
        eng = _engine(top_logprobs=4)
        eng.start_sync()
        try:
            r = eng.generate_sync(
                PROMPT, max_new_tokens=12, temperature=0.0,
                stop_on_eos=False, top_logprobs=3, timeout=120,
            )
            assert r.token_top_logprobs is not None
            assert len(r.token_top_logprobs) == len(r.token_ids) == 12
            for tok, lp, alts in zip(
                r.token_ids, r.token_logprobs, r.token_top_logprobs
            ):
                assert len(alts) == 3
                # Greedy: the chosen token IS the top-1 alternative and
                # its logprob matches.
                assert alts[0][0] == tok
                assert abs(alts[0][1] - lp) < 1e-4
                # Sorted descending.
                assert alts[0][1] >= alts[1][1] >= alts[2][1]
        finally:
            eng.stop_sync()

    def test_mega_and_plain_agree(self):
        a = _engine(top_logprobs=2)
        b = _engine(top_logprobs=2, mega_windows=4)
        for e in (a, b):
            e.start_sync()
        try:
            ra, rb = (
                e.generate_sync(
                    PROMPT, max_new_tokens=10, temperature=0.0,
                    stop_on_eos=False, top_logprobs=2, timeout=120,
                )
                for e in (a, b)
            )
            assert ra.token_ids == rb.token_ids
            assert [
                [t for t, _ in alts] for alts in ra.token_top_logprobs
            ] == [
                [t for t, _ in alts] for alts in rb.token_top_logprobs
            ]
        finally:
            a.stop_sync()
            b.stop_sync()

    def test_requires_compile_flag_and_cap(self):
        eng = _engine()
        eng.start_sync()
        try:
            with pytest.raises(ErrorInvalidParam, match="TPU_TOP_LOGPROBS"):
                eng.submit_generate(PROMPT, top_logprobs=2)
        finally:
            eng.stop_sync()
        eng = _engine(top_logprobs=2)
        eng.start_sync()
        try:
            with pytest.raises(ErrorInvalidParam, match=r"\[1, 2\]"):
                eng.submit_generate(PROMPT, top_logprobs=5)
        finally:
            eng.stop_sync()

    def test_without_request_flag_no_alternatives(self):
        eng = _engine(top_logprobs=2)
        eng.start_sync()
        try:
            r = eng.generate_sync(
                PROMPT, max_new_tokens=6, temperature=0.0,
                stop_on_eos=False, timeout=120,
            )
            assert r.token_top_logprobs is None
        finally:
            eng.stop_sync()
