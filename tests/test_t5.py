"""T5 encoder-decoder: parity against the torch T5 oracle (relative
position buckets, unscaled attention, gated-gelu FFN, cross-attention,
untied head) and batched greedy generation."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.t5 import (
    T5Config,
    init_t5,
    t5_decode,
    t5_encode,
    t5_generate,
)

CFG = T5Config(
    vocab_size=64, d_model=32, d_kv=8, n_heads=4, n_layers=2, d_ff=64,
    dtype=jnp.float32,
)


def test_generate_shapes_and_eos_padding():
    params = init_t5(jax.random.PRNGKey(0), CFG)
    toks = jnp.array([[5, 6, 7, 0], [8, 9, 0, 0]], dtype=jnp.int32)
    lens = jnp.array([3, 2], dtype=jnp.int32)
    out = np.asarray(t5_generate(params, toks, lens, CFG, max_new=8))
    assert out.shape == (2, 8)
    for row in out:
        if 1 in row.tolist():  # after EOS: zero-padded
            idx = row.tolist().index(1)
            assert all(t == 0 for t in row[idx + 1:])


def test_padding_invariance():
    """Extra right-padding on the encoder input must not change the
    generation (the length masks own validity)."""
    params = init_t5(jax.random.PRNGKey(1), CFG)
    lens = jnp.array([3], dtype=jnp.int32)
    a = t5_generate(
        params, jnp.array([[5, 6, 7, 0]], dtype=jnp.int32), lens, CFG,
        max_new=6,
    )
    b = t5_generate(
        params, jnp.array([[5, 6, 7, 0, 0, 0, 0]], dtype=jnp.int32), lens,
        CFG, max_new=6,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_t5_matches_torch_oracle():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=64, d_model=32, d_kv=8, num_heads=4, num_layers=2,
        d_ff=64, relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        feed_forward_proj="gated-gelu", tie_word_embeddings=False,
        dropout_rate=0.0,
    )
    torch.manual_seed(6)
    model = transformers.T5ForConditionalGeneration(hf_cfg)
    model.eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    L = CFG.n_layers

    def stack(fmt, transpose=True):
        a = np.stack([sd[fmt.format(i)] for i in range(L)])
        return jnp.asarray(
            np.swapaxes(a, -1, -2) if transpose else a, jnp.float32
        )

    def attn(side, layer_idx, pre):
        base = f"{side}.block.{{}}.layer.{layer_idx}."
        kind = "SelfAttention" if layer_idx == 0 else "EncDecAttention"
        return {
            f"{pre}wq": stack(base + kind + ".q.weight"),
            f"{pre}wk": stack(base + kind + ".k.weight"),
            f"{pre}wv": stack(base + kind + ".v.weight"),
            f"{pre}wo": stack(base + kind + ".o.weight"),
        }

    ffn_layer = {"encoder": 1, "decoder": 2}

    def ffn(side):
        base = f"{side}.block.{{}}.layer.{ffn_layer[side]}.DenseReluDense."
        return {
            "w_gate": stack(base + "wi_0.weight"),
            "w_up": stack(base + "wi_1.weight"),
            "w_down": stack(base + "wo.weight"),
        }

    enc = {
        "ln1": stack("encoder.block.{}.layer.0.layer_norm.weight", False),
        "ln2": stack("encoder.block.{}.layer.1.layer_norm.weight", False),
        **attn("encoder", 0, "sa_"),
        **ffn("encoder"),
    }
    dec = {
        "ln1": stack("decoder.block.{}.layer.0.layer_norm.weight", False),
        "ln2": stack("decoder.block.{}.layer.1.layer_norm.weight", False),
        "ln3": stack("decoder.block.{}.layer.2.layer_norm.weight", False),
        **attn("decoder", 0, "sa_"),
        **attn("decoder", 1, "ca_"),
        **ffn("decoder"),
    }
    params = {
        "embed": jnp.asarray(sd["shared.weight"]),
        "enc_rel_bias": jnp.asarray(sd[
            "encoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight"
        ]),
        "dec_rel_bias": jnp.asarray(sd[
            "decoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight"
        ]),
        "encoder": enc,
        "decoder": dec,
        "enc_norm": jnp.asarray(sd["encoder.final_layer_norm.weight"]),
        "dec_norm": jnp.asarray(sd["decoder.final_layer_norm.weight"]),
        "lm_head": jnp.asarray(np.swapaxes(sd["lm_head.weight"], 0, 1)),
    }
    rng = np.random.default_rng(0)
    inp = rng.integers(2, 64, size=(2, 9)).astype(np.int32)
    dec_inp = rng.integers(2, 64, size=(2, 5)).astype(np.int32)
    dec_inp[:, 0] = 0  # T5 decoder start token (pad)
    lens = np.array([9, 9], dtype=np.int32)

    enc_states = t5_encode(params, jnp.asarray(inp), jnp.asarray(lens), CFG)
    ours = np.asarray(t5_decode(
        params, jnp.asarray(dec_inp), enc_states, jnp.asarray(lens), CFG
    ))
    with torch.no_grad():
        theirs = model(
            input_ids=torch.tensor(inp, dtype=torch.long),
            attention_mask=torch.ones((2, 9), dtype=torch.long),
            decoder_input_ids=torch.tensor(dec_inp, dtype=torch.long),
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_t5_serves_through_engine():
    """The seq2seq family behind the engine's dynamic batcher: same
    text in → same ids out (deterministic greedy), batch composition
    doesn't change results, ctx.infer dispatch works."""
    import asyncio

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    eng = InferenceEngine("t5-tiny", max_batch=4, tokenizer=ByteTokenizer())
    eng.start_sync()
    try:
        solo = eng.seq2seq_sync("translate this text")
        assert isinstance(solo, list) and len(solo) >= 1
        # Concurrent submissions batch together; results must match solo.
        futs = [
            eng._batcher.submit(t)
            for t in ("translate this text", "another input", "a third")
        ]
        outs = [f.result(timeout=120) for f in futs]
        assert outs[0] == solo
        out = asyncio.new_event_loop().run_until_complete(
            eng.infer("translate this text")
        )
        assert out["token_ids"] == solo
        assert isinstance(out["text"], str)
    finally:
        eng.stop_sync()


def test_load_hf_t5_checkpoint_parity(tmp_path):
    """The production loader maps a saved HF flan-t5-style checkpoint
    and reproduces the torch logits (same oracle as the manual map)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from gofr_tpu.models.t5 import config_from_hf_t5, load_hf_t5

    hf_cfg = transformers.T5Config(
        vocab_size=64, d_model=32, d_kv=8, num_heads=4, num_layers=2,
        d_ff=64, relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        feed_forward_proj="gated-gelu", tie_word_embeddings=False,
        dropout_rate=0.0,
    )
    torch.manual_seed(8)
    model = transformers.T5ForConditionalGeneration(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)

    import dataclasses

    cfg = config_from_hf_t5(str(tmp_path))
    assert cfg.gated_ffn and not cfg.tied_head and cfg.d_kv == 8
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = load_hf_t5(str(tmp_path), cfg)
    rng = np.random.default_rng(1)
    inp = rng.integers(2, 64, size=(1, 7)).astype(np.int32)
    dec_inp = np.array([[0, 5, 9, 11]], dtype=np.int32)
    lens = np.array([7], dtype=np.int32)
    enc = t5_encode(params, jnp.asarray(inp), jnp.asarray(lens), cfg)
    ours = np.asarray(t5_decode(
        params, jnp.asarray(dec_inp), enc, jnp.asarray(lens), cfg
    ))
    with torch.no_grad():
        theirs = model(
            input_ids=torch.tensor(inp, dtype=torch.long),
            attention_mask=torch.ones((1, 7), dtype=torch.long),
            decoder_input_ids=torch.tensor(dec_inp, dtype=torch.long),
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_t5_checkpoint_boot_seam(tmp_path):
    """TPU_CHECKPOINT routes seq2seq engines to the T5 loader."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    import dataclasses

    from gofr_tpu.config import MockConfig
    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.serving.engine import InferenceEngine

    hf_cfg = transformers.T5Config(
        vocab_size=64, d_model=32, d_kv=8, num_heads=4, num_layers=2,
        d_ff=64, feed_forward_proj="gated-gelu",
        tie_word_embeddings=False, dropout_rate=0.0,
    )
    torch.manual_seed(9)
    transformers.T5ForConditionalGeneration(hf_cfg).save_pretrained(
        tmp_path, safe_serialization=True
    )
    from gofr_tpu.models.t5 import config_from_hf_t5, init_t5

    cfg = dataclasses.replace(
        config_from_hf_t5(str(tmp_path)), dtype=jnp.float32
    )
    register_model(ModelSpec(
        name="t5-ckpt-test", family="seq2seq", config=cfg, init=init_t5,
        eos_token=1,
    ))
    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "t5-ckpt-test",
        "TPU_CHECKPOINT": str(tmp_path),
        "TPU_MAX_BATCH": "2",
    }))
    eng.start_sync()
    try:
        a = eng.seq2seq_sync([5, 6, 7])
        b = eng.seq2seq_sync([5, 6, 7])
        assert a == b and len(a) >= 1
    finally:
        eng.stop_sync()


def test_t5_int8_quantization(tmp_path):
    """Weight-only int8 for the seq2seq family: quantized logits track
    bf16 (top-1 agreement), and TPU_QUANT=int8 boots from a checkpoint
    through from_config."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    import dataclasses

    from gofr_tpu.config import MockConfig
    from gofr_tpu.models.registry import ModelSpec, register_model
    from gofr_tpu.models.t5 import (
        config_from_hf_t5,
        init_t5,
        load_hf_t5,
        quantize_t5_params,
    )
    from gofr_tpu.ops.quant import Q8
    from gofr_tpu.serving.engine import InferenceEngine

    hf_cfg = transformers.T5Config(
        vocab_size=64, d_model=32, d_kv=8, num_heads=4, num_layers=2,
        d_ff=64, feed_forward_proj="gated-gelu",
        tie_word_embeddings=False, dropout_rate=0.0,
    )
    torch.manual_seed(10)
    transformers.T5ForConditionalGeneration(hf_cfg).save_pretrained(
        tmp_path, safe_serialization=True
    )
    cfg = dataclasses.replace(
        config_from_hf_t5(str(tmp_path)), dtype=jnp.float32
    )
    params = load_hf_t5(str(tmp_path), cfg)
    q = quantize_t5_params(params, "int8")
    assert isinstance(q["encoder"]["sa_wq"], Q8)
    assert isinstance(q["decoder"]["ca_wo"], Q8)
    assert not isinstance(q["encoder"]["ln1"], Q8)
    assert not isinstance(q["enc_rel_bias"], Q8)
    toks = jnp.array([[5, 9, 12, 3]], dtype=jnp.int32)
    lens = jnp.array([4], dtype=jnp.int32)
    dec = jnp.array([[0, 7, 11]], dtype=jnp.int32)
    lr = np.asarray(t5_decode(
        params, dec, t5_encode(params, toks, lens, cfg), lens, cfg
    ))
    lq = np.asarray(t5_decode(
        q, dec, t5_encode(q, toks, lens, cfg), lens, cfg
    ))
    agree = (lr.argmax(-1) == lq.argmax(-1)).mean()
    assert agree >= 0.66  # tiny random model; int8 keeps most top-1s

    register_model(ModelSpec(
        name="t5-q-test", family="seq2seq", config=cfg, init=init_t5,
        eos_token=1,
    ))
    eng = InferenceEngine.from_config(MockConfig({
        "TPU_MODEL": "t5-q-test",
        "TPU_CHECKPOINT": str(tmp_path),
        "TPU_QUANT": "int8",
        "TPU_MAX_BATCH": "2",
    }))
    assert eng.quant == "int8"
    eng.start_sync()
    try:
        a = eng.seq2seq_sync([5, 6, 7])
        assert a == eng.seq2seq_sync([5, 6, 7])
    finally:
        eng.stop_sync()


def test_t5_grpc_generate_routes_seq2seq():
    """Both gRPC Generate surfaces serve seq2seq engines (text in →
    generated text out) instead of raising the llm-only error."""
    import asyncio

    from gofr_tpu.grpc.inference import InferenceServicer
    from gofr_tpu.grpc.inference_typed import TypedInferenceServicer
    from gofr_tpu.grpc import inference_pb2
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    eng = InferenceEngine("t5-tiny", max_batch=2, tokenizer=ByteTokenizer())
    eng.start_sync()
    try:
        loop = asyncio.new_event_loop()
        out = loop.run_until_complete(
            InferenceServicer(eng).Generate({"prompt": "hi there"}, None)
        )
        assert out["tokens"] >= 1 and out["finish_reason"] == "stop"
        req = inference_pb2.GenerateRequest(prompt="hi there")
        t_out = loop.run_until_complete(
            TypedInferenceServicer(eng).Generate(req, None)
        )
        assert t_out.tokens == out["tokens"]
        assert t_out.text == out["text"]

        async def drain(agen):
            return [c async for c in agen]

        chunks = loop.run_until_complete(
            drain(InferenceServicer(eng).GenerateStream(
                {"prompt": "hi there"}, None
            ))
        )
        assert chunks[-1]["done"] and chunks[-1]["tokens"] == out["tokens"]
        # Stepped decode: pieces CONCATENATE to the unary text.
        assert "".join(c["text"] for c in chunks[:-1]) == out["text"]
        t_chunks = loop.run_until_complete(
            drain(TypedInferenceServicer(eng).GenerateStream(req, None))
        )
        assert t_chunks[-1].done and t_chunks[-1].tokens == out["tokens"]
        assert "".join(c.text for c in t_chunks[:-1]) == out["text"]
    finally:
        eng.stop_sync()


def test_t5_stream_is_stepped(monkeypatch):
    """A streaming seq2seq reply must arrive in MULTIPLE content chunks
    for a multi-token answer (r4 VERDICT weak #7: a streaming API that
    buffers the whole answer isn't streaming), token-identical to the
    one-shot batched program, on both gRPC surfaces."""
    import asyncio

    from gofr_tpu.grpc import inference_pb2
    from gofr_tpu.grpc.inference import InferenceServicer
    from gofr_tpu.grpc.inference_typed import TypedInferenceServicer
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    monkeypatch.setenv("TPU_SEQ2SEQ_CHUNK", "2")
    eng = InferenceEngine("t5-tiny", max_batch=2, tokenizer=ByteTokenizer())
    eng.start_sync()
    try:
        solo = eng.seq2seq_sync("translate this text")
        streamed = [
            t
            for ch in eng.seq2seq_stream_blocking("translate this text")
            for t in ch
        ]
        assert streamed == solo  # stepped path == one-shot program
        assert len(solo) >= 3, "answer too short to exercise chunking"

        async def drain(agen):
            return [c async for c in agen]

        loop = asyncio.new_event_loop()
        want_text = eng.tokenizer.decode(solo)
        chunks = loop.run_until_complete(
            drain(InferenceServicer(eng).GenerateStream(
                {"prompt": "translate this text"}, None
            ))
        )
        content, final = chunks[:-1], chunks[-1]
        assert len(content) >= 2, "stepped stream must emit ≥2 chunks"
        assert "".join(c["text"] for c in content) == want_text
        assert final["done"] and final["tokens"] == len(solo)
        req = inference_pb2.GenerateRequest(prompt="translate this text")
        t_chunks = loop.run_until_complete(
            drain(TypedInferenceServicer(eng).GenerateStream(req, None))
        )
        assert len(t_chunks[:-1]) >= 2
        assert "".join(c.text for c in t_chunks[:-1]) == want_text
        assert t_chunks[-1].done and t_chunks[-1].tokens == len(solo)
    finally:
        eng.stop_sync()
