"""Child process for the DCN two-host test (``tests/test_dcn.py``).

Runs as one of two cooperating processes: initializes the multi-host JAX
runtime via ``parallel/dcn.initialize_multihost`` (the non-no-op path),
proves a cross-process collective, then routes a request across "hosts"
through the service tier — process 0 serves ``/topology`` over the real
HTTP app surface, process 1 calls it through the inter-service client
behind the circuit breaker (SURVEY §2.6: DCN tier = jax.distributed
runtime + the service client/breaker reused verbatim).

Usage: python dcn_child.py <pid 0|1> <coordinator_port> <http_port> <tmpdir>
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid = int(sys.argv[1])
    coord_port = int(sys.argv[2])
    http_port = int(sys.argv[3])
    tmpdir = sys.argv[4]

    from gofr_tpu.config import MockConfig
    from gofr_tpu.parallel.dcn import initialize_multihost, process_topology

    distributed = initialize_multihost(MockConfig({
        "DCN_COORDINATOR": f"127.0.0.1:{coord_port}",
        "DCN_NUM_PROCESSES": "2",
        "DCN_PROCESS_ID": str(pid),
    }))
    assert distributed, "DCN config present → must take the distributed path"

    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    topo = process_topology()
    assert topo["process_count"] == 2, topo
    assert topo["global_devices"] > topo["local_devices"], topo

    # Cross-process collective: every host contributes pid+1; the gathered
    # sum (3.0) can only come out right if the DCN runtime spans processes.
    gathered = multihost_utils.process_allgather(jnp.array([float(pid + 1)]))
    result = {"pid": pid, "topo": topo, "allgather_sum": float(gathered.sum())}

    done_file = os.path.join(tmpdir, "peer_done")
    if pid == 0:
        import asyncio

        from gofr_tpu import App

        app = App(config=MockConfig({
            "APP_NAME": "dcn-host-0",
            "HTTP_PORT": str(http_port),
            "METRICS_PORT": "0",
        }))

        @app.get("/topology")
        async def topology(ctx):
            return process_topology()

        loop = asyncio.new_event_loop()
        threading.Thread(target=loop.run_forever, daemon=True).start()
        asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=30)
        deadline = time.time() + 120
        while not os.path.exists(done_file) and time.time() < deadline:
            time.sleep(0.2)
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=10)
        result["served_peer"] = os.path.exists(done_file)
    else:
        from gofr_tpu.service import CircuitBreakerConfig, new_http_service

        svc = new_http_service(
            f"http://127.0.0.1:{http_port}", None, None,
            CircuitBreakerConfig(threshold=50, interval_s=60.0),
        )
        body = None
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                resp = svc.get("/topology")
                if resp.status_code == 200:
                    body = json.loads(resp.body)
                    break
            except Exception:  # noqa: BLE001 — peer still booting
                pass
            time.sleep(0.5)
        assert body is not None, "never reached host 0 over the service tier"
        assert body["data"]["process_count"] == 2, body
        with open(done_file, "w") as f:
            f.write("ok")
        result["hop"] = body["data"]

    print("DCN_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
