"""Child process for the DCN two-host test (``tests/test_dcn.py``).

Runs as one of two cooperating processes: initializes the multi-host JAX
runtime via ``parallel/dcn.initialize_multihost`` (the non-no-op path),
proves a cross-process collective, then routes a request across "hosts"
through the service tier — process 0 serves ``/topology`` over the real
HTTP app surface, process 1 calls it through the inter-service client
behind the circuit breaker (SURVEY §2.6: DCN tier = jax.distributed
runtime + the service client/breaker reused verbatim).

Usage: python dcn_child.py <pid 0|1> <coordinator_port> <http_port> <tmpdir>
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid = int(sys.argv[1])
    coord_port = int(sys.argv[2])
    http_port = int(sys.argv[3])
    tmpdir = sys.argv[4]

    from gofr_tpu.config import MockConfig
    from gofr_tpu.parallel.dcn import initialize_multihost, process_topology

    distributed = initialize_multihost(MockConfig({
        "DCN_COORDINATOR": f"127.0.0.1:{coord_port}",
        "DCN_NUM_PROCESSES": "2",
        "DCN_PROCESS_ID": str(pid),
    }))
    assert distributed, "DCN config present → must take the distributed path"

    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    topo = process_topology()
    assert topo["process_count"] == 2, topo
    assert topo["global_devices"] > topo["local_devices"], topo

    # Cross-process collective: every host contributes pid+1; the gathered
    # sum (3.0) can only come out right if the DCN runtime spans processes.
    gathered = multihost_utils.process_allgather(jnp.array([float(pid + 1)]))
    result = {"pid": pid, "topo": topo, "allgather_sum": float(gathered.sum())}
    print(f"phase allgather done pid={pid}", flush=True)

    # Multi-host SERVING smoke (VERDICT r3 #9): one engine whose tp=2 mesh
    # takes one device from EACH process — its decode/prefill collectives
    # ride the DCN runtime, the serving analog of the training dryrun.
    # Both processes run the same SPMD program: requests are submitted
    # one-at-a-time from idle so the two schedulers issue identical jit
    # sequences (arrival timing can't reorder dispatches mid-stream).
    import numpy as np
    from jax.sharding import Mesh

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    import jax

    by_proc: dict = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    mesh_devs = np.array(
        [sorted(by_proc[p], key=lambda d: d.id)[0] for p in sorted(by_proc)]
    )
    mesh = Mesh(mesh_devs, ("tp",))
    multihost_utils.sync_global_devices("engine-init")
    print(f"phase engine-init pid={pid}", flush=True)
    engine = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(), mesh=mesh, seed=0,
    )
    print(f"phase engine-built pid={pid}", flush=True)
    engine.start_sync()
    r = engine.generate_sync(
        "dcn serving smoke", max_new_tokens=16, temperature=0.0,
        stop_on_eos=False, timeout=180,
    )
    engine.stop_sync()
    print(f"phase engine-done pid={pid}", flush=True)
    result["engine_tokens"] = [int(t) for t in r.token_ids]

    # DCN × ICI composition (r4 VERDICT next #10): dp OVER processes ×
    # tp WITHIN each process — the topology a real multi-host pod
    # serves. Params and the KV cache shard over tp inside each host
    # (those collectives ride ICI) and replicate over the dp axis that
    # spans the DCN boundary; one SPMD program covers the pod.
    mesh2_devs = np.array([
        sorted(by_proc[p], key=lambda d: d.id)[:2] for p in sorted(by_proc)
    ])  # [dp = processes, tp = local devices]
    mesh2 = Mesh(mesh2_devs, ("dp", "tp"))
    multihost_utils.sync_global_devices("engine2-init")
    print(f"phase engine2-init pid={pid}", flush=True)
    engine2 = InferenceEngine(
        "llama-tiny", n_slots=2, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(), mesh=mesh2, seed=0,
    )
    engine2.start_sync()
    r2 = engine2.generate_sync(
        "dcn serving smoke", max_new_tokens=16, temperature=0.0,
        stop_on_eos=False, timeout=180,
    )
    engine2.stop_sync()
    print(f"phase engine2-done pid={pid}", flush=True)
    result["engine_dp_tp_tokens"] = [int(t) for t in r2.token_ids]

    done_file = os.path.join(tmpdir, "peer_done")
    if pid == 0:
        import asyncio

        from gofr_tpu import App

        app = App(config=MockConfig({
            "APP_NAME": "dcn-host-0",
            "HTTP_PORT": str(http_port),
            "METRICS_PORT": "0",
        }))

        @app.get("/topology")
        async def topology(ctx):
            return process_topology()

        loop = asyncio.new_event_loop()
        threading.Thread(target=loop.run_forever, daemon=True).start()
        asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=30)
        deadline = time.time() + 120
        while not os.path.exists(done_file) and time.time() < deadline:
            time.sleep(0.2)
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=10)
        result["served_peer"] = os.path.exists(done_file)
    else:
        from gofr_tpu.service import CircuitBreakerConfig, new_http_service

        svc = new_http_service(
            f"http://127.0.0.1:{http_port}", None, None,
            CircuitBreakerConfig(threshold=50, interval_s=60.0),
        )
        body = None
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                resp = svc.get("/topology")
                if resp.status_code == 200:
                    body = json.loads(resp.body)
                    break
            except Exception:  # noqa: BLE001 — peer still booting
                pass
            time.sleep(0.5)
        assert body is not None, "never reached host 0 over the service tier"
        assert body["data"]["process_count"] == 2, body
        with open(done_file, "w") as f:
            f.write("ok")
        result["hop"] = body["data"]

    print("DCN_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
