"""Multi-replica chaos suite for the replica-tier failover router
(ISSUE 4 acceptance gate).

Everything is driven deterministically through ``gofr_tpu/faults`` —
no TPU, no sleeps-as-synchronization: faults target ONE replica via
the injection context's ``engine=`` argument, backoff waits go through
recording sleep hooks, the prober runs inline (``probe_once()``, no
thread), and budgets/deadlines ride injectable clocks.

Covered:

* routing policy: least-loaded among SERVING, spill to DEGRADED, never
  RESTARTING/DOWN or probe-demoted; no routable replica → 502;
* THE acceptance path: a replica forced DOWN mid-stream (crash loop
  exhausts ``TPU_RESTART_MAX``) hands its live request to a sibling —
  the client's NON-greedy token stream is byte-identical to a
  fault-free run, zero 5xx, the pool stays SERVING, and the dead
  replica is re-admitted only after a passing synthetic probe;
* probe-driven recovery: a failed synthetic generation demotes a
  replica that still claims SERVING and asks its supervisor to
  restart; a passing probe re-admits it and resets the crash-loop
  counter;
* hedged unary retries: a slow primary is raced by a budgeted hedge on
  a second replica (first success wins, loser cancelled); the hedge
  budget is a deterministic token bucket and hedging is deadline-aware;
* submit-time rerouting: a draining replica's 503 reroutes to a
  sibling instead of failing the caller;
* seeded-sampling replay continuity (single engine): a non-greedy
  stream crosses a mid-generation restart byte-identically because the
  sampling counter is restored, not restarted at 0;
* remote replicas: HTTPReplica serves unary generations and its health
  probe demotes an unreachable upstream.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from gofr_tpu import faults
from gofr_tpu.errors import ErrorNoHealthyReplica, ErrorServiceUnavailable
from gofr_tpu.metrics import new_metrics_manager
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.lifecycle import Deadline, HedgeBudget
from gofr_tpu.serving.supervisor import EngineSupervisor
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.serving.types import _GenRequest
from gofr_tpu.service.replica_pool import (
    EngineReplica,
    Replica,
    ReplicaPool,
)

POOL_INSTRUMENTS_COUNTERS = (
    "app_tpu_engine_restarts_total",
    "app_tpu_requests_replayed_total",
    "app_tpu_watchdog_trips_total",
    "app_tpu_requests_shed_total",
    "app_tpu_requests_cancelled_total",
    "app_tpu_deadline_exceeded_total",
    "app_tpu_tokens_generated",
    "app_tpu_prefix_hits",
    "app_tpu_failovers_total",
    "app_tpu_probe_failures_total",
    "app_tpu_hedged_requests_total",
)
POOL_INSTRUMENTS_GAUGES = (
    "app_tpu_engine_state",
    "app_tpu_replica_state",
    "app_tpu_queue_depth",
    "app_tpu_kv_slots_in_use",
    "app_tpu_hbm_used_bytes",
    "app_tpu_kv_blocks_free",
)


def _metrics_manager():
    m = new_metrics_manager()
    for name in POOL_INSTRUMENTS_COUNTERS:
        m.new_counter(name)
    for name in POOL_INSTRUMENTS_GAUGES:
        m.new_gauge(name)
    for name in ("app_tpu_infer_latency", "app_tpu_batch_size",
                 "app_tpu_spec_tokens_per_step"):
        m.new_histogram(name)
    return m


def counter_total(metrics, name: str) -> float:
    inst = {i.name: i for i in metrics.instruments()}[name]
    return sum(inst.collect().values())


@pytest.fixture(scope="module")
def metrics():
    return _metrics_manager()


@pytest.fixture(autouse=True)
def _fault_hygiene():
    yield
    faults.reset()


def _drain_stream(req, timeout=120.0) -> list[int]:
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        tok = req.stream.get(timeout=max(deadline - time.monotonic(), 0.1))
        if tok is None:
            return toks
        toks.append(tok)


def _wait_until(cond, timeout=30.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


def _make_supervised(metrics, *, max_restarts=3, **eng_kw):
    """One engine + supervisor, every timing seam injected (recording
    sleep — backoff adds no wall clock). Replicas built this way share
    the default engine seed, so params AND the counter-based sampling
    base key are identical across the pool — the precondition for
    byte-identical cross-replica replay."""
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=256, tokenizer=ByteTokenizer(),
        metrics=metrics, **eng_kw,
    )
    sleeps: list[tuple[str, float]] = []
    sup = EngineSupervisor(
        eng,
        max_restarts=max_restarts,
        backoff_s=0.25,
        backoff_reset_s=60.0,
        rng=random.Random(1234),
        sleep=lambda s: sleeps.append((eng.state, s)),
        metrics=metrics,
    ).start()
    eng.start_sync()
    return eng, sup, sleeps


def _make_pool(metrics, replicas, **kw):
    kw.setdefault("probe_interval_s", 0)  # no thread: tests drive probes
    kw.setdefault("probe_timeout_s", 60.0)
    kw.setdefault("rng", random.Random(7))
    return ReplicaPool(replicas, metrics=metrics, **kw)


@pytest.fixture(scope="module")
def engines(metrics):
    """ONE supervised engine pair shared by the chaos tests below:
    engine construction + first-dispatch compiles dominate this suite's
    wall clock, and every test that wounds an engine restores it to
    SERVING before finishing. max_restarts=1 so a targeted persistent
    fault exhausts the crash-loop budget with exactly two crashes."""
    eng_a, sup_a, _ = _make_supervised(metrics, max_restarts=1)
    eng_b, sup_b, _ = _make_supervised(metrics, max_restarts=1)
    yield (eng_a, sup_a), (eng_b, sup_b)
    faults.reset()
    sup_a.stop()
    sup_b.stop()
    eng_a.stop_sync()
    eng_b.stop_sync()


def _pool_of(metrics, eng_a, eng_b, **kw):
    return _make_pool(
        metrics,
        [EngineReplica("a", eng_a), EngineReplica("b", eng_b)],
        **kw,
    )


def _release_pool(pool):
    """Detach a per-test pool WITHOUT closing the shared engines (which
    ``pool.close()`` would)."""
    pool.stop_prober()
    for replica in pool.replicas:
        if isinstance(replica, EngineReplica):
            replica.engine.set_replica_handoff(None)


# ----------------------------------------------------------------------
# routing policy (stub replicas — pure policy, no jax)
# ----------------------------------------------------------------------


class _StubReplica(Replica):
    supports_stream = True

    def __init__(self, name, state="SERVING", load=0, tput=0.0):
        super().__init__(name)
        self.state_value = state
        self.load_value = load
        self.tput = tput
        self.submits = 0

    def state(self):
        return self.state_value

    def load(self):
        return self.load_value

    def throughput(self):
        return self.tput

    def submit(self, prompt, **kw):
        self.submits += 1
        req = _GenRequest(
            prompt_ids=[1], max_new_tokens=1, temperature=0.0,
            stop_on_eos=False,
        )
        req.future.set_result(f"ok-{self.name}")
        req.stream.put(None)
        return req

    def probe(self, timeout_s):
        return "pass", ""


def test_pick_least_loaded_serving_spills_to_degraded():
    a = _StubReplica("a", load=5)
    b = _StubReplica("b", load=1)
    c = _StubReplica("c", state="DEGRADED", load=0)
    pool = _make_pool(None, [a, b, c])
    # Least-loaded among SERVING wins — DEGRADED never preferred while
    # any SERVING replica exists, even at load 0.
    assert pool.pick().name == "b"
    # SERVING gone → spill to DEGRADED.
    a.state_value = "DOWN"
    b.state_value = "RESTARTING"
    assert pool.pick().name == "c"
    # Nothing routable → 502, fast.
    c.state_value = "DOWN"
    with pytest.raises(ErrorNoHealthyReplica):
        pool.pick()


def test_pick_round_robin_tie_break_and_exclude():
    a, b = _StubReplica("a"), _StubReplica("b")
    pool = _make_pool(None, [a, b])
    first = pool.pick()
    second = pool.pick()
    # Equal load: consecutive picks rotate instead of pinning one
    # replica.
    assert {first.name, second.name} == {"a", "b"}
    assert pool.pick(exclude=[a]).name == "b"
    with pytest.raises(ErrorNoHealthyReplica):
        pool.pick(exclude=[a, b])


def test_weighted_pick_routes_by_estimated_completion_time():
    # Equal queues, 4× throughput difference: the faster replica has
    # the lower estimated completion time.
    a = _StubReplica("a", load=4, tput=100.0)
    b = _StubReplica("b", load=4, tput=400.0)
    pool = _make_pool(None, [a, b])
    assert pool.pick().name == "b"
    # A deeper queue on the fast replica still wins while its ECT is
    # lower: (7+1)/400 = 0.02s < (1+1)/50 = 0.04s.
    a.load_value, a.tput = 1, 50.0
    b.load_value, b.tput = 7, 400.0
    assert pool.pick().name == "b"
    # ...until the queue outweighs the speed: (39+1)/400 > (1+1)/50.
    b.load_value = 39
    assert pool.pick().name == "a"


def test_weighted_pick_degrades_to_least_loaded_without_signal():
    # No replica reports throughput (cold pool, HTTP-only) → the scores
    # collapse to load ordering, and equal loads still round-robin.
    a = _StubReplica("a", load=3)
    b = _StubReplica("b", load=1)
    pool = _make_pool(None, [a, b])
    assert pool.pick().name == "b"
    # A replica WITHOUT a measurement is assumed as fast as the fastest
    # measured sibling (cold ≈ idle), so its shorter queue wins.
    a.load_value, a.tput = 2, 100.0
    b.load_value, b.tput = 1, 0.0
    assert pool.pick().name == "b"


def test_unweighted_pick_restores_raw_queue_length_routing():
    a = _StubReplica("a", load=1, tput=10.0)
    b = _StubReplica("b", load=5, tput=1000.0)
    pool = _make_pool(None, [a, b], weighted=False)
    assert pool.pick().name == "a"  # raw least-loaded ignores speed
    pool_w = _make_pool(None, [a, b])
    assert pool_w.pick().name == "b"  # default weighted pick uses it


def test_probe_demotion_blocks_routing_even_while_serving():
    a, b = _StubReplica("a"), _StubReplica("b")
    pool = _make_pool(None, [a, b])
    a.probe_failed = True  # demoted: state() still says SERVING
    assert pool.pick().name == "b"
    assert pool.pick().name == "b"
    b.probe_failed = True
    with pytest.raises(ErrorNoHealthyReplica):
        pool.pick()


def test_pool_health_aggregation_and_state_gauge(metrics):
    a = _StubReplica("a")
    down = _StubReplica("d", state="DOWN")
    pool = _make_pool(metrics, [a, down])
    health = pool.health_check()
    assert health["status"] == "UP"  # one replica down ≠ pool down
    assert health["state"] == "SERVING"
    assert health["details"]["serving"] == 1
    assert health["details"]["total"] == 2
    assert health["details"]["replicas"]["d"]["state"] == "DOWN"
    gauge = {
        i.name: i for i in metrics.instruments()
    }["app_tpu_replica_state"].collect()
    assert sorted(gauge.values()) == [0.0, 3.0]
    # Every replica unroutable → pool DOWN on the health surface too.
    a.state_value = "DEGRADED"
    assert pool.health_check()["state"] == "DEGRADED"
    a.state_value = "DOWN"
    health = pool.health_check()
    assert health["status"] == "DOWN"
    assert health["state"] == "DOWN"


def test_hedge_budget_token_bucket_deterministic():
    now = [0.0]
    budget = HedgeBudget(burst=2.0, rate_per_s=1.0, clock=lambda: now[0])
    assert budget.try_acquire()
    assert budget.try_acquire()
    assert not budget.try_acquire()  # drained — no partial takes
    now[0] = 0.5
    assert not budget.try_acquire()  # half a token refilled: not enough
    now[0] = 1.5
    assert budget.try_acquire()
    # Refill caps at burst, never beyond.
    now[0] = 1000.0
    assert budget.available() == pytest.approx(2.0)


def test_probe_busy_verdict_never_demotes_or_restarts():
    """Overload is NOT failure: a probe the replica SHEDS (429) or that
    times out behind real queued work must leave routing state and the
    supervisor untouched — demoting a merely-busy replica would cascade
    its load onto the siblings until the whole pool restarts."""
    import concurrent.futures as cf

    from gofr_tpu.errors import ErrorTooManyRequests

    class _BusyEngine:
        state = "SERVING"
        family = "stub"  # EngineReplica.load() reads queues on llm only

        def __init__(self, exc):
            self._exc = exc
            self._supervisor = None
            self._handoff = None

        def set_replica_handoff(self, handoff):
            self._handoff = handoff

        def synthetic_probe(self, timeout_s):
            raise self._exc

    shed = EngineReplica("shed", _BusyEngine(ErrorTooManyRequests("full")))
    verdict, reason = shed.probe(timeout_s=1.0)
    assert verdict == "busy"

    class _CongestedReplica(EngineReplica):
        def load(self):
            return 5  # probe queued behind real work

    congested = _CongestedReplica(
        "congested", _BusyEngine(cf.TimeoutError())
    )
    verdict, _ = congested.probe(timeout_s=0.0)
    assert verdict == "busy"

    class _WedgedIdleReplica(EngineReplica):
        def load(self):
            return 1  # nothing queued but the probe: truly broken

    wedged = _WedgedIdleReplica("wedged", _BusyEngine(cf.TimeoutError()))
    verdict, _ = wedged.probe(timeout_s=0.0)
    assert verdict == "fail"

    # Pool-level: a busy sweep changes nothing — still routable, no
    # probe-failure metric, no supervisor notification.
    pool = _make_pool(None, [shed])
    sweep = pool.probe_once()
    assert sweep["shed"].startswith("busy")
    assert not shed.probe_failed
    assert pool.pick().name == "shed"


def test_fast_fail_retry_spends_the_hedge_budget():
    """A fast-failing primary is retried on a sibling ONLY while the
    token bucket has budget; drained, the caller gets the primary's
    error instead of an unbudgeted retry storm."""

    class _FailingResultReplica(_StubReplica):
        def submit(self, prompt, **kw):
            self.submits += 1
            req = _GenRequest(
                prompt_ids=[1], max_new_tokens=1, temperature=0.0,
                stop_on_eos=False,
            )
            req.future.set_exception(ErrorServiceUnavailable("mid-flight"))
            req.stream.put(None)
            return req

    bad, good = _FailingResultReplica("bad"), _StubReplica("good")
    pool = _make_pool(
        None, [bad, good],
        hedge_delay_s=0.0,
        hedge_budget=HedgeBudget(burst=1.0, rate_per_s=0.0),
    )
    # Budget has one token: the first request's failed primary (bad,
    # picked by rotation) retries on good and succeeds.
    assert pool.generate_sync("x", timeout=10) == "ok-good"
    assert bad.submits == 1 and good.submits == 1
    # Bucket drained: the next failed primary may NOT retry even though
    # a healthy sibling is right there.
    bad.submits = good.submits = 0
    with pytest.raises(ErrorServiceUnavailable):
        pool.generate_sync("x", timeout=10)
    assert bad.submits == 1 and good.submits == 0

    # And with NO routable sibling at all, the budget is never consumed
    # for a hedge that cannot launch — tokens wait for a sibling to
    # recover instead of draining on impossible attempts.
    solo_budget = HedgeBudget(burst=1.0, rate_per_s=0.0)
    solo = _make_pool(
        None, [_FailingResultReplica("solo")],
        hedge_delay_s=0.0, hedge_budget=solo_budget,
    )
    with pytest.raises(ErrorServiceUnavailable):
        solo.generate_sync("x", timeout=10)
    assert solo_budget.available() == pytest.approx(1.0)


def test_should_hedge_is_budgeted_and_deadline_aware():
    clock = [0.0]
    pool = _make_pool(
        None, [_StubReplica("a"), _StubReplica("b")],
        hedge_budget=HedgeBudget(burst=1.0, rate_per_s=0.0,
                                 clock=lambda: clock[0]),
    )
    expired = Deadline(10.0, clock=lambda: 20.0)
    assert not pool.should_hedge(expired)  # never hedge doomed work
    live = Deadline(10.0, clock=lambda: 0.0)
    assert pool.should_hedge(live)  # spends the single token
    assert not pool.should_hedge(live)  # budget drained → ride primary
    assert not pool.should_hedge(None)


# ----------------------------------------------------------------------
# THE acceptance path: replica DOWN mid-stream → sibling completes it
# ----------------------------------------------------------------------


def test_replica_down_mid_stream_fails_over_byte_identical(metrics, engines):
    """Force replica A into a crash loop that exhausts its restart
    budget MID-STREAM: the pool hands the live request to replica B,
    the client's non-greedy SSE stream is byte-identical to a
    fault-free run (counter-restored sampling), there are zero 5xx,
    the pool stays SERVING around the DOWN replica, and A is
    re-admitted only after a passing synthetic probe."""
    (eng_a, sup_a), (eng_b, sup_b) = engines
    pool = _pool_of(metrics, eng_a, eng_b)
    params = dict(
        max_new_tokens=32, temperature=0.9, seed=4242, stop_on_eos=False,
    )
    try:
        failovers0 = counter_total(metrics, "app_tpu_failovers_total")
        # Fault-free reference — and the cross-replica determinism
        # precondition: both replicas (same params, same engine seed)
        # produce the identical sampled stream.
        ref = eng_b.generate_sync("failover mid-stream", **params)
        ref_a = eng_a.generate_sync("failover mid-stream", **params)
        assert ref_a.token_ids == ref.token_ids
        assert len(ref.token_ids) == 32

        # Replica A's device dies from its 5th dispatch ON — persistent,
        # targeted: B never sees the fault. Crash 1 lands mid-stream;
        # the recovery replay's prefill is crash 2, which exhausts
        # max_restarts=1 and lands A in DOWN.
        a_hits = {"n": 0}

        def crash_a(engine=None, **kw):
            if engine is eng_a:
                a_hits["n"] += 1
                if a_hits["n"] >= 5:
                    raise RuntimeError("injected: replica A device loss")

        faults.arm("scheduler.device_step", action=crash_a)
        req = pool.submit_generate("failover mid-stream", **params)
        # Tokens consumed BEFORE the crash prove this is a continuation,
        # not a fresh retry.
        pre = [req.stream.get(timeout=120) for _ in range(3)]
        assert all(t is not None for t in pre)
        rest = _drain_stream(req)
        result = req.future.result(timeout=120)

        # Byte-identical NON-GREEDY stream across the replica loss: the
        # sampling counter resumed at the delivered-token count on B.
        assert pre + rest == ref.token_ids
        assert result.token_ids == ref.token_ids
        assert result.finish_reason == ref.finish_reason
        # Zero 5xx: the future resolved with a result, never an error.
        # Carried twice: A's own replay attempt, then the adoption by B.
        assert req.replays == 2
        assert counter_total(
            metrics, "app_tpu_failovers_total"
        ) == failovers0 + 1

        # A is DOWN and routed AROUND: the pool stays SERVING and new
        # work lands on B.
        assert _wait_until(lambda: eng_a.state == "DOWN")
        assert pool.state == "SERVING"
        assert pool.health_check()["status"] == "UP"
        assert pool.pick() .name == "b"
        after = pool.generate_sync(
            "failover mid-stream", timeout=120, **params
        )
        assert after.token_ids == ref.token_ids

        # Re-admission ONLY after a passing synthetic probe: with the
        # fault still armed, the revive's probe fails and A stays out of
        # rotation; once disarmed, one probe sweep re-admits it.
        sweep = pool.probe_once()
        assert sweep["a"].startswith("fail") or sweep["a"] == "down"
        assert pool.replicas[0].probe_failed
        assert pool.pick().name == "b"

        faults.reset()
        assert _wait_until(lambda: eng_a.state in ("SERVING", "DOWN"))
        sweep = pool.probe_once()
        assert _wait_until(
            lambda: pool.probe_once().get("a") == "pass", timeout=60
        )
        assert not pool.replicas[0].probe_failed
        assert eng_a.state == "SERVING"
        assert sup_a.consecutive_failures == 0
        # And A serves identical streams again (params were reused).
        again = eng_a.generate_sync("failover mid-stream", **params)
        assert again.token_ids == ref.token_ids
    finally:
        faults.reset()
        _release_pool(pool)


# ----------------------------------------------------------------------
# probe-driven demotion + supervisor restart
# ----------------------------------------------------------------------


def test_probe_failure_demotes_and_restarts_supervised_replica(
    metrics, engines
):
    """A replica that still CLAIMS SERVING but fails its synthetic
    generation is demoted from routing AND its supervisor restarts it —
    recovery on probe evidence, not just on crash/trip."""
    (eng_a, sup_a), (eng_b, sup_b) = engines
    pool = _pool_of(metrics, eng_a, eng_b)
    try:
        probe_fail0 = counter_total(metrics, "app_tpu_probe_failures_total")
        ref = eng_b.generate_sync(
            "probe demotion", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )

        def fail_submit_a(engine=None, **kw):
            if engine is eng_a:
                raise RuntimeError("injected: submit path broken on A")

        faults.arm("engine.submit", action=fail_submit_a)
        restarts_before = sup_a.restarts
        sweep = pool.probe_once()
        assert sweep["a"].startswith("fail")
        assert sweep["b"] == "pass"
        assert pool.replicas[0].probe_failed
        assert counter_total(
            metrics, "app_tpu_probe_failures_total"
        ) == probe_fail0 + 1
        # Routed around while demoted — even though eng_a's own state
        # machine may still say SERVING.
        assert pool.pick().name == "b"
        via_pool = pool.generate_sync(
            "probe demotion", timeout=120, max_new_tokens=8,
            temperature=0.0, stop_on_eos=False,
        )
        assert via_pool.token_ids == ref.token_ids

        # The supervisor treated the failed probe as a detected failure
        # and warm-restarted the engine.
        assert _wait_until(lambda: sup_a.restarts == restarts_before + 1)
        faults.reset()
        assert _wait_until(lambda: eng_a.state == "SERVING")
        # Passing probe → re-admitted, crash-loop counter reset.
        assert _wait_until(
            lambda: pool.probe_once().get("a") == "pass", timeout=60
        )
        assert not pool.replicas[0].probe_failed
        assert sup_a.consecutive_failures == 0
    finally:
        faults.reset()
        _release_pool(pool)


# ----------------------------------------------------------------------
# hedged unary retries
# ----------------------------------------------------------------------


def test_hedged_unary_request_wins_on_second_replica(metrics, engines):
    """A stalled primary triggers one budgeted hedge on a sibling; the
    first success answers the caller and the loser is cancelled so no
    replica decodes for a caller that already has its result."""
    (eng_a, sup_a), (eng_b, sup_b) = engines
    pool = _pool_of(
        metrics, eng_a, eng_b,
        hedge_delay_s=0.0,  # hedge immediately: deterministic, no sleeps
        hedge_budget=HedgeBudget(burst=4.0, rate_per_s=0.0),
    )
    try:
        hedged0 = counter_total(metrics, "app_tpu_hedged_requests_total")
        ref = eng_b.generate_sync(
            "hedge me", max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
        gate_in, gate_out = threading.Event(), threading.Event()

        def stall_a(engine=None, **kw):
            if engine is eng_a:
                gate_in.set()
                gate_out.wait(timeout=120)

        faults.arm("scheduler.window", action=stall_a, times=1)
        assert gate_in.wait(30)  # A's scheduler is parked: requests hang
        result = pool.generate_sync(
            "hedge me", timeout=120, max_new_tokens=8, temperature=0.0,
            stop_on_eos=False,
        )
        assert result.token_ids == ref.token_ids
        assert counter_total(
            metrics, "app_tpu_hedged_requests_total"
        ) == hedged0 + 1
        # The loser (parked on A) was cancelled, not left to decode.
        gate_out.set()
        assert _wait_until(
            lambda: all(s is None for s in eng_a._slots)
            and eng_a._pending.empty()
        )
    finally:
        faults.reset()
        _release_pool(pool)


def test_submit_reroutes_around_draining_replica(metrics, engines):
    """A graceful-draining replica 503s its submits; the router treats
    that as a reroute signal and places the request on a sibling —
    the caller never sees the 503."""
    (eng_a, sup_a), (eng_b, sup_b) = engines
    pool = _pool_of(metrics, eng_a, eng_b)
    try:
        ref = eng_b.generate_sync(
            "reroute", max_new_tokens=6, temperature=0.0, stop_on_eos=False
        )
        with eng_a._submit_lock:
            eng_a._draining = True  # graceful drain: submits 503
        try:
            req = pool.submit_generate(
                "reroute", max_new_tokens=6, temperature=0.0,
                stop_on_eos=False,
            )
            result = req.future.result(timeout=120)
            assert result.token_ids == ref.token_ids
        finally:
            with eng_a._submit_lock:
                eng_a._draining = False
        # With EVERY replica draining, the pool answers 503/502 fast
        # (the last shed error wins so Retry-After semantics survive).
        with eng_a._submit_lock:
            eng_a._draining = True
        with eng_b._submit_lock:
            eng_b._draining = True
        try:
            with pytest.raises(
                (ErrorNoHealthyReplica, ErrorServiceUnavailable)
            ):
                pool.submit_generate(
                    "reroute", max_new_tokens=6, temperature=0.0,
                    stop_on_eos=False,
                )
        finally:
            with eng_a._submit_lock:
                eng_a._draining = False
            with eng_b._submit_lock:
                eng_b._draining = False
    finally:
        faults.reset()
        _release_pool(pool)


# ----------------------------------------------------------------------
# container seam: TPU_REPLICAS builds the pool
# ----------------------------------------------------------------------


def test_pool_from_config_builds_supervised_engine_replicas():
    """`TPU_REPLICAS > 1` makes container.tpu a ReplicaPool: N
    supervised engines with pool handoffs installed, serving through
    the same engine-shaped surface."""
    from gofr_tpu.config import MockConfig
    from gofr_tpu.serving.backend import new_tpu_from_config

    pool = new_tpu_from_config(MockConfig({
        "TPU_MODEL": "llama-tiny",
        "TPU_REPLICAS": "2",
        "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "128",
        "TPU_DECODE_WINDOW": "4",
        "TPU_RESTART_MAX": "2",
        "TPU_PROBE_INTERVAL_S": "0",
        "TPU_POOL_MAX_REPLICAS": "3",
        "TPU_SCALE_UP_WAIT_S": "7",
        "TPU_SCALE_INTERVAL_S": "0",
    }))
    try:
        assert isinstance(pool, ReplicaPool)
        assert pool.model_name == "llama-tiny"
        assert pool.family == "llm"
        assert len(pool.replicas) == 2
        for replica in pool.replicas:
            assert replica.engine._supervisor is not None
            assert replica.engine._handoff is not None
        pool.start_sync()
        assert pool.state == "SERVING"
        # Wiring only — no generate here: routing/serving through a pool
        # is covered above, and a from_config generate would pay two
        # more engine compiles for no new coverage.
        health = pool.health_check()
        assert health["status"] == "UP"
        assert health["details"]["total"] == 2
        assert pool.pick().name in ("engine-0", "engine-1")
        # TPU_POOL_MAX_REPLICAS above the configured fleet arms a
        # PoolScaler with an in-proc engine spawn factory (decision
        # logic is covered in tests/test_remote_failover.py).
        assert pool.scaler is not None
        assert pool.scaler.min_replicas == 2
        assert pool.scaler.max_replicas == 3
        assert pool.scaler.scale_up_wait_s == 7.0
    finally:
        pool.close()


# ----------------------------------------------------------------------
# remote replicas (HTTPService-backed)
# ----------------------------------------------------------------------


class _Harness:
    """Boot a gofr_tpu App on an ephemeral port (httptest.Server role)."""

    def __init__(self, app):
        import asyncio

        self.app = app
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )

    def __enter__(self):
        import asyncio

        self._thread.start()
        asyncio.run_coroutine_threadsafe(
            self.app.start(), self._loop
        ).result(10)
        return self

    def __exit__(self, *exc):
        import asyncio

        asyncio.run_coroutine_threadsafe(
            self.app.stop(), self._loop
        ).result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self._loop.close()

    @property
    def address(self):
        return f"http://127.0.0.1:{self.app.http_port}"


def test_http_replica_serves_unary_and_probe_demotes_dead_upstream():
    """A UNARY-ONLY remote replica (``stream=False`` — any plain
    OpenAI-compatible upstream) answers unary generations through its
    endpoint; once the upstream dies, the next probe demotes it and the
    pool fails fast with 502. Streaming remotes are covered by
    tests/test_remote_failover.py."""
    from gofr_tpu import App
    from gofr_tpu.config import MockConfig
    from gofr_tpu.http.response import Raw
    from gofr_tpu.service import new_http_service
    from gofr_tpu.service.replica_pool import HTTPReplica

    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))

    @app.post("/v1/completions")
    def completions(ctx):  # noqa: ARG001
        return Raw({
            "choices": [
                {"text": "remote completion", "finish_reason": "stop"}
            ],
            "usage": {"prompt_tokens": 2},
        })

    with _Harness(app) as harness:
        svc = new_http_service(harness.address)
        replica = HTTPReplica("remote-0", svc, stream=False)
        pool = _make_pool(None, [replica])
        try:
            result = pool.generate_sync(
                "hello remote", timeout=30, max_new_tokens=4,
                temperature=0.0,
            )
            assert result.text == "remote completion"
            assert result.finish_reason == "stop"
            assert pool.probe_once() == {"remote-0": "pass"}
            assert pool.state == "SERVING"
            # STREAM handles never route to a unary-only remote replica
            # — a 200 SSE with zero tokens would be worse than an
            # honest 502.
            with pytest.raises(ErrorNoHealthyReplica):
                pool.submit_generate("hello remote", max_new_tokens=4)
        finally:
            pool_alive = pool
    # The upstream is gone: the probe demotes the replica and routing
    # fails fast instead of hanging on a dead address.
    sweep = pool_alive.probe_once()
    assert sweep["remote-0"] != "pass"
    assert pool_alive.replicas[0].probe_failed
    assert pool_alive.state == "DOWN"
    with pytest.raises(ErrorNoHealthyReplica):
        pool_alive.generate_sync("hello remote", timeout=10, max_new_tokens=4)
    pool_alive.close()


# ----------------------------------------------------------------------
# seeded-sampling replay continuity (single engine)
# ----------------------------------------------------------------------


def test_replay_state_snapshots_sampling_counter():
    req = _GenRequest(
        prompt_ids=[1, 2], max_new_tokens=10, temperature=0.9,
        stop_on_eos=False, seed=7,
    )
    req.token_ids.extend([5, 6, 7])
    snap = req.replay_state()
    assert snap is not None
    assert snap.n_sampled == 3  # one counter step per delivered token
    assert snap.emitted_ids == [5, 6, 7]


def test_non_greedy_stream_byte_identical_across_restart(metrics, engines):
    """Satellite acceptance: a SAMPLED (non-greedy) stream crosses a
    mid-generation engine restart byte-identically. Before the exact
    (regeneration) replay, the continuation's re-prefilled K/V differed
    from the decode-written original by bf16 rounding and sampled a
    different — still valid, but different — path."""
    (eng, sup), _unused = engines
    eng.set_replica_handoff(None)  # single-engine scenario: no pool
    sup.note_probe_success()  # fresh crash-loop window for this test
    # 40 tokens = 5 decode windows: the 5th dispatch (after=4) lands
    # deterministically MID-generation, with window 1 already streamed.
    params = dict(
        max_new_tokens=40, temperature=0.9, seed=777, stop_on_eos=False,
    )
    try:
        ref = eng.generate_sync("sampled continuity", **params)
        greedy = eng.generate_sync(
            "sampled continuity", max_new_tokens=40, temperature=0.0,
            stop_on_eos=False,
        )
        # Sanity: the reference really is a sampled path, not greedy.
        assert ref.token_ids != greedy.token_ids
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("injected mid-sample device loss"),
            after=4, times=1,
        )
        req = eng.submit_generate("sampled continuity", **params)
        toks = _drain_stream(req)
        result = req.future.result(timeout=120)
        assert req.replays == 1
        assert toks == ref.token_ids
        assert result.token_ids == ref.token_ids
    finally:
        faults.reset()


def test_fast_replay_mode_restores_counter_without_regeneration(
    metrics, engines
):
    """TPU_REPLAY_EXACT=false: sampled replays take the FAST re-prefill
    path — one prefill pass covering the delivered prefix, sampling
    counter restored (ReplayState.n_sampled → the noff plane) so the
    continuation stays on the same counter path. Byte-exactness is the
    regeneration mode's contract, not this one's (prefill-kernel bf16
    rounding may flip a token); what must hold: no duplicates, no gaps,
    exact budget."""
    (eng, sup), _unused = engines
    eng.set_replica_handoff(None)  # single-engine scenario: no pool
    sup.note_probe_success()  # fresh crash-loop window for this test
    eng.replay_exact = False
    params = dict(
        max_new_tokens=40, temperature=0.9, seed=31337, stop_on_eos=False,
    )
    try:
        ref = eng.generate_sync("fast replay path", **params)
        faults.arm(
            "scheduler.device_step",
            raises=RuntimeError("injected fast-replay device loss"),
            after=4, times=1,
        )
        req = eng.submit_generate("fast replay path", **params)
        toks = _drain_stream(req)
        result = req.future.result(timeout=120)
        assert req.replays == 1
        assert req.replay_skip == 0  # fast path: nothing re-generated
        assert req.replayed_tokens > 0  # the prefix was RE-PREFILLED
        # Exact budget, the pre-crash prefix intact on the stream, and
        # the result mirrors exactly what the client streamed.
        assert len(toks) == 40
        prefix = req.replayed_tokens
        assert toks[:prefix] == ref.token_ids[:prefix]
        assert result.token_ids == toks
    finally:
        eng.replay_exact = True
        faults.reset()
