"""Serving engine concurrency stress: many submitters, mixed
temperatures and lengths, interleaved prefix registrations, mid-flight
cancellations, and a stop/start cycle — no request may hang, leak a
slot, or land on an unresolved future. This is the adversarial
counterpart to test_serving.py's single-behavior tests: the scheduler's
invariants under concurrent load."""

from __future__ import annotations

import random
import threading
from concurrent.futures import CancelledError

import pytest

from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer

PREFIX = "System: stress. "


def _wait_slots_free(engine, timeout: float = 15.0) -> None:
    """The scheduler clears a slot AFTER resolving its future — poll
    briefly instead of racing that window."""
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(s is None for s in engine._slots) and not engine._prefilling:
            return
        time.sleep(0.05)
    raise AssertionError(
        f"slots never drained: {engine._slots} {engine._prefilling}"
    )


@pytest.fixture(scope="module")
def engine():
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, tokenizer=ByteTokenizer(),
        prefix_slots=2,
    )
    eng.start_sync()
    yield eng
    eng.stop_sync()


def test_concurrent_mixed_load_all_requests_resolve(engine):
    rng = random.Random(0)
    results, errors = [], []
    lock = threading.Lock()

    def client(seed: int) -> None:
        r = random.Random(seed)
        for i in range(4):
            prompt = (PREFIX if r.random() < 0.5 else "") + f"client {seed} msg {i}"
            try:
                out = engine.generate_sync(
                    prompt,
                    max_new_tokens=r.randint(1, 12),
                    temperature=r.choice([0.0, 0.8]),
                    stop_on_eos=False,
                    timeout=120,
                )
                with lock:
                    results.append(out)
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(exc)

    def registrar() -> None:
        try:
            engine.register_prefix_sync(PREFIX, timeout=120)
            engine.register_prefix_sync("Other prefix. ", timeout=120)
            engine.register_prefix_sync(PREFIX + "deeper ", timeout=120)
        except Exception as exc:  # noqa: BLE001
            with lock:
                errors.append(exc)

    threads = [threading.Thread(target=client, args=(s,)) for s in range(8)]
    threads.append(threading.Thread(target=registrar))
    rng.shuffle(threads)
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive(), "stress client hung"

    assert not errors, errors
    assert len(results) == 32
    for out in results:
        assert 1 <= len(out.token_ids) <= 12
        assert out.ttft_s >= 0
    # All slots drained back to free.
    _wait_slots_free(engine)


def test_cancellations_under_load_free_all_slots(engine):
    reqs = [
        engine.submit_generate(
            f"cancel target {i}", max_new_tokens=64, temperature=0.0,
            stop_on_eos=False,
        )
        for i in range(12)
    ]
    # Partition by cancel()'s actual outcome: a fast scheduler may finish
    # a target before the cancel loop reaches it (cancel() → False).
    cancelled = [
        r for i, r in enumerate(reqs) if i % 3 == 0 and r.future.cancel()
    ]
    survivors = [r for r in reqs if r not in cancelled]
    assert cancelled, "no cancel landed before completion — inconclusive"
    for req in survivors:
        out = req.future.result(timeout=120)
        assert len(out.token_ids) == 64
    # Cancelled requests' streams must terminate too (None sentinel).
    deadline = 12.0
    for req in cancelled:
        with pytest.raises(CancelledError):
            req.future.result(timeout=1)
        got = req.stream.get(timeout=deadline)
        while got is not None:
            got = req.stream.get(timeout=deadline)
    # Engine healthy afterwards.
    out = engine.generate_sync(
        "after cancels", max_new_tokens=4, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    assert len(out.token_ids) == 4
    _wait_slots_free(engine)


def test_stop_start_cycle_preserves_service_and_prefixes(engine):
    engine.register_prefix_sync(PREFIX + "cycle ", timeout=120)
    before = engine.generate_sync(
        PREFIX + "cycle check", max_new_tokens=6, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    engine.stop_sync()
    with pytest.raises(RuntimeError):
        engine.submit_generate("down", max_new_tokens=1)
    engine.start_sync()
    after = engine.generate_sync(
        PREFIX + "cycle check", max_new_tokens=6, temperature=0.0,
        stop_on_eos=False, timeout=120,
    )
    # Pool and params survive the cycle; greedy output is reproducible.
    assert after.token_ids == before.token_ids


def test_mixed_sampling_features_concurrent_stress():
    """Cross-feature interaction stress: concurrent requests mixing
    seeds, penalties, logit_bias, top_logprobs, and uneven budgets on a
    mega-window engine — per-request invariants must hold even as the
    slot-state/admission uploads interleave."""
    import random

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, window_k=4, mega_windows=4,
        enable_penalties=True, top_logprobs=2, tokenizer=ByteTokenizer(),
    )
    eng.start_sync()
    rng = random.Random(0)
    try:
        reqs = []
        for i in range(24):
            kw = {"max_new_tokens": rng.choice([3, 7, 12, 20])}
            style = i % 4
            if style == 0:
                kw.update(temperature=0.9, seed=1234)  # repro pair group
            elif style == 1:
                kw.update(temperature=0.0, frequency_penalty=1.2)
            elif style == 2:
                kw.update(temperature=0.0, logit_bias={9: -100})
            else:
                kw.update(temperature=0.0, top_logprobs=2)
            prompt = f"prompt {i % 3}"
            kw["_prompt"] = prompt
            reqs.append((kw, eng.submit_generate(
                prompt, stop_on_eos=False,
                **{k: v for k, v in kw.items() if k != "_prompt"}
            )))
        results = [(kw, r.future.result(timeout=180)) for kw, r in reqs]
        seeded = {}
        for kw, res in results:
            assert len(res.token_ids) == kw["max_new_tokens"]
            if "seed" in kw:
                key = (kw["max_new_tokens"], kw["_prompt"])
                if key in seeded:
                    assert res.token_ids == seeded[key]  # same seed+params
                else:
                    seeded[key] = res.token_ids
            if "logit_bias" in kw:
                assert 9 not in res.token_ids
            if "top_logprobs" in kw:
                assert len(res.token_top_logprobs) == len(res.token_ids)
                for tok, alts in zip(res.token_ids, res.token_top_logprobs):
                    assert alts[0][0] == tok  # greedy == top-1
            else:
                assert res.token_top_logprobs is None
    finally:
        eng.stop_sync()


def test_lora_cross_feature_concurrent_stress():
    """Adapters join the cross-feature stress: concurrent requests mix
    LoRA adapters with seeds, penalties, logit_bias and uneven budgets
    on one mega-window engine. Invariants: greedy same-adapter repeats
    are identical, adapters differ from base, budgets exact, bias bans
    hold under adapters too."""
    import random

    import jax

    from gofr_tpu.models.transformer import lora_dims
    from gofr_tpu.models.registry import get_model
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    cfg = get_model("llama-tiny").config
    eng = InferenceEngine(
        "llama-tiny", n_slots=4, max_len=128, window_k=4, mega_windows=4,
        enable_penalties=True, tokenizer=ByteTokenizer(),
        lora_slots=2, lora_rank=4,
    )
    eng.start_sync()
    rng = random.Random(1)
    try:
        for ai, name in enumerate(("a1", "a2")):
            leaves = {}
            for ti, t in enumerate(("wq", "wv")):
                d_in, d_out = lora_dims(cfg, t)
                k1, k2 = jax.random.split(
                    jax.random.fold_in(jax.random.PRNGKey(40 + ai), ti)
                )
                leaves[t] = (
                    0.5 * jax.random.normal(k1, (cfg.n_layers, d_in, 4)),
                    0.5 * jax.random.normal(k2, (cfg.n_layers, 4, d_out)),
                )
            eng.load_lora(name, leaves)
        reqs = []
        for i in range(24):
            kw = {
                "max_new_tokens": rng.choice([4, 9, 15]),
                "adapter": ("", "a1", "a2")[i % 3],
                "temperature": 0.0,
            }
            if i % 4 == 0:
                kw["frequency_penalty"] = 1.1
            if i % 5 == 0:
                kw["logit_bias"] = {7: -100}
            reqs.append((kw, eng.submit_generate(
                "same prompt", stop_on_eos=False, **kw
            )))
        results = [(kw, r.future.result(timeout=180)) for kw, r in reqs]
        groups: dict = {}
        for kw, res in results:
            assert len(res.token_ids) == kw["max_new_tokens"]
            if "logit_bias" in kw:
                assert 7 not in res.token_ids
            key = (
                kw["adapter"], kw["max_new_tokens"],
                kw.get("frequency_penalty", 0), "logit_bias" in kw,
            )
            if key in groups:
                assert res.token_ids == groups[key]  # deterministic
            else:
                groups[key] = res.token_ids
        # Adapter isolation: same budget/features, different adapter →
        # different streams (random adapters shift greedy paths).
        plain = {
            k: v for k, v in groups.items() if k[2] == 0 and not k[3]
        }
        by_budget: dict = {}
        for (ad, n, _, _), toks in plain.items():
            by_budget.setdefault(n, {})[ad] = toks
        checked = 0
        for n, outs in by_budget.items():
            if len(outs) >= 2:
                assert len({tuple(v) for v in outs.values()}) == len(outs)
                checked += 1
        assert checked >= 1
    finally:
        eng.stop_sync()
