"""Multi-LoRA serving: per-request adapters batched into one program.

The oracle is weight merging: serving with adapter slot a must equal
serving a model whose weights were merged W' = W + A_a @ B_a offline
(f32 tiny model, greedy). Batch isolation: concurrent requests on
different adapters must reproduce their solo outputs exactly — the
per-slot gather cannot leak across rows.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.registry import get_model
from gofr_tpu.models.transformer import (
    TransformerConfig,
    init_lora,
    init_transformer,
    lora_dims,
    transformer_forward,
)
from gofr_tpu.serving.engine import InferenceEngine
from gofr_tpu.serving.tokenizer import ByteTokenizer

CFG: TransformerConfig = get_model("llama-tiny-f32").config
TARGETS = ("wq", "wk", "wv", "wo")


def _rand_adapter(seed: int, rank: int = 4, scale: float = 0.5) -> dict:
    """{target: (a, b)} random leaves in the engine's load_lora form."""
    key = jax.random.PRNGKey(seed)
    leaves = {}
    for t in TARGETS:
        d_in, d_out = lora_dims(CFG, t)
        key, k1, k2 = jax.random.split(key, 3)
        leaves[t] = (
            scale * jax.random.normal(k1, (CFG.n_layers, d_in, rank)),
            scale * jax.random.normal(k2, (CFG.n_layers, rank, d_out)),
        )
    return leaves


def _merged_params(params: dict, leaves: dict) -> dict:
    merged = {**params, "layers": dict(params["layers"])}
    for t, (a, b) in leaves.items():
        delta = jnp.einsum("ldr,lro->ldo", a, b).astype(
            merged["layers"][t].dtype
        )
        merged["layers"][t] = merged["layers"][t] + delta
    return merged


def _engine(**kw):
    eng = InferenceEngine(
        "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(), lora_slots=2, lora_rank=4, **kw,
    )
    eng.start_sync()
    return eng


def _gen(eng, prompt, n=10, **kw):
    return eng.generate_sync(
        prompt, max_new_tokens=n, temperature=0.0, stop_on_eos=False,
        timeout=120, **kw,
    ).token_ids


def test_forward_adapter_matches_merged_weights():
    """transformer_forward with aids == forward on merged weights; rows
    with aid 0 are untouched base rows."""
    params = init_transformer(jax.random.PRNGKey(0), CFG)
    leaves = _rand_adapter(7)
    lora = init_lora(CFG, 3, 4, TARGETS)
    for t, (a, b) in leaves.items():
        lora[t + "_lora_a"] = lora[t + "_lora_a"].at[:, 2].set(a)
        lora[t + "_lora_b"] = lora[t + "_lora_b"].at[:, 2].set(b)
    p_lora = {**params, "layers": {**params["layers"], **lora}}
    tokens = jnp.array([[1, 5, 9, 2], [3, 8, 4, 6]], dtype=jnp.int32)
    out = np.asarray(transformer_forward(
        p_lora, tokens, CFG, aids=jnp.array([0, 2], dtype=jnp.int32)
    ))
    base = np.asarray(transformer_forward(params, tokens, CFG))
    merged = np.asarray(transformer_forward(
        _merged_params(params, leaves), tokens, CFG
    ))
    np.testing.assert_allclose(out[0], base[0], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(out[1], merged[1], atol=1e-4, rtol=1e-4)
    assert not np.allclose(out[1], base[1], atol=1e-2)


def test_engine_adapter_matches_merged_engine():
    """Greedy generation with adapter == generation on an engine booted
    from the merged checkpoint."""
    leaves = _rand_adapter(11)
    eng = _engine()
    try:
        base = _gen(eng, "hello")
        eng.load_lora("tuned", leaves)
        tuned = _gen(eng, "hello", adapter="tuned")
        base_params = init_transformer(
            jax.random.PRNGKey(0), CFG
        )  # engine seed=0 default
        merged_eng = InferenceEngine(
            "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
            tokenizer=ByteTokenizer(),
            params=_merged_params(eng.params, leaves),
        )
        merged_eng.start_sync()
        try:
            want = _gen(merged_eng, "hello")
        finally:
            merged_eng.stop_sync()
        assert tuned == want
        assert tuned != base
        assert _gen(eng, "hello") == base  # base unaffected
        del base_params
    finally:
        eng.stop_sync()


def test_concurrent_adapters_batch_isolation():
    """Requests on base + two adapters running CONCURRENTLY in one
    engine reproduce their solo outputs token for token."""
    a1, a2 = _rand_adapter(21), _rand_adapter(22)
    eng = _engine()
    try:
        eng.load_lora("a1", a1)
        eng.load_lora("a2", a2)
        solo = {
            "": _gen(eng, "hello"),
            "a1": _gen(eng, "hello", adapter="a1"),
            "a2": _gen(eng, "hello", adapter="a2"),
        }
        assert len({tuple(v) for v in solo.values()}) == 3
        reqs = [
            eng.submit_generate(
                "hello", max_new_tokens=10, temperature=0.0,
                stop_on_eos=False, adapter=name,
            )
            for name in ("", "a1", "a2", "a1")
        ]
        outs = [r.future.result(timeout=120).token_ids for r in reqs]
        assert outs[0] == solo[""]
        assert outs[1] == solo["a1"]
        assert outs[2] == solo["a2"]
        assert outs[3] == solo["a1"]
    finally:
        eng.stop_sync()


def test_mega_window_adapter_parity():
    """Mega-window dispatch honors per-slot adapters identically."""
    leaves = _rand_adapter(31)
    plain = _engine()
    mega = _engine(mega_windows=4)
    try:
        plain.load_lora("t", leaves)
        mega.load_lora("t", leaves)
        assert _gen(plain, "ab", adapter="t") == _gen(
            mega, "ab", adapter="t"
        )
    finally:
        plain.stop_sync()
        mega.stop_sync()


def test_spec_window_adapter_parity():
    """Greedy speculative decoding is lossless under an adapter too."""
    leaves = _rand_adapter(41)
    plain = _engine()
    spec = InferenceEngine(
        "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(), lora_slots=2, lora_rank=4,
        spec_tokens=2,
    )
    spec.start_sync()
    try:
        plain.load_lora("t", leaves)
        spec.load_lora("t", leaves)
        assert _gen(plain, "ab", adapter="t") == _gen(
            spec, "ab", adapter="t"
        )
    finally:
        plain.stop_sync()
        spec.stop_sync()


def test_ffn_targets_through_engine():
    """FFN LoRA targets (w_gate/w_up/w_down) apply on EVERY serving path
    — chunked prefill, decode, and speculative verify — not just the
    full-sequence forward (regression: the three inline layer bodies
    dropped aids on their _ffn_dense calls)."""
    all_targets = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
    key = jax.random.PRNGKey(61)
    leaves = {}
    for t in all_targets:
        d_in, d_out = lora_dims(CFG, t)
        key, k1, k2 = jax.random.split(key, 3)
        leaves[t] = (
            0.5 * jax.random.normal(k1, (CFG.n_layers, d_in, 4)),
            0.5 * jax.random.normal(k2, (CFG.n_layers, 4, d_out)),
        )
    for spec_tokens in (0, 2):
        eng = InferenceEngine(
            "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
            tokenizer=ByteTokenizer(), lora_slots=1, lora_rank=4,
            lora_targets=",".join(all_targets), spec_tokens=spec_tokens,
        )
        eng.start_sync()
        try:
            eng.load_lora("full", leaves)
            got = _gen(eng, "hello", adapter="full")
            merged_eng = InferenceEngine(
                "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
                tokenizer=ByteTokenizer(),
                params=_merged_params(eng.params, leaves),
            )
            merged_eng.start_sync()
            try:
                assert got == _gen(merged_eng, "hello"), (
                    f"spec_tokens={spec_tokens}"
                )
            finally:
                merged_eng.stop_sync()
        finally:
            eng.stop_sync()


def test_multi_chunk_prefill_uses_fresh_adapter():
    """Deep multi-chunk prefill (prefill_depth>1) must prefill with the
    REQUEST's adapter, not the slot's previous occupant's (regression:
    the aids plane uploaded only on the single-chunk path)."""
    leaves = _rand_adapter(71)
    long_prompt = "abcdefgh" * 16  # 128 chars → 8 chunks of 16
    kw = dict(
        n_slots=2, max_len=256, window_k=4, tokenizer=ByteTokenizer(),
        prefill_chunk=16, prefill_depth=4,
    )
    eng = InferenceEngine(
        "llama-tiny-f32", lora_slots=1, lora_rank=4, **kw
    )
    eng.start_sync()
    try:
        eng.load_lora("t", leaves)
        # Park the base request in slot 0 first so the adapter request
        # reuses a slot whose host aid was 0.
        base_out = _gen(eng, long_prompt)
        got = _gen(eng, long_prompt, adapter="t")
        merged_eng = InferenceEngine(
            "llama-tiny-f32",
            params=_merged_params(eng.params, leaves), **kw,
        )
        merged_eng.start_sync()
        try:
            want = _gen(merged_eng, long_prompt)
        finally:
            merged_eng.stop_sync()
        assert got == want
        assert got != base_out
    finally:
        eng.stop_sync()


def test_reload_with_fewer_targets_zeroes_stale_deltas():
    """Re-loading a name with fewer targets must clear the old version's
    other-target deltas (regression: load_lora wrote without zeroing)."""
    v1 = _rand_adapter(81)  # wq, wk, wv, wo
    v2 = {"wq": v1["wq"]}  # only wq survives
    eng = _engine()
    try:
        eng.load_lora("a", v1)
        eng.load_lora("a", v2)
        got = _gen(eng, "hello", adapter="a")
        merged_eng = InferenceEngine(
            "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
            tokenizer=ByteTokenizer(),
            params=_merged_params(eng.params, v2),
        )
        merged_eng.start_sync()
        try:
            assert got == _gen(merged_eng, "hello")
        finally:
            merged_eng.stop_sync()
    finally:
        eng.stop_sync()


def test_adapter_slot_management():
    eng = _engine()
    try:
        assert eng.lora_names() == []
        eng.load_lora("x", _rand_adapter(1))
        eng.load_lora("y", _rand_adapter(2))
        assert eng.lora_names() == ["x", "y"]
        with pytest.raises(RuntimeError, match="slots in use"):
            eng.load_lora("z", _rand_adapter(3))
        base = _gen(eng, "hi")
        x_out = _gen(eng, "hi", adapter="x")
        eng.unload_lora("x")
        assert eng.lora_names() == ["y"]
        with pytest.raises(Exception):
            _gen(eng, "hi", adapter="x")
        # Freed slot is reusable; zeroed slot serves base until then.
        eng.load_lora("z", _rand_adapter(3))
        assert eng.lora_names() == ["y", "z"]
        assert _gen(eng, "hi") == base
        assert x_out != base
    finally:
        eng.stop_sync()


def test_prefix_pool_per_adapter():
    """Prefix-KV reuse composes with LoRA: a prefix registered under an
    adapter is reused ONLY by same-adapter requests, outputs match the
    no-pool engines exactly, and unloading the adapter purges its
    pooled prefixes."""
    leaves = _rand_adapter(91)
    prefix = "system: answer briefly. "
    suffix = "hello there"
    kw = dict(
        n_slots=4, max_len=128, window_k=4, tokenizer=ByteTokenizer(),
        lora_slots=2, lora_rank=4,
    )
    eng = InferenceEngine("llama-tiny-f32", prefix_slots=2, **kw)
    eng.start_sync()
    try:
        eng.load_lora("t", leaves)
        eng.register_prefix_sync(prefix)
        eng.register_prefix_sync(prefix, adapter="t")
        assert len(eng._prefix_pool) == 2
        got_base = _gen(eng, prefix + suffix)
        got_tuned = _gen(eng, prefix + suffix, adapter="t")
        ref = InferenceEngine("llama-tiny-f32", **kw)
        ref.start_sync()
        try:
            ref.load_lora("t", leaves)
            assert got_base == _gen(ref, prefix + suffix)
            assert got_tuned == _gen(ref, prefix + suffix, adapter="t")
        finally:
            ref.stop_sync()
        assert got_base != got_tuned
        eng.unload_lora("t")
        assert len(eng._prefix_pool) == 1  # adapter prefix purged
    finally:
        eng.stop_sync()


def test_prefix_pool_purged_on_adapter_reload():
    """Re-loading an adapter name invalidates its pooled prefixes (the
    pooled K/V was computed under the old weights), and a prefix
    registration still in flight across the reload is dropped with -1
    instead of registering stale rows."""
    v1, v2 = _rand_adapter(95), _rand_adapter(96)
    kw = dict(
        n_slots=4, max_len=128, window_k=4, tokenizer=ByteTokenizer(),
        lora_slots=2, lora_rank=4, prefix_slots=2,
    )
    eng = InferenceEngine("llama-tiny-f32", **kw)
    eng.start_sync()
    try:
        eng.load_lora("t", v1)
        eng.register_prefix_sync("shared preamble. ", adapter="t")
        assert len(eng._prefix_pool) == 1
        eng.load_lora("t", v2)  # reload → v1-weight prefix must die
        assert len(eng._prefix_pool) == 0
        got = _gen(eng, "shared preamble. hi", adapter="t")
        ref = InferenceEngine(
            "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
            tokenizer=ByteTokenizer(),
            params=_merged_params(eng.params, v2),
        )
        ref.start_sync()
        try:
            assert got == _gen(ref, "shared preamble. hi")
        finally:
            ref.stop_sync()
    finally:
        eng.stop_sync()

    # In-flight registration racing a reload: whichever side wins, no
    # stale entry may survive — either the store is dropped (-1) or the
    # reload's purge removes the just-stored entry.
    eng = InferenceEngine("llama-tiny-f32", **kw)
    eng.start_sync()
    try:
        eng.load_lora("t", v1)
        req = eng.register_prefix("stale preamble. ", adapter="t")
        eng.load_lora("t", v2)
        res = req.future.result(timeout=120)
        assert res == -1 or len(eng._prefix_pool) == 0
        assert len(eng._prefix_pool) == 0
    finally:
        eng.stop_sync()


def test_adapter_churn_under_load():
    """load_lora/unload_lora while the engine is serving: in-flight base
    streams must be unaffected, every request must complete, and the
    engine must return to idle with all slots free."""
    import threading

    eng = _engine()
    try:
        expected = _gen(eng, "hello", n=24)
        stop = threading.Event()
        churn_err = []

        def churn():
            i = 0
            try:
                while not stop.is_set():
                    name = f"churn-{i % 2}"
                    eng.load_lora(name, _rand_adapter(100 + i % 3))
                    eng.unload_lora(name)
                    i += 1
            except Exception as exc:  # noqa: BLE001
                churn_err.append(exc)

        t = threading.Thread(target=churn)
        t.start()
        try:
            reqs = [
                eng.submit_generate(
                    "hello", max_new_tokens=24, temperature=0.0,
                    stop_on_eos=False,
                )
                for _ in range(8)
            ]
            outs = [r.future.result(timeout=120).token_ids for r in reqs]
        finally:
            stop.set()
            t.join(timeout=30)
        assert not churn_err, churn_err
        assert all(o == expected for o in outs)
        assert eng.lora_names() == []
        assert all(s is None for s in eng._slots)
    finally:
        eng.stop_sync()


def test_reload_fails_inflight_instead_of_mixing():
    """Overwriting a slot that live requests still route to must FAIL
    those requests — one completion must never mix tokens from two
    adapters (same-name reload), and a request queued across a reload
    fails at admission instead of running under the wrong weights."""
    import time as _time

    a1, a2 = _rand_adapter(31), _rand_adapter(32)
    eng = _engine()
    try:
        eng.load_lora("tuned", a1)
        req = eng.submit_generate(
            "hello", max_new_tokens=100, temperature=0.0,
            stop_on_eos=False, adapter="tuned",
        )
        deadline = _time.time() + 60
        while not req.token_ids and _time.time() < deadline:
            _time.sleep(0.002)
        assert req.token_ids, "request never started decoding"
        eng.load_lora("tuned", a2)
        with pytest.raises(RuntimeError, match="overwritten"):
            req.future.result(timeout=120)
        # The reloaded adapter serves fresh requests with the NEW weights.
        got = _gen(eng, "hello", adapter="tuned")
        ref = InferenceEngine(
            "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
            tokenizer=ByteTokenizer(),
            params=_merged_params(eng.params, a2),
        )
        ref.start_sync()
        try:
            assert got == _gen(ref, "hello")
        finally:
            ref.stop_sync()

        # Queued across a reload: fill every slot with long base runs so
        # the adapter request cannot be admitted before the reload lands.
        blockers = [
            eng.submit_generate(
                "hold", max_new_tokens=100, temperature=0.0,
                stop_on_eos=False,
            )
            for _ in range(4)
        ]
        queued = eng.submit_generate(
            "hello", max_new_tokens=4, temperature=0.0,
            stop_on_eos=False, adapter="tuned",
        )
        eng.load_lora("tuned", a1)
        with pytest.raises(RuntimeError, match="queued|overwritten"):
            queued.future.result(timeout=120)
        for b in blockers:
            b.future.result(timeout=120)
    finally:
        eng.stop_sync()


def test_fresh_load_prefers_idle_slot():
    """A fresh load after an unload picks the free slot with no live
    traffic, so requests finishing against base (documented unload
    semantics) are not silently switched onto the new adapter.

    White-box: the engine is never STARTED and the draining request is
    pinned into a slot directly — racing a real generation against
    unload_lora is timing-dependent (on a fast run the request finishes
    first and slot 1 is legitimately reused)."""
    from gofr_tpu.serving.types import _ActiveSeq, _GenRequest

    eng = InferenceEngine(
        "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
        tokenizer=ByteTokenizer(), lora_slots=2, lora_rank=4,
    )
    eng.load_lora("old", _rand_adapter(41))
    assert eng._lora_names["old"] == 1
    req = _GenRequest(
        prompt_ids=[1, 2], max_new_tokens=8, temperature=0.0,
        stop_on_eos=False, aid=1, lora_gen=eng._lora_gen[1],
    )
    eng._slots[0] = _ActiveSeq(request=req, last_token=-1)
    eng.unload_lora("old")  # in-flight finishes on base (documented)
    eng.load_lora("new", _rand_adapter(42))
    assert eng._lora_names["new"] == 2  # slot 1 still draining
    assert not req.future.done()  # the draining request was untouched
    # Forced reuse: with slot 2 also taken, a load MUST take slot 1 and
    # fail its draining request rather than mix weight sets.
    eng.load_lora("third", _rand_adapter(43))
    assert eng._lora_names["third"] == 1
    with pytest.raises(RuntimeError, match="overwritten"):
        req.future.result(timeout=5)


def test_engine_without_lora_rejects():
    eng = InferenceEngine(
        "llama-tiny-f32", n_slots=2, max_len=64,
        tokenizer=ByteTokenizer(),
    )
    try:
        with pytest.raises(RuntimeError, match="TPU_LORA_SLOTS"):
            eng.load_lora("x", _rand_adapter(1))
    finally:
        eng.close()


def test_peft_checkpoint_load(tmp_path):
    """HF PEFT format: adapter_config.json + safetensors, rank below the
    compiled rank (zero-pad), alpha scaling folded in — output equals
    the merged oracle with scale alpha/r."""
    from safetensors.numpy import save_file

    r, alpha = 2, 8.0
    rng = np.random.default_rng(5)
    tensors = {}
    leaves_scaled = {}
    for t in ("wq", "wv"):
        d_in, d_out = lora_dims(CFG, t)
        mod = {"wq": "q_proj", "wv": "v_proj"}[t]
        a = np.zeros((CFG.n_layers, d_in, 4), dtype=np.float32)
        b = np.zeros((CFG.n_layers, 4, d_out), dtype=np.float32)
        for i in range(CFG.n_layers):
            wa = rng.standard_normal((r, d_in)).astype(np.float32) * 0.5
            wb = rng.standard_normal((d_out, r)).astype(np.float32) * 0.5
            tensors[
                f"base_model.model.model.layers.{i}.self_attn.{mod}"
                f".lora_A.weight"
            ] = wa
            tensors[
                f"base_model.model.model.layers.{i}.self_attn.{mod}"
                f".lora_B.weight"
            ] = wb
            a[i, :, :r] = wa.T
            b[i, :r, :] = wb.T * (alpha / r)
        leaves_scaled[t] = (jnp.asarray(a), jnp.asarray(b))
    (tmp_path / "adapter_config.json").write_text(json.dumps({
        "r": r, "lora_alpha": alpha,
        "target_modules": ["q_proj", "v_proj"],
    }))
    save_file(tensors, str(tmp_path / "adapter_model.safetensors"))

    eng = _engine()
    try:
        eng.load_lora("peft", str(tmp_path))
        got = _gen(eng, "hello", adapter="peft")
        merged_eng = InferenceEngine(
            "llama-tiny-f32", n_slots=4, max_len=128, window_k=4,
            tokenizer=ByteTokenizer(),
            params=_merged_params(eng.params, leaves_scaled),
        )
        merged_eng.start_sync()
        try:
            assert got == _gen(merged_eng, "hello")
        finally:
            merged_eng.stop_sync()
    finally:
        eng.stop_sync()


def test_peft_rank_too_big_rejected(tmp_path):
    (tmp_path / "adapter_config.json").write_text(json.dumps({
        "r": 64, "lora_alpha": 64, "target_modules": ["q_proj"],
    }))
    eng = _engine()
    try:
        with pytest.raises(ValueError, match="TPU_LORA_RANK"):
            eng.load_lora("big", str(tmp_path))
    finally:
        eng.stop_sync()


def _memorize_tokens() -> list[int]:
    text = b"the quick brown fox jumps over the lazy dog. " * 3
    return np.frombuffer(text, dtype=np.uint8).astype(np.int32)[
        :128
    ].tolist()


def test_train_adapter_then_serve():
    """The train→serve loop: fine-tune LoRA factors on a frozen base
    (the base tree must come out bit-identical), load them into a
    serving engine, and the adapter stream must reproduce the memorized
    text while the base stream does not."""
    from gofr_tpu.parallel.sharding import make_lora_train_step

    base = init_transformer(jax.random.PRNGKey(0), CFG)
    base_flat = [np.asarray(x) for x in jax.tree_util.tree_leaves(base)]
    init_state, step = make_lora_train_step(
        CFG, base, rank=8, learning_rate=3e-3
    )
    lora, opt = init_state(jax.random.PRNGKey(1))
    toks = jnp.asarray(_memorize_tokens())[None, :]
    first = last = None
    for _ in range(60):
        loss, lora, opt = step(lora, opt, toks)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.5
    for before, after in zip(
        base_flat, jax.tree_util.tree_leaves(base)
    ):
        np.testing.assert_array_equal(before, np.asarray(after))

    eng = InferenceEngine(
        "llama-tiny-f32", n_slots=2, max_len=160, window_k=4,
        tokenizer=ByteTokenizer(), params=base, lora_slots=1, lora_rank=8,
    )
    eng.start_sync()
    try:
        idx = eng.load_lora("memorized", {t: lora[t] for t in lora})
        assert idx == 1
        prompt = bytes(_memorize_tokens()[:20]).decode()
        cont = bytes(_memorize_tokens()[20:36]).decode()
        tuned = eng.generate_sync(
            prompt, max_new_tokens=16, temperature=0.0, stop_on_eos=False,
            adapter="memorized", timeout=120,
        )
        plain = eng.generate_sync(
            prompt, max_new_tokens=16, temperature=0.0, stop_on_eos=False,
            timeout=120,
        )
        assert tuned.text == cont  # memorization served through the engine
        assert plain.text != cont
    finally:
        eng.stop_sync()


def test_train_adapter_qlora_int8_base():
    """QLoRA shape: the frozen base is int8-quantized; training still
    converges (gradients flow only through the f32 factors)."""
    from gofr_tpu.ops.quant import Q8
    from gofr_tpu.parallel.sharding import make_lora_train_step
    from gofr_tpu.serving.engine import InferenceEngine as _E

    eng = _E(
        "llama-tiny", n_slots=2, max_len=64, tokenizer=ByteTokenizer(),
        quant="int8",
    )
    base = eng.params
    eng.close()
    assert isinstance(base["layers"]["wq"], Q8)
    cfg = get_model("llama-tiny").config
    init_state, step = make_lora_train_step(
        cfg, base, rank=4, learning_rate=3e-3
    )
    lora, opt = init_state(jax.random.PRNGKey(1))
    toks = jnp.asarray(_memorize_tokens())[None, :64]
    first = last = None
    for _ in range(30):
        loss, lora, opt = step(lora, opt, toks)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.8


def test_train_adapter_on_mesh():
    """LoRA factors shard with their base projections (minus the adapter
    axis) over a dp×tp mesh; one step runs and the loss is finite."""
    from gofr_tpu.parallel import make_mesh
    from gofr_tpu.parallel.sharding import (
        make_lora_train_step,
        named_shardings,
        prune_specs,
    )
    from gofr_tpu.models.transformer import transformer_param_specs

    mesh = make_mesh({"dp": 2, "tp": 2})
    specs = prune_specs(transformer_param_specs(CFG), mesh)
    base = jax.jit(
        lambda k: init_transformer(k, CFG),
        out_shardings=named_shardings(specs, mesh),
    )(jax.random.PRNGKey(0))
    init_state, step = make_lora_train_step(
        CFG, base, rank=4, mesh=mesh, learning_rate=3e-3
    )
    lora, opt = init_state(jax.random.PRNGKey(1))
    assert "tp" in str(lora["wq"][1].sharding.spec)  # b shards out over tp
    toks = jnp.asarray(_memorize_tokens())[None, :64]
    toks = jnp.broadcast_to(toks, (2, 64))
    loss, lora, opt = step(lora, opt, toks)
    assert np.isfinite(float(loss))


def test_grpc_kwargs_pass_adapter():
    """Both gRPC surfaces (JSON + typed proto) forward the adapter."""
    from gofr_tpu.grpc import inference_pb2
    from gofr_tpu.grpc.inference import InferenceServicer
    from gofr_tpu.grpc.inference_typed import TypedInferenceServicer

    class _Eng:
        tokenizer = None

    kw = InferenceServicer(_Eng())._gen_kwargs(
        {"prompt": "x", "adapter": "tuned"}, False
    )
    assert kw["adapter"] == "tuned"
    kw2 = InferenceServicer(_Eng())._gen_kwargs({"prompt": "x"}, False)
    assert "adapter" not in kw2
    req = inference_pb2.GenerateRequest(prompt="x", adapter="tuned")
    _, tkw = TypedInferenceServicer(_Eng())._gen_kwargs(req)
    assert tkw["adapter"] == "tuned"


def test_openai_surface_routes_adapters():
    """The OpenAI surface serves adapters as model ids: /v1/models lists
    them, completions route by model name, unknown models still 404."""
    import asyncio
    import http.client
    import threading

    from gofr_tpu import App
    from gofr_tpu.config import MockConfig
    from gofr_tpu.serving.openai_compat import add_openai_routes

    app = App(config=MockConfig({
        "APP_NAME": "lora-test", "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_MODEL": "llama-tiny-f32", "TPU_KV_SLOTS": "4",
        "TPU_MAX_LEN": "128", "TPU_LORA_SLOTS": "2", "TPU_LORA_RANK": "4",
    }))
    add_openai_routes(app)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    asyncio.run_coroutine_threadsafe(app.start(), loop).result(timeout=120)
    try:
        app.container.tpu.load_lora("tuned", _rand_adapter(51))

        def call(method, path, body=None):
            c = http.client.HTTPConnection(
                "127.0.0.1", app.http_port, timeout=120
            )
            c.request(
                method, path, body=json.dumps(body) if body else None
            )
            r = c.getresponse()
            return r.status, json.loads(r.read())

        _, models = call("GET", "/v1/models")
        ids = {m["id"] for m in models["data"]}
        assert "tuned" in ids
        body = {
            "model": "tuned", "prompt": "hello", "max_tokens": 6,
            "temperature": 0,
        }
        st, r_tuned = call("POST", "/v1/completions", body)
        assert st == 200
        st, r_base = call(
            "POST", "/v1/completions", {**body, "model": "llama-tiny-f32"}
        )
        assert st == 200
        assert r_tuned["choices"][0]["text"] != r_base["choices"][0]["text"]
        st, _ = call(
            "POST", "/v1/completions", {**body, "model": "missing"}
        )
        assert st == 404
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)


def test_boot_time_adapters_from_config(tmp_path):
    """TPU_LORA_ADAPTERS=name=path[,name2=p2] loads PEFT checkpoints at
    engine boot (the from_config seam); malformed entries fail loudly."""
    from safetensors.numpy import save_file

    from gofr_tpu.config import MockConfig

    rng = np.random.default_rng(9)
    tensors = {}
    for t, mod in (("wq", "q_proj"), ("wv", "v_proj")):
        d_in, d_out = lora_dims(CFG, t)
        for i in range(CFG.n_layers):
            tensors[
                f"base_model.model.model.layers.{i}.self_attn.{mod}"
                f".lora_A.weight"
            ] = rng.standard_normal((4, d_in)).astype(np.float32) * 0.5
            tensors[
                f"base_model.model.model.layers.{i}.self_attn.{mod}"
                f".lora_B.weight"
            ] = rng.standard_normal((d_out, 4)).astype(np.float32) * 0.5
    (tmp_path / "adapter_config.json").write_text(json.dumps({
        "r": 4, "lora_alpha": 4.0,
        "target_modules": ["q_proj", "v_proj"],
    }))
    save_file(tensors, str(tmp_path / "adapter_model.safetensors"))

    cfg = {
        "TPU_MODEL": "llama-tiny-f32", "TPU_KV_SLOTS": "2",
        "TPU_MAX_LEN": "128", "TPU_LORA_SLOTS": "2", "TPU_LORA_RANK": "4",
        "TPU_LORA_ADAPTERS": f"boot={tmp_path}",
    }
    eng = InferenceEngine.from_config(MockConfig(cfg))
    assert eng.lora_names() == ["boot"]
    eng.start_sync()
    try:
        base = eng.generate_sync(
            "hi", max_new_tokens=6, temperature=0.0, stop_on_eos=False,
            timeout=120,
        ).token_ids
        tuned = eng.generate_sync(
            "hi", max_new_tokens=6, temperature=0.0, stop_on_eos=False,
            timeout=120, adapter="boot",
        ).token_ids
        assert tuned != base  # the boot adapter actually loaded weights
    finally:
        eng.stop_sync()

    with pytest.raises(ValueError, match="name=path"):
        InferenceEngine.from_config(MockConfig({
            **cfg, "TPU_LORA_ADAPTERS": "not-an-assignment",
        }))
